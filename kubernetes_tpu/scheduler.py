"""The scheduler: the driving loop over queue → device pass → bind.

The batched equivalent of ScheduleOne (pkg/scheduler/schedule_one.go:65):
instead of popping one pod, running the framework's extension points over a
goroutine pool, and binding asynchronously, we pop a batch in QueueSort order,
run the compiled device pass (filter+score+select+commit for every pod in the
batch in one dispatch), then apply the resulting assignments to the host cache
(the assume step — the device already committed them to its state) and hand
unschedulable pods back to the queue."""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field

import jax
import numpy as np

from .api import types as t
from .cache import Cache
from .engine.features import build_pod_batch
from .engine.packing import pack_batch
from .faults import EngineFault
from .engine.pass_ import PassCache, filter_op_names
from .framework.config import DEFAULT_PROFILE, Profile
from .framework.events import NORMAL, WARNING, EventBroadcaster
from .framework.flight import FlightRecorder
from .framework.metrics import MetricsRegistry, TenantMetrics, pod_tenant
from .framework.status import Diagnosis
from .framework.tracing import Trace
from .intern import InternTable
from .ops.common import registered_subset
from .preemption import PreemptionEvaluator
from .queue import Event, EventCtx, QueuedPodInfo, SchedulingQueue
from .utils import device_fetch
from .snapshot import SnapshotBuilder

from functools import partial  # noqa: E402

import jax.numpy as jnp  # noqa: E402


@partial(jax.jit, static_argnums=3)
def _expand_uniform(small, valid, nomrow, k):
    """Broadcast a uniform batch's single representative feature row to
    the full batch axis on device (see _dispatch_batch: identical rows
    need not ride the tunnel k times)."""
    out = {
        kk: jnp.broadcast_to(v[0], (k,) + v.shape[1:])
        for kk, v in small.items()
    }
    out["valid"] = valid
    out["nominated_row"] = nomrow
    return out


@dataclass
class ScheduleOutcome:
    pod: t.Pod
    node_name: str | None  # None → unschedulable this round
    score: int = 0
    feasible_nodes: int = 0
    nominated_node: str | None = None  # set when preemption picked victims
    victims: int = 0
    # Victim identities for an out-of-process host's async DELETE calls
    # (prepareCandidate, preemption.go:342): uids for sidecar-cache
    # addressing, namespace/name refs for the API DELETE.
    victim_uids: tuple[str, ...] = ()
    victim_names: tuple[str, ...] = ()
    # Why the pod failed (framework/types.go Diagnosis): which plugins
    # rejected nodes, from the device pass's per-op fail bitmask.
    diagnosis: Diagnosis | None = None


@dataclass
class SchedulerMetrics:
    """Counters mirroring the reference's core series
    (pkg/scheduler/metrics/metrics.go:138 schedule_attempts_total etc.)."""

    schedule_attempts: int = 0
    scheduled: int = 0
    unschedulable: int = 0
    preemptions: int = 0
    deferred: int = 0  # chunk-conflict deferrals resolved by the strict tail
    pinned_batches: int = 0  # batches served by the pinned fast path
    # Conflict-aware chunk packing (engine/packing.py): batches reordered,
    # residual same-chunk collisions the plans accepted, and the last
    # batch's plan shape (width / class count) for the gauges.
    packed_batches: int = 0
    pack_collisions: int = 0
    pack_width: int = 0
    pack_classes: int = 0
    # Carried DomTables (ISSUE 13): main-pass dispatches that reused last
    # batch's domain aggregates vs. ones that rebuilt from cluster state.
    dom_carry_hits: int = 0
    dom_carry_rebuilds: int = 0
    batches: int = 0
    device_time_s: float = 0.0
    featurize_time_s: float = 0.0
    first_scheduled_ts: float = 0.0
    last_scheduled_ts: float = 0.0
    throughput_samples: list = field(default_factory=list)
    # Per-pod e2e scheduling latency (enqueue → bind), the analog of
    # pod_scheduling_sli_duration_seconds (metrics/metrics.go:225).
    e2e_latency_samples: list = field(default_factory=list)
    # Histograms: per-extension-point durations + SLI
    # (framework_extension_point_duration_seconds, metrics.go:245).
    registry: MetricsRegistry = field(default_factory=MetricsRegistry)


# Hard filters that read MUTABLE per-node state (pods' labels/ports/volumes
# on the node — anything a strict-tail placement can change).  Node-static
# filters (taints, labels, capacity, unschedulable) are NOT here: tail
# commits cannot invalidate them, and resources re-check via _fits_now.
DYNAMIC_HARD_OPS = frozenset(
    {
        "InterPodAffinity", "PodTopologySpread", "NodePorts",
        "VolumeRestrictions", "NodeVolumeLimits", "VolumeBinding",
        "VolumeZone", "DynamicResources",
    }
)


class TPUScheduler:
    def __init__(
        self,
        profile: Profile = DEFAULT_PROFILE,
        batch_size: int = 256,
        queue: SchedulingQueue | None = None,
        enable_preemption: bool = True,
        mesh=None,
        chunk_size: int = 1,
        profiles: list[Profile] | None = None,
        extenders: list | None = None,
        consistency_check_every: int = 0,
        feature_gates=None,
        inline_preempt_commit: bool | None = None,
        flight_capacity: int = 4096,
        tenant_attribution: bool = True,
        pipeline_depth: int = 1,
    ):
        from .framework.features import DEFAULT_GATES

        # Feature gates (pkg/features/kube_features.go): runtime behavior
        # switches; see framework/features.py for the wired subset.
        self.feature_gates = feature_gates or DEFAULT_GATES
        # Restrict to plugins whose vectorized ops are registered (a no-op
        # once the op inventory is complete; prevents KeyError mid-build-out).
        self.profile = registered_subset(profile)
        # Multi-profile map (profile/profile.go:47): schedulerName →
        # compiled program variant.  `profile` stays the default; extra
        # profiles get their own XLA programs via PassCache and pods select
        # by .spec.scheduler_name.  Pods naming an unknown scheduler are not
        # ours (eventhandlers.go responsibleForPod) and are ignored.
        self.profiles: dict[str, Profile] = {self.profile.name: self.profile}
        for p in profiles or ():
            self.profiles[p.name] = registered_subset(p)
        if not self.feature_gates.enabled("DynamicResourceAllocation"):
            # plugins/registry.go:49: the DRA plugin is only registered when
            # the gate is on; with it off the plugin simply doesn't exist.
            import dataclasses as _dc

            self.profiles = {
                name: _dc.replace(
                    p,
                    **{
                        fld: tuple(
                            f for f in getattr(p, fld) if f != "DynamicResources"
                        )
                        for fld in (
                            "filters", "pre_enqueue", "pre_filter",
                            "post_filter", "reserve", "pre_bind",
                        )
                    },
                )
                for name, p in self.profiles.items()
            }
            self.profile = self.profiles[self.profile.name]
        # Gate off ⇒ the plugin exists at NO extension point: claims are
        # never allocated at Reserve/PreBind either (the reference scheduler
        # simply has no DRA code registered).
        self._dra_enabled = self.feature_gates.enabled("DynamicResourceAllocation")
        # Out-of-process extenders (pkg/scheduler/extender.go); a non-empty
        # chain routes scheduling through the per-pod eval-only path.
        self.extenders = list(extenders or ())
        self.batch_size = batch_size
        # chunk_size=1 → strictly sequential-equivalent scan (parity mode);
        # >1 → C pods per device step with conflict-deferral + a strict tail
        # pass for the deferred readers (engine/pass_.py module docstring).
        assert batch_size % chunk_size == 0, "batch_size must be a chunk multiple"
        self.chunk_size = chunk_size
        # Strict tail batches are padded to this fixed shape (one compile).
        # Small on purpose: the chunk=1 tail pass costs one scan step per
        # SLOT whether occupied or not, so a 64-slot tail is 4× cheaper
        # than 256 for the common few-dozen-deferral case; large deferral
        # bursts are first drained by a chunked replay (see _complete_batch).
        self.tail_size = min(batch_size, 64)
        self.interns = InternTable()
        self.builder = SnapshotBuilder(self.interns)
        self.cache = Cache(self.builder)
        self.queue = queue or SchedulingQueue()
        self.queue.use_queueing_hints = self.feature_gates.enabled(
            "SchedulerQueueingHints"
        )
        self.queue.respect_scheduling_gates = self.feature_gates.enabled(
            "PodSchedulingReadiness"
        )
        self.queue.gates_apply_to = lambda pod: "SchedulingGates" in (
            (self._profile_for(pod) or self.profile).pre_enqueue
        )
        # Featurizers read gates via FeaturizeContext.gates (the
        # plfeature.Features snapshot, plugins/registry.go:49).
        self.builder.feature_gates = self.feature_gates
        self.passes = PassCache()
        # Carried DomTables (ISSUE 13): the previous main pass's final
        # (group_dom, et_dom) device arrays plus the (schema,
        # mutation_epoch) token they are valid under.  Derivable state — a
        # restart/recovery rebuilds from the journaled store and the carry
        # starts cold; any host-side mutation (node churn, deletes,
        # preemption evictions, recovery reconcile) bumps the builder's
        # mutation_epoch and forces the next pass to rebuild on device.
        self._dom_carry: tuple | None = None
        self._dom_token: tuple | None = None
        self._dom_zeros: dict[tuple, tuple] = {}
        self.metrics = SchedulerMetrics()
        # Event recorder (client-go record.EventBroadcaster analog): the
        # structured Scheduled/FailedScheduling/Preempted/GangWaiting
        # narration, counted into scheduler_events_total{reason} and
        # readable via the sidecar `events` frame.
        self.events = EventBroadcaster(registry=self.metrics.registry)
        self.recorder = self.events.new_recorder()
        # Flight recorder (framework/flight.py): one per-phase attribution
        # record per scheduled batch + state-transition markers, in a
        # bounded ring.  Always on; auto-dumps on engine fault/quarantine
        # (and SIGTERM via the CLI), readable via the `flight` frame,
        # GET /debug/flight, and the `flight` subcommand.
        self.flight = FlightRecorder(capacity=flight_capacity)
        # Per-schedule_batch phase accumulator (set by schedule_batch,
        # filled by _dispatch_batch/_complete_batch; None outside a batch
        # so direct _schedule_infos callers skip recording).
        self._flight_acc: dict | None = None
        # True while inside the batch-recovery bisect: nested recoveries
        # record markers but only the OUTERMOST failure writes the
        # auto-dump (a 256-pod bisect must not shed a file per halving).
        self._recovering = False
        # Cross-boundary tracing: (trace_id, parent_span_id) of the REMOTE
        # caller's span — the sidecar server sets it from the envelope so
        # the next batch's root span joins the client's trace.
        self.trace_parent: tuple[str, str | None] | None = None
        # The most recent batch's root span (the server echoes its span_id
        # in the schedule response) and a ring of slow span trees for the
        # debugger dump.
        self.last_batch_span: Trace | None = None
        self.slow_spans: deque = deque(maxlen=16)
        self._install_metric_collectors()
        # Per-tenant SLO attribution (ISSUE 12): pods carry a tenant id
        # (framework/metrics.py TENANT_LABEL_KEY); admission / bind /
        # preemption / deferral count into the bounded-cardinality
        # scheduler_tenant_*_total families.  Observational only — a
        # scheduler with attribution off binds bit-identically.
        self.tenant_metrics = (
            TenantMetrics(self.metrics.registry) if tenant_attribution else None
        )
        if self.tenant_metrics is not None:
            self.queue.tenant_note = self.tenant_metrics.note_pod
        self.preemption = PreemptionEvaluator(self) if enable_preemption else None
        # Inline preemptor commit (perf mode): a successful dry-run commits
        # the preemptor immediately instead of nominate + requeue — sound
        # IN-PROCESS because victim deletion is synchronous here, so the
        # retry's nominated fast path would take exactly the freed node the
        # what-if verified.  Stays OFF in parity mode (chunk_size=1) and
        # for wire deployments (the HOST owns the victims' API deletes —
        # the sidecar must hand the nomination back, not act on it).
        # Pods with Permit groups or relevant Reserve plugins always take
        # the nominate path (their Reserve/Permit chains run on the retry).
        if inline_preempt_commit is None:
            inline_preempt_commit = chunk_size > 1
        self.inline_preempt_commit = inline_preempt_commit
        # Gang scheduling (the out-of-tree coscheduling plugin's PodGroup):
        # group name → PodGroup; bound-member counts for quorum checks.
        # The queue shares gang_bound as its admission credit so PreEnqueue
        # parking and the Permit gate agree.
        self.pod_groups: dict[str, t.PodGroup] = {}
        self.gang_bound: dict[str, int] = {}
        # PodDisruptionBudgets (preemption criterion 1, the disruption
        # controller's state in-process).
        self.pdbs: dict[str, t.PodDisruptionBudget] = {}
        from .controllers import (
            DisruptionController,
            NodeLifecycleController,
            PodGCController,
            TaintEvictionController,
        )

        # Controller clock override (tests / deterministic harnesses):
        # None = the default domain (wall monotonic, or the node-lifecycle
        # controller's logical clock once armed) — see _now().
        self.clock = None
        self.disruption_controller = DisruptionController(self)
        self.taint_eviction = TaintEvictionController(self)
        # The failure-response WRITER half (ISSUE 9): heartbeat-staleness
        # taint writer + pod GC.  Disarmed by default — nodes that never
        # renew a Lease are exempt, so embedders keep the consumer-only
        # behavior until they arm the loop (serve --node-grace-s).
        self.node_lifecycle = NodeLifecycleController(self)
        self.pod_gc = PodGCController(self)
        # Called with the node name after a journaled taint write applies
        # (the speculative frontend registers an invalidation here —
        # taints flip feasibility globally, exactly like a wire-fed taint
        # change through its note_add path).
        self.taints_changed_hook = None
        # Uids ever evicted through the requeue path (taint eviction /
        # pod GC) — the dump's loop-closure evidence: an evicted uid
        # bound again means eviction → requeue → reschedule completed
        # for that pod.  Membership-only (no iteration-order dependence);
        # journal replay repopulates it, so the count survives a crash.
        self._evicted_uids: set[str] = set()
        # Nominator (backend/queue/nominator.go): preemptors' claims on
        # their freed nodes — uid → (node name, row delta, priority).  The
        # fit filter counts these on their nodes so a same/next-batch pod
        # cannot steal a freed node (framework.go:973), and the retrying
        # preemptor takes its nominated node via the engine's fast path.
        self.nominator: dict[str, tuple[str, dict, int]] = {}
        # WaitOnPermit room (framework.go:1503): gang → [(qp, node, score,
        # feasible)] of members assumed-but-not-bound until quorum forms.
        self.permit_waiting: dict[str, list] = {}
        self.permit_wait_since: dict[str, float] = {}
        self.permit_timeout_s = 60.0  # coscheduling PermitWaitingTimeSeconds
        # Host-side extension points (framework/hostplugins.py): the loop
        # runs whatever is registered here and special-cases nothing —
        # coscheduling is one PermitPlugin, volume/DRA reservation are
        # ReservePlugins (runtime/framework.go:1359,1443).
        from .framework.coscheduling import CoschedulingPermit
        from .framework.hostplugins import DEFAULT_RESERVE_PLUGINS

        self.permit_plugins = [CoschedulingPermit()]
        self.reserve_plugins = list(DEFAULT_RESERVE_PLUGINS)
        # Waiting-room group → owning PermitPlugin (for timeout/rollback).
        self.permit_wait_owner: dict[str, object] = {}
        # PreBind wait room (the blocking tail of volume_binding.go:521
        # BindPodVolumes, made non-blocking): pod uid → entry while an
        # external provisioner works; see notify_prebind /
        # expire_waiting_prebinds.  Timeout = the reference's bindTimeout
        # default (volumebinding DefaultBindTimeoutSeconds, 600s).
        self.prebind_waiting: dict[str, dict] = {}
        self.prebind_timeout_s = 600.0
        # Gang members whose PreBind completed while group-mates still wait:
        # group → [{qp, undos, node}].  A later timeout in the group rolls
        # these back too (all-or-nothing); the group's last completion
        # clears its list.
        self.prebind_done_pending: dict[str, list[dict]] = {}
        # Binds completed by informer-driven notify_prebind between batches;
        # the next schedule_batch returns them so outcome-consuming drivers
        # (the benchmark harness) observe wait-mode binds.
        self._prebind_outcomes: list[ScheduleOutcome] = []
        # Assumed-pod TTL (cache.go:42 ticks cleanupAssumedPods at 1s; the
        # 30s expiry mirrors durationToExpireAssumedPod's safety-net role).
        self.assume_ttl_s = 30.0
        # LogIfLong threshold for the per-batch cycle span (the reference
        # logs any >100ms CYCLE; a batch amortizes hundreds of cycles, so
        # the default only surfaces genuinely slow batches).
        self.trace_threshold_s = 2.0
        self._next_assumed_sweep = 0.0
        self.queue.gang_credit = lambda g: (
            self.gang_bound.get(g, 0)
            + len(self.permit_waiting.get(g, ()))
            + self.fleet_gang_credit(g)
        )
        if mesh is not None:
            # Multi-chip: node axis sharded over the mesh (parallel/mesh.py);
            # XLA inserts the ICI collectives for the cross-shard reductions.
            self.builder.set_mesh(mesh)
        self._cycle = 0
        # Truncated (parity) mode: percentage_of_nodes_to_score != 100
        # reproduces the reference's adaptive search truncation + rotating
        # start + zone-interleaved order; needs the sequential scan.
        self._truncated = any(
            p.percentage_of_nodes_to_score != 100 for p in self.profiles.values()
        )
        if self._truncated:
            assert chunk_size == 1, (
                "percentage_of_nodes_to_score != 100 (parity mode) requires "
                "chunk_size=1 (sequential-equivalent scan)"
            )
        self._eval_passes: dict = {}  # extender path: per-profile eval pass
        # Decision provenance (framework/provenance.py): OFF by default —
        # a ProvenanceRing only once arm_provenance() is called, so the
        # unarmed hot path pays a single `is not None` test per bind and
        # stays byte-identical.  The attribution passes compile lazily on
        # the first explain, never from the scheduling loop.
        self.provenance = None
        self._attr_passes: dict = {}
        # Placed-but-not-yet-journaled tie-break steps (uid → device
        # step), staged at phase-1 and drained into the bind WAL record
        # so journal-mode explain reproduces selectHost exactly even
        # when the ring was never armed.  Only populated while a journal
        # or the ring is attached; entries for pods whose bind rolls
        # back are overwritten at their next placement.
        self._tie_pending: dict = {}
        # Periodic host↔device comparer (the cache debugger's SIGUSR2 check
        # run on a schedule): 0 = disabled.
        self.consistency_check_every = consistency_check_every
        # Prefetched next batch: (infos, featurize work) — schedule_batch
        # featurizes batch k+1 while the device crunches batch k.  The
        # speculative sidecar frontend counts these uids among its
        # in-flight set (speculate._prefetched_uids) so hint admission
        # never double-commits a prefetched pod.
        self._prefetched: tuple | None = None
        self._prefetch_enabled = True
        # Software pipeline (ISSUE 15, engine/pipeline.py): depth 1 is
        # the serial loop (the parity oracle) — commits stage + drain at
        # exactly the inline-apply point, one group fsync per batch.
        # Depth >= 2 additionally dispatches batch k+1 BEFORE draining
        # batch k's staged commit group, so the fsync and the apply loop
        # run under the in-flight device pass (featurize(k+1) already
        # overlaps device(k) via the prefetch).  Bindings stay
        # bit-identical across depths: the predispatched pass is
        # discarded and re-dispatched whenever any state it read changed
        # (engine/pipeline.predispatch_valid).
        self.pipeline_depth = max(1, int(pipeline_depth))
        # The current batch's staged commit group (engine/pipeline.py
        # CommitTicket) — never outlives its schedule_batch call.
        self._pending_ticket = None
        # A device pass dispatched one cycle early (Predispatch), picked
        # up by the next schedule_batch.
        self._predispatched = None
        # Adaptive predispatch gate: every invalidated predispatch threw
        # away a full device pass and re-dispatched (churn workloads
        # mutate host state between EVERY batch, so the double buffer
        # only doubles device cost there).  Consecutive invalidations
        # back the gate off — skip-and-decay halves the retry rate under
        # sustained churn while recovering immediately once hits return.
        self._pd_consec_invalid = 0
        # Called between the async device dispatch and the blocking fetch
        # of each batch — host work done here (the speculative frontend's
        # hint parse/build) hides under the in-flight pass.
        self.post_dispatch_hook = None
        # Uids of the batch currently in flight (popped, not yet
        # committed).  The post-dispatch hook's admission path must not
        # re-add one of these to the active queue: the commit's
        # queue.done() would strand a stale active entry and a later
        # pop_batch would find a uid with no info record.
        self._inflight_uids: frozenset = frozenset()
        # Fault injection hook (faults.FaultPlan.install_engine): called
        # with the batch's pods at the top of every device dispatch.  None
        # in production; the batch-recovery path it exercises (bisect +
        # quarantine) is always armed — a REAL engine exception takes the
        # same road.
        self.fault_injector = None
        # Write-ahead binding journal (journal.py): None in the default
        # in-memory configuration; attach_journal() arms the commit-path
        # hooks, snapshot cadence and scheduler_journal_* metrics.
        self.journal = None
        self.snapshot_every_batches = 0
        self._last_snapshot_batch = 0
        # Speculative frontend (sidecar/speculate.py), when one wraps this
        # scheduler: registered so snapshots can persist its decision-cache
        # epoch.  _recovered_spec_epoch carries the journaled epoch across
        # recovery, so a restarted frontend resumes the monotonic sequence
        # instead of cold-starting at 0 (subscribers hold epoch-stamped
        # decisions; a reset would violate the Push ordering contract).
        self._spec_frontend = None
        self._recovered_spec_epoch = 0
        # Journal bind records whose node was unknown at recovery time —
        # informers.reconcile_after_recovery re-applies them once the
        # LIST delivers the node (or drops them when it never does).
        self._recovered_bindings: dict[str, dict] = {}
        # Fleet recovery surfaces (journal.recover): crash-orphaned 2PC
        # reservations (presumed abort — the router re-admits the gang)
        # and journaled shard-map handoffs (takeover redoes a lost map
        # write idempotently).
        self._recovered_gang_intents: dict[str, dict] = {}
        self._recovered_handoffs: list[dict] = []
        # Shard scope (fleet/owner.py): a fleet owner's store holds ONLY
        # its shard's nodes.  When set, add_node consults the predicate
        # and drops foreign nodes (counted — a misconfigured feed should
        # be visible, not silently absorbed into the wrong shard).
        self.shard_guard = None
        self.shard_rejected_nodes = 0
        # In-flight fleet 2PC reservations: pod uid → {pod, node, undos,
        # gang} between reserve_proposed and commit/abort_reserved.
        self._fleet_reserved: dict[str, dict] = {}
        # Gang quorum credit earned on OTHER shards (fleet/router.py
        # installs a counter over its fleet-wide gang_bound): the queue's
        # PreEnqueue admission must count members a different owner
        # already bound, or a gang split across shards never reaches
        # quorum anywhere.
        self.fleet_gang_credit = lambda g: 0
        # Eviction requeue sink (fleet/owner.py): a shard owner's local
        # queue is never drained by the router, so an armed lifecycle
        # controller's evict-as-requeue must hand the unbound pod BACK to
        # the router (which can rebind it on a different shard) instead
        # of parking it locally.  None (the default) keeps the single-
        # scheduler behavior: the evicted pod re-enters this queue.
        self.eviction_requeue_hook = None
        # Rotating scan start (schedule_one.go nextStartNodeIndex).
        self._next_start = 0
        # Shapes of the last scheduled batch (for warm_tail precompilation).
        self._last_batch_meta: tuple | None = None
        # Pre-intern the hot topology keys so node rows materialize them.
        for key in ("kubernetes.io/hostname", "topology.kubernetes.io/zone",
                    "topology.kubernetes.io/region"):
            self.builder.ensure_topo_key(key)

    def _install_metric_collectors(self) -> None:
        """Register the scrape-time gauge/counter sync on the registry:
        point-in-time series (queue depths, cache sizes, compiled-program
        and device-memory stats) are sampled when `/metrics` or the
        sidecar `metrics` frame renders, so the hot loop pays nothing."""
        reg = self.metrics.registry
        # Hot-path counter cached as an attribute (registry.reset() clears
        # values in place, so the handle stays valid across bench resets).
        self._dispatch_counter = reg.counter(
            "scheduler_device_dispatch_total",
            "Device pass dispatches by kind (batch/pinned/tail/eval).",
        )
        # Flight-recorder phase attribution (the tiled per-batch segments;
        # journal_append/journal_fsync nest inside featurize+commit and
        # are exported for the durability-tax view, not the tiling sum).
        self._phase_hist = reg.histogram(
            "scheduler_phase_duration_seconds",
            "Per-batch scheduling phase duration, by phase.",
        )
        # The tpulint-clean companion of the upstream-parity
        # plugin_execution_duration_seconds exposition: same sampled
        # observations, scheduler_-prefixed family.
        self._plugin_hist = reg.histogram(
            "scheduler_plugin_duration_seconds",
            "Sampled per-plugin duration, by plugin and extension point.",
        )
        attempts = reg.counter(
            "scheduler_schedule_attempts_total",
            "Scheduling attempts by result (metrics.go:138 analog).",
        )
        preempt = reg.counter(
            "scheduler_preemption_attempts_total",
            "Successful preemption candidates.",
        )
        batches = reg.counter(
            "scheduler_batches_total",
            "Device batches run; kinds partition (full + pinned = all).",
        )
        deferred = reg.counter(
            "scheduler_deferred_pods_total",
            "Pods deferred to the strict tail by chunk conflicts.",
        )
        # Conflict-aware chunk packing + carried DomTables (ISSUE 13).
        packed = reg.counter(
            "scheduler_chunk_packed_batches_total",
            "Batches reordered by the conflict-aware chunk packer.",
        )
        pack_coll = reg.counter(
            "scheduler_chunk_pack_collisions_total",
            "Residual same-chunk same-class pods accepted by pack plans "
            "(each is an expected strict-tail deferral).",
        )
        pack_width = reg.gauge(
            "scheduler_chunk_pack_width",
            "Chunk width the last pack plan chose.",
        )
        pack_classes = reg.gauge(
            "scheduler_chunk_pack_classes",
            "Conflict classes in the last packed batch.",
        )
        dom_carry = reg.counter(
            "scheduler_chunk_dom_carry_total",
            "Main-pass dispatches by domain-table source (carried vs "
            "rebuilt from cluster state).",
        )
        # Heterogeneity attribution (ISSUE 14): armed only when a
        # registered profile ships a throughput matrix — homogeneous
        # deployments pay nothing and export no empty families.
        # _hetero_classes caches the bounded label vocabularies
        # (accelerator classes × workload classes from the matrix
        # config; off-config values fold to "other").
        matrix_accels: set = set()
        matrix_classes: set = set()
        for p in self.profiles.values():
            for wclass, row in p.throughput_matrix:
                matrix_classes.add(wclass)
                matrix_accels.update(a for a, _tp in row)
        self._hetero_classes = (
            (frozenset(matrix_accels), frozenset(matrix_classes))
            if matrix_classes
            else None
        )
        self._hetero_bound = reg.counter(
            "scheduler_hetero_bound_total",
            "Pods bound, by the chosen node's accelerator class and the "
            "pod's workload class (heterogeneity profiles).",
        )
        self._profile_bound = reg.counter(
            "scheduler_profile_bound_total",
            "Pods bound per scheduler profile (the multi-profile map's "
            "serving split).",
        )
        self._measured_tput = reg.gauge(
            "scheduler_measured_throughput_millis",
            "Flight-derived measured milli-throughput per (workload "
            "class, accelerator class) — published when a measured "
            "matrix artifact is armed (framework/measured.py).",
        )
        # Software pipeline (ISSUE 15): predispatch double-buffer hits vs
        # invalidations (a miss re-dispatches serially — correctness is
        # free, overlap is not), drain placement (overlapped under an
        # in-flight pass vs inline at the serial point), and the wall
        # seconds the overlap actually saved (per-batch stage sum minus
        # batch wall, the flight recorder's overlap-coverage numerator).
        self._pipeline_predispatch_counter = reg.counter(
            "scheduler_pipeline_predispatch_total",
            "Predispatched device passes by pickup result "
            "(hit/invalidated).",
        )
        self._pipeline_drain_counter = reg.counter(
            "scheduler_pipeline_drains_total",
            "Staged commit-group drains by placement (overlapped/inline).",
        )
        self._pipeline_overlap_counter = reg.counter(
            "scheduler_pipeline_overlap_saved_seconds_total",
            "Wall seconds saved by stage overlap (serial stage sum minus "
            "batch wall, clamped at zero).",
        )
        # Poison-batch recovery observability: how often the engine raised
        # mid-batch and how many pods ended up isolated.  The quarantine
        # DEPTH rides scheduler_pending_pods{queue="quarantine"} below.
        self._engine_fault_counter = reg.counter(
            "scheduler_engine_faults_total",
            "Engine exceptions caught by the batch-recovery path.",
        )
        self._quarantine_counter = reg.counter(
            "scheduler_quarantined_pods_total",
            "Pods isolated into the quarantine pool after engine faults.",
        )
        # Rejection attribution (NodeToStatusMap analog): which plugin
        # made a pod unschedulable.  Incremented once per rejecting
        # plugin at the filter-reject diagnosis site, and as
        # plugin="EngineFault" at quarantine parks — label cardinality
        # is bounded by the profiles' filter-op registry.
        self._unsched_reasons = reg.counter(
            "scheduler_unschedulable_reasons_total",
            "Unschedulable verdicts attributed to the rejecting plugin.",
        )
        # Failure-response loop (controllers.py): lifecycle transitions
        # are counted at the write site; the per-state gauge, the GC
        # reasons and the eviction total are scraped below.
        self._lifecycle_transitions = reg.counter(
            "scheduler_node_lifecycle_transitions_total",
            "Node lifecycle state transitions written as taints, by "
            "target state.",
        )
        self._pod_gc_counter = reg.counter(
            "scheduler_pod_gc_total",
            "Pods collected by the GC sweeps, by reason.",
        )
        lifecycle_state = reg.gauge(
            "scheduler_node_lifecycle_state",
            "Lease-tracked nodes by lifecycle state.",
        )
        taint_evictions = reg.counter(
            "scheduler_taint_evictions_total",
            "Pods evicted by the NoExecute taint-eviction controller.",
        )
        pending = reg.gauge(
            "scheduler_pending_pods", "Pending pods by queue class."
        )
        cache_g = reg.gauge(
            "scheduler_cache_size", "Cached cluster objects by kind."
        )
        snap = reg.gauge(
            "scheduler_snapshot_node_rows", "Device snapshot node-row capacity."
        )
        programs = reg.gauge(
            "scheduler_jax_compiled_programs",
            "Compiled XLA program variants held.",
        )
        devmem = reg.gauge(
            "scheduler_device_memory_bytes",
            "Device allocator stats when the backend reports them.",
        )

        def collect(_reg) -> None:
            m = self.metrics
            # The reference's partitioning label set {scheduled,
            # unschedulable, error} (metrics.go:138): the cells sum to the
            # attempt total, so sum(rate(...)) dashboards stay honest.
            # "error" is the residual — attempts whose pods are neither
            # bound nor pooled (in-flight waits, rollbacks).
            attempts.set(m.scheduled, result="scheduled")
            attempts.set(m.unschedulable, result="unschedulable")
            attempts.set(
                max(m.schedule_attempts - m.scheduled - m.unschedulable, 0),
                result="error",
            )
            preempt.set(m.preemptions)
            # Disjoint cells (m.batches counts every batch, pinned ones
            # included): sum() over the label reproduces the true total.
            batches.set(max(m.batches - m.pinned_batches, 0), kind="full")
            batches.set(m.pinned_batches, kind="pinned")
            deferred.set(m.deferred)
            packed.set(m.packed_batches)
            pack_coll.set(m.pack_collisions)
            pack_width.set(m.pack_width)
            pack_classes.set(m.pack_classes)
            dom_carry.set(m.dom_carry_hits, result="hit")
            dom_carry.set(m.dom_carry_rebuilds, result="rebuild")
            for q, depth in self.queue.depths().items():
                pending.set(depth, queue=q)
            for state, count in self.node_lifecycle.stats()["states"].items():
                lifecycle_state.set(count, state=state)
            taint_evictions.set(self.taint_eviction.evictions)
            cache_g.set(len(self.cache.nodes), kind="nodes")
            cache_g.set(len(self.cache.pods), kind="pods")
            cache_g.set(
                sum(1 for p in self.cache.pods.values() if p.assumed),
                kind="assumed",
            )
            snap.set(getattr(self.builder.schema, "N", 0) or 0)
            programs.set(len(self.passes) + len(self._eval_passes))
            try:
                stats = jax.local_devices()[0].memory_stats() or {}
            except Exception:  # CPU backends return None / lack the call
                stats = {}
            for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
                if k in stats:
                    devmem.set(stats[k], kind=k)

        reg.add_collector(collect)

    # -- durability (journal.py) ---------------------------------------------

    def attach_journal(self, journal, snapshot_every_batches: int = 0) -> None:
        """Arm the write-ahead binding journal: every bind/preempt/
        quarantine/delete decision is appended (and fsync'd, per the
        journal's policy) BEFORE it is applied, snapshots checkpoint the
        store+queue every ``snapshot_every_batches`` batches (0 = only on
        explicit snapshot), and the journal's counters export as
        scheduler_journal_* at scrape time.  Recovery (journal.recover)
        must run BEFORE attaching — its replay drives this scheduler's
        mutation surface, which would otherwise re-journal every record."""
        self.journal = journal
        self.queue.journal = journal
        if snapshot_every_batches:
            self.snapshot_every_batches = snapshot_every_batches
        reg = self.metrics.registry
        appends = reg.counter(
            "scheduler_journal_appends_total",
            "Decisions durably appended to the write-ahead journal.",
        )
        fsyncs = reg.counter(
            "scheduler_journal_fsync_total", "Journal fsync calls."
        )
        fenced = reg.counter(
            "scheduler_journal_fenced_total",
            "Appends rejected by the lease-epoch fence (deposed writer).",
        )
        group_commits = reg.counter(
            "scheduler_journal_group_commits_total",
            "Group-commit fsync barriers (one durability fsync per "
            "staged commit group).",
        )
        group_size = reg.gauge(
            "scheduler_journal_last_group_size",
            "Records covered by the last group-commit fsync barrier.",
        )
        snaps = reg.counter(
            "scheduler_journal_snapshots_total",
            "Checkpoints written (log truncated at each barrier).",
        )
        replayed = reg.counter(
            "scheduler_journal_replayed_records_total",
            "Records applied by the last recovery replay.",
        )
        seq_g = reg.gauge(
            "scheduler_journal_last_seq", "Sequence number of the last record."
        )
        wal_g = reg.gauge(
            "scheduler_journal_wal_bytes", "Current journal file size."
        )

        def collect(_reg) -> None:
            j = self.journal
            if j is None:
                return
            appends.set(j.appends)
            fsyncs.set(j.fsyncs)
            fenced.set(j.fenced)
            group_commits.set(j.group_commits)
            group_size.set(j.last_group_size)
            snaps.set(j.snapshots)
            replayed.set(j.replayed)
            seq_g.set(j.seq)
            try:
                import os as _os

                wal_g.set(_os.path.getsize(j.wal_path))
            except OSError:
                wal_g.set(0)

        reg.add_collector(collect)

    def _journal_append(self, rtype: str, **data) -> None:
        """Write-ahead one decision.  StaleEpochError propagates — a
        deposed leader must stop committing, not commit unjournaled."""
        if self.journal is not None:
            self.journal.append(rtype, data)

    def _journal_bind(self, pod: t.Pod, node_name: str) -> None:
        if self.journal is not None:
            from .api import serialize

            data = {
                "uid": pod.uid,
                "node": node_name,
                "pod": serialize.to_dict(pod),
            }
            # Decision provenance rides the WAL: the device tie-break
            # step makes a journal-mode explain's selectHost trace exact
            # without the in-memory ring (replay ignores the field).
            tie = self._tie_pending.pop(pod.uid, None)
            if tie is not None and tie >= 0:
                data["tie"] = tie
            seq = self.journal.append("bind", data)
            if self.provenance is not None and seq is not None:
                self.provenance.note_seq(pod.uid, seq)

    def maybe_snapshot(self) -> bool:
        """Checkpoint when the cadence is due AND the log has grown since
        the last barrier (an idle scheduler never rewrites its snapshot)."""
        j = self.journal
        if j is None or not self.snapshot_every_batches:
            return False
        if self._last_snapshot_batch > self.metrics.batches:
            # The batch counter moved backwards (the bench harness resets
            # metrics after warmup): re-base instead of stalling the
            # cadence until the counter catches back up.
            self._last_snapshot_batch = 0
        if (
            self.metrics.batches - self._last_snapshot_batch
            < self.snapshot_every_batches
        ):
            return False
        if j.seq == j.snapshot_seq:
            return False
        from . import journal as journal_mod

        j.snapshot(journal_mod.scheduler_state(self))
        self._last_snapshot_batch = self.metrics.batches
        return True

    def _note_slow_span(self, tr: Trace) -> None:
        """on_slow hook: keep the logged span TREE for the debugger dump
        (the `dump` frame surfaces the joined host↔sidecar trace)."""
        self.slow_spans.append(tr.as_dict())

    # -- flight recorder (framework/flight.py) -------------------------------

    def _trace_id(self) -> str | None:
        """The current batch's trace id (joins events and flight records
        to the span tree — and, over the wire, to the HOST's trace)."""
        span = self.last_batch_span
        return span.trace_id if span is not None else None

    def _trace_extra(self) -> dict:
        """Event extra carrying the originating trace id, so an event can
        be joined to its batch's flight record and span tree."""
        tid = self._trace_id()
        return {"trace_id": tid} if tid else {}

    def _note_tenant(self, event: str, pod: t.Pod) -> None:
        """Count one tenant event (bound/preempted; admission/deferral
        ride the queue's tenant_note hook) — a no-op with attribution
        off."""
        if self.tenant_metrics is not None:
            self.tenant_metrics.note(event, pod_tenant(pod))

    def _note_bound(self, pod: t.Pod, node_name: str) -> None:
        """Per-bind attribution, every bind path: the tenant counter
        plus — when any registered profile carries a throughput matrix —
        the heterogeneity split (scheduler_hetero_bound_total by the
        chosen node's accelerator class × the pod's workload class;
        label values bounded by the matrix config, everything else
        folds to "-"/"other") and the per-profile serving split
        (scheduler_profile_bound_total, bounded by the profile map)."""
        self._note_tenant("bound", pod)
        key = self.hetero_bind_key(pod, node_name)
        if key is None:
            return
        wl, al = key.split("|", 1)
        self._hetero_bound.inc(accel=al, workload_class=wl)
        # The per-batch heterogeneity split on the flight record — the
        # deterministic input framework/measured.py folds into measured
        # throughput rows (counts, never wall time).
        acc = self._flight_acc
        if acc is not None:
            h = acc.setdefault("hetero", {})
            h[key] = h.get(key, 0) + 1
        profile = self._profile_for(pod) or self.profile
        self._profile_bound.inc(profile=profile.name)

    def hetero_bind_key(self, pod: t.Pod, node_name: str) -> str | None:
        """The bounded ``"workload_class|accel"`` key for one bind — None
        when no registered profile carries a throughput matrix.  Label
        values are bounded by the matrix config (everything else folds to
        "-"/"other"), shared by the hetero counter, the per-batch flight
        ``hetero`` field, and the fleet owners' per-op commit records, so
        measured-matrix derivation sees one vocabulary everywhere."""
        if self._hetero_classes is None:
            return None
        accels, wclasses = self._hetero_classes
        from .ops.throughput import ACCEL_LABEL_KEY, WORKLOAD_CLASS_LABEL_KEY

        rec = self.cache.nodes.get(node_name)
        accel = (
            rec.node.metadata.labels.get(ACCEL_LABEL_KEY, "")
            if rec is not None
            else ""
        )
        wclass = pod.metadata.labels.get(WORKLOAD_CLASS_LABEL_KEY, "")
        al = (accel if accel in accels else "other") if accel else "-"
        wl = (wclass if wclass in wclasses else "other") if wclass else "-"
        return f"{wl}|{al}"

    def note_measured_matrix(self, matrix) -> None:
        """Publish a measured throughput matrix into the
        scheduler_measured_throughput_millis gauge family — called when
        serve arms a measured artifact (``--measured-matrix``) so a
        scrape shows exactly which rows the profile scores against.
        Accepts the profile's tuple-of-rows form, a measured artifact
        document, or its ``{wclass: {accel: milli}}`` mapping."""
        rows = matrix.get("matrix", matrix) if isinstance(matrix, dict) else matrix
        if isinstance(rows, dict):
            rows = tuple(
                (w, tuple(sorted(r.items()))) for w, r in sorted(rows.items())
            )
        for wclass, row in rows:
            for accel, milli in row:
                self._measured_tput.set(
                    float(milli),
                    workload_class=str(wclass),
                    accel=str(accel),
                )

    def _flight_add(self, key: str, n) -> None:
        acc = self._flight_acc
        if acc is not None:
            acc[key] = acc.get(key, 0) + n

    def _flight_phase(self, key: str, secs: float) -> None:
        """Accumulate one tiled phase segment (drain/predispatch — the
        pipeline stages recorded outside _complete_batch's tiling)."""
        acc = self._flight_acc
        if acc is not None and secs > 0:
            ph = acc["phases"]
            ph[key] = ph.get(key, 0.0) + secs

    # -- software pipeline (ISSUE 15, engine/pipeline.py) ---------------------

    def _pipeline_active(self) -> bool:
        """Deferred drain + predispatch apply only on the single-profile
        batch path: multi-profile groups, extender chains, and truncated
        (parity) mode keep the serial order — depth 1 everywhere."""
        return (
            self.pipeline_depth >= 2
            and not self._truncated
            and len(self.profiles) == 1
            and not self.extenders
        )

    @property
    def has_inflight_work(self) -> bool:
        """Work popped from the queue but not yet completed: a prefetched
        (featurized) batch or a predispatched device pass.  Drivers that
        loop on queue length must also drain these."""
        return self._prefetched is not None or self._predispatched is not None

    def _drain_pending(self, overlapped: bool) -> float:
        """Drain the current staged commit group (group fsync + applies,
        engine/pipeline.drain_commit).  Returns the drain's host seconds;
        records the `drain` flight phase and the placement counter."""
        ticket = self._pending_ticket
        if ticket is None or ticket.drained:
            return 0.0
        from .engine.pipeline import drain_commit

        drain_s = drain_commit(self, ticket)
        # Fully drained: release the scheduler's reference so an idle
        # process does not pin the last batch's pods/outcomes until the
        # next batch overwrites the slot.  (A mid-drain exception leaves
        # the ticket in place with its progress counters — the recovery
        # drain resumes it.)
        self._pending_ticket = None
        if ticket.staged:
            self._flight_phase("drain", drain_s)
            self._pipeline_drain_counter.inc(
                kind="overlapped" if overlapped else "inline"
            )
        return drain_s

    def _predispatch_next(self, tr) -> bool:
        """Dispatch the prefetched batch k+1 NOW (before batch k's drain)
        so the drain's fsync + applies run under the in-flight device
        pass.  The pass is picked up — or invalidated and re-dispatched —
        by the next schedule_batch (engine/pipeline.predispatch_valid).
        Returns whether a pass was dispatched."""
        pre = self._prefetched
        if pre is None:
            return False
        if self._pd_consec_invalid > 0:
            # Churn regime: a recent predispatch was thrown away at
            # pickup — it cost a whole wasted device pass.  Sit this
            # batch out and decay, so sustained churn converges to ~one
            # probe per penalty window instead of doubling device time
            # every batch, while a single transient mutation costs only
            # a few skipped overlaps.
            self._pd_consec_invalid -= 1
            return False
        infos, work = pre
        if work["version"] != self.builder.feature_version():
            return False  # stale featurization: let the serial path redo it
        from .engine.pipeline import Predispatch, nominator_token

        self._prefetched = None
        cycle0 = self._cycle
        t_pd = time.perf_counter()
        try:
            # _dispatch_batch may permute its local infos (the packer);
            # keep OUR list in original pop order for re-dispatch.  The
            # packer also rebinds work["batch"]/work["deltas"] on the
            # dict it is handed — dispatch a shallow COPY so a failure
            # below cannot restore a work dict whose rows were permuted
            # while infos kept pop order (the serial retry would read
            # each pod against another pod's feature row).
            ctx = self._dispatch_batch(list(infos), self.profile, dict(work))
        except Exception:
            # A dispatch failure (engine fault) must surface inside the
            # VICTIM batch's own cycle for recovery attribution: restore
            # the pop and let the next cycle dispatch serially.
            self._cycle = cycle0
            self._prefetched = (infos, work)
            return False
        self._predispatched = Predispatch(
            infos=list(infos),
            ctx=ctx,
            profile=self.profile,
            version=self.builder.feature_version(),
            mutation_epoch=self.builder.mutation_epoch,
            schema=self.builder.schema,
            nominator_token=nominator_token(self),
            cycle0=cycle0,
            t_dispatch=t_pd,
        )
        self._flight_phase("predispatch", time.perf_counter() - t_pd)
        if tr is not None:
            tr.step("predispatched next batch")
        return True

    def _observe_plugin(self, plugin: str, point: str, secs: float) -> None:
        """One sampled per-plugin duration, fanned to the upstream-parity
        exposition, the scheduler_plugin_duration_seconds family, and the
        current flight record."""
        self.metrics.registry.observe_plugin(plugin, point, secs)
        self._plugin_hist.observe(secs, plugin=plugin, extension_point=point)
        acc = self._flight_acc
        if acc is not None:
            key = f"{plugin}/{point}"
            acc["plugins"][key] = acc["plugins"].get(key, 0.0) + secs

    def _record_flight(self, acc: dict, t0: float, snap_s: float, jbase) -> None:
        """Finalize one per-batch flight record: close the phase tiling
        (featurize/device/commit/snapshot + the explicit `other` residual
        — pop, expiry sweeps, loop overhead), attach the journal's
        append/fsync slice deltas, and observe every phase into
        scheduler_phase_duration_seconds."""
        phases = acc["phases"]
        if snap_s > 0:
            phases["snapshot"] = phases.get("snapshot", 0.0) + snap_s
        wall = time.perf_counter() - t0
        # Per-stage serial sum BEFORE the residual: with the pipeline on,
        # a predispatched batch's device window started in the PREVIOUS
        # call, so the stage sum can exceed this call's wall — the excess
        # is exactly the wall time stage overlap saved vs running the
        # stages serially.
        serial_s = sum(phases.values())
        saved_s = max(serial_s - wall, 0.0)
        phases["other"] = max(wall - serial_s, 0.0)
        rec = {
            "pods": acc["pods"],
            "scheduled": acc["scheduled"],
            "unschedulable": acc["unschedulable"],
            "deferred": acc.get("deferred", 0),
            "dispatch": acc["dispatches"],
            "wall_s": round(wall, 6),
            "phases": {k: round(v, 6) for k, v in phases.items()},
        }
        if self.pipeline_depth >= 2:
            serial_total = serial_s + phases["other"]
            rec["overlap"] = {
                "serial_s": round(serial_total, 6),
                "saved_s": round(saved_s, 6),
                # wall saved vs the serial stage sum — 0.0 with nothing
                # overlapped, approaching the device share as the commit
                # stage fully hides under the next in-flight pass.
                "coverage": round(saved_s / serial_total, 4)
                if serial_total > 0
                else 0.0,
            }
            if saved_s > 0:
                self._pipeline_overlap_counter.inc(saved_s)
        if acc.get("hetero"):
            rec["hetero"] = {
                k: acc["hetero"][k] for k in sorted(acc["hetero"])
            }
        if acc.get("drained"):
            rec["drained"] = acc["drained"]
        if acc.get("group_fsyncs"):
            rec["group_fsyncs"] = acc["group_fsyncs"]
        if acc["plugins"]:
            rec["plugins"] = {
                k: round(v, 6) for k, v in sorted(acc["plugins"].items())
            }
        j = self.journal
        if j is not None and jbase is not None:
            append_s = j.append_latency.total - jbase[2]
            fsync_s = j.fsync_s - jbase[3]
            rec["journal"] = {
                "appends": j.appends - jbase[0],
                "fsyncs": j.fsyncs - jbase[1],
                "append_s": round(append_s, 6),
                "fsync_s": round(fsync_s, 6),
            }
            # Sub-slices of featurize/commit (journaled deletes can land
            # pre-dispatch), exported for the durability-tax view — they
            # deliberately stay OUT of the tiling sum above.
            self._phase_hist.observe(append_s, phase="journal_append")
            self._phase_hist.observe(fsync_s, phase="journal_fsync")
        span = self.last_batch_span
        if span is not None:
            rec["trace_id"] = span.trace_id
            rec["span_id"] = span.span_id
        for k, v in phases.items():
            self._phase_hist.observe(v, phase=k)
        self.flight.record_batch(rec)

    def warm_tail(self) -> None:
        """Pre-compile the programs a measured window would otherwise
        compile lazily: the dirty-row scatter flush (always) and the strict
        tail pass (chunked mode, once a batch has established shapes)."""
        # Warmup binds are device-side commits (never dirty), so without
        # this the first host-side mutation (node churn, a delete) pays the
        # scatter's XLA compile inside the measured window.  The device
        # mirror must exist first — a flush against no mirror takes the
        # full-rebuild branch and compiles nothing — and flushing a clean
        # row is idempotent (host == device values).
        if self.cache.nodes:
            self.builder.state()  # ensure the mirror exists
            rec = next(iter(self.cache.nodes.values()))
            self.builder._dirty_rows.add(rec.row)
            self.builder.state()
        if self.chunk_size == 1 or self._last_batch_meta is None:
            return
        shapes, active = self._last_batch_meta
        ts = self.tail_size
        sub = {
            k: np.zeros((ts,) + shape[1:], dtype) for k, (shape, dtype) in shapes.items()
        }
        sub["valid"] = np.zeros(ts, np.bool_)
        sub.setdefault("step_offset", np.zeros(ts, np.int32))
        inv = self._full_inv()
        state = self.builder.state()
        strict = self.passes.get(
            self.profile, self.builder.schema, self.builder.res_col, active, 1,
            carry_dom=True,
        )
        # All-invalid batch: commits nothing; discard the (identical) state.
        ph = self._dom_placeholder()
        strict(state, sub, inv, np.uint32(0), ph[0], ph[1], np.bool_(False))
        # Uniform-batch broadcast program (_expand_uniform): template
        # workloads' first uniform batch would otherwise pay this XLA
        # compile mid-window (warmup batches with per-pod labels never
        # take the uniform path).
        kfull = next(iter(shapes.values()))[0][0]
        small = {
            k: np.zeros((1,) + shape[1:], dtype)
            for k, (shape, dtype) in shapes.items()
            if k not in ("valid", "nominated_row", "pin_row")
        }
        _expand_uniform(
            small, np.zeros(kfull, np.bool_), np.full(kfull, -1, np.int32),
            kfull,
        )

    # -- controller clock / the failure-response loop (ISSUE 9) --------------

    def _note_lifecycle_transition(self, target: str) -> None:
        self._lifecycle_transitions.inc(to=target)

    def _note_pod_gc(self, reason: str) -> None:
        self._pod_gc_counter.inc(reason=reason)

    def _now(self) -> float:
        """The controllers' shared clock: an explicit override wins
        (tests); an ARMED node-lifecycle controller supplies its logical
        clock (the Lease high-water mark — liveness, taint grace, GC
        horizons and eviction deadlines all become a pure function of the
        fed operation stream, which is what makes the chaos harness's
        bit-identical-reschedule oracle and the soak's same-seed
        determinism hold); otherwise wall monotonic (the pre-lifecycle
        behavior every existing caller sees)."""
        if self.clock is not None:
            return self.clock()
        if self.node_lifecycle.armed:
            return self.node_lifecycle.now()
        return time.monotonic()

    def renew_node_lease(self, lease: t.Lease) -> None:
        """Lease informer (coordination.k8s.io): one node-heartbeat
        renewal.  Feeds the node-lifecycle controller's staleness clock;
        armed, a renewal also drives the transition/eviction/GC tick."""
        self.node_lifecycle.renew(lease.node_name, lease.renew_time)

    def remove_node_lease(self, node_name: str) -> None:
        """Lease DELETED (or absent from a relist): the node drops out of
        heartbeat tracking — unleased nodes are lifecycle-exempt, the
        documented pre-ISSUE-9 behavior.  The Lease Reflector's
        LIST-as-replace delivers this (informers.KIND_HANDLERS), so a
        takeover that relists Leases converges on exactly the host-truth
        tracked set."""
        self.node_lifecycle.forget_node(node_name)

    def write_node_taints(
        self, name: str, taints: tuple, reason: str = ""
    ) -> bool:
        """Write a node's full taint set through the journaled update
        path (the node-lifecycle controller's API PATCH analog).  The
        decision is write-ahead journaled BEFORE it applies, so a crash
        mid-transition replays it deterministically; an identical taint
        set is a no-op and journals nothing.  Returns whether a write
        happened."""
        rec = self.cache.nodes.get(name)
        if rec is None:
            return False
        taints = tuple(taints)
        if rec.node.spec.taints == taints:
            return False
        from .api import serialize

        self._journal_append(
            "taint",
            node=name,
            taints=[serialize.to_dict(taint) for taint in taints],
            reason=reason,
            # The logical time of the write: replay advances the
            # lifecycle clock here, so a recovered process re-arms
            # eviction deadlines against the incident's clock instead of
            # a rewound zero (a feed whose clock kept running would
            # otherwise fire every restored grace instantly).
            ts=self._now(),
        )
        self._apply_node_taints(name, taints)
        return True

    def _apply_node_taints(self, name: str, taints: tuple) -> None:
        """Apply a (journaled) taint set: route through update_node so
        the precise NODE_TAINT requeue event fires and the NoExecute
        eviction re-judges the node's pods — exactly what a wire-fed
        taint update would do.  Also the journal-replay apply site."""
        rec = self.cache.nodes.get(name)
        if rec is None:
            return
        import copy

        node = copy.deepcopy(rec.node)
        node.spec.taints = tuple(taints)
        self.update_node(node)
        if self.taints_changed_hook is not None:
            # The speculative frontend's decision cache reads taints as
            # global feasibility: invalidate like a wire-fed taint change.
            self.taints_changed_hook(name)

    def evict_pod(
        self, uid: str, reason: str = "eviction", pod: t.Pod | None = None
    ) -> bool:
        """Journaled evict-with-requeue: the binding is dropped and the
        pod re-enters the queue UNBOUND, to reschedule on a surviving
        node — the eviction half of upstream's sequence fused with the
        workload controller's recreate half (this repo has none).  The
        ``evict`` record is write-ahead journaled so a crash between the
        eviction and the re-bind replays the requeue instead of losing
        the pod.  ``pod`` supplies the object when the uid is not cached
        (a recovered orphan binding whose node never relisted)."""
        pr = self.cache.pods.get(uid)
        source = pr.pod if pr is not None else pod
        if source is None:
            return False
        import copy

        from .api import serialize

        requeued = copy.deepcopy(source)
        requeued.spec.node_name = ""
        requeued.status.nominated_node_name = ""
        requeued.__dict__.pop("_uid", None)
        self._journal_append(
            "evict",
            uid=uid,
            pod=serialize.to_dict(requeued),
            reason=reason,
            ts=self._now(),
        )
        self._apply_eviction(uid, requeued, reason=reason)
        return True

    def _apply_eviction(
        self, uid: str, requeued: t.Pod, reason: str = "eviction"
    ) -> None:
        """Apply a (journaled) eviction: unwind the binding's state, then
        requeue the unbound copy.  Also the journal-replay apply site —
        replaying an evict for a pod the snapshot never bound still
        requeues it (the delete half no-ops)."""
        self._unwind_pod(uid, notify=False)
        self._evicted_uids.add(uid)
        self.recorder.event(
            uid,
            NORMAL,
            "Evicted",
            f"Evicted {uid} ({reason}); requeued for rescheduling",
            **self._trace_extra(),
        )
        if self.eviction_requeue_hook is not None:
            # Fleet owner: the router requeues (and may rebind the pod on
            # a DIFFERENT shard); journal replay routes here too, so a
            # takeover surfaces crash-interrupted evictions to the router
            # instead of stranding them in a queue nothing drains.
            self.eviction_requeue_hook(uid, requeued, reason)
        else:
            self.add_pod(requeued)

    # -- cluster events (the informer surface, eventhandlers.go:341) ---------

    def add_node(self, node: t.Node) -> None:
        if self.shard_guard is not None and not self.shard_guard(node.name):
            # Not this shard's node (fleet partitioning): the shard map,
            # not the feed, decides ownership.
            self.shard_rejected_nodes += 1
            return
        self.cache.add_node(node)
        # Replay CSINode/ResourceSlices that arrived before their Node
        # (informer races).
        csinode = self.builder.volumes.csinodes.get(node.name)
        if csinode is not None:
            self.builder.set_csinode_limits(self.cache.row_of(node.name), csinode)
        for (nname, cls) in self.builder.dra.slices:
            if nname == node.name:
                self.builder.set_dra_cap(self.cache.row_of(node.name), nname, cls)
        cat = self.builder.dra
        for uid, charges in list(cat.pending_external.items()):
            if charges and charges[0][0] == node.name:
                del cat.pending_external[uid]
                self.builder.apply_external_claim(
                    self.cache.row_of(node.name), uid,
                    [(sig, cnt) for _n, sig, cnt in charges], +1,
                )
                cat.row_charged[uid] = charges
        # Replay parked pool-overlap corrections whose base charges just
        # replayed (external claims of this node).
        for uid in list(cat.pending_corr):
            claim = cat.claims.get(uid)
            if (
                claim is not None
                and claim.allocated_node == node.name
                and uid in cat.row_charged
            ):
                corr = cat.pending_corr.pop(uid)
                cat.corrections[uid] = corr
                self.builder.apply_dra_correction(
                    self.cache.row_of(node.name), corr, +1
                )
        # Lifecycle state rides the node's taints (recovery replay and
        # wire-fed taints both land here); heartbeats ride Leases.
        self.node_lifecycle.observe_node(node)
        self.queue.on_event(
            Event.NODE_ADD, self._free_ctx({self.cache.row_of(node.name)})
        )

    def update_node(self, node: t.Node) -> None:
        """Diff the node against its cached record to emit the precise event
        kinds (the reference computes ActionType the same way,
        eventhandlers.go:341 nodeSchedulingPropertiesChange) — so a pod
        rejected only by TaintToleration wakes on the taint removal, not on
        every capacity change (VERDICT r1 weak-5)."""
        old = self.cache.nodes.get(node.name)
        if old is None:  # unknown node: an informer add delivered as update
            self.add_node(node)
            return
        old_node = old.node
        self.cache.update_node(node)
        ev = Event(0)
        if old_node.spec.taints != node.spec.taints:
            ev |= Event.NODE_TAINT
            # NoExecute eviction judges the node's pods on a taint change
            # (tainteviction handleNodeUpdate); the lifecycle controller
            # adopts whatever state the new taint set encodes.
            self.node_lifecycle.observe_node(node)
            self.taint_eviction.handle_node(node)
        if old_node.metadata.labels != node.metadata.labels:
            ev |= Event.NODE_LABEL
        if (
            old_node.spec.unschedulable != node.spec.unschedulable
            or old_node.status.allocatable != node.status.allocatable
            or old_node.status.images != node.status.images
        ):
            ev |= Event.NODE_UPDATE
        if ev:
            # The free-capacity payload lets the fit hint skip pods this
            # node still can't seat; taint/label-only updates carry it too
            # (only fit consults it, and its mask gates on NODE_UPDATE).
            self.queue.on_event(ev, self._free_ctx({old.row}))

    def remove_node(self, name: str) -> None:
        # Externally-charged claims on the vanishing node: the row is
        # cleared wholesale, so re-park their charges as pending (a
        # returning node replays them, like slices/CSINode).
        cat = self.builder.dra
        for uid, charges in list(cat.row_charged.items()):
            if charges and charges[0][0] == name:
                del cat.row_charged[uid]
                cat.pending_external[uid] = charges
        # Applied pool-overlap corrections died with the row too: park them
        # for replay alongside the base charges.
        for uid in list(cat.corrections):
            claim = cat.claims.get(uid)
            if claim is not None and claim.allocated_node == name:
                cat.pending_corr[uid] = cat.corrections.pop(uid)
        # Bound gang members vanish with the node; their quorum credit must
        # go with them (same invariant as delete_pod).
        rec = self.cache.nodes.get(name)
        if rec is not None:
            for uid in rec.pods:
                pr = self.cache.pods.get(uid)
                if pr is not None and pr.bound and pr.pod.spec.pod_group:
                    self._debit_gang(pr.pod.spec.pod_group)
        self.cache.remove_node(name)
        # Waiting gang members assumed on the removed node lost their
        # assumption (cache.remove_node vaporized their records): send them
        # back to the gang pool to retry with their gang.
        if rec is not None and self.permit_waiting:
            for qp, _n, _s, _f in self._drop_permit_waiters(set(rec.pods)):
                self.queue.requeue_gang_member(qp)
        # A deleted node leaves the lifecycle/GC tracking maps — its
        # pods vanished with it, so there is nothing left to collect.
        self.node_lifecycle.forget_node(name)
        self.pod_gc.forget_node(name)

    def add_pod(self, pod: t.Pod) -> None:
        """Unassigned pods enter the queue; assigned pods enter the cache
        (eventhandlers.go:126 addPodToSchedulingQueue / :203 addPodToCache)."""
        if not pod.spec.node_name and self._profile_for(pod) is None:
            return  # another scheduler's pod (responsibleForPod)
        if pod.spec.node_name:
            if pod.uid in self.cache.pods:
                # Upsert of a known bound pod (watch re-delivery): route
                # through the diffing update path — re-running add would
                # double-apply the resource delta and gang credit (ADVICE r2).
                self.update_pod(pod)
                return
            # A pod we knew as PENDING arriving bound (another scheduler —
            # or this host's degraded mode — bound it; the replay after a
            # resync re-ships it with its node) must leave the queue: a
            # later drain re-scheduling an already-bound pod would
            # double-apply its resource delta.
            self.queue.delete(pod.uid)
            self.cache.add_pod(pod)
            # Informer-delivered bound gang members count toward quorum —
            # delete_pod debits symmetrically.
            if pod.spec.pod_group:
                self.gang_bound[pod.spec.pod_group] = (
                    self.gang_bound.get(pod.spec.pod_group, 0) + 1
                )
            # A pod arriving bound to a NoExecute-tainted node is judged
            # immediately (tainteviction handlePodUpdate).
            self.taint_eviction.handle_pod_assigned(pod, pod.spec.node_name)
            self.queue.on_event(Event.POD_ADD)
        else:
            if pod.uid in self.cache.pods:
                # At-least-once re-delivery: a pod we already hold bound/
                # assumed arriving WITHOUT its node (a host's resync replay
                # recorded it before the binding response landed).  The
                # commit already happened — re-queueing would double-apply
                # its resource delta on the next drain.
                return
            self.queue.add(pod)

    def update_pod(self, pod: t.Pod) -> None:
        """Pod informer update (eventhandlers.go:136 updatePodInScheduling-
        Queue / :235 updatePodInCache), diffed so routine status-only
        updates are no-ops.  A cached (bound/assumed) pod's label or spec
        change rewrites its node's row delta — including the group/term
        domain tensors on device — and fires POD_UPDATE so e.g. a waiting
        anti-affinity pod wakes when the blocking pod's label changes."""
        pr = self.cache.pods.get(pod.uid)
        if pr is not None:
            if pod.spec.node_name and pod.spec.node_name != pr.node_name:
                # The upsert carries a DIFFERENT node: host truth rebound
                # the pod (a resync replay overriding a stale local
                # placement — the host store is the apiserver analog).
                # Relocate via remove+add (cache.go updatePod's
                # removePod+addPod contract); cache.update_pod alone only
                # rewrites the delta on the pod's current node.
                self.delete_pod(pod.uid, notify=False)
                self.add_pod(pod)
                return
            old = pr.pod
            if (
                old.metadata.labels == pod.metadata.labels
                and old.spec == pod.spec
            ):
                # Status/metadata-only: keep the fresher object in BOTH
                # mirrors (the node record feeds preemption's victim
                # ordering — a stale start_time there would change the
                # eviction order).
                pr.pod = pod
                node_rec = self.cache.nodes.get(pr.node_name)
                if node_rec is not None:
                    node_rec.pods[pod.uid] = pod
                    # start_time feeds victim ordering: the staged victim
                    # tensors for this node are stale.
                    self.cache._bump_pods_gen(node_rec)
                return
            self.cache.update_pod(pod)
            self.queue.on_event(
                Event.POD_UPDATE, self._free_ctx({self.cache.nodes[pr.node_name].row})
            )
            return
        if pod.spec.node_name:
            self.add_pod(pod)  # informer add delivered as update
            return
        if self._profile_for(pod) is None:
            return
        self.queue.update(pod)

    def _free_ctx(self, rows) -> EventCtx:
        """EventCtx summarizing free capacity on the given node rows AFTER
        the current host-state change, with nominated pods' claims
        subtracted (a freed node a preemptor nominated is not actually free
        to a waiting pod — the fit overlay would reject it anyway)."""
        host = self.builder.host
        nom_req: dict[int, np.ndarray] = {}
        nom_cnt: dict[int, int] = {}
        if self.nominator:
            for _uid, (node_name, delta, _p) in self.nominator.items():
                rec = self.cache.nodes.get(node_name)
                if rec is None or rec.row not in rows:
                    continue
                d = delta["req"]
                acc = nom_req.get(rec.row)
                if acc is None:
                    acc = np.zeros(host["alloc"].shape[1], np.int64)
                    nom_req[rec.row] = acc
                acc[: d.shape[0]] += d
                nom_cnt[rec.row] = nom_cnt.get(rec.row, 0) + 1
        max_free = None
        max_slots = 0
        for r in rows:
            free = host["alloc"][r] - host["req"][r]
            if r in nom_req:
                free = free - nom_req[r]
            slots = int(host["allowed_pods"][r] - host["num_pods"][r]) - nom_cnt.get(r, 0)
            max_free = free if max_free is None else np.maximum(max_free, free)
            max_slots = max(max_slots, slots)
        return EventCtx(max_free=max_free, max_slots=max_slots)

    def _drop_permit_waiters(self, uids) -> list:
        """Remove the given pods from the WaitOnPermit room (deleted pods,
        pods vaporized by node removal) so gang quorum credit and later
        finalize/expiry don't see ghosts.  Returns the dropped entries."""
        dropped: list = []
        for g in list(self.permit_waiting):
            entries = self.permit_waiting[g]
            kept = [e for e in entries if e[0].pod.uid not in uids]
            if len(kept) != len(entries):
                dropped.extend(e for e in entries if e[0].pod.uid in uids)
                if kept:
                    self.permit_waiting[g] = kept
                else:
                    self.permit_waiting.pop(g)
                    self.permit_wait_since.pop(g, None)
                    self.permit_wait_owner.pop(g, None)
        return dropped

    def delete_pod(self, uid: str, notify: bool = True) -> None:
        """``notify=False`` batches the requeue wake-up: preemption deletes
        victims in bulk and fires ONE POD_DELETE for the batch (a per-victim
        scan of the unschedulable pool is O(victims × pool))."""
        # Write-ahead: the deletion (a preemption victim's eviction, an
        # informer delete) is durable before any state unwinds — recovery
        # must not resurrect a deleted pod's binding.
        self._journal_append("delete", uid=uid)
        self._unwind_pod(uid, notify)

    def _mark_inflight(self, infos: list) -> None:
        """A prefetched or predispatched batch is now in flight for real:
        gang members leave the queue's pending-quorum tracking (the pop
        re-tracked them so a dissolved batch could reactivate cleanly)."""
        for qp in infos:
            if qp.pod.spec.pod_group:
                self.queue._untrack_gang_member(qp.pod)

    def _dissolve_inflight(self, infos: list, uid: str) -> None:
        """Hand an in-flight batch (prefetched or predispatched) back to
        the queue minus the departing pod: the dead member is dropped —
        the pop re-tracked it in _gang_members (gang_pending quorum
        credit), so untrack or the dead uid overcounts quorum forever
        and Permit waits on a ghost — and every survivor reactivates."""
        for qp in infos:
            if qp.pod.uid == uid:
                self.queue._info.pop(uid, None)
                self.queue._untrack_gang_member(qp.pod)
                continue
            self.queue.reactivate(qp)

    def _unwind_pod(self, uid: str, notify: bool = True) -> None:
        """The state unwind a pod's departure requires — shared by
        delete_pod (journaled ``delete``) and _apply_eviction (journaled
        ``evict``): prefetch dissolution, wait-room exits, nomination and
        eviction-timer cleanup, DRA release, cache/queue removal."""
        # A pod held in the prefetched batch would otherwise be scheduled
        # after its deletion: dissolve the prefetch back into the queue.
        if self._prefetched is not None and any(
            qp.pod.uid == uid for qp in self._prefetched[0]
        ):
            infos_p, _work = self._prefetched
            self._prefetched = None
            self._dissolve_inflight(infos_p, uid)
        # Same for a PREDISPATCHED batch (ISSUE 15): the early device
        # pass included the pod, and an unbound pod's deletion moves no
        # validity token (no cache entry → no dirty row), so pickup
        # would complete the pass and bind a deleted pod.  Discard the
        # pass outright — rewind the tie-break cycle counter and hand
        # the surviving members back to the queue, exactly like the
        # prefetch dissolution above.
        pd = self._predispatched
        if pd is not None and any(qp.pod.uid == uid for qp in pd.infos):
            self._predispatched = None
            self._cycle = pd.cycle0
            self._dissolve_inflight(pd.infos, uid)
        self._drop_permit_waiters({uid})
        # A deleted pod leaves the PreBind wait room: revert its Reserve
        # chain now (the cache entry goes below with the delete); scrub it
        # from gang-rollback records so a later group timeout cannot unwind
        # a pod that no longer exists.
        entry = self.prebind_waiting.pop(uid, None)
        if entry is not None:
            for rp, u in reversed(entry["undos"]):
                rp.unreserve(u, self)
        for e in self.prebind_waiting.values():
            e["mates"] = [m for m in e["mates"] if m[0].pod.uid != uid]
        for g in list(self.prebind_done_pending):
            self.prebind_done_pending[g] = [
                d for d in self.prebind_done_pending[g]
                if d["qp"].pod.uid != uid
            ]
        self.nominator.pop(uid, None)
        # A deleted pod's pending NoExecute eviction dies with it — a
        # re-created pod with the same namespace/name must not inherit
        # the old deadline (or its per-taint clocks).
        self.taint_eviction.cancel(uid)
        # DRA: drop the pod's claim reservations; claims nobody reserves
        # deallocate (the resourceclaim controller's cleanup).  Externally-
        # charged claims discharge their phantom row reservation here.
        by_claim: dict[tuple[str, str], list[tuple[str, int]]] = {}
        for cuid, node_name, sig, cnt in self.builder.dra.release_pod(uid):
            by_claim.setdefault((cuid, node_name), []).append((sig, cnt))
        for (cuid, node_name), charges in by_claim.items():
            nrec = self.cache.nodes.get(node_name)
            if nrec is not None:
                self.builder.apply_external_claim(nrec.row, cuid, charges, -1)
        self._drain_dra_corrections()
        rec = self.cache.pods.get(uid)
        if rec is not None:
            # A bound gang member leaving drops its gang below quorum for
            # future Permit checks (ADVICE r1: gang_bound never decremented).
            g = rec.pod.spec.pod_group
            if g and rec.bound:
                self._debit_gang(g)
            node_rec = self.cache.nodes.get(rec.node_name)
            self.cache.remove_pod(uid)
            if notify:
                ctx = (
                    self._free_ctx({node_rec.row}) if node_rec is not None else None
                )
                self.queue.on_event(Event.POD_DELETE, ctx)
        else:
            self.queue.delete(uid)

    def add_pdb(self, pdb: t.PodDisruptionBudget) -> None:
        """PodDisruptionBudget informer: preemption counts victims against
        these budgets (pickOneNodeForPreemption criterion 1).  Budgets
        carrying SPEC fields get their status recomputed from live pod
        state by the disruption controller (controllers.py)."""
        self.pdbs[pdb.name] = pdb
        self.disruption_controller.sync_one(pdb)

    def _debit_gang(self, group: str) -> None:
        left = self.gang_bound.get(group, 0) - 1
        if left > 0:
            self.gang_bound[group] = left
        else:
            self.gang_bound.pop(group, None)

    def add_pod_group(self, group: t.PodGroup) -> None:
        """Register a gang (coscheduling-style PodGroup: all-or-nothing
        below minMember).  Members park in the queue's gang pool until the
        gang can reach quorum, then release together into one batch."""
        self.pod_groups[group.name] = group
        self.queue.register_gang(group.name, group.min_member)
        self.queue.on_event(Event.POD_ADD)

    # -- volume objects (PV/PVC/StorageClass/CSINode informers) --------------

    def add_pv(self, pv: t.PersistentVolume) -> None:
        fulfilled = self.builder.volumes.add_pv(pv)
        if fulfilled:
            # The provisioner delivered a claimRef'd PV for an open intent:
            # complete the waiting PreBinds.
            self.notify_prebind({f"pvc:{u}" for u in fulfilled})
        self.queue.on_event(Event.PV_ADD)

    def add_pvc(self, pvc: t.PersistentVolumeClaim) -> None:
        self.builder.volumes.add_pvc(pvc)
        self.queue.on_event(Event.PVC_ADD)

    def add_storage_class(self, sc: t.StorageClass) -> None:
        self.builder.volumes.add_class(sc)
        self.queue.on_event(Event.PVC_ADD)

    def add_resource_claim(self, claim: t.ResourceClaim) -> None:
        """ResourceClaim informer (DRA).  Externally-allocated claims
        charge their node's device row immediately as phantom reservations
        (the claim assume-cache sees status.allocation; without this an
        informer-delivered allocated claim would leave the node's devices
        looking free).  Charges for nodes not yet cached park in
        pending_external — add_node replays them, like CSINode/slices."""
        cat = self.builder.dra
        uid = claim.uid
        deltas = cat.add_claim(claim)
        neg = [(n, sig, cnt) for n, sig, cnt, s in deltas if s < 0]
        pos = [(n, sig, cnt) for n, sig, cnt, s in deltas if s > 0]
        if neg:
            if cat.pending_external.pop(uid, None) is None:
                charged = cat.row_charged.pop(uid, None)
                if charged is not None:
                    rec = self.cache.nodes.get(charged[0][0])
                    if rec is not None:
                        self.builder.apply_external_claim(
                            rec.row, uid,
                            [(sig, cnt) for _n, sig, cnt in charged], -1,
                        )
        if pos:
            rec = self.cache.nodes.get(pos[0][0])  # one node per allocation
            if rec is None:
                cat.pending_external[uid] = pos
            else:
                self.builder.apply_external_claim(
                    rec.row, uid, [(sig, cnt) for _n, sig, cnt in pos], +1
                )
                cat.row_charged[uid] = pos
        self._drain_new_pools()
        self._drain_dra_corrections()
        self.queue.on_event(Event.CLAIM_ADD)

    def _drain_new_pools(self) -> None:
        """Backfill cap AND alloc columns for selector pools registered
        since the last drain (a claim introduced a new (class, selector)
        pool; every cached node publishing that class gets its
        matching-device count, and devices already owned under other pools
        charge the new one)."""
        cat = self.builder.dra
        if not cat.new_pools:
            return
        sigs, cat.new_pools = list(cat.new_pools), []
        for sig in sigs:
            cls, _reqs = cat.pools[sig]
            for (nname, c) in list(cat.slices):
                if c != cls:
                    continue
                rec = self.cache.nodes.get(nname)
                if rec is not None:
                    self.builder.set_pool_cap(rec.row, nname, sig)
                    alloc = cat.new_pool_alloc(nname, sig)
                    if alloc:
                        self.builder.set_pool_alloc(rec.row, sig, alloc)

    def _drain_dra_corrections(self) -> None:
        """Apply queued pool-overlap corrections (ClaimCatalog.corr_events)
        to node rows — allocation named devices that overlap pools beyond
        the claim's request pools (or a deallocation reversed them)."""
        cat = self.builder.dra
        if not cat.corr_events:
            return
        events, cat.corr_events = cat.corr_events, []
        for node_name, charges, sign in events:
            rec = self.cache.nodes.get(node_name)
            if rec is not None:
                self.builder.apply_dra_correction(rec.row, charges, sign)

    def add_resource_slice(self, s: t.ResourceSlice) -> None:
        """ResourceSlice informer (DRA): per-node published device counts."""
        self.builder.dra.add_slice(s)
        rec = self.cache.nodes.get(s.node_name)
        if rec is not None:
            self.builder.set_dra_cap(rec.row, s.node_name, s.device_class)
        self.queue.on_event(Event.CLAIM_ADD)

    def add_csinode(self, csinode: t.CSINode) -> None:
        self.builder.volumes.add_csinode(csinode)
        rec = self.cache.nodes.get(csinode.name)
        if rec is not None:
            self.builder.set_csinode_limits(rec.row, csinode)
        self.queue.on_event(Event.NODE_UPDATE)

    # -- object deletions (the generalized Reflector's DELETED surface) ------
    # A watch DELETED (or a LIST-replace repairing a missed delete) must
    # land for every kind the plugins consume, not just Pod/Node — these
    # are the removal halves of the add_* informer handlers above.

    def remove_pv(self, name: str) -> None:
        vols = self.builder.volumes
        pv = vols.pvs.pop(name, None)
        if pv is None:
            return
        vols.unbound.get(pv.storage_class, {}).pop(name, None)
        vols.epoch += 1

    def remove_pvc(self, uid: str) -> None:
        vols = self.builder.volumes
        pvc = vols.pvcs.pop(uid, None)
        if pvc is None:
            return
        # An open provisioning intent dies with its claim.
        vols.provisioning.pop(uid, None)
        vols.pvc_users.pop(uid, None)
        vols.epoch += 1

    def remove_storage_class(self, name: str) -> None:
        if self.builder.volumes.classes.pop(name, None) is not None:
            self.builder.volumes.epoch += 1

    def remove_csinode(self, name: str) -> None:
        vols = self.builder.volumes
        old = vols.csinodes.pop(name, None)
        if old is None:
            return
        vols.epoch += 1
        rec = self.cache.nodes.get(name)
        if rec is not None:
            # Restore the removed drivers to the no-CSINode default
            # (unlimited — the snapshot's 2^31-1 fill).
            self.builder.set_csinode_limits(
                rec.row,
                t.CSINode(
                    name, {d: 2**31 - 1 for d in old.driver_limits}
                ),
            )
        self.queue.on_event(Event.NODE_UPDATE)

    def remove_pdb(self, name: str) -> None:
        self.pdbs.pop(name, None)

    def remove_resource_claim(self, uid: str) -> None:
        """A deleted claim discharges whatever it held: route a
        deallocated copy through the diffing add path (which reverses
        external row charges and corrections), then drop the object."""
        cat = self.builder.dra
        claim = cat.claims.get(uid)
        if claim is None:
            return
        if claim.allocated_node:
            import dataclasses

            self.add_resource_claim(
                dataclasses.replace(
                    claim,
                    allocated_node="",
                    reserved_for=(),
                    allocated_devices=(),
                )
            )
        cat.claims.pop(uid, None)
        self.queue.on_event(Event.CLAIM_ADD)

    def remove_resource_slice(self, uid: str) -> None:
        """``uid`` is the Reflector's composite "node/device_class" key;
        the node's published capacity for that class drops to zero."""
        node_name, device_class = uid.split("/", 1)
        cat = self.builder.dra
        key = (node_name, device_class)
        if cat.slices.pop(key, None) is None:
            return
        cat.devices.pop(key, None)
        cat.device_owner.pop(key, None)
        cat.epoch += 1
        rec = self.cache.nodes.get(node_name)
        if rec is not None:
            # Caps recompute to 0 over the emptied device set; allocated
            # charges stay until their claims release (upstream drains a
            # slice before deleting it — a dangling allocation is the
            # claim's problem, not the slice informer's).
            self.builder.set_dra_cap(rec.row, node_name, device_class)
        self.queue.on_event(Event.CLAIM_ADD)

    # -- scheduling ------------------------------------------------------------

    def dump_state(self) -> dict:
        """Debugger dump (backend/cache/debugger CacheDumper.DumpAll): per-
        node pod counts, queue depths, gang/nominator state, and the
        host↔device mirror comparison.  The journal key appears only when
        durability is armed — the golden dump fixtures pin the journal-less
        shape."""
        if self.journal is not None:
            base = {"journal": self.journal.stats()}
        else:
            base = {}
        if self.node_lifecycle.armed:
            # Only when the failure-response loop is armed — the golden
            # dump fixtures pin the disarmed shape (like the journal key).
            base["node_lifecycle"] = self.node_lifecycle.stats()
            base["pod_gc"] = self.pod_gc.stats()
            rebound = sum(
                1
                for uid in self._evicted_uids
                if (pr := self.cache.pods.get(uid)) is not None and pr.bound
            )
            base["evictions"] = {
                "total": self.taint_eviction.evictions,
                "evicted_uids": len(self._evicted_uids),
                # Loop closure per pod: evicted uids bound again.
                "rebound": rebound,
            }
        return {
            **base,
            "nodes": {
                name: {
                    "row": rec.row,
                    "pods": sorted(rec.pods),
                    "zone": rec.zone,
                }
                for name, rec in self.cache.nodes.items()
            },
            "pods": {
                uid: {"node": pr.node_name, "assumed": pr.assumed, "bound": pr.bound}
                for uid, pr in self.cache.pods.items()
            },
            "queue": self.queue.dump(),
            "gang_bound": dict(self.gang_bound),
            "nominated": {u: n for u, (n, _d, _p) in self.nominator.items()},
            "permit_waiting": {
                g: [e[0].pod.uid for e in lst]
                for g, lst in self.permit_waiting.items()
            },
            "mirror_equal": self.builder.host_mirror_equal(),
            "metrics": self.metrics.registry.summary(),
            # Slow-cycle span trees (cross-boundary: server-side spans
            # carry the client's trace id) and the recent event ring.
            "slow_spans": list(self.slow_spans),
            "events": self.events.list(limit=50),
        }

    def check_consistency(self) -> None:
        """The cache comparer (debugger/comparer.go): verify the host
        staging arrays and the device mirror agree.  Called every
        ``consistency_check_every`` batches when configured.  Raises (not
        assert — the configured comparer must survive ``python -O``)."""
        if not self.builder.host_mirror_equal():
            raise RuntimeError(
                "host/device mirror divergence — dump_state() for details"
            )

    def rebuild_device_state(self) -> None:
        """Recovery: drop the device mirror and rebuild everything from host
        truth on the next pass (the builder's _dirty_all path).  The restart
        analog of the reference's informer resync (app/server.go:249–271) for
        a live process whose device state is suspect — host staging is the
        authoritative cache, the device tensors are a pure mirror of it."""
        self.builder.invalidate_device()

    def _record_preemption(self, qp: QueuedPodInfo, outcome, res, delta) -> None:
        """Shared PostFilter bookkeeping for a successful preemption
        (prepareCandidate, preemption.go:342): outcome fields, the
        nominator's claim on the freed node, and the immediate retry (the
        reference waits on the victims' graceful deletion; in-process
        deletion is synchronous)."""
        # Write-ahead: the victims' deletions were journaled by delete_pod;
        # this record preserves the NOMINATION so a restart routes the
        # still-pending preemptor back onto its freed node.
        self._journal_append(
            "preempt",
            uid=qp.pod.uid,
            node=res.node_name,
            priority=qp.pod.spec.priority,
            victims=[v.uid for v in res.victims],
        )
        self.metrics.preemptions += 1
        outcome.nominated_node = res.node_name
        outcome.victims = len(res.victims)
        outcome.victim_uids = tuple(v.uid for v in res.victims)
        outcome.victim_names = tuple(
            f"{v.namespace}/{v.name}" for v in res.victims
        )
        self._emit_preempted(qp.pod, res)
        self.nominator[qp.pod.uid] = (
            res.node_name, delta, qp.pod.spec.priority
        )
        qp.nom_pin_failed = False  # fresh nomination: the pin may try again
        self.queue.add(qp.pod)

    def _emit_preempted(self, preemptor: t.Pod, res) -> None:
        """Preempted events on the victims (preemption.go:362 emits on
        each victim pod; the reference's reason is "Preempted")."""
        for v in res.victims:
            self._note_tenant("preempted", v)
            self.recorder.event(
                v.uid, NORMAL, "Preempted",
                f"Preempted by {preemptor.uid} on node {res.node_name}",
                **self._trace_extra(),
            )

    def _fits_now(self, node_name: str, delta: dict) -> bool:
        """Host-truth capacity re-check before INLINE-committing a
        SPECULATIVE preemption result: its dry-run saw the post-scan state,
        so a strict-tail commit landing on the chosen node after dispatch
        could invalidate it (the victims are already evicted from host
        truth when this runs).  A failed check falls back to the
        nominate-and-retry path, which validates itself."""
        rec = self.cache.nodes.get(node_name)
        if rec is None:
            return False
        h = self.builder.host
        row = rec.row
        req = delta["req"]
        free = h["alloc"][row, : req.shape[0]] - h["req"][row, : req.shape[0]]
        if ((req > 0) & (req > free)).any():
            return False
        return h["num_pods"][row] < h["allowed_pods"][row]

    def _can_commit_inline(self, qp: QueuedPodInfo) -> bool:
        """Inline preemptor commit is limited to pods with no Permit group
        and no relevant Reserve plugin — those chains run on the
        nominate-and-retry path, which stays the general route."""
        g, _pl = self._permit_group(qp.pod)
        if g is not None:
            return False
        return not any(rp.relevant(qp.pod, self) for rp in self._reserve_for(qp.pod))

    def _commit_preempted(
        self, qp: QueuedPodInfo, outcome, res, delta, now: float
    ) -> None:
        """Commit a successful preemptor onto its freed node in THIS batch
        (perf mode; see inline_preempt_commit).  The victims were already
        deleted synchronously by preempt_batch, so this is exactly what the
        nominated retry would do next batch — minus a full device pass."""
        self._journal_bind(qp.pod, res.node_name)
        m = self.metrics
        m.preemptions += 1
        self._emit_preempted(qp.pod, res)
        self.cache.assume_pod(
            qp.pod, res.node_name, device_already=False, delta=delta
        )
        # A live nomination from an earlier nominate-path round is spent
        # now (the placed path pops it on assume; a bound pod would leak
        # the claim forever otherwise).
        self.nominator.pop(qp.pod.uid, None)
        qp.pod.spec.node_name = res.node_name
        qp.pod.status.nominated_node_name = ""
        self.cache.finish_binding(qp.pod.uid)
        self.queue.done(qp.pod.uid)
        # NoExecute judgment at bind, after the binding bookkeeping (an
        # immediate eviction deletes the cache entry).
        self.taint_eviction.handle_pod_assigned(qp.pod, res.node_name)
        outcome.node_name = res.node_name
        outcome.nominated_node = res.node_name
        outcome.victims = len(res.victims)
        outcome.victim_uids = tuple(v.uid for v in res.victims)
        outcome.victim_names = tuple(
            f"{v.namespace}/{v.name}" for v in res.victims
        )
        # The failure loop already counted this outcome unschedulable.
        m.unschedulable -= 1
        if m.scheduled == 0:
            m.first_scheduled_ts = now
        m.scheduled += 1
        m.last_scheduled_ts = now
        lat = now - qp.initial_attempt_timestamp
        m.e2e_latency_samples.append(lat)
        m.registry.scheduling_sli.observe(lat)
        self._note_bound(qp.pod, res.node_name)
        self.recorder.event(
            qp.pod.uid, NORMAL, "Scheduled",
            f"Successfully assigned {qp.pod.uid} to {res.node_name} "
            "(inline preemption commit)",
        )

    def _permit_group(self, pod: t.Pod):
        """The (group, owning PermitPlugin) a pod waits under, or
        (None, None) when no registered plugin claims it.  Plugins run only
        for profiles listing them at the permit point (the per-profile
        framework: a profile without the plugin simply lacks it)."""
        from .framework.config import PLUGIN_POINTS

        permitted = (self._profile_for(pod) or self.profile).permit
        for pl in self.permit_plugins:
            name = getattr(pl, "name", None)
            # Only config-addressable plugins are subject to the profile's
            # permit list; programmatically-registered ones (the generic
            # host-plugin surface) always run.
            if name in PLUGIN_POINTS and name not in permitted:
                continue
            g = pl.group_of(pod)
            if g is not None:
                return g, pl
        return None, None

    def _reserve_for(self, pod: t.Pod) -> list:
        """Reserve plugins enabled for the pod's profile (profile.reserve —
        the per-profile Reserve list, types.go Plugins.Reserve).  Plugins
        not addressable from config (no registered name) always run."""
        from .framework.config import PLUGIN_POINTS

        enabled = (self._profile_for(pod) or self.profile).reserve
        return [
            rp for rp in self.reserve_plugins
            if getattr(rp, "name", None) not in PLUGIN_POINTS
            or rp.name in enabled
        ]

    def expire_waiting_gangs(self, timeout_s: float | None = None) -> int:
        """WaitOnPermit timeout: forget and re-park members of groups whose
        missing peers never arrived (framework.go:1503 WaitOnPermit;
        coscheduling's PermitWaitingTimeSeconds).  Each group expires on
        its owning plugin's timeout; the plugin owns the requeue."""
        now = time.monotonic()
        default = self.permit_plugins[0] if self.permit_plugins else None
        expired = []
        for g, since in self.permit_wait_since.items():
            pl = self.permit_wait_owner.get(g, default)
            timeout = pl.timeout_s(self) if timeout_s is None else timeout_s
            if now - since > timeout:
                expired.append((g, pl))
        n = 0
        for g, pl in expired:
            self.permit_wait_since.pop(g, None)
            self.permit_wait_owner.pop(g, None)
            for qp, _node, _s, _f in self.permit_waiting.pop(g, ()):
                self.cache.forget_pod(qp.pod.uid)
                pl.on_rollback(qp, self)
                n += 1
        return n

    def notify_prebind(self, keys) -> list[ScheduleOutcome]:
        """Resolve PreBind wait keys (an informer event satisfied them —
        e.g. the provisioner's PV arrived).  Entries whose last key
        resolves complete their bind.  The outcomes are ALSO queued for the
        next schedule_batch return (outcome-consuming drivers observe
        wait-mode binds there); the returned list is informational."""
        done: list[ScheduleOutcome] = []
        if not self.prebind_waiting:
            return done
        keys = set(keys)
        now = time.monotonic()
        for uid in list(self.prebind_waiting):
            entry = self.prebind_waiting[uid]
            entry["keys"] -= keys
            if entry["keys"]:
                continue
            del self.prebind_waiting[uid]
            done.append(self._complete_prebind(entry, now))
        self._prebind_outcomes.extend(done)
        return done

    def _complete_prebind(self, entry: dict, now: float) -> ScheduleOutcome:
        """The bind tail a parked pod skipped (finish_binding + metrics)."""
        qp = entry["qp"]
        g = entry["g"]
        m = self.metrics
        self._journal_bind(qp.pod, entry["node"])
        qp.pod.spec.node_name = entry["node"]
        self.cache.finish_binding(qp.pod.uid)
        self.taint_eviction.handle_pod_assigned(qp.pod, entry["node"])
        if qp.pod.spec.pod_group:
            self.gang_bound[qp.pod.spec.pod_group] = (
                self.gang_bound.get(qp.pod.spec.pod_group, 0) + 1
            )
        if g:
            # Group-mates still waiting?  This bind stays revocable until
            # the whole group lands (all-or-nothing gang contract).
            if any(e["g"] == g for e in self.prebind_waiting.values()):
                self.prebind_done_pending.setdefault(g, []).append(
                    {"qp": qp, "undos": entry["undos"], "node": entry["node"]}
                )
            else:
                self.prebind_done_pending.pop(g, None)
        if m.scheduled == 0:
            m.first_scheduled_ts = now
        m.scheduled += 1
        m.last_scheduled_ts = now
        lat = now - qp.initial_attempt_timestamp
        m.e2e_latency_samples.append(lat)
        m.registry.scheduling_sli.observe(lat)
        self._note_bound(qp.pod, entry["node"])
        self.recorder.event(
            qp.pod.uid, NORMAL, "Scheduled",
            f"Successfully assigned {qp.pod.uid} to {entry['node']} "
            "(PreBind wait completed)",
        )
        return ScheduleOutcome(
            qp.pod, entry["node"], entry["score"], entry["feasn"]
        )

    def _unwind_reserved(self, uid: str, undos, was_bound: bool) -> None:
        """Revert a pod's Reserve chain + cache assume (the shared unwind of
        the PreBind-timeout paths).  ``was_bound`` keeps the throughput
        metrics honest: a finalized bind that reverts post-batch leaves
        ``scheduled``."""
        for rp, u in reversed(undos):
            rp.unreserve(u, self)
        if uid in self.cache.pods:
            self.cache.forget_pod(uid)
        m = self.metrics
        if was_bound:
            m.scheduled -= 1
        m.unschedulable += 1

    def expire_waiting_prebinds(self, timeout_s: float | None = None) -> int:
        """Time out PreBind waits (the bindTimeout unwind: Unreserve +
        requeue, volume_binding.go PreBind error path).  A gang member's
        timeout rolls its whole group back — the gang contract is
        all-or-nothing, so batch-mates bound immediately AND members whose
        own waits already completed (prebind_done_pending) revert like a
        lost PV race."""
        now = time.monotonic()
        limit = self.prebind_timeout_s if timeout_s is None else timeout_s
        n = 0
        for uid in [
            u for u, e in self.prebind_waiting.items()
            if now - e["since"] > limit
        ]:
            entry = self.prebind_waiting.pop(uid, None)
            if entry is None:
                continue  # a mate's rollback already consumed it
            n += 1
            self._unwind_reserved(uid, entry["undos"], was_bound=False)
            qp, g, gpl = entry["qp"], entry["g"], entry["gpl"]
            if g:
                gpl.on_rollback(qp, self)
                for qp2, _out2, undos2 in entry["mates"]:
                    self._unwind_reserved(qp2.pod.uid, undos2, was_bound=True)
                    qp2.pod.spec.node_name = None
                    self._debit_gang(g)
                    gpl.on_rollback(qp2, self)
                # Fellow parked members of the SAME group revert too.
                for uid2 in [
                    u for u, e in self.prebind_waiting.items() if e["g"] == g
                ]:
                    e2 = self.prebind_waiting.pop(uid2)
                    self._unwind_reserved(uid2, e2["undos"], was_bound=False)
                    gpl.on_rollback(e2["qp"], self)
                # Members whose own provisioning completed while the group
                # was still pending revert with it.
                for d in self.prebind_done_pending.pop(g, ()):
                    qp3 = d["qp"]
                    self._unwind_reserved(
                        qp3.pod.uid, d["undos"], was_bound=True
                    )
                    qp3.pod.spec.node_name = None
                    self._debit_gang(g)
                    gpl.on_rollback(qp3, self)
                self.queue.readmit_gang(g)
            else:
                # done() dropped the queue's info entry when the pod
                # parked — restore_backoff re-owns it.
                self.queue.restore_backoff(qp)
        return n

    def _profile_for(self, pod: t.Pod) -> Profile | None:
        """frameworkForPod (schedule_one.go:379): exact schedulerName match;
        an UNSET name (the API default "default-scheduler") falls to the
        default profile, any other unknown name is not our pod."""
        p = self.profiles.get(pod.spec.scheduler_name)
        if p is not None:
            return p
        if pod.spec.scheduler_name == "default-scheduler":
            return self.profile
        return None

    def _schedule_one_extender(self, qp: QueuedPodInfo) -> ScheduleOutcome:
        """One reference scheduling cycle with an extender chain: eval-only
        device pass → host extender filter/prioritize → host selectHost →
        assume → Reserve plugins → bind (findNodesThatPassExtenders,
        schedule_one.go:704; prioritizeNodes, :799).  Unschedulable pods
        run PostFilter preemption with extender ProcessPreemption veto
        (schedule_one.go:749); gang Permit semantics remain batch-path
        only (an extender profile schedules pod-at-a-time)."""
        from .extender import run_extender_chain

        profile = self._profile_for(qp.pod) or self.profile
        m = self.metrics
        m.schedule_attempts += 1
        m.batches += 1
        t0 = time.perf_counter()
        # Resolve the pod's own nomination to a row (like _inject_nomrows)
        # — only worth the lookup when any nominated claims exist.
        nomrow = self._resolve_nomrow(qp.pod) if self.nominator else -1
        batch, deltas, active, inv, feasible, total, t1 = self._run_eval_pass(
            qp.pod, profile, nomrow
        )
        m.featurize_time_s += t1 - t0
        m.device_time_s += time.perf_counter() - t1
        rows = np.nonzero(feasible)[0]
        names = [self.cache.node_name_at_row(int(r)) for r in rows]
        scores = {nm: int(total[r]) for nm, r in zip(names, rows)}
        now = time.monotonic()
        try:
            nodes, combined, _unres = run_extender_chain(
                self.extenders, qp.pod, names, scores
            )
        except Exception:
            # A non-ignorable extender failed: a cycle ERROR, not pod-level
            # unschedulability — retry on a timer (handleSchedulingFailure).
            self.queue.add_backoff(qp)
            m.unschedulable += 1
            return ScheduleOutcome(qp.pod, None, 0, len(names))
        if not nodes:
            m.unschedulable += 1
            # Extender rejections requeue on any event (schedule_one.go:528).
            plugins = {"Extender"} if names else set(profile.filters)
            self.recorder.event(
                qp.pod.uid, WARNING, "FailedScheduling",
                f"0/{self.cache.node_count()} nodes available: rejected by "
                + ", ".join(sorted(plugins)),
                plugins=sorted(plugins),
                **self._trace_extra(),
            )
            qp.delta = deltas[0]
            outcome = ScheduleOutcome(
                qp.pod, None, 0, len(names),
                diagnosis=Diagnosis(unschedulable_plugins=plugins),
            )
            # PostFilter (schedule_one.go:749): extender profiles run
            # preemption too; extenders with a preempt verb veto the chosen
            # candidate (ProcessPreemption, preemption.go:249).
            if (
                self.preemption is not None
                and "DefaultPreemption" in profile.post_filter
            ):
                rows = {
                    k: [np.asarray(v)[0]] for k, v in batch.items() if k != "valid"
                }
                preempt_exts = [
                    ex
                    for ex in self.extenders
                    if getattr(ex, "supports_preemption", False)
                    and ex.is_interested(qp.pod)
                ]

                def _ext_ok(pod, node_name, victims) -> bool:
                    want = {v.uid for v in victims}
                    for ex in preempt_exts:
                        try:
                            kept = ex.process_preemption(
                                pod, {node_name: victims}
                            )
                        except Exception:
                            if ex.ignorable:
                                continue
                            return False
                        # The engine picked a MINIMAL victim set: the node
                        # survives only if the extender keeps all of it.
                        if node_name not in kept or set(
                            kept[node_name]
                        ) != want:
                            return False
                    return True

                res = self.preemption.preempt_batch(
                    [qp.pod], rows, active, inv, profile=profile,
                    candidate_filter=_ext_ok if preempt_exts else None,
                )[0]
                # A zero-victim "candidate" here means the node was already
                # engine-feasible and only the EXTENDER rejected it — a
                # retry would hot-loop against the same rejection, so only
                # an eviction counts as progress on this path.
                if res is not None and res.victims:
                    self._record_preemption(qp, outcome, res, deltas[0])
                    if res.node_name in self.cache.nodes:
                        freed = {self.cache.nodes[res.node_name].row}
                        self.queue.on_event(
                            Event.POD_DELETE, self._free_ctx(freed)
                        )
                    return outcome
            self.queue.add_unschedulable(qp, plugins)
            return outcome
        best = max(enumerate(nodes), key=lambda p: (combined[p[1]], -p[0]))[1]
        self.cache.assume_pod(qp.pod, best, device_already=False, delta=deltas[0])

        def _fail_bind(undos):
            for rp2, u2 in reversed(undos):
                rp2.unreserve(u2, self)
            self.cache.forget_pod(qp.pod.uid)
            self.queue.add_backoff(qp)
            m.unschedulable += 1
            return ScheduleOutcome(qp.pod, None, 0, len(nodes))

        # Reserve through the same plugin chain the batch path runs.
        undos: list = []
        for rp in self._reserve_for(qp.pod):
            if not rp.relevant(qp.pod, self):
                continue
            u = rp.reserve(qp.pod, best, self)
            if u is None:
                return _fail_bind(undos)
            undos.append((rp, u))
        binder = next((ex for ex in self.extenders if getattr(ex, "bind_verb", "")), None)
        if binder is not None and not binder.bind(qp.pod, best):
            return _fail_bind(undos)
        self._journal_bind(qp.pod, best)
        qp.pod.spec.node_name = best
        self.cache.finish_binding(qp.pod.uid)
        self.taint_eviction.handle_pod_assigned(qp.pod, best)
        self.queue.done(qp.pod.uid)
        if m.scheduled == 0:
            m.first_scheduled_ts = now
        m.scheduled += 1
        m.last_scheduled_ts = now
        m.e2e_latency_samples.append(now - qp.initial_attempt_timestamp)
        self._note_bound(qp.pod, best)
        self.recorder.event(
            qp.pod.uid, NORMAL, "Scheduled",
            f"Successfully assigned {qp.pod.uid} to {best}",
        )
        if (
            self.consistency_check_every
            and m.batches % self.consistency_check_every == 0
        ):
            self.check_consistency()
        return ScheduleOutcome(qp.pod, best, combined[best], len(nodes))

    # -- fleet protocol surface (fleet/owner.py) ---------------------------
    #
    # A shard owner schedules pods it does not own end to end: the router
    # scatter-gathers per-shard PROPOSALS (eval-only per-node verdicts),
    # makes the global selectHost decision itself, and commits on the
    # winning shard — so an N-shard fleet reproduces the single
    # scheduler's choice whenever per-node scores are shard-independent
    # (trivially true for the filter-only golden profile; score ops that
    # normalize over the candidate set trade this for partition locality,
    # the Tesserae compromise documented in fleet/router.py).

    def _resolve_nomrow(self, pod: t.Pod) -> int:
        """The pod's own nominated node as a snapshot row (-1 when unset
        or unknown) — without it, a retrying preemptor's nominated claim
        in the fit overlay makes its freed node look full to itself."""
        nn = pod.status.nominated_node_name
        if nn:
            rec_n = self.cache.nodes.get(nn)
            if rec_n is not None:
                return rec_n.row
        return -1

    def _run_eval_pass(self, pod: t.Pod, profile, nomrow: int):
        """One-pod eval-only device pass (build_eval_pass, cached per
        (profile, schema, res_col, active)): featurize, run, fetch.
        Shared by the extender path (_schedule_one_extender) and the
        fleet propose path so the cache key and nomination handling
        cannot drift apart.  Returns (batch, deltas, active, inv,
        feasible, total, t_featurized) — the timestamp splits featurize
        from device time for the callers that meter them."""
        from .engine.pass_ import build_eval_pass

        batch, deltas, active = build_pod_batch(
            [pod], self.builder, profile, 1
        )
        inv = self._full_inv()
        t_feat = time.perf_counter()
        state = self.builder.state()
        key = (
            profile, self.builder.schema,
            tuple(sorted(self.builder.res_col.items())), active,
        )
        run = self._eval_passes.get(key)
        if run is None:
            run = build_eval_pass(
                profile, self.builder.schema, self.builder.res_col, active
            )
            self._eval_passes[key] = run
        pf = {k: np.asarray(v)[0] for k, v in batch.items() if k != "valid"}
        pf["nominated_row"] = np.int32(nomrow)
        feasible, total = device_fetch(run(state, pf, inv))
        self._dispatch_counter.inc(kind="eval")
        return batch, deltas, active, inv, feasible, total, t_feat

    def propose_pod(self, pod: t.Pod, span: Trace | None = None) -> dict:
        """Eval-only proposal: this shard's per-node verdicts for one pod
        — feasible node names (snapshot row order), their total scores,
        and the pod's resolved nomination when locally feasible.  No
        commit, no queue interaction; the same compiled eval pass the
        extender path uses (_run_eval_pass).  ``span`` (the fleet op
        span the router's trace context opened) gains Featurize /
        DevicePass children — the sidecar leg of the joined
        router→owner→sidecar tree — and the result carries the
        feat_s/dev_s split for the owner's flight record."""
        if not self.cache.nodes:
            return {"feasible": [], "scores": [], "nominated": None}
        profile = self._profile_for(pod) or self.profile
        nomrow = self._resolve_nomrow(pod)
        t0 = time.perf_counter()
        batch, _deltas, _active, _inv, feasible, total, t_feat = (
            self._run_eval_pass(pod, profile, nomrow)
        )
        t_end = time.perf_counter()
        if span is not None:
            # Post-hoc children over the measured boundaries: the eval
            # pass ran featurize then the device program; the sub-spans
            # carry those exact windows.
            feat = span.nest("Featurize")
            feat._t0, feat._t_end = t0, t_feat
            dev = span.nest("DevicePass")
            dev._t0, dev._t_end = t_feat, t_end
        rows = np.nonzero(feasible)[0]
        names = [self.cache.node_name_at_row(int(r)) for r in rows]
        nn = pod.status.nominated_node_name
        return {
            "feasible": names,
            "scores": [int(total[r]) for r in rows],
            "nominated": nn if nomrow >= 0 and bool(feasible[nomrow]) else None,
            # The pod's featurized request vector — the router's queue
            # needs it for the precise fit-wake hint (queue._fit_hint),
            # which the single scheduler gets from its own deltas.
            "req": [int(x) for x in np.asarray(batch["req"])[0]],
            # The featurize/device wall split, for the owner's per-op
            # flight record (phase attribution in the merged fleet
            # timeline; wall-derived — never hashed).
            "feat_s": round(t_feat - t0, 6),
            "dev_s": round(t_end - t_feat, 6),
        }

    # -- decision provenance (framework/provenance.py) ---------------------

    def arm_provenance(self, capacity: int = 4096) -> None:
        """Start recording decision capsules (explain-this-binding).
        Idempotent; OFF by default — unarmed runs pay one `is not None`
        test per bind and stay byte-identical."""
        if self.provenance is None:
            from .framework.provenance import ProvenanceRing

            self.provenance = ProvenanceRing(capacity)

    def _tie_step_of(self, i, ctx, batch) -> int:
        """The device tie-break step for batch slot ``i`` — cycle base
        plus the slot's step offset, the exact value select_and_commit
        hashed.  -1 on the pinned fast path (no per-step scan seed)."""
        soff = batch.get("step_offset")
        if soff is None:
            return -1
        return (
            int(ctx.get("cycle0", 0)) + int(np.asarray(soff)[i])
        ) & 0xFFFFFFFF

    def _provenance_capture(
        self, uid, node_name, row, i, ctx, batch, scores, feas, fails, profile
    ) -> None:
        """Record one live decision into the armed ring — called from the
        commit path only when arm_provenance() ran."""
        from .framework.provenance import DecisionCapsule

        tie_step = self._tie_step_of(i, ctx, batch)
        cap = DecisionCapsule(
            uid=uid,
            node=node_name,
            row=int(row),
            score=int(scores[i]),
            feasn=int(feas[i]),
            fail_mask=int(fails[i]),
            tie_step=tie_step,
            profile=profile.name,
            nomrow=int(ctx["nomrow"][i]),
            kind="pinned" if ctx.get("pinned") else "batch",
        )
        cap.preemption = self.provenance.take_pending_preemption(uid)
        self.provenance.record(cap)

    def _run_attribution_pass(self, pod: t.Pod, profile, nomrow: int):
        """One-pod attribution pass (build_attribution_pass, cached like
        _eval_passes): featurize, run, fetch.  Returns (active, ok_cols
        (F,N), feasible (N,), score_cols (S,N), total (N,))."""
        from .engine.pass_ import build_attribution_pass

        batch, _deltas, active = build_pod_batch(
            [pod], self.builder, profile, 1
        )
        inv = self._full_inv()
        state = self.builder.state()
        key = (
            profile, self.builder.schema,
            tuple(sorted(self.builder.res_col.items())), active,
        )
        run = self._attr_passes.get(key)
        if run is None:
            run = build_attribution_pass(
                profile, self.builder.schema, self.builder.res_col, active
            )
            self._attr_passes[key] = run
        pf = {k: np.asarray(v)[0] for k, v in batch.items() if k != "valid"}
        pf["nominated_row"] = np.int32(nomrow)
        ok_cols, feasible, score_cols, total = device_fetch(
            run(state, pf, inv)
        )
        self._dispatch_counter.inc(kind="eval")
        return active, ok_cols, feasible, score_cols, total

    def _provenance_sibling(self) -> "TPUScheduler":
        """A fresh, journal-less scheduler with this one's compiled-pass
        configuration — the reconstruction target for journal-mode
        explain.  The sibling never schedules; it only holds replayed
        state for the attribution pass."""
        return type(self)(
            profile=self.profile,
            batch_size=self.batch_size,
            chunk_size=self.chunk_size,
            profiles=[
                p
                for n, p in sorted(self.profiles.items())
                if n != self.profile.name
            ],
            feature_gates=self.feature_gates,
            enable_preemption=self.preemption is not None,
        )

    def explain_pod(
        self,
        uid: str,
        seq: int | None = None,
        mode: str | None = None,
        pod: t.Pod | None = None,
    ) -> dict:
        """The structured decision record for one pod: re-run its
        Filter+Score through the attribution pass against the CURRENT
        store, or (``mode="journal"``, or automatically when the armed
        ring recorded the bind's journal seq) against a journal-
        reconstructed store as of just before its bind record — per-op
        per-node filter verdicts with the rejecting plugin named, per-op
        normalized score columns, the selectHost tie-break trace, and
        the recorded live decision when provenance was armed.  Read
        path only: nothing commits, no queue state moves."""
        from .engine.pass_ import filter_op_names, score_op_names
        from .framework import provenance as prov

        cap = self.provenance.get(uid) if self.provenance is not None else None
        # Local pod wins over a caller-supplied one (fleet scatter passes
        # ``pod=`` so a shard that never saw the pod can still attribute
        # it against its partition of nodes).
        pr = self.cache.pods.get(uid)
        if pr is not None:
            pod = pr.pod
        else:
            qp = self.queue._info.get(uid)
            if qp is not None:
                pod = qp.pod
        if pod is None:
            return {"uid": uid, "error": "unknown pod (not bound, not queued)"}
        upto = None
        if seq is not None and seq > 0:
            upto = seq - 1
            # An explicit seq targets ONE decision; a ring capsule
            # stamped with a different seq describes another (newer)
            # bind of this uid and must not color this record.
            if cap is not None and cap.seq is not None and cap.seq != seq:
                cap = None
        elif (
            mode != "current"
            and cap is not None
            and cap.seq is not None
            and self.journal is not None
        ):
            upto = cap.seq - 1
        if mode == "journal" and upto is None:
            return {
                "uid": uid,
                "error": (
                    "journal mode needs a journaled, provenance-recorded "
                    "bind (or an explicit seq)"
                ),
            }
        target, used_mode, notes = self, "current", []
        wal_tie: int | None = None
        from .api import serialize

        if upto is not None and self.journal is not None:
            from . import journal as journal_mod

            sib = self._provenance_sibling()
            try:
                journal_mod.reconstruct_at(sib, self.journal, upto)
                target, used_mode = sib, "journal"
                # The bind record (seq upto+1) serialized the pod BEFORE
                # spec.node_name was stamped — that pre-bind pod is what
                # the device actually featurized — and carries the tie-
                # break step, so the selectHost trace is exact without
                # an armed ring.
                for rec_j in self.journal.replay(count=False)[1]:
                    if (
                        rec_j["q"] == upto + 1
                        and rec_j["t"] == "bind"
                        and rec_j["d"].get("uid") == uid
                    ):
                        pod = serialize.pod_from_data(rec_j["d"]["pod"])
                        wal_tie = rec_j["d"].get("tie")
                        break
            except ValueError as exc:
                # The snapshot barrier passed the bind seq: the WAL
                # prefix is gone — degrade to the current store, loudly.
                used_mode = "current"
                notes.append(f"reconstruction unavailable: {exc}")
        if used_mode == "current" and pr is not None:
            # Already placed: re-filtering the live pod would pin
            # NodeName to its bound node and double-count its own
            # committed usage.  Strip the binding on a copy; the
            # verdicts still include the pod's own resources.
            pod = serialize.pod_from_data(serialize.to_dict(pod))
            pod.spec.node_name = ""
            notes.append(
                "pod already placed: current-mode verdicts include its "
                "own committed usage (use journal mode for bit-identity)"
            )
        profile = self._profile_for(pod) or self.profile
        # A surviving capsule describes THIS decision (a mismatched-seq
        # one was dropped above), so its recorded nomination row wins —
        # the reconstructed store resolves nominations as of the replay
        # point, not as the device saw them at decision time.
        if used_mode == "journal" and cap is not None:
            nomrow = cap.nomrow
        else:
            nomrow = target._resolve_nomrow(pod)
        if not target.cache.nodes:
            return {"uid": uid, "mode": used_mode, "error": "no nodes"}
        active, ok_cols, feasible, score_cols, total = (
            target._run_attribution_pass(pod, profile, nomrow)
        )
        # Trim the schema's padding rows: real nodes only, row order
        # preserved (padding rows are never feasible, so the kth-tie
        # cumsum over the filtered arrays is unchanged).
        rows = [
            r
            for r in range(int(np.asarray(total).shape[0]))
            if target.cache.node_name_at_row(r) is not None
        ]
        names = [target.cache.node_name_at_row(r) for r in rows]
        idx = np.asarray(rows, np.int64)
        pos_of = {r: p for p, r in enumerate(rows)}
        ok_f = (
            np.asarray(ok_cols)[:, idx]
            if np.asarray(ok_cols).size
            else np.zeros((0, len(rows)), bool)
        )
        sc_f = (
            np.asarray(score_cols)[:, idx]
            if np.asarray(score_cols).size
            else np.zeros((0, len(rows)), np.int64)
        )
        rec = prov.assemble_record(
            uid=uid,
            mode=used_mode,
            profile=profile,
            active=active,
            node_names=names,
            filter_names=filter_op_names(profile, active),
            score_ops=score_op_names(profile, active),
            ok_cols=ok_f,
            feasible=np.asarray(feasible)[idx],
            score_cols=sc_f,
            total=np.asarray(total)[idx],
            nomrow=pos_of.get(int(nomrow), -1),
            capsule=cap,
            truncated=self._truncated,
            tie_step=wal_tie,
        )
        rec["bound_node"] = pr.node_name if pr is not None else None
        if self.provenance is None:
            notes.append(
                "provenance unarmed: no recorded live decision; "
                "tie step recovered from the bind WAL record"
                if wal_tie is not None
                else "provenance unarmed: no recorded live decision; "
                "tie-break trace degrades to kth=0"
            )
        if notes:
            rec["note"] = "; ".join(notes)
        return rec

    def reserve_proposed(self, pod: t.Pod, node_name: str, gang: str = "") -> bool:
        """Phase 1 of the fleet's two-phase commit: assume the pod onto
        the node and run the Reserve chain, journaling a ``gang_reserve``
        INTENT first — a crash between phases leaves the intent without a
        bind record, which recovery resolves as presumed-abort (the
        assume was never durable truth).  Returns False (fully unwound)
        when a Reserve plugin refuses."""
        self._journal_append(
            "gang_reserve", uid=pod.uid, node=node_name, gang=gang
        )
        delta = self.builder.pod_delta_vectors(pod)
        self.cache.assume_pod(pod, node_name, device_already=False, delta=delta)
        undos: list = []
        for rp in self._reserve_for(pod):
            if not rp.relevant(pod, self):
                continue
            u = rp.reserve(pod, node_name, self)
            if u is None:
                for rp2, u2 in reversed(undos):
                    rp2.unreserve(u2, self)
                self.cache.forget_pod(pod.uid)
                return False
            undos.append((rp, u))
        self._fleet_reserved[pod.uid] = {
            "pod": pod, "node": node_name, "undos": undos, "gang": gang,
        }
        return True

    def abort_reserved(self, uid: str) -> None:
        """2PC abort: unwind the Reserve chain and forget the assume.
        Journaled (``gang_abort``) so replay distinguishes a resolved
        intent from a crash-orphaned one — either way nothing durable
        was applied, so replay applies nothing."""
        entry = self._fleet_reserved.pop(uid, None)
        if entry is None:
            return
        self._journal_append("gang_abort", uid=uid, gang=entry["gang"])
        for rp, u in reversed(entry["undos"]):
            rp.unreserve(u, self)
        self.cache.forget_pod(uid)

    def commit_reserved(self, uid: str) -> ScheduleOutcome | None:
        """Phase 2: the binding becomes durable truth — journal the bind
        record, then finish the binding (WAL journal-before-apply)."""
        entry = self._fleet_reserved.pop(uid, None)
        if entry is None:
            return None
        pod, node_name = entry["pod"], entry["node"]
        self._journal_bind(pod, node_name)
        self.nominator.pop(pod.uid, None)
        pod.spec.node_name = node_name
        pod.status.nominated_node_name = ""
        self.cache.finish_binding(pod.uid)
        self.taint_eviction.handle_pod_assigned(pod, node_name)
        g = pod.spec.pod_group
        if g:
            self.gang_bound[g] = self.gang_bound.get(g, 0) + 1
        m = self.metrics
        now = time.monotonic()
        if m.scheduled == 0:
            m.first_scheduled_ts = now
        m.scheduled += 1
        m.last_scheduled_ts = now
        self._note_bound(pod, node_name)
        self.recorder.event(
            pod.uid, NORMAL, "Scheduled",
            f"Successfully assigned {pod.uid} to {node_name}",
        )
        # One fleet commit ≈ one reference scheduling cycle (the extender
        # path counts the same way): tick the snapshot cadence, or a
        # fleet owner's WAL would grow forever — the router never drives
        # schedule_batch, so the batch-loop call site can't fire here.
        self.metrics.batches += 1
        self.maybe_snapshot()
        return ScheduleOutcome(pod, node_name)

    def commit_proposed(self, pod: t.Pod, node_name: str) -> ScheduleOutcome | None:
        """One-phase commit for a routed singleton pod (no gang): reserve
        + immediate commit, the fleet analog of the extender path's bind
        tail."""
        self.metrics.schedule_attempts += 1
        if not self.reserve_proposed(pod, node_name):
            self.metrics.unschedulable += 1
            return None
        return self.commit_reserved(pod.uid)

    def preempt_propose(self, pod: t.Pod) -> dict | None:
        """Dry-run preemption for a foreign pod against THIS shard's
        nodes: the best local candidate (node + victim identities +
        the pickOneNode comparison key material) or None.  Nothing is
        applied — the router compares candidates across shards and calls
        execute_preemption on the winner only."""
        if self.preemption is None or not self.cache.nodes:
            return None
        profile = self._profile_for(pod) or self.profile
        batch, _deltas, active = build_pod_batch([pod], self.builder, profile, 1)
        rows = {k: [np.asarray(v)[0]] for k, v in batch.items() if k != "valid"}
        res = self.preemption.preempt_batch(
            [pod], rows, active, self._full_inv(), profile=profile,
            dry_run=True,
        )[0]
        if res is None:
            return None
        return {
            "node": res.node_name,
            "victims": [
                {
                    "uid": v.uid,
                    "name": f"{v.namespace}/{v.name}",
                    "priority": v.spec.priority,
                    "start_time": v.status.start_time,
                    "pod_group": v.spec.pod_group,
                }
                for v in res.victims
            ],
            # pickOneNodeForPreemption's lexicographic key over THIS
            # candidate (preemption.py eval_one, chunk==1 branch), so the
            # router's cross-shard arbitration reproduces the global
            # pick: per-shard minimization then a key compare across the
            # shard winners equals one global minimization, because every
            # criterion is a per-candidate property.
            "key": self._preempt_key(res.victims),
        }

    def _preempt_key(self, victims) -> list[int]:
        """[pdb violations, max victim priority, priority sum, victim
        count, negated-earliest-start] — ascending-lexicographic, exactly
        the device's chunk==1 narrowing order (latest earliest-start
        among the HIGHEST-priority victims wins, in microseconds)."""
        violations = 0
        for pdb in self.pdbs.values():
            cnt = sum(
                1
                for v in victims
                if v.namespace == pdb.namespace
                and t.label_selector_matches(pdb.selector, v.metadata.labels)
            )
            violations += max(0, cnt - pdb.disruptions_allowed)
        prios = [v.spec.priority for v in victims]
        max_prio = max(prios) if prios else -1
        starts = [
            v.status.start_time
            for v in victims
            if v.spec.priority == max_prio and v.status.start_time is not None
        ]
        if starts:
            start_key = int(-min(starts) * 1e6)
        else:
            start_key = -(2**61)
        return [violations, max_prio, sum(prios), len(victims), start_key]

    def execute_preemption(
        self, pod: t.Pod, node_name: str, victim_uids: list[str]
    ) -> dict:
        """Apply a chosen preemption on THIS shard (the victim owner's
        half of the cross-shard protocol): delete the victims (each
        deletion write-ahead journaled by delete_pod), debit PDB budgets,
        journal the preemptor's NOMINATION claim, and protect the freed
        node in the fit overlay so a same-round pod cannot steal it."""
        victims = []
        for uid in victim_uids:
            pr = self.cache.pods.get(uid)
            if pr is not None:
                victims.append(pr.pod)
        debits: dict[str, int] = {}
        if self.provenance is not None and victims:
            # Rationale BEFORE the deletes: _preempt_key reads the PDB
            # budgets the loop below debits.
            self.provenance.note_preemption(
                pod.uid,
                {
                    "node": node_name,
                    "victims": [v.uid for v in victims],
                    "key": self._preempt_key(victims),
                },
            )
        for vic in victims:
            self.delete_pod(vic.uid, notify=False)
            for name, n in self.debit_matching_pdbs(vic).items():
                debits[name] = debits.get(name, 0) + n
        self._journal_append(
            "preempt",
            uid=pod.uid,
            node=node_name,
            priority=pod.spec.priority,
            victims=[v.uid for v in victims],
        )
        self.metrics.preemptions += 1
        pod.status.nominated_node_name = node_name
        self.nominator[pod.uid] = (
            node_name,
            self.builder.pod_delta_vectors(pod),
            pod.spec.priority,
        )
        rec = self.cache.nodes.get(node_name)
        if rec is not None:
            self.queue.on_event(Event.POD_DELETE, self._free_ctx({rec.row}))
        for v in victims:
            self._note_tenant("preempted", v)
            self.recorder.event(
                v.uid, NORMAL, "Preempted",
                f"Preempted by {pod.uid} on node {node_name}",
            )
        return {
            "node": node_name,
            "victims": [v.uid for v in victims],
            # Evicted gang members: the router debits its FLEET-wide
            # quorum credit (the local _debit_gang ran inside delete_pod).
            "victim_groups": [
                v.spec.pod_group for v in victims if v.spec.pod_group
            ],
            # Raw victim tenant ids — the router feeds them through ITS
            # bounded labeler into the fleet-aggregated preempted counter
            # (the victim pods live only on this shard).
            "victim_tenants": [pod_tenant(v) or "" for v in victims],
            # PDB state is cluster-global but budgets are debited where
            # the victim died — the router broadcasts these to the other
            # shards (apply_pdb_debit) so every owner's pickOneNode
            # violation counts match the single scheduler's.
            "pdb_debits": [{"name": n, "n": c} for n, c in sorted(debits.items())],
            # Freed capacity on the victims' node, nominated claims
            # already subtracted — the router's POD_DELETE wake hint.
            "freed": self.fleet_free_ctx([node_name]),
        }

    def debit_matching_pdbs(self, pod: t.Pod) -> dict[str, int]:
        """Debit every budget matching ``pod`` by one disruption and
        return {pdb name: debit} — the single accounting shared by the
        preemption path (execute_preemption) and the fleet owner's
        eviction path (fleet/owner.py _on_eviction); the router
        broadcasts the returned debits to the other shards."""
        debits: dict[str, int] = {}
        for pdb in self.pdbs.values():
            if pod.namespace == pdb.namespace and t.label_selector_matches(
                pdb.selector, pod.metadata.labels
            ):
                pdb.disruptions_allowed -= 1
                debits[pdb.name] = debits.get(pdb.name, 0) + 1
        return debits

    def apply_pdb_debit(self, name: str, n: int) -> None:
        """Mirror a foreign shard's preemption debit on the local PDB copy
        (the router broadcasts execute_preemption's pdb_debits)."""
        pdb = self.pdbs.get(name)
        if pdb is not None:
            pdb.disruptions_allowed -= n

    def fleet_free_ctx(self, node_names: list[str]) -> dict | None:
        """JSON-able free-capacity summary of the named nodes (the
        EventCtx payload, queue.py) — the router rebuilds an EventCtx from
        it to drive ITS queue's precise fit-wake hints, since only the
        owning shard can see the node's host arrays."""
        rows = {
            self.cache.nodes[nm].row
            for nm in node_names
            if nm in self.cache.nodes
        }
        if not rows:
            return None
        ctx = self._free_ctx(rows)
        return {
            "max_free": [int(x) for x in ctx.max_free],
            "max_slots": int(ctx.max_slots),
        }

    def _dom_placeholder(self) -> tuple:
        """Schema-shaped zero (group_dom, et_dom) arrays for rebuild-path
        dispatches — the compiled pass takes the carry operands either way
        (ONE program; the cond picks rebuild when dom_valid is False)."""
        s = self.builder.schema
        key = (s.G, s.TK, s.DV, s.ET)
        ph = self._dom_zeros.get(key)
        if ph is None:
            if len(self._dom_zeros) > 4:
                self._dom_zeros.clear()
            ph = (
                jnp.zeros((s.G, s.TK, s.DV), jnp.float32),
                jnp.zeros((s.ET, s.DV), jnp.float32),
            )
            self._dom_zeros[key] = ph
        return ph

    def _full_inv(self) -> dict:
        """Batch invariants, plus — in truncated (parity) mode only — the
        scan-order inputs (zone-interleaved positions, rotating start); the
        full-evaluation pass never reads them, so skip the O(N) rebuild.
        Always carries the nominated-pod overlay (zeros when empty, so the
        compiled program never changes shape)."""
        inv = self.builder.batch_invariants()
        if self._truncated:
            inv["order_pos"] = self.cache.order_pos(self.builder.schema.N)
            inv["scan_start"] = np.uint32(self._next_start)
        s = self.builder.schema
        nom_req = np.zeros((s.N, s.R), np.int64)
        nom_cnt = np.zeros(s.N, np.int32)
        nom_prio = np.full(s.N, -(2**31), np.int32)
        for _uid, (node_name, delta, prio) in self.nominator.items():
            rec = self.cache.nodes.get(node_name)
            if rec is None:
                continue
            d = delta["req"]
            nom_req[rec.row, : d.shape[0]] += d
            nom_cnt[rec.row] += 1
            nom_prio[rec.row] = max(nom_prio[rec.row], prio)
        inv["nom_req"], inv["nom_cnt"], inv["nom_prio"] = nom_req, nom_cnt, nom_prio
        return inv

    def schedule_batch(self) -> list[ScheduleOutcome]:
        """Pop up to batch_size pods and schedule them in one device pass
        per profile (pods group by .spec.scheduler_name).  Binds completed
        between batches by informer-driven notify_prebind are prepended to
        the returned outcomes."""
        t0 = time.perf_counter()
        j = self.journal
        jbase = (
            (j.appends, j.fsyncs, j.append_latency.total, j.fsync_s)
            if j is not None
            else None
        )
        acc = self._flight_acc = {
            "phases": {}, "plugins": {}, "pods": 0,
            "scheduled": 0, "unschedulable": 0, "dispatches": [],
        }
        snap_s = 0.0
        try:
            out = self._schedule_batch_inner()
            if self._prebind_outcomes:
                out = self._prebind_outcomes + list(out)
                self._prebind_outcomes = []
            # Pipeline safety net: a staged commit group never outlives
            # its schedule_batch call (the outcomes below report applied,
            # durable binds; the snapshot must see them too).  Normally a
            # no-op — _batch_traced_inner drained already.
            self._drain_pending(overlapped=False)
            # Checkpoint at the quiescent point between batches (assume/
            # forget deltas settled); the cadence gate inside keeps this
            # free when journaling is off or the log hasn't grown.
            t_snap = time.perf_counter()
            self.maybe_snapshot()
            snap_s = time.perf_counter() - t_snap
        finally:
            self._flight_acc = None
            # One record per batch that actually dispatched (empty polls
            # and the per-pod extender path stay off the ring).
            if acc["pods"]:
                self._record_flight(acc, t0, snap_s, jbase)
        return out

    def _schedule_batch_inner(self) -> list[ScheduleOutcome]:
        if self.permit_wait_since:
            self.expire_waiting_gangs()
        if self.prebind_waiting:
            self.expire_waiting_prebinds()
        now = time.monotonic()
        if now >= self._next_assumed_sweep:
            # cache.go:42 starts cleanupAssumedPods on a 1s ticker; the batch
            # loop's analog is a time-gated sweep at the top of each batch.
            # Permit-room waiters are assumed deliberately (gang quorum) and
            # expire through expire_waiting_gangs, not the TTL.
            self._next_assumed_sweep = now + 1.0
            if self.node_lifecycle.armed:
                # One lifecycle tick chains the whole failure-response
                # clock (transitions → eviction deadlines → GC sweep) on
                # the logical Lease clock.
                self.node_lifecycle.tick()
            else:
                if self.taint_eviction.pending:
                    self.taint_eviction.tick(self._now())
                if self.pod_gc.armed:
                    self.pod_gc.sweep(self._now())
            waiting = {
                e[0].pod.uid
                for entries in self.permit_waiting.values()
                for e in entries
            }
            # PreBind-waiting pods are deliberately assumed too; they
            # expire through expire_waiting_prebinds, not the TTL.
            waiting |= set(self.prebind_waiting)
            for pod in self.cache.cleanup_assumed(self.assume_ttl_s, skip=waiting):
                # No informer to re-deliver the still-pending pod (the
                # reference relies on the apiserver watch for that) — requeue
                # directly so the pod gets another cycle.
                self.queue.add(pod)
        pre = self._prefetched
        self._prefetched = None
        pd = self._predispatched
        self._predispatched = None
        if pd is not None:
            # A device pass dispatched one cycle early (the pipeline's
            # double buffer) — validated or re-dispatched below.  infos
            # is the ORIGINAL pop order (the packer may have permuted
            # the dispatched ctx's copy).
            infos = pd.infos
            work = None
            self._mark_inflight(infos)
        elif pre is not None:
            infos, work = pre
            self._mark_inflight(infos)
        else:
            infos = self.queue.pop_batch(self.batch_size)
            work = None
        if not infos:
            return []
        # Cycle span (utiltrace "Scheduling" + LogIfLong,
        # schedule_one.go:412): step log emitted only past the threshold.
        # schedule_batch covers a whole BATCH, so the default threshold is
        # per-batch, not per-pod.  When a remote caller's trace context is
        # installed (the sidecar envelope's trace_id/parent_span_id) this
        # root span joins that trace, so a slow server-side cycle logs the
        # CLIENT's trace id — on EVERY path: single-profile, multi-profile,
        # and the extender chain all share the one root span contract.
        tp = self.trace_parent
        with Trace(
            "ScheduleBatch", self.trace_threshold_s,
            trace_id=tp[0] if tp else None,
            parent_span_id=tp[1] if tp else None,
            on_slow=self._note_slow_span,
            pods=len(infos),
        ) as tr:
            self.last_batch_span = tr
            if self.extenders:
                # Extender chain: per-pod eval-only path (see extender.py).
                out: list[ScheduleOutcome] = []
                for qp in infos:
                    out.append(self._schedule_one_extender(qp))
                tr.step("extender chain complete")
                return out
            if len(self.profiles) == 1:
                try:
                    return self._batch_traced(tr, infos, work, pd)
                except Exception as exc:
                    return self._recover_batch(infos, self.profile, exc)
            by_profile: dict[str, list[QueuedPodInfo]] = {}
            for qp in infos:
                prof = self._profile_for(qp.pod) or self.profile
                by_profile.setdefault(prof.name, []).append(qp)
            out = []
            for name, group in by_profile.items():
                with tr.nest("ProfileBatch", profile=name, pods=len(group)):
                    try:
                        out.extend(
                            self._schedule_infos(group, self.profiles[name])
                        )
                    except Exception as exc:
                        out.extend(
                            self._recover_batch(group, self.profiles[name], exc)
                        )
            return out

    def _batch_traced(
        self, tr: Trace, infos: list[QueuedPodInfo], work: dict | None,
        pd=None,
    ) -> list[ScheduleOutcome]:
        """One single-profile batch under the cycle span (exception-safe:
        Trace.__exit__ emits the step log for slow batches even when the
        batch raises — exactly the batches an operator needs timed)."""
        self._inflight_uids = frozenset(qp.pod.uid for qp in infos)
        try:
            return self._batch_traced_inner(tr, infos, work, pd)
        finally:
            self._inflight_uids = frozenset()

    def _batch_traced_inner(
        self, tr: Trace, infos: list[QueuedPodInfo], work: dict | None,
        pd=None,
    ) -> list[ScheduleOutcome]:
        if pd is not None:
            from .engine.pipeline import predispatch_valid

            if predispatch_valid(self, pd):
                # Nothing the early dispatch read has changed: complete
                # the in-flight pass as-is (its device time overlapped
                # the previous batch's drain and the inter-call gap).
                ctx = pd.ctx
                self._pipeline_predispatch_counter.inc(result="hit")
                self._pd_consec_invalid = 0
                tr.step("picked up predispatched device pass")
            else:
                # Host state moved under the early dispatch (informer
                # mutation, taint write, nomination change): discard the
                # pass, rewind the tie-break cycle counter, and dispatch
                # against current truth — exactly what the serial loop
                # would compute, so bindings stay bit-identical.
                self._cycle = pd.cycle0
                self._pipeline_predispatch_counter.inc(result="invalidated")
                # Each miss burned a device pass: back the gate off for
                # a few batches (capped so it always re-probes; a hit
                # resets instantly).
                self._pd_consec_invalid = min(
                    self._pd_consec_invalid + 4, 16
                )
                with tr.nest("DevicePassDispatch"):
                    ctx = self._dispatch_batch(infos, self.profile, None)
                tr.step("re-dispatched invalidated predispatch")
        else:
            with tr.nest("DevicePassDispatch") as _sp:
                ctx = self._dispatch_batch(infos, self.profile, work)
            tr.step("dispatched device pass")
        # Overlap victim packing + transfer with the in-flight device pass
        # when recent batches needed preemption (the dispatch is async; the
        # ~O(nodes) packing walk rides inside the pass's device time).
        prepacked = None
        if (
            self.preemption is not None
            and self.chunk_size > 1
            and self.preemption.expect_failures
            and self.preemption.worth_prepacking(qp.pod for qp in infos)
        ):
            prepacked = self.preemption.pack_victims(self.profile, ctx["active"])
            tr.step("prepacked victim tensors")
        ctx["prepacked"] = prepacked
        if prepacked is not None:
            # Chain the dry-run on the in-flight pass's device verdicts —
            # its compute overlaps the main fetch + strict tail, and its
            # results ride the first host round trip (ADVICE: the three
            # fetches of a failing batch collapse toward one).
            ctx["spec"] = self.preemption.dispatch_speculative(ctx, prepacked)
            if ctx["spec"] is not None:
                tr.step("dispatched speculative preemption")
        if self.post_dispatch_hook is not None:
            # Deserialization/admission work rides the in-flight pass
            # (and feeds the queue the prefetch below pops from).
            self.post_dispatch_hook()
            tr.step("ran post-dispatch hook")
        # Overlap featurize(k+1) with device(k) — the VERDICT r1 host
        # ceiling.  Gated off when the active ops read mutable host
        # catalogs (volume/DRA binds bump the feature version every
        # batch, which would drop the prefetch anyway).
        if self._prefetch_enabled and not ctx["active"] & {
            "VolumeBinding", "DynamicResources"
        }:
            nxt = self.queue.pop_batch(self.batch_size)
            if nxt:
                # Prefetched gang members still count as "coming" for
                # the WaitOnPermit quorum (gang_pending) until their
                # batch actually runs.
                for qp in nxt:
                    if qp.pod.spec.pod_group:
                        self.queue._track_gang_member(qp)
                self._prefetched = (
                    nxt, self._featurize_batch(nxt, self.profile)
                )
                tr.step("prefetched next batch")
        with tr.nest("CompleteBatch"):
            out = self._complete_batch(
                ctx, defer_drain=self._pipeline_active()
            )
        # Pipeline depth >= 2: dispatch batch k+1 BEFORE draining batch
        # k's staged commit group, so the group fsync and the apply loop
        # run while the device crunches the next pass.  With no next
        # batch (queue dry) the drain runs inline — still one fsync for
        # the whole group.
        predispatched = False
        ticket = self._pending_ticket
        if (
            ticket is not None
            and not ticket.drained
            and self._pipeline_active()
        ):
            predispatched = self._predispatch_next(tr)
        self._drain_pending(overlapped=predispatched)
        tr.step("completed (bind/permit/postfilter)")
        return out

    def _featurize_batch(self, infos: list[QueuedPodInfo], profile: Profile) -> dict:
        """Host featurization for one batch — separable from dispatch so the
        driver can overlap featurize(k+1) with device(k).  Featurization may
        grow vocab/schema (forcing a state rebuild at dispatch).  Always
        pads to the full batch size: one batch shape → one XLA program."""
        t0 = time.perf_counter()
        # ~10% of batches record per-plugin featurize durations
        # (plugin_execution_duration_seconds, metrics.go:256).
        sample = (
            {} if self.metrics.registry.sample_plugins("featurize") else None
        )
        batch, deltas, active = build_pod_batch(
            [qp.pod for qp in infos], self.builder, profile, self.batch_size,
            sample_into=sample,
        )
        if sample:
            for op_name, secs in sample.items():
                self._observe_plugin(op_name, "Featurize", secs)
        return {
            "batch": batch, "deltas": deltas, "active": active,
            "feat_s": time.perf_counter() - t0,
            "version": self.builder.feature_version(),
        }

    @staticmethod
    def _pin_name(pod: t.Pod) -> str | None:
        """See engine.features.pin_name (PreFilterResult node-set reduction,
        schedule_one.go:504).  spec.nodeName pods never reach the queue
        (they arrive bound)."""
        from .engine.features import pin_name

        return pin_name(pod)

    def _pin_rows(
        self, infos: list[QueuedPodInfo]
    ) -> tuple[np.ndarray, np.ndarray] | None:
        """(batch,) pinned row per pod plus a nominated-pin mask, or None
        unless EVERY pod resolves to one candidate row (-1 rows mean the
        pin names no live node — immediately infeasible).

        Two pin sources: the pod's own constraints (NodeName / the
        metadata.name matchFields shape — PreFilterResult node-set
        reduction, schedule_one.go:504), and a LIVE NOMINATION with its
        claim still held (evaluateNominatedNode, schedule_one.go:547: the
        nominated node is evaluated alone first).  A nominated pin that
        fails falls back to the full pass next batch (upstream falls back
        to the full node list in the same cycle), so the completion path
        requeues those instead of running PostFilter again."""
        rows = np.full(self.batch_size, -1, np.int32)
        nom = np.zeros(self.batch_size, np.bool_)
        for i, qp in enumerate(infos):
            name = self._pin_name(qp.pod)
            if name is None:
                nn = qp.pod.status.nominated_node_name
                if (
                    nn
                    and qp.pod.uid in self.nominator
                    and not getattr(qp, "nom_pin_failed", False)
                ):
                    rec = self.cache.nodes.get(nn)
                    if rec is not None:
                        rows[i] = rec.row
                        nom[i] = True
                        continue
                return None
            rec = self.cache.nodes.get(name)
            rows[i] = rec.row if rec is not None else -1
        return rows, nom

    def _inject_nomrows(self, work: dict, infos: list[QueuedPodInfo]) -> None:
        """Resolve nominated node names to ROW indices at DISPATCH time, not
        featurize time: a remove_node/add_node pair between prefetch and
        dispatch can reuse a freed row for a different node, so rows resolved
        at prefetch would point the nominated fast path (and the nominator
        self-exclusion) at the wrong node (ADVICE r2).  Nomination is pod
        STATUS — the featurize cache keys on (namespace, labels, spec) only —
        so injection after featurization is always required anyway."""
        nomrow = np.full(self.batch_size, -1, np.int32)
        if self.nominator:
            for i, qp in enumerate(infos):
                nn = qp.pod.status.nominated_node_name
                if nn:
                    rec = self.cache.nodes.get(nn)
                    if rec is not None:
                        nomrow[i] = rec.row
        work["batch"]["nominated_row"] = nomrow
        work["nomrow"] = nomrow

    def _dispatch_batch(
        self, infos: list[QueuedPodInfo], profile: Profile, work: dict | None = None
    ) -> dict:
        """Flush state and dispatch the device pass (async).  A prefetched
        ``work`` is dropped when anything featurization reads changed since
        (catalog binds, vocab growth from another profile's batch)."""
        t_f0 = time.perf_counter()  # flight tiling: featurize segment start
        if self.fault_injector is not None:
            # Injected engine faults fire HERE — before featurization and
            # any state mutation — so the recovery path retries against
            # clean state, exactly like an exception thrown by the real
            # featurize/dispatch code below would.
            self.fault_injector.on_engine_dispatch([qp.pod for qp in infos])
        if work is not None and work["version"] != self.builder.feature_version():
            work = None  # stale prefetch
        if work is None:
            work = self._featurize_batch(infos, profile)
        self._inject_nomrows(work, infos)
        t1 = time.perf_counter()
        # Batch invariants (interned term → topo slot) may grow TK/DV: build
        # them after featurization, before the state flush.
        inv = self._full_inv()
        # Carried-DomTables validity must be judged BEFORE state() clears
        # the dirty flags: the carry is sound only when nothing host-side
        # mutated since it was stashed (mutation_epoch) AND no dirty rows
        # are about to be flushed into the device state under it.
        dom_ok = (
            self._dom_carry is not None
            and self._dom_token
            == (self.builder.schema, self.builder.mutation_epoch)
            and not self.builder._dirty_all
            and not self.builder._dirty_rows
        )
        state = self.builder.state()
        # Pinned fast path (PreFilterResult node-set reduction): every pod
        # resolved to one candidate row and no active op needs the domain
        # tables ⇒ one vmapped own-row evaluation instead of the (K, N)
        # scan.  Decision-identical (see build_pinned_pass); truncated
        # (parity) mode keeps the full pass for its processed-node counters.
        from .engine.pass_ import PINNED_SAFE_OPS

        if not self._truncated and work["active"] <= PINNED_SAFE_OPS:
            pins = self._pin_rows(infos)
            if pins is not None:
                pin_rows, nom_pinned = pins
                work["batch"]["pin_row"] = pin_rows
                run = self.passes.get_pinned(
                    profile, self.builder.schema, self.builder.res_col,
                    work["active"],
                )
                batch_d, inv_d = jax.device_put((work["batch"], inv))
                new_state, result = run(state, batch_d, inv_d)
                self._cycle += len(infos)
                # The pinned pass commits on device without returning its
                # domain tables — the carry no longer matches device state.
                self._dom_carry = None
                self.metrics.pinned_batches += 1
                self._dispatch_counter.inc(kind="pinned")
                return dict(
                    work, infos=infos, profile=profile, inv=inv, inv_d=inv_d,
                    new_state=new_state, result=result, t1=t1, t_f0=t_f0,
                    schema=self.builder.schema, chunk=self.chunk_size,
                    pinned=True, nom_pinned=nom_pinned,
                )
        chunk = self.chunk_size
        cycle0 = self._cycle
        pack_s = 0.0
        if chunk > 1 and work["active"] & {
            "PodTopologySpread", "InterPodAffinity", "NodePorts"
        }:
            # Conflict-aware chunk packing (engine/packing.py): same-class
            # pods (the hard write→read signals the device defers on) land
            # in DIFFERENT chunk slices at the widest collision-free width,
            # with class-relative order preserved — the scan stays
            # sequential-equivalent and the deferral cascade never forms.
            # Replaces the old duplicate-count chunk halving, which shrank
            # device parallelism exactly when affinity workloads needed it
            # most (and re-walked every pod per halving iteration on this
            # hot path).
            t_pack0 = time.perf_counter()
            npods = len(infos)
            plan = pack_batch(work["batch"], npods, chunk)
            chunk = plan.width
            if plan.perm is not None:
                perm = plan.perm
                infos = [infos[j] for j in perm]
                work["deltas"] = [work["deltas"][j] for j in perm]
                full_perm = np.arange(self.batch_size, dtype=np.int64)
                full_perm[:npods] = perm
                work["batch"] = {
                    key2: np.asarray(arr)[full_perm]
                    for key2, arr in work["batch"].items()
                }
                # Tie-break seeds ride the pod: row r re-draws the seed of
                # its ORIGINAL dispatch position, so the packed scan picks
                # exactly what the sequential scan would have picked.
                soff = np.arange(self.batch_size, dtype=np.int32)
                soff[:npods] = perm
                work["batch"]["step_offset"] = soff
                self.metrics.packed_batches += 1
                self._flight_add("packed", 1)
            self.metrics.pack_collisions += plan.collisions
            self.metrics.pack_width = plan.width
            self.metrics.pack_classes = plan.n_classes
            pack_s = time.perf_counter() - t_pack0
        if "step_offset" not in work["batch"]:
            # Identity offsets: ONE compiled program shape whether or not
            # this batch was reordered.
            work["batch"]["step_offset"] = np.arange(
                self.batch_size, dtype=np.int32
            )
        run = self.passes.get(
            profile, self.builder.schema, self.builder.res_col, work["active"],
            chunk, carry_dom=True,
        )
        uniform = False
        if chunk > 1 and not self._truncated:
            # Template-batch flag for the pass's all-fail shortcut: every
            # pod featurization-identical (pass_.py uniform_all).  Pods
            # without a signature memo (pinned shapes) count as distinct.
            sigs = {
                getattr(qp.pod, "_featsig", None) or i
                for i, qp in enumerate(infos)
            }
            uniform = len(sigs) == 1
            work["batch"]["uniform_all"] = np.bool_(uniform)
        # ONE coalesced host→device transfer for the whole input pytree:
        # letting the jit boundary ship each feature/invariant array
        # individually costs a full tunnel round trip per array (~60ms each
        # when the device is busy — the dominant per-batch fixed cost on
        # axon), so ~20 arrays ride one batched_device_put instead.
        batch_np = work["batch"]
        if uniform:
            # A uniform batch's feature rows are identical by the same
            # signature equality the all-fail shortcut trusts: ship ONE
            # representative row and broadcast on device — ~0.5MB of
            # identical rows otherwise ride the tunnel every preemption/
            # daemonset batch.  valid (padding) and nominated_row (injected
            # post-featurize) genuinely vary per pod and ship in full.
            bkeys = tuple(sorted(
                kk for kk in batch_np
                if kk not in (
                    "valid", "nominated_row", "uniform_all", "step_offset"
                )
            ))
            small = {kk: np.ascontiguousarray(batch_np[kk][:1]) for kk in bkeys}
            small_d, valid_d, nom_d, soff_d, inv_d = jax.device_put(
                (small, batch_np["valid"], batch_np["nominated_row"],
                 batch_np["step_offset"], inv)
            )
            batch_d = _expand_uniform(
                small_d, valid_d, nom_d, batch_np["valid"].shape[0]
            )
            batch_d["uniform_all"] = batch_np["uniform_all"]
            batch_d["step_offset"] = soff_d
        else:
            batch_d, inv_d = jax.device_put((batch_np, inv))
        dom_in = self._dom_carry if dom_ok else self._dom_placeholder()
        new_state, result, dom_out = run(
            state, batch_d, inv_d, np.uint32(cycle0), dom_in[0], dom_in[1],
            np.bool_(dom_ok),
        )
        if dom_ok:
            self.metrics.dom_carry_hits += 1
        else:
            self.metrics.dom_carry_rebuilds += 1
        self._cycle += len(infos)
        self._dispatch_counter.inc(kind="batch")
        return dict(
            work, infos=infos, profile=profile, inv=inv, inv_d=inv_d,
            batch_d=batch_d, new_state=new_state, result=result, t1=t1,
            t_f0=t_f0, schema=self.builder.schema, chunk=chunk,
            cycle0=cycle0, pack_s=pack_s, dom_out=dom_out,
        )

    def _schedule_infos(
        self, infos: list[QueuedPodInfo], profile: Profile | None = None
    ) -> list[ScheduleOutcome]:
        profile = profile or self.profile
        return self._complete_batch(self._dispatch_batch(infos, profile))

    # -- poison-batch recovery ---------------------------------------------

    def _recover_batch(
        self, infos: list[QueuedPodInfo], profile: Profile, exc: Exception
    ) -> list[ScheduleOutcome]:
        """An engine exception failed a whole batch: isolate the poison
        pod(s) and complete the healthy remainder, so one bad pod can
        never wedge the cluster (handleSchedulingFailure's keep-making-
        progress contract, applied to a batch).

        An ``EngineFault`` that names its pods is split directly; an
        anonymous exception is bisected — halve, retry, recurse — which
        terminates in O(k log k) sub-batches and quarantines exactly the
        singletons that still raise alone.  The device mirror is rebuilt
        from host truth before every retry: a mid-batch failure leaves it
        suspect, and host staging is the authoritative cache."""
        # A deferred commit group staged before the exception holds real,
        # reserve-complete binds: drain it first (journal + apply) so the
        # cached-placement check below sees them as the committed pods
        # they are — not as retriable in-flight state.
        self._drain_pending(overlapped=False)
        self._engine_fault_counter.inc()
        self.flight.record_marker(
            "engine_fault",
            error=f"{type(exc).__name__}: {exc}",
            pods=len(infos),
            **self._trace_extra(),
        )
        outer = not self._recovering
        self._recovering = True
        try:
            self.rebuild_device_state()
            # A mid-COMMIT failure (_complete_batch phase 2+) leaves part
            # of the batch already assumed in the host cache;
            # re-dispatching those pods would double-apply their resource
            # deltas.  They are committed — report their cached placement
            # instead of retrying.
            out: list[ScheduleOutcome] = []
            uncommitted: list[QueuedPodInfo] = []
            for qp in infos:
                pr = self.cache.pods.get(qp.pod.uid)
                if pr is not None and pr.node_name:
                    out.append(ScheduleOutcome(qp.pod, pr.node_name))
                else:
                    uncommitted.append(qp)
            infos = uncommitted
            if not infos:
                return out
            if isinstance(exc, EngineFault) and exc.pod_uids:
                poison = [qp for qp in infos if qp.pod.uid in exc.pod_uids]
                healthy = [
                    qp for qp in infos if qp.pod.uid not in exc.pod_uids
                ]
                if poison:
                    out.extend(
                        self._quarantine_poison(qp, exc) for qp in poison
                    )
                    if healthy:
                        out.extend(self._schedule_safe(healthy, profile))
                    return out
            if len(infos) == 1:
                out.append(self._quarantine_poison(infos[0], exc))
                return out
            mid = len(infos) // 2
            for half in (infos[:mid], infos[mid:]):
                out.extend(self._schedule_safe(half, profile))
            return out
        finally:
            if outer:
                self._recovering = False
                # ONE dump per incident, written after the whole recovery
                # (bisect + quarantines) so the artifact carries every
                # marker — not one file per halving or per poison pod.
                self.flight.dump("engine_fault")

    def _schedule_safe(
        self, infos: list[QueuedPodInfo], profile: Profile
    ) -> list[ScheduleOutcome]:
        try:
            return self._schedule_infos(infos, profile)
        except Exception as exc:
            return self._recover_batch(infos, profile, exc)

    def _quarantine_poison(
        self, qp: QueuedPodInfo, exc: Exception
    ) -> ScheduleOutcome:
        """Park one poison pod in the queue's quarantine pool and narrate
        it: a FailedScheduling event carrying the exception (the operator's
        why-is-my-pod-stuck surface) plus the quarantine counters."""
        if self.journal is not None:
            from .api import serialize

            # Write-ahead: quarantine is a durable decision — a restart
            # must not feed a known-poison pod back into a batch.
            self.journal.append(
                "quarantine",
                {
                    "uid": qp.pod.uid,
                    "attempts": qp.attempts,
                    "pod": serialize.to_dict(qp.pod),
                },
            )
        self.queue.quarantine(qp)
        self._quarantine_counter.inc()
        self._unsched_reasons.inc(plugin="EngineFault")
        # Marker only: quarantine is always reached inside the batch-
        # recovery path, whose outermost exit writes the one dump for the
        # whole incident (quarantine markers included).
        self.flight.record_marker(
            "quarantine",
            pod=qp.pod.uid,
            error=f"{type(exc).__name__}: {exc}",
            **self._trace_extra(),
        )
        # The failed batch never reached _complete_batch's per-pod attempt
        # accounting: count the attempt here so the exported
        # scheduler_schedule_attempts_total cells keep summing to the
        # attempt total.
        self.metrics.schedule_attempts += 1
        self.metrics.unschedulable += 1
        self.recorder.event(
            qp.pod.uid, WARNING, "FailedScheduling",
            f"pod quarantined: engine dispatch raised "
            f"{type(exc).__name__}: {exc}",
            quarantined=True,
            **self._trace_extra(),
        )
        return ScheduleOutcome(
            qp.pod, None,
            diagnosis=Diagnosis(unschedulable_plugins={"EngineFault"}),
        )

    def _complete_batch(
        self, ctx: dict, defer_drain: bool = False
    ) -> list[ScheduleOutcome]:
        infos, profile = ctx["infos"], ctx["profile"]
        batch, deltas, active = ctx["batch"], ctx["deltas"], ctx["active"]
        nomrow, inv = ctx["nomrow"], ctx["inv"]
        new_state, result, t1 = ctx["new_state"], ctx["result"], ctx["t1"]
        # One host round trip for all result arrays (the tunnel to the device
        # has high per-transfer latency; never sync field-by-field) — the
        # speculative preemption results ride the same fetch.
        spec = ctx.get("spec")
        if spec is not None:
            (picks, scores, feas, fails, processed,
             sp_picks, sp_vmask) = device_fetch(
                (result.picks, result.scores, result.feasible_counts,
                 result.fail_masks, result.processed,
                 spec["out"].picks, spec["out"].vic_mask)
            )
            ctx["spec_res"] = (sp_picks, sp_vmask)
        else:
            picks, scores, feas, fails, processed = device_fetch(
                (result.picks, result.scores, result.feasible_counts,
                 result.fail_masks, result.processed)
            )
        if self._truncated:
            # Advance the rotating start by this batch's processedNodes sum
            # (modular sums compose across the scan's per-step updates).
            self._next_start = (self._next_start + int(processed.sum())) % max(
                self.cache.node_count(), 1
            )
        # Strict tail: chunk-deferred pods (pick == -2) re-run through the
        # sequential-equivalent chunk=1 pass against the committed state, in
        # original order, until none remain (a deferred pod never defers
        # again there).  The tail REORDERS commits after later chunks, so the
        # deferred pods are RE-FEATURIZED against the now-complete term/group
        # vocabularies — a pod's original features only matched the terms
        # interned before it, which is sound solely under batch-order commits.
        deferred = [i for i in range(len(infos)) if picks[i] == -2]
        if deferred and ctx.get("pinned"):
            # Pinned same-row overflow mates retry next batch (an earlier
            # mate's failure may have freed their room; the strict-tail
            # machinery keys on scan internals the pinned pass lacks).
            picks = picks.copy()
            for i in deferred:
                self.queue.reactivate(infos[i])
                picks[i] = -3  # handled: neither bind nor failure
            deferred = []
        # Prefetch featurization of batch k+1 may have GROWN the schema
        # while batch k was in flight; the compiled tail/preemption programs
        # for the old shapes cannot mix with the rebuilt state.  Rare (a
        # vocab crossed a power-of-two bucket): requeue the affected pods —
        # they reschedule next batch under the grown schema.
        schema_grew = ctx["schema"] != self.builder.schema
        tail_placed = False  # did the strict tail COMMIT anything?
        if deferred and schema_grew:
            for i in deferred:
                self.queue.reactivate(infos[i])
            picks = picks.copy()
            picks[deferred] = -3  # handled: neither bind nor failure
            deferred = []
        if deferred:
            picks, scores, feas, fails = (
                picks.copy(), scores.copy(), feas.copy(), fails.copy()
            )
            self.metrics.deferred += len(deferred)
            self._flight_add("deferred", len(deferred))

            def run_tail(idx_list: list[int], chunk_level: int, size: int) -> list[int]:
                """Re-featurize + re-run the given pods against the committed
                state; fills the result arrays and returns indices that
                deferred AGAIN (possible only when chunk_level > 1).

                Seeds: the tail re-run IS each pod's real decision, so it
                draws the pod's ORIGINAL step seed (batch seed base +
                per-pod step offset) — tie-breaks agree with the
                sequential chunk=1 scan, and the tail never advances
                ``_cycle`` (the next batch's seeds stay aligned with the
                parity oracle's).  The main pass's domain tables thread
                through as a valid carry: nothing host-side mutates
                between the scan and its tail."""
                nonlocal new_state
                run2 = self.passes.get(
                    profile, self.builder.schema, self.builder.res_col,
                    active, chunk_level, carry_dom=True,
                )
                soff_batch = np.asarray(batch["step_offset"], np.int32)
                still: list[int] = []
                for lo in range(0, len(idx_list), size):
                    idx = idx_list[lo : lo + size]
                    sub, sub_deltas, _ = build_pod_batch(
                        [infos[i].pod for i in idx], self.builder, profile,
                        size, force_active=active,
                    )
                    sub["nominated_row"] = np.full(size, -1, np.int32)
                    sub["nominated_row"][: len(idx)] = nomrow[idx]
                    for j, i in enumerate(idx):
                        deltas[i] = sub_deltas[j]
                    # Per-pod bucket dims (own terms, devices) are padded to
                    # the sub-batch max; pad up to the original batch's
                    # shapes so the compiled pass sees one shape set.
                    from .ops.common import FEATURE_FILLS

                    for key2, arr in sub.items():
                        tgt = batch[key2].shape[1:]
                        if arr.shape[1:] != tgt:
                            padw = [(0, 0)] + [
                                (0, tg - cur) for cur, tg in zip(arr.shape[1:], tgt)
                            ]
                            sub[key2] = np.pad(
                                arr, padw, constant_values=FEATURE_FILLS.get(key2, 0)
                            )
                    sub["step_offset"] = np.zeros(size, np.int32)
                    sub["step_offset"][: len(idx)] = soff_batch[idx]
                    sub_d = jax.device_put(sub)  # one coalesced transfer
                    dom_cur = ctx["dom_out"]
                    new_state, res, ctx["dom_out"] = run2(
                        new_state, sub_d, ctx["inv_d"], np.uint32(ctx["cycle0"]),
                        dom_cur[0], dom_cur[1], np.bool_(True),
                    )
                    p2, s2, f2, fl2 = device_fetch(
                        (res.picks, res.scores, res.feasible_counts, res.fail_masks)
                    )
                    self._dispatch_counter.inc(kind="tail")
                    picks[idx], scores[idx], feas[idx], fails[idx] = (
                        p2[: len(idx)], s2[: len(idx)], f2[: len(idx)], fl2[: len(idx)],
                    )
                    still.extend(i for j, i in enumerate(idx) if p2[j] == -2)
                return still

            # Round 1 — large bursts replay through the SAME chunked program
            # against the committed state: most deferrals are positional
            # (e.g. a freshly-added empty node attracting every chunk-mate,
            # the churn-workload magnet); once earlier commits are visible
            # they place cleanly in one pass instead of one scan step each.
            all_deferred = list(deferred)
            if ctx["chunk"] > 1 and len(deferred) > self.tail_size:
                deferred = run_tail(deferred, ctx["chunk"], self.batch_size)
            # Round 2 — strict sequential-equivalent finisher (chunk=1
            # never defers, so this always terminates).
            if deferred:
                run_tail(deferred, 1, self.tail_size)
            tail_placed = any(picks[i] >= 0 for i in all_deferred)
        t2 = time.perf_counter()
        self._last_batch_meta = (
            {
                k: (v.shape, np.asarray(v).dtype)
                for k, v in batch.items()
                if k != "uniform_all"  # scalar flag, not a feature row
            },
            active,
        )
        self.builder.absorb_device_state(new_state)
        # Carry the scan-maintained domain tables into the next batch —
        # valid only under the exact (schema, mutation_epoch) they were
        # stashed at; any host mutation in between forces a device-side
        # rebuild.  A batch whose prefetch grew the schema mid-flight
        # drops the carry (its arrays are shaped for the old buckets).
        if ctx.get("pinned"):
            pass  # carry already dropped at dispatch
        elif ctx["schema"] == self.builder.schema and "dom_out" in ctx:
            self._dom_carry = ctx["dom_out"]
            self._dom_token = (
                self.builder.schema, self.builder.mutation_epoch
            )
        else:
            self._dom_carry = None

        outcomes: list[ScheduleOutcome] = []
        now = time.monotonic()
        # The batch's staged commit group (engine/pipeline.CommitTicket):
        # binds that pass Permit + Reserve stage here and journal + apply
        # together under ONE group fsync — at the serial point below
        # (depth 1, or any batch with failures), or deferred under the
        # next batch's in-flight device pass (_batch_traced_inner).
        from .engine.pipeline import CommitTicket

        ticket = CommitTicket(now=now)
        if self.queue.admission is not None:
            # This batch's weighted-fair debits (pop order), captured by
            # the batch's OWN uids — at depth 2 the prefetch has already
            # popped batch k+1, whose intents must ride k+1's ticket.
            # Failed pods' debits stay in: an admission attempt costs
            # credit whether or not the bind lands.
            ticket.admission = self.queue.admission.take_intents(
                [qp.pod.uid for qp in infos]
            )
        self._pending_ticket = ticket
        m = self.metrics
        m.batches += 1
        m.featurize_time_s += ctx["feat_s"]
        m.device_time_s += t2 - t1
        m.registry.observe_point("Featurize", ctx["feat_s"])
        m.registry.observe_point("DevicePass", t2 - t1)
        m.registry.attempt_duration.observe(t2 - t1 + ctx["feat_s"])
        failed: list[tuple[int, QueuedPodInfo, ScheduleOutcome]] = []
        nom_pinned = ctx.get("nom_pinned")
        # Phase 1 — assume every pick (cache.go:361 AssumePod; the device
        # already committed the deltas in-scan).
        placed: list[tuple[int, QueuedPodInfo, str]] = []
        for i, qp in enumerate(infos):
            m.schedule_attempts += 1
            row = int(picks[i])
            if row < 0 and row != -3 and nom_pinned is not None and nom_pinned[i]:
                # The nominated node alone failed: fall back to the FULL
                # node list next batch (schedule_one.go:547 does so in the
                # same cycle) — NOT the failure path, whose PostFilter
                # would preempt again on top of a live nomination.
                qp.nom_pin_failed = True
                self.queue.reactivate(qp)
                continue
            if row >= 0:
                node_name = self.cache.node_name_at_row(row)
                assert node_name is not None, f"pick={row} maps to no node"
                self.cache.assume_pod(qp.pod, node_name, device_already=True, delta=deltas[i])
                # A placed pod's nomination is spent (nominator.go deletes
                # on assume).
                if self.nominator:
                    self.nominator.pop(qp.pod.uid, None)
                qp.pod.status.nominated_node_name = ""
                placed.append((i, qp, node_name))
                if self.journal is not None or self.provenance is not None:
                    self._tie_pending[qp.pod.uid] = self._tie_step_of(
                        i, ctx, batch
                    )
                if self.provenance is not None:
                    self._provenance_capture(
                        qp.pod.uid, node_name, row, i, ctx, batch,
                        scores, feas, fails, profile,
                    )
            elif row == -3:
                continue  # already requeued (schema grew mid-flight)
            else:
                failed.append((i, qp, None))

        # Phase 2 — Permit (RunPermitPlugins, runtime/framework.go:1443;
        # reference extension-point order: Permit precedes PreBind, so a
        # cancelled group never durably binds volumes).  Each registered
        # PermitPlugin judges the batch's placed pods and returns
        # group-level allow/wait/reject; the loop owns only the generic
        # mechanics (waiting room, rollback, timeouts).
        rollback: set[str] = set()
        wait: set[str] = set()
        admitted: set[str] = set()
        owner: dict[str, object] = {}
        if placed or self.permit_waiting:
            placed_view = [(qp, node) for _i, qp, node in placed]
            decisions = [
                (plugin, plugin.judge_batch(placed_view, self))
                for plugin in self.permit_plugins
            ]
            # Most-restrictive-wins across plugins (RunPermitPlugins stops
            # at the first reject; any wait holds the pod): reject > wait >
            # admit, with the group owned by its most restrictive decider.
            for plugin, dec in decisions:
                for g in dec.reject:
                    rollback.add(g)
                    owner[g] = plugin
            for plugin, dec in decisions:
                for g in dec.wait - rollback:
                    wait.add(g)
                    owner.setdefault(g, plugin)
            for plugin, dec in decisions:
                for g in dec.admit - rollback - wait:
                    admitted.add(g)
                    owner.setdefault(g, plugin)

        # Waiters of rejected groups roll back with their group; waiters of
        # admitted groups join this batch's finalize list.
        entries: list[tuple[QueuedPodInfo, str, int, int]] = [
            (qp, node, int(scores[i]), int(feas[i])) for i, qp, node in placed
        ]
        for g in rollback:
            self.permit_wait_since.pop(g, None)
            pl = owner.get(g) or self.permit_wait_owner.get(g)
            self.permit_wait_owner.pop(g, None)
            for qp, _node, _s, feasn in self.permit_waiting.pop(g, ()):
                self.cache.forget_pod(qp.pod.uid)
                outcomes.append(ScheduleOutcome(qp.pod, None, 0, feasn))
                pl.on_rollback(qp, self)
        for g in admitted:
            self.permit_wait_since.pop(g, None)
            self.permit_wait_owner.pop(g, None)
            entries.extend(self.permit_waiting.pop(g, ()))
        for g in wait:
            # One GangWaiting per group per batch (the coscheduling
            # plugin's Permit-wait narration); the ring aggregates repeats.
            self.recorder.event(
                f"podgroup/{g}", NORMAL, "GangWaiting",
                f"gang {g} waiting on Permit for quorum",
            )

        # Phase 3 — Reserve + PreBind + bind: each registered ReservePlugin
        # reserves host-side state on the chosen node (VolumeBinding PreBind
        # volume_binding.go:521, DRA claim allocation), unwinding in reverse
        # on failure (RunReservePluginsUnreserve).  A pod that lost a
        # same-batch race is forgotten and retried — the assume/forget
        # protocol (cache.go:404 ForgetPod).  If the loser belongs to a
        # permit group, the whole group rolls back with it — including
        # reverting peers' reservations — so a gang never lands partially
        # bound below minMember (ADVICE r1).
        finalized_by_group: dict[str, list] = {}
        race_rollback: set[str] = set()  # transient (PV race): retry on timer
        prebind_parked: set[str] = set()  # pods gone to the PreBind wait room
        prebind_s = 0.0
        # Per-plugin sampled Reserve durations: ONE gate per batch (the
        # reference samples per scheduling attempt, schedule_one.go:104).
        sample_rp = bool(entries) and m.registry.sample_plugins("reserve")
        for qp, node_name, score, feasn in entries:
            g, gpl = self._permit_group(qp.pod)
            if g in rollback:
                self.cache.forget_pod(qp.pod.uid)
                outcomes.append(ScheduleOutcome(qp.pod, None, 0, feasn))
                # Plugin rollback (not add_unschedulable): an ex-waiter's
                # queue._info entry was dropped by done() when it entered the
                # waiting room and must be restored with the original qp.
                gpl.on_rollback(qp, self)
                continue
            if g in wait:
                # WaitOnPermit: off-queue, still assumed, until quorum or
                # the owning plugin's timeout (expire_waiting_gangs).
                self.queue.done(qp.pod.uid)
                self.permit_waiting.setdefault(g, []).append(
                    (qp, node_name, score, feasn)
                )
                self.permit_wait_since.setdefault(g, now)
                self.permit_wait_owner[g] = owner.get(g, gpl)
                continue
            undos: list = []  # [(plugin, undo)] in reserve order
            reserve_failed = False
            relevant = [
                rp for rp in self._reserve_for(qp.pod) if rp.relevant(qp.pod, self)
            ]
            t_pb = time.perf_counter() if relevant else 0.0
            for rp in relevant:
                t_rp = time.perf_counter() if sample_rp else 0.0
                u = rp.reserve(qp.pod, node_name, self)
                if sample_rp:
                    self._observe_plugin(
                        getattr(rp, "name", type(rp).__name__), "Reserve",
                        time.perf_counter() - t_rp,
                    )
                if u is None:
                    for rp2, u2 in reversed(undos):
                        rp2.unreserve(u2, self)
                    reserve_failed = True
                    break
                undos.append((rp, u))
            if relevant:
                prebind_s += time.perf_counter() - t_pb
            if reserve_failed:
                # Reserve lost a same-batch race (PV or claim allocation).
                self.cache.forget_pod(qp.pod.uid)
                outcomes.append(ScheduleOutcome(qp.pod, None, 0, feasn))
                if g:
                    # The whole group retries together, with peers'
                    # reservations reverted.
                    rollback.add(g)
                    race_rollback.add(g)
                    gpl.on_rollback(qp, self)
                    # Same-batch mates are still STAGED (their journal
                    # records unwritten, gang credit uncounted): unstage
                    # — nothing on the log or in spec to unwind.
                    for qp2, out2, undos2 in finalized_by_group.pop(g, ()):
                        for rp2, u2 in reversed(undos2):
                            rp2.unreserve(u2, self)
                        ticket.unstage(qp2.pod.uid)
                        self.cache.forget_pod(qp2.pod.uid)
                        out2.node_name, out2.score = None, 0
                        gpl.on_rollback(qp2, self)
                    # Same-batch mates already parked in the PreBind wait
                    # room revert with the group too.
                    for uid2 in [
                        u for u, e in self.prebind_waiting.items()
                        if e["g"] == g
                    ]:
                        e = self.prebind_waiting.pop(uid2)
                        prebind_parked.discard(uid2)
                        for rp2, u2 in reversed(e["undos"]):
                            rp2.unreserve(u2, self)
                        self.cache.forget_pod(uid2)
                        outcomes.append(
                            ScheduleOutcome(e["qp"].pod, None, 0, e["feasn"])
                        )
                        gpl.on_rollback(e["qp"], self)
                else:
                    self.queue.add_backoff(qp)
                continue
            pending = set()
            for rp, u in undos:
                hook = getattr(rp, "prebind_pending", None)
                if hook is not None:
                    pending.update(hook(qp.pod, u, self))
            if pending:
                # PreBind wait (RunPreBindPlugins inside the detached
                # bindingCycle, volume_binding.go:521): the pod stays
                # ASSUMED off-queue until every key resolves
                # (notify_prebind) or the bind timeout unreserves it —
                # the batch itself never blocks.
                self.queue.done(qp.pod.uid)
                self.prebind_waiting[qp.pod.uid] = {
                    "qp": qp, "node": node_name, "score": score,
                    "feasn": feasn, "undos": undos, "keys": pending,
                    "g": g, "gpl": gpl, "since": time.monotonic(),
                    "mates": [],
                }
                prebind_parked.add(qp.pod.uid)
                continue
            # Write-ahead at GROUP scope (engine/pipeline.drain_commit):
            # the bind STAGES here; its journal record and its apply
            # (spec mutation + finish_binding + queue/gang bookkeeping)
            # both happen at the drain, where the whole group's records
            # go durable under ONE fsync before any of them applies —
            # the crash analog of etcd acknowledging a batched txn
            # before the scheduler trusts any write in it.
            outcome = ScheduleOutcome(qp.pod, node_name, score, feasn)
            outcomes.append(outcome)
            ticket.stage(qp, node_name, outcome)
            if g:
                finalized_by_group.setdefault(g, []).append(
                    (qp, outcome, undos)
                )
        # A parked gang member pins its batch-mates' records so a PreBind
        # timeout can roll the whole gang back (the repo's gang contract is
        # all-or-nothing; mates bound this batch revert like a lost PV race).
        for uid in prebind_parked:
            entry = self.prebind_waiting.get(uid)
            if entry is not None and entry["g"]:
                entry["mates"] = list(finalized_by_group.get(entry["g"], ()))
        # A group rolled back by a transient PV race re-admits behind backoff
        # right away — no cluster event will ever fire in a quiet cluster,
        # and the race loser's next attempt resolves against the updated
        # volume catalog.
        for g in race_rollback:
            self.queue.readmit_gang(g)
        # Plugins see their groups that are now waiting (e.g. coscheduling
        # re-attempts queue admission: waiter credit grew).
        for plugin in self.permit_plugins:
            plugin_waits = {g for g in wait if owner.get(g) is plugin}
            if plugin_waits:
                plugin.post_batch(plugin_waits, self)
        if prebind_s:
            m.registry.observe_point("PreBind", prebind_s)
        # Drain the staged commit group at the SERIAL point — unless the
        # pipeline defers it under the next dispatch.  Any batch with
        # failures drains here regardless: PostFilter's victim deletes
        # journal with their own fsyncs, and the WAL's replay order must
        # keep this batch's bind records AHEAD of them (delete-then-bind
        # replay would resurrect a preempted pod).
        drain_inline_s = 0.0
        if not defer_drain or failed:
            drain_inline_s = self._drain_pending(overlapped=False)
        # Metrics after rollbacks settled (success = outcome kept its
        # node).  Staged successes are accounted by the drain (inline
        # above at depth 1, under the next device pass at depth >= 2).
        for outcome in outcomes:
            if outcome.node_name:
                if ticket.holds(outcome.pod.uid):
                    continue  # success accounting rides the drain
                # Not staged: an inline preemptor commit
                # (_commit_preempted journals + applies directly) —
                # its success accounting happens here.
                if m.scheduled == 0:
                    m.first_scheduled_ts = now
                m.scheduled += 1
                m.last_scheduled_ts = now
                self._note_bound(outcome.pod, outcome.node_name)
                self.recorder.event(
                    outcome.pod.uid, NORMAL, "Scheduled",
                    f"Successfully assigned {outcome.pod.uid} to "
                    f"{outcome.node_name}",
                )
            else:
                m.unschedulable += 1
                # Rollback/race failures carry no device diagnosis; the
                # engine-rejected failures get theirs (with the plugin
                # set) in the diagnosis loop below.
                self.recorder.event(
                    outcome.pod.uid, WARNING, "FailedScheduling",
                    f"0/{self.cache.node_count()} nodes available "
                    "(batch rollback or lost race)",
                    **self._trace_extra(),
                )
        # Diagnosis from the device's per-op fail bitmask (bit order =
        # filter_op_names): which plugins rejected nodes this cycle.  A
        # uniform failing batch (5k no-fit pods, the Unschedulable shape)
        # produces ONE distinct mask — build each mask's plugin set once.
        bit_names = filter_op_names(profile, active)
        mask_sets: dict[int, set] = {}
        failed2 = []
        for i, qp, _ in failed:
            mask = int(fails[i])
            plugins = mask_sets.get(mask)
            if plugins is None:
                plugins = {
                    name for b, name in enumerate(bit_names) if mask & (1 << b)
                }
                mask_sets[mask] = plugins
            diag = Diagnosis(unschedulable_plugins=plugins)
            outcome = ScheduleOutcome(qp.pod, None, 0, int(feas[i]), diagnosis=diag)
            m.unschedulable += 1
            for name in sorted(plugins):
                self._unsched_reasons.inc(plugin=name)
            # FailedScheduling with the diagnosis plugin set (the fitError
            # message shape: "0/N nodes are available: ...").
            self.recorder.event(
                qp.pod.uid, WARNING, "FailedScheduling",
                f"0/{self.cache.node_count()} nodes available: rejected by "
                + (", ".join(sorted(plugins)) if plugins else "no feasible nodes"),
                plugins=sorted(plugins),
                **self._trace_extra(),
            )
            outcomes.append(outcome)
            failed2.append((i, qp, outcome))
        failed = failed2

        # PostFilter: one batched preemption pass for every failure
        # (schedule_one.go:196 RunPostFilterPlugins → DefaultPreemption).
        results = [None] * len(failed)
        ran_postfilter = False
        t_post = time.perf_counter()
        # (Preemption also sits out a schema-grown batch: its pass would mix
        # old-shape feature rows with rebuilt state; failures just requeue.)
        spec_applied = False
        if (
            failed
            and self.preemption is not None
            and "DefaultPreemption" in profile.post_filter
            and not schema_grew
        ):
            ran_postfilter = True
            if spec is not None and "spec_res" in ctx:
                # The dry-run already ran, chained on the scan's verdicts;
                # interpret its results for the pods that FINALLY failed
                # (tail placements simply never apply theirs).
                by_index = self.preemption.collect_speculative(
                    spec, ctx["spec_res"],
                    {i: qp.pod for i, qp, _ in failed},
                )
                results = [by_index.get(i) for i, _qp, _ in failed]
                spec_applied = True
            else:
                rows = {
                    key: [np.asarray(arr)[i] for i, _, _ in failed]
                    for key, arr in batch.items()
                    if key not in ("valid", "pin_row", "uniform_all")
                }
                results = self.preemption.preempt_batch(
                    [qp.pod for _, qp, _ in failed], rows, active,
                    ctx["inv_d"], profile=profile,
                    prepacked=ctx.get("prepacked"),
                )
        if self.preemption is not None:
            # Prepack victim tensors next batch only while failures recur.
            self.preemption.expect_failures = bool(failed)
        any_victims = False
        # A SPECULATIVE result's dry-run predates the strict tail.  Inline
        # commit needs its verdict still valid against post-tail truth:
        # resources re-check via _fits_now always; hard filters that read
        # MUTABLE node state (affinity/spread/ports/volumes/DRA) cannot be
        # re-checked host-side, so when the tail actually placed something
        # AND such an op is active, speculative results take the
        # nominate-and-retry path (which re-validates on device).
        spec_inline_ok = not spec_applied or not tail_placed or not (
            active & DYNAMIC_HARD_OPS
        )
        for (i, qp, outcome), res in zip(failed, results):
            if res is not None:
                if self.provenance is not None:
                    # pickOneNode rationale BEFORE the commit path's
                    # victim deletes debit the PDB budgets the key reads.
                    self.provenance.note_preemption(
                        qp.pod.uid,
                        {
                            "node": res.node_name,
                            "victims": [v.uid for v in res.victims],
                            "key": self._preempt_key(res.victims),
                        },
                    )
                if (
                    self.inline_preempt_commit
                    and self._can_commit_inline(qp)
                    and (
                        not spec_applied
                        or (
                            spec_inline_ok
                            and self._fits_now(res.node_name, deltas[i])
                        )
                    )
                ):
                    self._commit_preempted(qp, outcome, res, deltas[i], now)
                else:
                    # The fit overlay protects the freed node from same/
                    # next-batch stealers, and the retry's fast path takes
                    # it (nominator.go AddNominatedPod).
                    self._record_preemption(qp, outcome, res, deltas[i])
                any_victims = any_victims or bool(res.victims)
            elif self.preemption is not None and schema_grew:
                # Preemption sat this batch out (its compiled pass cannot
                # mix old-shape feature rows with the rebuilt state) — the
                # failure must RETRY next batch rather than park: in a
                # quiet cluster no event would ever wake it, while the
                # reference would have run PostFilter on this very cycle.
                self.queue.reactivate(qp)
            else:
                # Precise requeue hints: wait only on events the plugins that
                # actually rejected nodes care about (isPodWorthRequeuing,
                # scheduling_queue.go:406).  Empty diagnosis (e.g. zero valid
                # nodes) falls back to the whole filter set.
                plugins = outcome.diagnosis.unschedulable_plugins if outcome.diagnosis else set()
                qp.delta = deltas[i]  # the object-aware hints read req
                self.queue.add_unschedulable(
                    qp, plugins or set(profile.filters)
                )
        if any_victims:
            # One batched POD_DELETE for every victim this pass, carrying
            # the affected nodes' post-eviction free capacity (minus the
            # preemptors' nominated claims) so the fit hint wakes only pods
            # the freed space could actually seat — without this, every
            # victim deletion re-activates the whole unschedulable pool
            # (the preemption-async churn VERDICT r2 weak-1 named).
            freed_rows = {
                self.cache.nodes[res.node_name].row
                for res in results
                if res is not None and res.victims
                and res.node_name in self.cache.nodes
            }
            self.queue.on_event(Event.POD_DELETE, self._free_ctx(freed_rows))
        if ran_postfilter:
            m.registry.observe_point("PostFilter", time.perf_counter() - t_post)
        if (
            self.consistency_check_every
            and m.batches % self.consistency_check_every == 0
        ):
            # Quiescent point: host assume/forget deltas all applied.
            self.check_consistency()
        acc = self._flight_acc
        if acc is not None:
            # Flight tiling for this dispatch→complete unit: the three
            # segments share boundary timestamps, so they sum to the
            # unit's wall time exactly (multi-profile batches accumulate
            # one unit per group; `other` in _record_flight absorbs the
            # gaps between units).
            t_flight_end = time.perf_counter()
            ph = acc["phases"]
            pack_s = ctx.get("pack_s", 0.0)
            ph["featurize"] = ph.get("featurize", 0.0) + (t1 - ctx["t_f0"])
            # The packer runs between t1 and dispatch: carve its slice out
            # of the device segment so the tiling still sums to wall time.
            if pack_s > 0.0:
                ph["packing"] = ph.get("packing", 0.0) + pack_s
            ph["device"] = ph.get("device", 0.0) + (t2 - t1 - pack_s)
            # An inline drain ran inside the commit window and recorded
            # its own `drain` segment — carve it out so the tiling still
            # sums to wall time.
            ph["commit"] = ph.get("commit", 0.0) + max(
                t_flight_end - t2 - drain_inline_s, 0.0
            )
            acc["pods"] += len(infos)
            acc["scheduled"] += sum(1 for o in outcomes if o.node_name)
            acc["unschedulable"] += sum(
                1 for o in outcomes if not o.node_name
            )
            acc["dispatches"].append(
                "pinned" if ctx.get("pinned") else "batch"
            )
        return outcomes

    def schedule_all_pending(
        self, max_rounds: int = 10_000, wait_backoff: bool = False
    ) -> list[ScheduleOutcome]:
        """Drain the active queue (benchmark driver).  With ``wait_backoff``
        the loop also sleeps through backoff expiries (so preempted pods get
        their retry) until only unschedulable/gated pods remain."""
        all_outcomes: list[ScheduleOutcome] = []
        for _ in range(max_rounds):
            out = self.schedule_batch()
            if out:
                all_outcomes.extend(out)
                continue
            if len(self.queue) or self.has_inflight_work:
                # A whole batch can yield zero outcomes (members moved to
                # the WaitOnPermit room) while pods remain active,
                # prefetched, or predispatched.
                if (
                    self.queue.last_pop_throttled
                    and not self.has_inflight_work
                ):
                    # Pods remain but every tenant is credit-blocked
                    # (weighted-fair admission): looping cannot admit
                    # them — only the logical clock can, via refill or
                    # the aging escape.  Stop instead of spinning.
                    break
                continue
            if wait_backoff and self.queue.sleep_until_backoff():
                continue
            break
        return all_outcomes

