"""Deterministic fault injection for the host↔sidecar dispatch path.

The two-tier split (SURVEY §7) puts a process boundary in the middle of
the scheduling loop, and the reference scheduler's answer to a flaky
apiserver — error → backoff requeue, keep making progress
(schedule_one.go handleSchedulingFailure) — must hold across it.  This
module is the test substrate for that claim: a ``FaultPlan`` describes a
reproducible sequence of transport and engine failures, wraps the client
side of the sidecar socket pair and the scheduler's engine dispatch, and
fires each fault on exactly the Nth matching call.  Seeded, counted and
recorded, so a failing fault-matrix case replays bit-identically.

Fault kinds on the wire (applied when the client writes a request frame):

- ``hang``          — swallow the request; the sidecar never sees it, the
                      client's recv blocks until its deadline fires (the
                      hung-sidecar shape: process alive, dispatch wedged).
- ``slow``          — delay the request by ``delay_s`` then deliver it
                      (degraded-but-alive; must NOT trip deadlines when
                      ``delay_s`` < the client deadline).
- ``crash``         — deliver nothing and sever the connection (the
                      sidecar died mid-call; recv sees EOF immediately).
- ``partial_write`` — deliver a torn frame (half the bytes) then sever
                      (crash mid-write; the server's framed read must
                      treat the tail as EOF, not parse garbage).

Engine faults (applied when the scheduler dispatches a device batch):

- ``engine``        — raise from inside the batch.  With ``pod`` set, the
                      rule poisons that pod: every batch containing it
                      raises (the poison-pod shape quarantine exists
                      for); without ``pod``, the Nth dispatch raises once
                      (a transient engine failure).

Process-kill faults (the crash analog of the wire matrix, PR 3): a
``KillSwitch`` SIGKILLs the process at a named crash point inside the
write-ahead journal (journal.py) — ``pre-append`` (decision lost),
``post-append`` (durable but unapplied), ``torn-append`` (half a record
on disk), ``pre-snapshot`` (compaction about to start), ``mid-snapshot``
(torn checkpoint temp), ``mid-truncate`` (snapshot replaced, log not
yet truncated), ``post-truncate`` (compaction cycle just completed).
Armed from the environment
(``TPU_JOURNAL_KILL=point:nth``) so a child process under
scripts/run_fault_matrix.py --kill dies exactly once, at exactly the
probed window; the parent then recovers a fresh process from the journal
and asserts bit-identical bindings.

Every fired fault is appended to ``plan.fired`` as ``(kind, op, count)``;
two plans built from the same rules and seed fire identically, which is
what ``replay()`` returns and what scripts/run_fault_matrix.py sweeps.
"""

from __future__ import annotations

import os
import random
import signal
import socket
import struct
import threading
import time
from dataclasses import dataclass, field

_LEN = struct.Struct(">I")


class EngineFault(RuntimeError):
    """An engine failure attributable to specific pods.  The scheduler's
    batch recovery uses ``pod_uids`` to isolate the poison pods directly;
    an exception without attribution is bisected instead."""

    def __init__(self, msg: str, pod_uids: tuple[str, ...] = ()):
        super().__init__(msg)
        self.pod_uids = tuple(pod_uids)


@dataclass
class FaultRule:
    """One fault: ``kind`` fired on the ``nth`` call matching ``op``.

    ``op`` matches the envelope's message kind ("schedule", "add",
    "remove", "dump", …) or "*" for any request frame; engine rules
    ignore it.  ``every`` keeps firing from the nth match onward (a
    persistently hung sidecar); pod-keyed engine rules are inherently
    ``every`` — the poison is a property of the pod, not of one call."""

    kind: str                 # hang | slow | crash | partial_write | engine
    op: str = "*"
    nth: int = 1
    every: bool = False
    times: int = 0            # with every: fire at most this many (0 = ∞)
    delay_s: float = 0.05     # slow: injected latency
    pod: str | None = None    # engine: poison pod uid
    attributed: bool = True   # engine: raise EngineFault(pod_uids) vs bare


class FaultPlan:
    """A seeded, replayable fault schedule.

    Wire faults install via ``wrap(sock)`` (or ``wrap_client(client)``);
    engine faults install via ``install_engine(scheduler)``.  The plan is
    shared mutable state across every wrapped socket — reconnects re-wrap
    the fresh socket through the same plan, so an ``every`` rule keeps
    biting across resyncs exactly like a genuinely wedged sidecar would."""

    def __init__(self, rules: list[FaultRule] | None = None, seed: int = 0):
        self.seed = seed
        self.rules = list(rules or ())
        self.rng = random.Random(seed)
        self.fired: list[tuple[str, str, int]] = []
        self._op_counts: dict[str, int] = {}
        self._engine_calls = 0
        self._lock = threading.Lock()

    # -- construction ------------------------------------------------------

    def add_rule(self, kind: str, **kw) -> "FaultPlan":
        self.rules.append(FaultRule(kind, **kw))
        return self

    def replay(self) -> "FaultPlan":
        """A fresh plan with the same rules and seed — fires identically
        against the same call sequence (the reproducibility contract)."""
        return FaultPlan(
            [FaultRule(**vars(r)) for r in self.rules], seed=self.seed
        )

    # -- wire side ---------------------------------------------------------

    def wrap(self, sock: socket.socket) -> "FaultySocket":
        return FaultySocket(sock, self)

    def wrap_client(self, client) -> None:
        """Wrap a SidecarClient's live socket in place."""
        client.sock = self.wrap(client.sock)

    def _match_wire(self, op: str) -> FaultRule | None:
        with self._lock:
            count = self._op_counts.get(op, 0) + 1
            self._op_counts[op] = count
            for r in self.rules:
                if r.kind == "engine" or r.op not in ("*", op):
                    continue
                if count == r.nth or (
                    r.every
                    and count >= r.nth
                    and (r.times == 0 or count < r.nth + r.times)
                ):
                    self.fired.append((r.kind, op, count))
                    return r
        return None

    # -- engine side -------------------------------------------------------

    def install_engine(self, scheduler) -> None:
        scheduler.fault_injector = self

    def on_engine_dispatch(self, pods) -> None:
        """Called by TPUScheduler at the top of every device-batch
        dispatch (bisect retries included).  Raises to poison the batch."""
        with self._lock:
            self._engine_calls += 1
            n = self._engine_calls
            for r in self.rules:
                if r.kind != "engine":
                    continue
                if r.pod is not None:
                    poisoned = [p.uid for p in pods if p.uid == r.pod]
                    if not poisoned:
                        continue
                    self.fired.append(("engine", r.pod, n))
                    if r.attributed:
                        raise EngineFault(
                            f"injected engine fault for {r.pod}",
                            tuple(poisoned),
                        )
                    raise RuntimeError(
                        f"injected unattributed engine fault (batch of "
                        f"{len(pods)})"
                    )
                if n == r.nth or (r.every and n >= r.nth):
                    self.fired.append(("engine", "*", n))
                    raise EngineFault("injected engine fault", ())


KILL_POINTS = (
    "pre-append", "post-append", "torn-append", "pre-snapshot",
    "mid-snapshot", "mid-truncate", "post-truncate",
    # Fleet handoff window (fleet/shardmap.py): the transfer is journaled
    # but the shard-map file rewrite has not landed — takeover must redo
    # the idempotent rewrite from the journal.
    "pre-map-write",
    # The two remaining windows inside a live resize (ISSUE 11, the
    # autoscaler-initiated handoff): the acquiring owner has journaled
    # the handoff record but not yet imported a single node
    # (post-journal/pre-import — fleet/owner.py import_nodes), and the
    # map file is rewritten but the losing owner still holds its copies
    # (mid-drop — fleet/router.py apply_handoff; takeover's map
    # enforcement finishes the interrupted drop).
    "post-handoff-append",
    "mid-drop",
    # Group-commit / pipeline windows (ISSUE 15, engine/pipeline.py +
    # journal.py group()): the commit stage is staged but nothing
    # journaled yet (stage-boundary — the drain is about to run, often
    # under an in-flight device pass), the group's records are written
    # but the single fsync barrier has not returned (mid-group-fsync —
    # none of the group applied), the barrier returned but the applies
    # have not run (post-group-fsync — durable, unapplied: replay makes
    # the whole group live), and the group's LAST record torn mid-write
    # (torn-group-tail — open-time repair truncates it; the complete
    # prefix replays).
    "stage-boundary",
    "mid-group-fsync",
    "post-group-fsync",
    "torn-group-tail",
    # Warm-standby promotion windows (ISSUE 18, fleet/standby.py): the
    # pool picked a warm child but has not claimed it (standby-pre-claim
    # — the claim file does not exist; a restarted promoter re-picks),
    # the claim and the pool's journal record landed but the apply has
    # not run (standby-mid-promotion — replay finishes the promotion
    # bookkeeping; the fleet-side map/handoff truth is the takeover
    # machinery's as usual), and the promotion applied but the caller
    # died before using the owner (standby-post-promote — the slot is
    # consumed either way; the map write it feeds is covered by
    # pre-map-write).
    "standby-pre-claim",
    "standby-mid-promotion",
    "standby-post-promote",
    # Soak-driver checkpoint window (ISSUE 18, loadgen/checkpoint.py):
    # the new checkpoint is fully written and fsync'd under a temp name
    # but os.replace has not run — resume must come up on the PREVIOUS
    # complete checkpoint, never a torn half.
    "mid-checkpoint",
)


class KillSwitch:
    """A process-kill fault: SIGKILL self when the Nth hit of the armed
    crash point arrives.  The journal consults the module-level
    ``journal.CRASH`` switch at every point via ``should_fire`` (which
    counts EVERY point so nth is deterministic per point) and calls
    ``fire`` only on a match — SIGKILL is not catchable, so the process
    dies exactly where a power cut would have killed it."""

    def __init__(self, point: str, nth: int = 1):
        assert point in KILL_POINTS, point
        self.point = point
        self.nth = nth
        self.counts: dict[str, int] = {}

    def should_fire(self, point: str) -> bool:
        c = self.counts.get(point, 0) + 1
        self.counts[point] = c
        return point == self.point and c == self.nth

    def fire(self) -> None:
        os.kill(os.getpid(), signal.SIGKILL)
        time.sleep(60)  # SIGKILL delivery races the return; never proceed

    def arm(self) -> "KillSwitch":
        from . import journal as _journal

        _journal.CRASH = self
        return self

    @classmethod
    def from_env(cls, var: str = "TPU_JOURNAL_KILL") -> "KillSwitch | None":
        """``TPU_JOURNAL_KILL=point[:nth]`` — the child-process arming
        protocol the kill matrix uses (the switch must be armed in the
        victim process, not the sweeping parent)."""
        spec = os.environ.get(var, "")
        if not spec:
            return None
        point, _, nth = spec.partition(":")
        return cls(point, int(nth or 1))


def _frame_op(data: bytes) -> str:
    """Envelope message kind of one length-prefixed frame ("?" when the
    buffer isn't a single parseable frame — faults still count it)."""
    try:
        (n,) = _LEN.unpack(data[:4])
        if len(data) != 4 + n:
            return "?"
        from .sidecar import sidecar_pb2 as pb  # lazy: avoid import cycle

        env = pb.Envelope()
        env.ParseFromString(data[4:])
        return env.WhichOneof("msg") or "?"
    except Exception:
        return "?"


class FaultySocket:
    """A socket proxy applying a FaultPlan to outbound request frames.

    Clients write one full frame per ``sendall`` (write_frame), so the
    proxy can classify the envelope and consult the plan per call.  Reads
    and everything else delegate untouched — response-side faults are
    modeled as request-side ones (a swallowed request IS an unanswered
    call from where the client sits)."""

    def __init__(self, sock: socket.socket, plan: FaultPlan):
        self._sock = sock
        self._plan = plan

    def sendall(self, data: bytes) -> None:
        rule = self._plan._match_wire(_frame_op(data))
        if rule is None:
            return self._sock.sendall(data)
        if rule.kind == "slow":
            time.sleep(rule.delay_s)
            return self._sock.sendall(data)
        if rule.kind == "hang":
            return None  # swallowed: the sidecar never sees the request
        if rule.kind == "partial_write":
            torn = data[: max(1, len(data) // 2)]
            try:
                self._sock.sendall(torn)
            finally:
                self._sever()
            return None
        if rule.kind == "crash":
            self._sever()
            return None
        raise ValueError(f"unknown wire fault {rule.kind!r}")

    def _sever(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()

    def __getattr__(self, name):
        return getattr(self._sock, name)
