"""String interning: the bridge from the reference's string-keyed world
(labels, taints, topology values, resource names) to dense integer ids that
vectorize on device.

The reference matches labels with string comparisons inside the per-node hot
loop (e.g. labels.Selector in every affinity plugin).  Arbitrary string ops do
not vectorize on a TPU, so every string the device needs is interned host-side
into a vocabulary; device tensors hold only ids.  Vocabularies only grow;
ids are stable for the life of the process, so device tensors never need
re-keying when new strings appear.
"""

from __future__ import annotations

from typing import Hashable, Iterable


def term_key(category: int, weight: int, term, namespace: str) -> tuple:
    """Canonical hashable identity of a pod (anti-)affinity term.

    Namespaces default to the owning pod's namespace when the term names none
    and has no namespaceSelector (framework/types.go newAffinityTerm)."""
    ns = tuple(sorted(term.namespaces))
    if not ns and term.namespace_selector is None:
        ns = (namespace,)
    return (category, weight, term.topology_key, ns, term.namespace_selector, term.label_selector)


class Vocab:
    """A grow-only bijection value → dense id (0-based). Thread-hostile by
    design: interning happens only on the (single-threaded) snapshot path,
    matching the reference's single scheduling goroutine."""

    __slots__ = ("_to_id", "_to_val", "name")

    def __init__(self, name: str = ""):
        self.name = name
        self._to_id: dict[Hashable, int] = {}
        self._to_val: list[Hashable] = []

    def id(self, value: Hashable) -> int:
        """Intern value, returning its id (allocating if new)."""
        i = self._to_id.get(value)
        if i is None:
            i = len(self._to_val)
            self._to_id[value] = i
            self._to_val.append(value)
        return i

    def get(self, value: Hashable) -> int:
        """Return id or -1 without interning (for read-only lookups)."""
        return self._to_id.get(value, -1)

    def value(self, i: int) -> Hashable:
        return self._to_val[i]

    def ids(self, values: Iterable[Hashable]) -> list[int]:
        return [self.id(v) for v in values]

    def __len__(self) -> int:
        return len(self._to_val)

    def __contains__(self, value: Hashable) -> bool:
        return value in self._to_id


class InternTable:
    """All vocabularies the snapshot/feature builders share.

    - ``label_keys``:   label key → id (for Exists/DoesNotExist ops)
    - ``label_pairs``:  (key, value) → id (for In/NotIn/equality ops)
    - ``taints``:       (key, value, effect) → id
    - ``topo_keys``:    topology key → per-key slot index (bounded by schema.TK)
    - ``topo_vals[k]``: per-topology-key value vocab (node's zone id, etc.)
    - ``namespaces``:   namespace → id
    - ``groups``:       (namespace_id, frozenset(labels.items())) → pod group id
    - ``ports``:        (protocol, hostIP, port) → id
    - ``images``:       image name → id
    - ``node_names``:   node name → id (== snapshot row index is NOT guaranteed;
                        row index mapping lives in the cache)
    """

    def __init__(self) -> None:
        self.label_keys = Vocab("label_keys")
        self.label_pairs = Vocab("label_pairs")
        self.taints = Vocab("taints")
        self.topo_keys = Vocab("topo_keys")
        self.topo_vals: list[Vocab] = []
        self.namespaces = Vocab("namespaces")
        self.groups = Vocab("groups")
        self.terms = Vocab("terms")  # existing-pod (anti-)affinity terms
        self.devices = Vocab("devices")  # in-tree device-volume ids
        self.drivers = Vocab("drivers")  # CSI driver names
        # CSI volume unique names (nodevolumelimits/csi.go volumeUniqueName:
        # bound → driver/volumeHandle; unbound → driver/claim-uid), so a
        # volume shared by several pods on a node attaches — and counts —
        # once.
        self.csivols = Vocab("csivols")
        self.device_classes = Vocab("device_classes")  # DRA device classes
        self.dra_claims = Vocab("dra_claims")  # DRA claim uids
        self.ports = Vocab("ports")
        self.images = Vocab("images")
        self.node_names = Vocab("node_names")

    def topo_key_slot(self, key: str) -> int:
        slot = self.topo_keys.id(key)
        while len(self.topo_vals) <= slot:
            self.topo_vals.append(Vocab(f"topo_vals[{len(self.topo_vals)}]"))
        return slot

    def topo_value_id(self, key: str, value: str) -> int:
        return self.topo_vals[self.topo_key_slot(key)].id(value)

    HOSTNAME_KEY = "kubernetes.io/hostname"

    def max_topo_vocab(self) -> int:
        """Largest per-key domain vocabulary EXCLUDING the hostname key
        (drives Schema.DV).  Hostname domains are one-node domains and every
        device op takes a per-node fast path for them, so their huge
        vocabulary must not inflate the segment tables."""
        host_slot = self.topo_keys.get(self.HOSTNAME_KEY)
        return max(
            (len(v) for i, v in enumerate(self.topo_vals) if i != host_slot),
            default=0,
        )

    def term_id(self, category: int, weight: int, term, namespace: str) -> int:
        """Intern a pod (anti-)affinity term of an existing pod.

        ``category``: 0 required-affinity, 1 required-anti-affinity,
        2 preferred-affinity, 3 preferred-anti-affinity."""
        return self.terms.id(term_key(category, weight, term, namespace))

    def group_id(self, namespace: str, labels: dict[str, str]) -> int:
        """Pod label-group id: pods with identical (namespace, labels) share a
        group.  Affinity/spread counting then becomes per-group arithmetic —
        the device never sees individual pod labels."""
        key = (self.namespaces.id(namespace), frozenset(labels.items()))
        return self.groups.id(key)

    def group_labels(self, gid: int) -> tuple[str, dict[str, str]]:
        ns_id, fs = self.groups.value(gid)  # type: ignore[misc]
        return str(self.namespaces.value(ns_id)), dict(fs)
