"""String interning: the bridge from the reference's string-keyed world
(labels, taints, topology values, resource names) to dense integer ids that
vectorize on device.

The reference matches labels with string comparisons inside the per-node hot
loop (e.g. labels.Selector in every affinity plugin).  Arbitrary string ops do
not vectorize on a TPU, so every string the device needs is interned host-side
into a vocabulary; device tensors hold only ids.  Vocabularies only grow;
ids are stable for the life of the process, so device tensors never need
re-keying when new strings appear.
"""

from __future__ import annotations

from typing import Hashable, Iterable


def term_key(category: int, weight: int, term, namespace: str) -> tuple:
    """Canonical hashable identity of a pod (anti-)affinity term.

    Namespaces default to the owning pod's namespace when the term names none
    and has no namespaceSelector (framework/types.go newAffinityTerm)."""
    ns = tuple(sorted(term.namespaces))
    if not ns and term.namespace_selector is None:
        ns = (namespace,)
    return (category, weight, term.topology_key, ns, term.namespace_selector, term.label_selector)


class Vocab:
    """A grow-only bijection value → dense id (0-based). Thread-hostile by
    design: interning happens only on the (single-threaded) snapshot path,
    matching the reference's single scheduling goroutine."""

    __slots__ = ("_to_id", "_to_val", "name")

    def __init__(self, name: str = ""):
        self.name = name
        self._to_id: dict[Hashable, int] = {}
        self._to_val: list[Hashable] = []

    def id(self, value: Hashable) -> int:
        """Intern value, returning its id (allocating if new)."""
        i = self._to_id.get(value)
        if i is None:
            i = len(self._to_val)
            self._to_id[value] = i
            self._to_val.append(value)
        return i

    def get(self, value: Hashable) -> int:
        """Return id or -1 without interning (for read-only lookups)."""
        return self._to_id.get(value, -1)

    def value(self, i: int) -> Hashable:
        return self._to_val[i]

    def ids(self, values: Iterable[Hashable]) -> list[int]:
        return [self.id(v) for v in values]

    def __len__(self) -> int:
        return len(self._to_val)

    def __contains__(self, value: Hashable) -> bool:
        return value in self._to_id


class InternTable:
    """All vocabularies the snapshot/feature builders share.

    - ``label_keys``:   label key → id (for Exists/DoesNotExist ops)
    - ``label_pairs``:  (key, value) → id (for In/NotIn/equality ops)
    - ``taints``:       (key, value, effect) → id
    - ``topo_keys``:    topology key → per-key slot index (bounded by schema.TK)
    - ``topo_vals[k]``: per-topology-key value vocab (node's zone id, etc.)
    - ``namespaces``:   namespace → id
    - ``groups``:       (namespace_id, frozenset(labels.items())) → pod group id
    - ``ports``:        (protocol, hostIP, port) → id
    - ``images``:       image name → id
    - ``node_names``:   node name → id (== snapshot row index is NOT guaranteed;
                        row index mapping lives in the cache)
    """

    def __init__(self) -> None:
        self.label_keys = Vocab("label_keys")
        self.label_pairs = Vocab("label_pairs")
        self.taints = Vocab("taints")
        self.topo_keys = Vocab("topo_keys")
        self.topo_vals: list[Vocab] = []
        self.namespaces = Vocab("namespaces")
        self.groups = Vocab("groups")
        self.terms = Vocab("terms")  # existing-pod (anti-)affinity terms
        self.devices = Vocab("devices")  # in-tree device-volume ids
        self.drivers = Vocab("drivers")  # CSI driver names
        # CSI volume unique names (nodevolumelimits/csi.go volumeUniqueName:
        # bound → driver/volumeHandle; unbound → driver/claim-uid), so a
        # volume shared by several pods on a node attaches — and counts —
        # once.
        self.csivols = Vocab("csivols")
        self.device_classes = Vocab("device_classes")  # DRA device classes
        self.dra_claims = Vocab("dra_claims")  # DRA claim uids
        self.ports = Vocab("ports")
        self.images = Vocab("images")
        self.node_names = Vocab("node_names")

    def topo_key_slot(self, key: str) -> int:
        slot = self.topo_keys.id(key)
        while len(self.topo_vals) <= slot:
            self.topo_vals.append(Vocab(f"topo_vals[{len(self.topo_vals)}]"))
        return slot

    def topo_value_id(self, key: str, value: str) -> int:
        return self.topo_vals[self.topo_key_slot(key)].id(value)

    HOSTNAME_KEY = "kubernetes.io/hostname"

    def max_topo_vocab(self) -> int:
        """Largest per-key domain vocabulary EXCLUDING the hostname key
        (drives Schema.DV).  Hostname domains are one-node domains and every
        device op takes a per-node fast path for them, so their huge
        vocabulary must not inflate the segment tables."""
        host_slot = self.topo_keys.get(self.HOSTNAME_KEY)
        return max(
            (len(v) for i, v in enumerate(self.topo_vals) if i != host_slot),
            default=0,
        )

    def term_id(self, category: int, weight: int, term, namespace: str) -> int:
        """Intern a pod (anti-)affinity term of an existing pod.

        ``category``: 0 required-affinity, 1 required-anti-affinity,
        2 preferred-affinity, 3 preferred-anti-affinity."""
        return self.terms.id(term_key(category, weight, term, namespace))

    def group_id(self, namespace: str, labels: dict[str, str]) -> int:
        """Pod label-group id: pods with identical (namespace, labels) share a
        group.  Affinity/spread counting then becomes per-group arithmetic —
        the device never sees individual pod labels."""
        key = (self.namespaces.id(namespace), frozenset(labels.items()))
        return self.groups.id(key)

    def group_labels(self, gid: int) -> tuple[str, dict[str, str]]:
        ns_id, fs = self.groups.value(gid)  # type: ignore[misc]
        return str(self.namespaces.value(ns_id)), dict(fs)


class GroupIndex:
    """Vectorized label-selector evaluation over pod label-GROUPS.

    The reference matches selectors against individual pods in the hot loop
    (labels.Selector.Matches per pod); here pods collapse into (namespace,
    labels) groups, and selector evaluation becomes boolean column algebra
    over two incrementally-maintained membership matrices —

      ``gp`` (G, LP): group g carries label pair p
      ``gk`` (G, LK): group g carries label key k

    — so matching one selector against EVERY group is a handful of numpy
    column reductions instead of an O(G) Python loop (the featurization
    hot-path cost VERDICT r2 measured on the affinity-heavy configs)."""

    def __init__(self, interns: InternTable) -> None:
        self.it = interns
        import numpy as np

        self._np = np
        self._n_groups = 0
        self.group_ns = np.zeros(0, np.int32)
        self.gp = np.zeros((0, 0), np.bool_)
        self.gk = np.zeros((0, 0), np.bool_)

    @staticmethod
    def _grow(np, arr, rows: int, cols: int):
        r = max(rows, arr.shape[0])
        c = max(cols, arr.shape[1])
        if (r, c) == arr.shape:
            return arr
        out = np.zeros((_cap(r), _cap(c)), np.bool_)
        out[: arr.shape[0], : arr.shape[1]] = arr
        return out

    def sync(self) -> None:
        """Absorb newly-interned groups (grow-only; ids are stable)."""
        it, np = self.it, self._np
        n = len(it.groups)
        if n == self._n_groups:
            return
        # Intern the new groups' pairs/keys first so column capacity is known.
        new = range(self._n_groups, n)
        pairs: list[tuple[int, int]] = []
        keys: list[tuple[int, int]] = []
        ns_ids = []
        for gid in new:
            ns_id, fs = it.groups.value(gid)  # type: ignore[misc]
            ns_ids.append(ns_id)
            for k, v in fs:
                pairs.append((gid, it.label_pairs.id((k, v))))
                keys.append((gid, it.label_keys.id(k)))
        self.gp = self._grow(np, self.gp, n, len(it.label_pairs))
        self.gk = self._grow(np, self.gk, n, len(it.label_keys))
        if self.group_ns.shape[0] < n:
            g2 = np.zeros(_cap(n), np.int32)
            g2[: self._n_groups] = self.group_ns[: self._n_groups]
            self.group_ns = g2
        self.group_ns[self._n_groups : n] = ns_ids
        for gid, pid in pairs:
            self.gp[gid, pid] = True
        for gid, kid in keys:
            self.gk[gid, kid] = True
        self._n_groups = n

    def match_selector(self, sel, ns_ids=None):
        """(G,) bool — label_selector_matches(sel, group labels) for every
        group, optionally restricted to a namespace-id set.  None selects
        nothing, empty selects everything (metav1 semantics)."""
        self.sync()
        it, np = self.it, self._np
        n = self._n_groups
        if sel is None:
            return np.zeros(n, np.bool_)
        ok = np.ones(n, np.bool_)
        gp, gk = self.gp, self.gk
        # Ids at or past the matrix width were interned AFTER the last group
        # sync (by term encoding, node rows, …): no group carries them.
        for k, v in sel.match_labels:
            pid = it.label_pairs.get((k, v))
            if pid < 0 or pid >= gp.shape[1]:
                return np.zeros(n, np.bool_)
            ok &= gp[:n, pid]
        for req in sel.match_expressions:
            kid = it.label_keys.get(req.key)
            has = (
                gk[:n, kid]
                if 0 <= kid < gk.shape[1]
                else np.zeros(n, np.bool_)
            )
            pids = [
                p
                for p in (it.label_pairs.get((req.key, v)) for v in req.values)
                if 0 <= p < gp.shape[1]
            ]
            anyp = (
                gp[:n, pids].any(axis=1) if pids else np.zeros(n, np.bool_)
            )
            op = req.operator
            if op == "In":
                ok &= anyp
            elif op == "NotIn":
                ok &= ~anyp  # key-missing groups pass (anyp implies has)
            elif op == "Exists":
                ok &= has
            elif op == "DoesNotExist":
                ok &= ~has
            else:
                raise ValueError(f"bad label selector operator {op}")
        if ns_ids is not None:
            ok = ok & np.isin(self.group_ns[:n], list(ns_ids))
        return ok


def _cap(n: int) -> int:
    c = 64
    while c < n:
        c *= 2
    return c


class TermIndex:
    """Incremental (ET, G) matrix: does interned existing-pod term t match
    pod group g (namespace AND label selector)?

    Featurization reads one COLUMN per pod (its group) — replacing the
    O(ET) per-pod Python loop that dominated the affinity-heavy configs.
    Growth is amortized on both axes:

      * new term → one row, vectorized over all groups (GroupIndex);
      * new group → one column, vectorized over all terms via a
        simple-selector encoding (match_labels conjunction + at most one
        In-disjunction covers the overwhelming share of real selectors);
        terms outside that shape fall back to per-term evaluation.

    Namespace matching rides a small (T, NS) matrix (namespace counts are
    tiny); namespaceSelector terms re-evaluate when namespace labels change
    (``ns_epoch``)."""

    def __init__(self, interns: InternTable, group_index: GroupIndex, namespace_labels: dict) -> None:
        import numpy as np

        from .api import types as t

        self._np = np
        self._t = t
        self.it = interns
        self.gi = group_index
        self.namespace_labels = namespace_labels  # live reference
        self.mat = np.zeros((0, 0), np.bool_)  # (T, G)
        self.cats = np.zeros(0, np.int8)
        self.weights = np.zeros(0, np.int64)
        self.ml_pairs = np.zeros((0, 0), np.bool_)  # (T, LP) AND-pairs
        self.in_pairs = np.zeros((0, 0), np.bool_)  # (T, LP) OR-pairs
        self.has_in = np.zeros(0, np.bool_)
        self.complex_sel = np.zeros(0, np.bool_)
        self.term_ns = np.zeros((0, 0), np.bool_)  # (T, NS)
        self._nt = 0
        self._ng = 0
        self._nns = 0
        self._ns_epoch = -1

    def _grow2(self, arr, rows: int, cols: int):
        np = self._np
        if arr.shape[0] >= rows and arr.shape[1] >= cols:
            return arr
        out = np.zeros((_cap(max(rows, arr.shape[0])), _cap(max(cols, arr.shape[1]))), np.bool_)
        out[: arr.shape[0], : arr.shape[1]] = arr
        return out

    def _grow1(self, arr, n: int, dtype=None):
        np = self._np
        if arr.shape[0] >= n:
            return arr
        out = np.zeros(_cap(n), dtype or arr.dtype)
        out[: arr.shape[0]] = arr
        return out

    def _ns_sel_of(self, tid: int):
        return self.it.terms.value(tid)[4]

    def _ns_match(self, tid: int, ns_id: int) -> bool:
        t = self._t
        _cat, _w, _topo, ns_tuple, ns_sel, _sel = self.it.terms.value(tid)
        name = self.it.namespaces.value(ns_id)
        if name in ns_tuple:
            return True
        return ns_sel is not None and t.label_selector_matches(
            ns_sel, self.namespace_labels.get(name, {})
        )

    def _encode_term(self, tid: int) -> None:
        """Simple-selector encoding for vectorized column fills."""
        it, np = self.it, self._np
        _cat, _w, _topo, _ns, _ns_sel, sel = it.terms.value(tid)
        if sel is None:
            self.complex_sel[tid] = True  # matches nothing; handled per group
            return
        in_reqs = [r for r in sel.match_expressions if r.operator == "In"]
        other = [r for r in sel.match_expressions if r.operator != "In"]
        if other or len(in_reqs) > 1:
            self.complex_sel[tid] = True
            return
        if in_reqs and not in_reqs[0].values:
            # In with an empty value set matches nothing; has_in must still
            # be True so the column path rejects every group (the scalar
            # reference does).
            self.has_in[tid] = True
            return
        pair_ids = [it.label_pairs.id((k, v)) for k, v in sel.match_labels]
        in_ids = [
            it.label_pairs.id((in_reqs[0].key, v)) for v in in_reqs[0].values
        ] if in_reqs else []
        self.ml_pairs = self._grow2(self.ml_pairs, self._cap_t(), len(it.label_pairs))
        self.in_pairs = self._grow2(self.in_pairs, self._cap_t(), len(it.label_pairs))
        for p in pair_ids:
            self.ml_pairs[tid, p] = True
        for p in in_ids:
            self.in_pairs[tid, p] = True
        self.has_in[tid] = bool(in_ids)

    def _cap_t(self) -> int:
        return max(self._nt, len(self.it.terms))

    def sync(self, ns_epoch: int = 0) -> None:
        """Absorb new terms / groups / namespaces; cheap when nothing grew."""
        it, np, t = self.it, self._np, self._t
        nt, ng, nns = len(it.terms), len(it.groups), len(it.namespaces)
        if (nt, ng, nns, ns_epoch) == (self._nt, self._ng, self._nns, self._ns_epoch):
            return
        self.gi.sync()
        if ns_epoch != self._ns_epoch and self._nt:
            # Namespace labels changed: re-evaluate namespaceSelector terms'
            # ns matrix (and rows below via the recompute flag).
            for tid in range(self._nt):
                if self._ns_sel_of(tid) is not None:
                    for nid in range(self._nns):
                        self.term_ns[tid, nid] = self._ns_match(tid, nid)
                    row = self.gi.match_selector(self.it.terms.value(tid)[5])
                    ns_ok = self.term_ns[tid, self.gi.group_ns[: self._ng]]
                    self.mat[tid, : self._ng] = row[: self._ng] & ns_ok
        # -- grow storage --
        self.mat = self._grow2(self.mat, nt, ng)
        self.cats = self._grow1(self.cats, nt)
        self.weights = self._grow1(self.weights, nt)
        self.has_in = self._grow1(self.has_in, nt)
        self.complex_sel = self._grow1(self.complex_sel, nt)
        self.term_ns = self._grow2(self.term_ns, nt, nns)
        self.ml_pairs = self._grow2(self.ml_pairs, nt, len(it.label_pairs))
        self.in_pairs = self._grow2(self.in_pairs, nt, len(it.label_pairs))
        # -- new namespaces: one column in term_ns per namespace --
        for nid in range(self._nns, nns):
            for tid in range(self._nt):
                self.term_ns[tid, nid] = self._ns_match(tid, nid)
        self._nns = nns
        # -- new groups: one matrix column each, vectorized over terms --
        old_nt = self._nt
        for gid in range(self._ng, ng):
            ns_id, _fs = it.groups.value(gid)
            gvec = self.gi.gp[gid]  # (LP_cap,)
            lp = gvec.shape[0]
            T = old_nt
            if T:
                ml = self.ml_pairs[:T, :lp]
                ok = ~((ml & ~gvec[None, :lp]).any(axis=1))
                # Required pairs beyond the group matrix width are pairs no
                # group carries yet — the conjunction fails for them.
                if self.ml_pairs.shape[1] > lp:
                    ok &= ~self.ml_pairs[:T, lp:].any(axis=1)
                inp = self.in_pairs[:T, :lp]
                ok &= ~self.has_in[:T] | (inp & gvec[None, :lp]).any(axis=1)
                complex_ids = np.nonzero(self.complex_sel[:T])[0]
                if complex_ids.size:
                    _ns_name, labels = it.group_labels(gid)
                    for tid in complex_ids:
                        sel = it.terms.value(int(tid))[5]
                        ok[tid] = t.label_selector_matches(sel, labels)
                ok &= self.term_ns[:T, ns_id]
                self.mat[:T, gid] = ok
        self._ng = ng
        # -- new terms: one row each, vectorized over groups --
        for tid in range(old_nt, nt):
            cat, w, _topo, ns_tuple, ns_sel, sel = it.terms.value(tid)
            self.cats[tid] = cat
            self.weights[tid] = w
            for nid in range(nns):
                self.term_ns[tid, nid] = self._ns_match(tid, nid)
            self._encode_term(tid)
            row = self.gi.match_selector(sel)
            ns_ok = self.term_ns[tid, self.gi.group_ns[:ng]]
            self.mat[tid, :ng] = row[:ng] & ns_ok
        self._nt = nt
        self._ns_epoch = ns_epoch

    def column(self, gid: int) -> "tuple":
        """(match (T,), cats (T,), weights (T,)) for one pod group."""
        nt = self._nt
        return self.mat[:nt, gid], self.cats[:nt], self.weights[:nt]
