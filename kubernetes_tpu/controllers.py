"""Control loops living beside the scheduler — the kube-controller-manager
slice the scheduling stack actually depends on (SURVEY §2.4 names the two
that interact with scheduling: disruption and tainteviction; ISSUE 9 adds
the failure-response WRITER half: nodelifecycle + podgc, closing the
node-dies → taint → grace → evict → requeue → reschedule loop).

DisruptionController: recomputes each PodDisruptionBudget's
status.disruptionsAllowed from live pod state, the way
pkg/controller/disruption/disruption.go:732 (trySync → getExpectedPodCount
→ updatePdbStatus) does, so preemption's budget accounting
(filterPodsWithPDBViolation, pickOneNodeForPreemption criterion 1) reads a
status that tracks the cluster rather than a hand-fed constant.

Formula (disruption.go:803 getExpectedPodCount, :993 updatePdbStatus):
  - maxUnavailable set: desiredHealthy = expected − scale(maxUnavailable,
    expected, round UP), floored at 0.
  - minAvailable int: desiredHealthy = minAvailable, expected = len(pods).
  - minAvailable "N%": desiredHealthy = scale(N%, expected, round UP).
  - disruptionsAllowed = max(0, currentHealthy − desiredHealthy).

Divergences (documented): expectedCount for percentage/maxUnavailable
budgets comes from the matching pods' controllers' scale upstream
(getExpectedScale walks ReplicaSet/Deployment owners); this repo has no
workload controllers, so expected = len(matching pods) — upstream's own
unmanaged-pods fallback behavior.  The disrupted-pods map (eviction-API
in-flight grace, :747 buildDisruptedPodMap) is unnecessary: evictions here
are synchronous deletes, and the preemption path's immediate decrement
(preemption.py _interpret_dryrun) models the eviction-time debit the
reference applies in the eviction subresource handler."""

from __future__ import annotations

import math

from .api import types as t


def scale_int_or_percent(value: int | str, total: int, round_up: bool) -> int:
    """intstr.GetScaledValueFromIntOrPercent: ints pass through; "N%"
    scales against ``total`` (disruption.go passes roundUp=true)."""
    if isinstance(value, int):
        return value
    s = value.strip()
    if not s.endswith("%"):
        raise ValueError(f"invalid IntOrString {value!r}: not an int or percent")
    pct = int(s[:-1])
    scaled = total * pct / 100.0
    return math.ceil(scaled) if round_up else math.floor(scaled)


class DisruptionController:
    """Recompute disruptionsAllowed for every budget that carries SPEC
    fields (min_available / max_unavailable).  Spec-less budgets keep
    their informer-fed status untouched — the wire path feeds
    status.disruptionsAllowed directly and remains authoritative for
    them."""

    def __init__(self, scheduler) -> None:
        self.sched = scheduler
        self._last_sync: tuple | None = None

    def _matching(self, pdb: t.PodDisruptionBudget) -> list:
        cache = self.sched.cache
        return [
            pr
            for pr in cache.pods.values()
            if pr.pod.namespace == pdb.namespace
            and t.label_selector_matches(pdb.selector, pr.pod.metadata.labels)
        ]

    def sync_one(self, pdb: t.PodDisruptionBudget) -> None:
        if pdb.min_available is None and pdb.max_unavailable is None:
            return  # status-managed by the informer feed
        matching = self._matching(pdb)
        expected = len(matching)
        # Healthy = running-and-ready (countHealthyPods, :909).  The
        # scheduling-level analog: a cached pod is bound or assumed onto a
        # node; queued pods are not healthy.
        healthy = sum(1 for pr in matching if pr.bound or pr.assumed)
        if pdb.max_unavailable is not None:
            mu = scale_int_or_percent(pdb.max_unavailable, expected, True)
            desired = max(0, expected - mu)
        elif isinstance(pdb.min_available, int):
            desired = pdb.min_available
        else:
            desired = scale_int_or_percent(pdb.min_available, expected, True)
        pdb.disruptions_allowed = max(0, healthy - desired)

    def sync(self) -> None:
        # Reconcile is event-driven upstream; the in-process analog gates
        # on the cache's global pod generation — an unchanged pod set (and
        # unchanged budget count) needs no rescan, so a preemption burst
        # pays one O(pods × spec-budgets) pass per batch of changes, not
        # one per attempt.
        cache = self.sched.cache
        key = (cache._pods_gen, len(self.sched.pdbs))
        if key == self._last_sync:
            return
        for pdb in self.sched.pdbs.values():
            self.sync_one(pdb)
        self._last_sync = key


class TaintEvictionController:
    """NoExecute taint eviction — pkg/controller/tainteviction/
    taint_eviction.go:84 (TaintEvictionController; processPodOnNode +
    getMinTolerationTime semantics):

      - a bound pod on a node with NoExecute taints it does NOT fully
        tolerate is evicted immediately;
      - a fully-tolerating pod whose matching tolerations carry
        tolerationSeconds is evicted after the MINIMUM of those seconds
        (a nil-seconds toleration alone means tolerate forever);
      - removing the taints cancels the pending eviction.

    In-process adaptation: upstream's per-pod timed workqueue
    (TimedWorkerQueue) becomes a deadline map ticked from the scheduler's
    batch loop (the same time-gated sweep that expires assumed pods);
    eviction is the scheduler's delete_pod — the API DELETE the upstream
    controller issues, minus the apiserver — or, when the node-lifecycle
    loop is armed (``requeue_evictions``), the scheduler's journaled
    evict_pod: binding dropped, pod re-queued unbound, the workload-
    controller-recreates-the-pod half of the production sequence this
    repo has no controllers to provide."""

    def __init__(self, scheduler) -> None:
        self.sched = scheduler
        # pod uid → (armed_at, deadline).  armed_at is the time the FIRST
        # still-present taint was judged; the deadline is min over the
        # CURRENT taints of (that taint's first-seen time + its grace),
        # so unrelated taint churn neither extends nor wrongly keeps a
        # removed taint's grace — and a taint REMOVED AND RE-ADDED gets a
        # fresh clock for its own grace (the per-taint refinement of
        # upstream's single scheduledEviction.CreatedAt, which would
        # inherit the stale timer — the re-arm gap ISSUE 9 names).
        self.pending: dict[str, tuple[float, float]] = {}
        # pod uid → {(taint key, value, effect): first-seen ts} for pods
        # with a pending eviction; read only while the uid is pending.
        self._seen: dict[str, dict[tuple, float]] = {}
        # Evict-as-requeue (armed with the node-lifecycle controller):
        # the evicted pod re-enters the queue unbound and reschedules on
        # a surviving node instead of vanishing.
        self.requeue_evictions = False
        self.evictions = 0

    def _no_execute(self, node: t.Node) -> list[t.Taint]:
        return [
            taint
            for taint in node.spec.taints
            if taint.effect == t.EFFECT_NO_EXECUTE
        ]

    def cancel(self, uid: str) -> None:
        """Drop a pending eviction and its per-taint clock — the single
        cancellation path (pod deleted, taints gone, GC of a stale
        terminating entry)."""
        self.pending.pop(uid, None)
        self._seen.pop(uid, None)

    def handle_node(self, node: t.Node, now: float | None = None) -> None:
        """Re-evaluate every pod on the node after a taint change
        (handleNodeUpdate, taint_eviction.go:331)."""
        rec = self.sched.cache.nodes.get(node.name)
        if rec is None:
            return
        taints = self._no_execute(node)
        now = self.sched._now() if now is None else now
        if not taints:
            # Taints gone: cancel pending evictions for this node's pods
            # (cancelWorkWithEvent).
            for uid in list(self.pending):
                pr = self.sched.cache.pods.get(uid)
                if pr is None or pr.node_name == node.name:
                    self.cancel(uid)
            return
        for uid, pod in list(rec.pods.items()):
            self.evaluate(uid, pod, taints, now)

    def handle_pod_assigned(self, pod: t.Pod, node_name: str) -> None:
        """A pod landed on (or arrived bound to) a node: if that node
        carries NoExecute taints, judge the pod (handlePodUpdate,
        taint_eviction.go:366)."""
        rec = self.sched.cache.nodes.get(node_name)
        if rec is None:
            return
        taints = self._no_execute(rec.node)
        if taints:
            self.evaluate(pod.uid, pod, taints, self.sched._now())

    def evaluate(
        self, uid: str, pod: t.Pod, taints: list[t.Taint], now: float
    ) -> None:
        # Per-taint judgment: each present taint contributes (first-seen
        # ts, grace) — grace = min over its MATCHING tolerations that set
        # seconds (getMinTolerationTime per taint); a taint whose matching
        # tolerations are all nil-seconds is tolerated forever and bounds
        # nothing.
        per_taint: list[tuple[tuple, float | None]] = []
        for taint in taints:
            matching = [
                tol for tol in pod.spec.tolerations if tol.tolerates(taint)
            ]
            if not matching:
                # Not fully tolerated: evict now (processPodOnNode's
                # len(usedTolerations) < len(taints) branch).
                self.cancel(uid)
                self._evict(uid)
                return
            secs = [
                tol.toleration_seconds
                for tol in matching
                if tol.toleration_seconds is not None
            ]
            tid = (taint.key, taint.value, taint.effect)
            per_taint.append((tid, min(secs) if secs else None))
        if all(grace is None for _tid, grace in per_taint):
            # Every taint tolerated forever: nothing schedules an eviction.
            self.cancel(uid)
            return
        # Each taint's clock starts at ITS first judgment while pending —
        # a re-evaluation keeps surviving taints' start times (unrelated
        # churn cannot push the eviction out), a removed taint's entry is
        # dropped (removing the short-grace taint while a longer-tolerated
        # one remains restores the longer deadline), and a taint removed
        # AND re-added re-enters with a fresh clock instead of inheriting
        # the stale timer.  A full taint removal cancelled the pending
        # entry, so a later re-taint of everything starts entirely fresh.
        prev_seen = self._seen.get(uid, {}) if uid in self.pending else {}
        seen: dict[tuple, float] = {}
        deadlines: list[float] = []
        for tid, grace in per_taint:
            first = prev_seen.get(tid, now)
            seen[tid] = first
            if grace is not None:
                deadlines.append(first + max(0.0, grace))
        self._seen[uid] = seen
        self.pending[uid] = (min(seen.values()), min(deadlines))

    def tick(self, now: float | None = None) -> int:
        """Fire due evictions; returns how many fired."""
        now = self.sched._now() if now is None else now
        due = [uid for uid, (_, dl) in self.pending.items() if dl <= now]
        for uid in due:
            self.cancel(uid)
            self._evict(uid)
        return len(due)

    def _evict(self, uid: str) -> None:
        if uid in self.sched.cache.pods:
            self.evictions += 1
            if self.requeue_evictions:
                self.sched.evict_pod(uid, reason="taint-eviction")
            else:
                self.sched.delete_pod(uid)


# ---------------------------------------------------------------------------
# NodeLifecycleController — the taint WRITER half of the failure-response
# loop (pkg/controller/nodelifecycle/node_lifecycle_controller.go)
# ---------------------------------------------------------------------------

# Upstream's condition taints (node_lifecycle_controller.go:64
# UnreachableTaintTemplate / NotReadyTaintTemplate; the NoSchedule pair is
# the condition-based taint loop, doNoScheduleTaintingPass).
NOT_READY_TAINT_KEY = "node.kubernetes.io/not-ready"
UNREACHABLE_TAINT_KEY = "node.kubernetes.io/unreachable"
LIFECYCLE_TAINT_KEYS = frozenset(
    {NOT_READY_TAINT_KEY, UNREACHABLE_TAINT_KEY}
)

NODE_READY = "ready"
NODE_NOT_READY = "notready"
NODE_UNREACHABLE = "unreachable"


def lifecycle_taints(state: str) -> tuple[t.Taint, ...]:
    """The taint pair a lifecycle state implies: the NoSchedule condition
    taint plus the NoExecute eviction trigger (TaintBasedEvictions)."""
    key = {
        NODE_NOT_READY: NOT_READY_TAINT_KEY,
        NODE_UNREACHABLE: UNREACHABLE_TAINT_KEY,
    }.get(state)
    if key is None:
        return ()
    return (
        t.Taint(key, "", t.EFFECT_NO_SCHEDULE),
        t.Taint(key, "", t.EFFECT_NO_EXECUTE),
    )


def state_from_taints(taints: tuple[t.Taint, ...]) -> str:
    """Derive the lifecycle state a node's taints encode — the recovery
    path's state source (journal replay re-applies the taints; the
    controller must not re-write them or re-count the transition)."""
    keys = {taint.key for taint in taints}
    if UNREACHABLE_TAINT_KEY in keys:
        return NODE_UNREACHABLE
    if NOT_READY_TAINT_KEY in keys:
        return NODE_NOT_READY
    return NODE_READY


class NodeLifecycleController:
    """Track per-node heartbeat freshness from wire-fed Lease renewals and
    write the NotReady/Unreachable taints through the scheduler's
    JOURNALED update path (scheduler.write_node_taints — WAL discipline,
    so a crash mid-transition replays deterministically).

    Clock model: liveness is judged on a LOGICAL clock — the high-water
    mark of every Lease ``renew_time`` the feed delivered — not wall
    time.  A node is stale when OTHER nodes' renewals have advanced the
    clock past its own last renewal + grace.  That makes the whole
    failure-response sequence a pure function of the operation stream:
    the soak's virtual and real pacing modes, and a crash-recovery
    replay, all transition at the identical points (the determinism the
    chaos harness's bit-identical-reschedule oracle needs).  Upstream
    gets the same effect from the apiserver's single clock stamping every
    Lease renewal.

    Disarmed (the default) the controller only records renewals: nodes
    are never tainted, so embedders that don't feed Leases keep the
    pre-lifecycle behavior.  ``arm()`` enables transitions and flips the
    TaintEvictionController to evict-as-requeue (the full production
    sequence: staleness → taint → tolerationSeconds grace → eviction →
    requeue → reschedule on a surviving node)."""

    def __init__(
        self,
        scheduler,
        grace_period_s: float = 40.0,
        unreachable_after_s: float = 100.0,
    ) -> None:
        self.sched = scheduler
        # Upstream defaults: node-monitor-grace-period 40s; the
        # unreachable horizon has no single upstream knob (Ready=Unknown
        # is immediate once the grace lapses) — ours staggers the two
        # states so both transitions are observable.
        self.grace_period_s = grace_period_s
        self.unreachable_after_s = unreachable_after_s
        self.armed = False
        # node name → last Lease renew_time (the feed's clock domain).
        self.heartbeats: dict[str, float] = {}
        # node name → lifecycle state (absent == ready).
        self.states: dict[str, str] = {}
        self._hw = 0.0  # logical-clock high-water mark
        self.transitions = 0

    def arm(
        self,
        grace_period_s: float | None = None,
        unreachable_after_s: float | None = None,
    ) -> None:
        if grace_period_s is not None:
            self.grace_period_s = grace_period_s
        if unreachable_after_s is not None:
            self.unreachable_after_s = unreachable_after_s
        if self.unreachable_after_s < self.grace_period_s:
            self.unreachable_after_s = self.grace_period_s
        self.armed = True
        # Evictions feed the requeue path: the evicted pod reschedules
        # elsewhere (this repo has no workload controllers to recreate it).
        self.sched.taint_eviction.requeue_evictions = True

    def now(self) -> float:
        return self._hw

    # -- feed --------------------------------------------------------------

    def renew(self, name: str, ts: float) -> None:
        """One Lease renewal (scheduler.renew_node_lease).  Renewals are
        monotone per node (a stale replayed Lease cannot rewind the
        clock); the fleet re-judges when the renewal ADVANCES the logical
        clock — the tick is op-driven, not timer-driven.  A same-stamp
        renewal (the rest of a heartbeat round) skips the fleet scan:
        judging an identical clock again is O(N) of no-ops per node, an
        O(N²) round at fleet scale — unless the renewing node itself was
        non-ready (its fresh heartbeat is the recovery the tick must
        write).  Deterministic either way: the skip is a pure function
        of (ts, states)."""
        if ts > self.heartbeats.get(name, -1.0):
            self.heartbeats[name] = ts
        advanced = ts > self._hw
        if advanced:
            self._hw = ts
        if self.armed and (
            advanced or self.states.get(name, NODE_READY) != NODE_READY
        ):
            self.tick()

    def observe_node(self, node: t.Node) -> None:
        """A Node add/update delivered its CURRENT taints: adopt the
        lifecycle state they encode (recovery replay re-applies our taint
        writes through this path; re-writing or re-counting the
        transition would diverge the journal from the uninterrupted
        run).  The GC's unreachable clock follows the adoption — a
        recovered dead node must still age toward the GC horizon."""
        state = state_from_taints(node.spec.taints)
        if state == NODE_READY:
            self.states.pop(node.name, None)
        else:
            self.states[node.name] = state
        self.sched.pod_gc.note_state(node.name, state, self._hw)
        # Journal-recovered transition stamps (journal.recover): adoption
        # happens at the RE-FEED's clock, but the GC horizon's zero point
        # is the recorded transition clock — a takeover restoring
        # heartbeats by Lease relist (not schedule re-derivation) must
        # not age a dead node from the feed time and sweep late.
        stamps = getattr(self.sched, "_recovered_taint_stamps", None)
        if stamps:
            rec = stamps.get(node.name)
            if rec is not None and rec[1] == state:
                stamps.pop(node.name, None)
                if state == NODE_UNREACHABLE:
                    since = self.sched.pod_gc._unreachable_since
                    cur = since.get(node.name)
                    if cur is None or rec[2] < cur:
                        since[node.name] = rec[2]

    def forget_node(self, name: str) -> None:
        self.heartbeats.pop(name, None)
        self.states.pop(name, None)

    # -- transitions -------------------------------------------------------

    def tick(self, now: float | None = None) -> int:
        """Judge every leased node against the logical clock and write
        the implied taint transitions; then run the consumers that share
        this clock (taint eviction deadlines, the pod-GC sweep).  Returns
        the number of transitions applied."""
        if not self.armed:
            return 0
        if now is not None and now > self._hw:
            self._hw = now
        now = self._hw
        fired = 0
        for name in sorted(self.heartbeats):
            if name not in self.sched.cache.nodes:
                continue
            age = now - self.heartbeats[name]
            if age <= self.grace_period_s:
                target = NODE_READY
            elif age <= self.unreachable_after_s:
                target = NODE_NOT_READY
            else:
                target = NODE_UNREACHABLE
            if self.states.get(name, NODE_READY) != target:
                self._transition(name, target, now)
                fired += 1
        # The downstream consumers tick on the same clock, in causal
        # order: taints just written arm deadlines (handle_node inside
        # the update path), due deadlines evict, and the GC sweeps what
        # eviction cannot reach (tolerate-forever pods on long-dead
        # nodes, stale terminating entries).
        self.sched.taint_eviction.tick(now)
        self.sched.pod_gc.sweep(now)
        return fired

    def _transition(self, name: str, target: str, now: float) -> None:
        rec = self.sched.cache.nodes.get(name)
        if rec is None:
            return
        keep = tuple(
            taint
            for taint in rec.node.spec.taints
            if taint.key not in LIFECYCLE_TAINT_KEYS
        )
        self.sched.write_node_taints(
            name, keep + lifecycle_taints(target), reason=f"lifecycle:{target}"
        )
        if target == NODE_READY:
            self.states.pop(name, None)
        else:
            self.states[name] = target
        self.transitions += 1
        self.sched._note_lifecycle_transition(target)
        self.sched.pod_gc.note_state(name, target, now)
        flight = getattr(self.sched, "flight", None)
        if flight is not None:
            flight.record_marker(
                "node_lifecycle",
                node=name,
                to=target,
                heartbeat=self.heartbeats.get(name, 0.0),
                logical_now=now,
            )
            if target == NODE_UNREACHABLE:
                # A node death is an incident: shed the evidence the way
                # engine faults and breaker trips do.
                flight.dump("node-unreachable")

    def stats(self) -> dict:
        counts = {NODE_READY: 0, NODE_NOT_READY: 0, NODE_UNREACHABLE: 0}
        # Heartbeat-tracked nodes PLUS nodes whose state was adopted
        # from taints before any renewal arrived (a takeover's relist,
        # a survivor's mid-incident absorb): `fleet status` must report
        # an adopted dead node as unreachable, not omit it.
        for name in sorted(set(self.heartbeats) | set(self.states)):
            counts[self.states.get(name, NODE_READY)] += 1
        return {
            "armed": self.armed,
            "grace_period_s": self.grace_period_s,
            "unreachable_after_s": self.unreachable_after_s,
            "logical_now": self._hw,
            "tracked": len(self.heartbeats),
            "transitions": self.transitions,
            "states": counts,
        }


class PodGCController:
    """The podgc slice (pkg/controller/podgc/gc_controller.go) this
    scheduler actually needs — the sweeps that reclaim pods the taint
    path cannot:

    - **unreachable** (gcOrphaned's spirit): pods bound to a node that
      has been Unreachable past ``gc_horizon_s`` — tolerate-forever pods
      a NoExecute eviction never touches — are evicted through the
      journaled requeue path (upstream force-deletes and lets the
      workload controller recreate; with no controllers here, requeue IS
      the recreate).
    - **orphaned**: recovery bindings whose node never relisted
      (informers.reconcile_after_recovery) requeue instead of silently
      dropping — the journal said these pods existed; losing the node
      must not lose the pods.
    - **terminating** (gcUnscheduledTerminating's analog): pending
      taint-eviction deadlines whose pod vanished with its node — stale
      timers that would otherwise leak until they misfire against a
      recreated uid."""

    def __init__(self, scheduler, gc_horizon_s: float = 300.0) -> None:
        self.sched = scheduler
        self.gc_horizon_s = gc_horizon_s
        self.armed = False
        self.collected = {"unreachable": 0, "orphaned": 0, "terminating": 0}
        # node name → logical ts of its transition to Unreachable.
        self._unreachable_since: dict[str, float] = {}

    def arm(self, gc_horizon_s: float | None = None) -> None:
        if gc_horizon_s is not None:
            self.gc_horizon_s = gc_horizon_s
        self.armed = True

    def note_state(self, name: str, state: str, now: float) -> None:
        if state == NODE_UNREACHABLE:
            self._unreachable_since.setdefault(name, now)
        else:
            self._unreachable_since.pop(name, None)

    def forget_node(self, name: str) -> None:
        self._unreachable_since.pop(name, None)

    def _collect(self, reason: str) -> None:
        self.collected[reason] += 1
        self.sched._note_pod_gc(reason)

    def collect_orphan(self, uid: str, pod: t.Pod) -> None:
        """A recovered journal binding whose node never relisted: the
        node is gone, the pod is not — journal the eviction and requeue
        it unbound (reconcile_after_recovery's drop leg routes here)."""
        self.sched.evict_pod(uid, reason="pod-gc-orphaned", pod=pod)
        self._collect("orphaned")

    def sweep(self, now: float) -> int:
        """Run the GC legs; returns pods collected this sweep."""
        if not self.armed:
            return 0
        n = 0
        cache = self.sched.cache
        for name in sorted(self._unreachable_since):
            if now - self._unreachable_since[name] < self.gc_horizon_s:
                continue
            rec = cache.nodes.get(name)
            if rec is None:
                self._unreachable_since.pop(name, None)
                continue
            for uid in sorted(rec.pods):
                self.sched.evict_pod(uid, reason="pod-gc-unreachable")
                self._collect("unreachable")
                n += 1
        tec = self.sched.taint_eviction
        for uid in list(tec.pending):
            if uid not in cache.pods:
                tec.cancel(uid)
                self._collect("terminating")
        return n

    def stats(self) -> dict:
        return {
            "armed": self.armed,
            "gc_horizon_s": self.gc_horizon_s,
            "collected": dict(self.collected),
            "unreachable_nodes": sorted(self._unreachable_since),
        }
