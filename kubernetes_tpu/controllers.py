"""Control loops living beside the scheduler — the kube-controller-manager
slice the scheduling stack actually depends on (SURVEY §2.4 names the two
that interact with scheduling: disruption and tainteviction).

DisruptionController: recomputes each PodDisruptionBudget's
status.disruptionsAllowed from live pod state, the way
pkg/controller/disruption/disruption.go:732 (trySync → getExpectedPodCount
→ updatePdbStatus) does, so preemption's budget accounting
(filterPodsWithPDBViolation, pickOneNodeForPreemption criterion 1) reads a
status that tracks the cluster rather than a hand-fed constant.

Formula (disruption.go:803 getExpectedPodCount, :993 updatePdbStatus):
  - maxUnavailable set: desiredHealthy = expected − scale(maxUnavailable,
    expected, round UP), floored at 0.
  - minAvailable int: desiredHealthy = minAvailable, expected = len(pods).
  - minAvailable "N%": desiredHealthy = scale(N%, expected, round UP).
  - disruptionsAllowed = max(0, currentHealthy − desiredHealthy).

Divergences (documented): expectedCount for percentage/maxUnavailable
budgets comes from the matching pods' controllers' scale upstream
(getExpectedScale walks ReplicaSet/Deployment owners); this repo has no
workload controllers, so expected = len(matching pods) — upstream's own
unmanaged-pods fallback behavior.  The disrupted-pods map (eviction-API
in-flight grace, :747 buildDisruptedPodMap) is unnecessary: evictions here
are synchronous deletes, and the preemption path's immediate decrement
(preemption.py _interpret_dryrun) models the eviction-time debit the
reference applies in the eviction subresource handler."""

from __future__ import annotations

import math
import time

from .api import types as t


def scale_int_or_percent(value: int | str, total: int, round_up: bool) -> int:
    """intstr.GetScaledValueFromIntOrPercent: ints pass through; "N%"
    scales against ``total`` (disruption.go passes roundUp=true)."""
    if isinstance(value, int):
        return value
    s = value.strip()
    if not s.endswith("%"):
        raise ValueError(f"invalid IntOrString {value!r}: not an int or percent")
    pct = int(s[:-1])
    scaled = total * pct / 100.0
    return math.ceil(scaled) if round_up else math.floor(scaled)


class DisruptionController:
    """Recompute disruptionsAllowed for every budget that carries SPEC
    fields (min_available / max_unavailable).  Spec-less budgets keep
    their informer-fed status untouched — the wire path feeds
    status.disruptionsAllowed directly and remains authoritative for
    them."""

    def __init__(self, scheduler) -> None:
        self.sched = scheduler
        self._last_sync: tuple | None = None

    def _matching(self, pdb: t.PodDisruptionBudget) -> list:
        cache = self.sched.cache
        return [
            pr
            for pr in cache.pods.values()
            if pr.pod.namespace == pdb.namespace
            and t.label_selector_matches(pdb.selector, pr.pod.metadata.labels)
        ]

    def sync_one(self, pdb: t.PodDisruptionBudget) -> None:
        if pdb.min_available is None and pdb.max_unavailable is None:
            return  # status-managed by the informer feed
        matching = self._matching(pdb)
        expected = len(matching)
        # Healthy = running-and-ready (countHealthyPods, :909).  The
        # scheduling-level analog: a cached pod is bound or assumed onto a
        # node; queued pods are not healthy.
        healthy = sum(1 for pr in matching if pr.bound or pr.assumed)
        if pdb.max_unavailable is not None:
            mu = scale_int_or_percent(pdb.max_unavailable, expected, True)
            desired = max(0, expected - mu)
        elif isinstance(pdb.min_available, int):
            desired = pdb.min_available
        else:
            desired = scale_int_or_percent(pdb.min_available, expected, True)
        pdb.disruptions_allowed = max(0, healthy - desired)

    def sync(self) -> None:
        # Reconcile is event-driven upstream; the in-process analog gates
        # on the cache's global pod generation — an unchanged pod set (and
        # unchanged budget count) needs no rescan, so a preemption burst
        # pays one O(pods × spec-budgets) pass per batch of changes, not
        # one per attempt.
        cache = self.sched.cache
        key = (cache._pods_gen, len(self.sched.pdbs))
        if key == self._last_sync:
            return
        for pdb in self.sched.pdbs.values():
            self.sync_one(pdb)
        self._last_sync = key


class TaintEvictionController:
    """NoExecute taint eviction — pkg/controller/tainteviction/
    taint_eviction.go:84 (TaintEvictionController; processPodOnNode +
    getMinTolerationTime semantics):

      - a bound pod on a node with NoExecute taints it does NOT fully
        tolerate is evicted immediately;
      - a fully-tolerating pod whose matching tolerations carry
        tolerationSeconds is evicted after the MINIMUM of those seconds
        (a nil-seconds toleration alone means tolerate forever);
      - removing the taints cancels the pending eviction.

    In-process adaptation: upstream's per-pod timed workqueue
    (TimedWorkerQueue) becomes a deadline map ticked from the scheduler's
    batch loop (the same time-gated sweep that expires assumed pods);
    eviction is the scheduler's delete_pod — the API DELETE the upstream
    controller issues, minus the apiserver."""

    def __init__(self, scheduler) -> None:
        self.sched = scheduler
        # pod uid → (armed_at, deadline).  armed_at is the time the FIRST
        # judgment scheduled the eviction (upstream's
        # scheduledEviction.CreatedAt); re-evaluations recompute the
        # deadline from it with the CURRENT taint set, so unrelated taint
        # churn neither extends nor wrongly keeps a removed taint's grace.
        self.pending: dict[str, tuple[float, float]] = {}
        self.evictions = 0

    def _no_execute(self, node: t.Node) -> list[t.Taint]:
        return [
            taint
            for taint in node.spec.taints
            if taint.effect == t.EFFECT_NO_EXECUTE
        ]

    def handle_node(self, node: t.Node, now: float | None = None) -> None:
        """Re-evaluate every pod on the node after a taint change
        (handleNodeUpdate, taint_eviction.go:331)."""
        rec = self.sched.cache.nodes.get(node.name)
        if rec is None:
            return
        taints = self._no_execute(node)
        now = time.monotonic() if now is None else now
        if not taints:
            # Taints gone: cancel pending evictions for this node's pods
            # (cancelWorkWithEvent).
            for uid in list(self.pending):
                pr = self.sched.cache.pods.get(uid)
                if pr is None or pr.node_name == node.name:
                    self.pending.pop(uid, None)
            return
        for uid, pod in list(rec.pods.items()):
            self.evaluate(uid, pod, taints, now)

    def handle_pod_assigned(self, pod: t.Pod, node_name: str) -> None:
        """A pod landed on (or arrived bound to) a node: if that node
        carries NoExecute taints, judge the pod (handlePodUpdate,
        taint_eviction.go:366)."""
        rec = self.sched.cache.nodes.get(node_name)
        if rec is None:
            return
        taints = self._no_execute(rec.node)
        if taints:
            self.evaluate(pod.uid, pod, taints, time.monotonic())

    def evaluate(
        self, uid: str, pod: t.Pod, taints: list[t.Taint], now: float
    ) -> None:
        used: list[t.Toleration] = []
        for taint in taints:
            matching = [
                tol for tol in pod.spec.tolerations if tol.tolerates(taint)
            ]
            if not matching:
                # Not fully tolerated: evict now (processPodOnNode's
                # len(usedTolerations) < len(taints) branch).
                self.pending.pop(uid, None)
                self._evict(uid)
                return
            used.extend(matching)
        # getMinTolerationTime: min over the used tolerations that SET
        # seconds; none set = tolerate forever.
        secs = [
            tol.toleration_seconds
            for tol in used
            if tol.toleration_seconds is not None
        ]
        if not secs:
            self.pending.pop(uid, None)
            return
        # Deadline = armed_at + min(current graces): the clock starts at
        # the FIRST judgment (processPodOnNode keeps
        # scheduledEviction.CreatedAt across re-evaluations, so unrelated
        # taint churn cannot push the eviction out), while the grace is
        # recomputed against the CURRENT taint set (removing the
        # short-grace taint while a longer-tolerated one remains restores
        # the longer deadline).  A full taint removal cleared pending, so
        # a later re-taint starts a fresh clock.
        prev = self.pending.get(uid)
        armed_at = prev[0] if prev is not None else now
        self.pending[uid] = (armed_at, armed_at + max(0.0, min(secs)))

    def tick(self, now: float | None = None) -> int:
        """Fire due evictions; returns how many fired."""
        now = time.monotonic() if now is None else now
        due = [uid for uid, (_, dl) in self.pending.items() if dl <= now]
        for uid in due:
            self.pending.pop(uid, None)
            self._evict(uid)
        return len(due)

    def _evict(self, uid: str) -> None:
        if uid in self.sched.cache.pods:
            self.evictions += 1
            self.sched.delete_pod(uid)
