"""CEL device-selector compilation — the vectorizable subset.

The reference evaluates request selectors as CEL programs over
``device.attributes`` (staging/src/k8s.io/dynamic-resource-allocation/cel/
compile.go; expressions like ``device.attributes["gpu.example.com/memory"]
.int >= 40`` — see structured/allocator_test.go and
dynamicresources_test.go:117).  Full CEL cannot run on device; this build
takes the NodeAffinity playbook (compiled requirement programs): the
selector grammar below — attribute comparisons joined by ``&&`` — compiles
once into requirement tuples evaluated host-side per DEVICE when selector
POOLS are (re)computed, so the per-pod/per-node hot path only reads pool
count columns.  Anything outside the subset is a hard config error, not a
silent mismatch (the reference likewise fails allocation on CEL compile
errors, allocator.go:159).

Grammar (conjunction of terms):

    expr     := term ("&&" term)*
    term     := attr [accessor] op literal
              | attr [".bool"]                (truthy)
              | "!" attr [".bool"]
              | STRING "in" "device.attributes"
              | "!(" STRING "in device.attributes" ")"
    attr     := device.attributes["KEY"]
    accessor := .bool | .int | .string
    op       := == | != | >= | <= | > | < | in
    literal  := int | "string" | true | false | [literal, ...]

CEL semantics note: a missing attribute makes the reference's expression
error, which the allocator treats as the device not matching; here a term
over a missing key evaluates false, the same observable outcome."""

from __future__ import annotations

import re
from dataclasses import dataclass

_ATTR = r'device\.attributes\["(?P<key>[^"\]]+)"\](?:\.(?P<acc>bool|int|string))?'
_LIT = r"""(?P<num>-?\d+)|"(?P<str>[^"]*)"|(?P<bool>true|false)|(?P<list>\[[^\]]*\])"""
_TERM_CMP = re.compile(
    rf"^{_ATTR}\s*(?P<op>==|!=|>=|<=|>|<|\bin\b)\s*(?:{_LIT})$"
)
_TERM_TRUTHY = re.compile(rf"^(?P<neg>!\s*)?{_ATTR}$")
_TERM_EXISTS = re.compile(
    r'^(?P<neg>!\s*\(\s*)?"(?P<key>[^"]+)"\s+in\s+device\.attributes\s*(?(neg)\))$'
)


def _same_kind(a, b) -> bool:
    """bool and int are distinct CEL types (True must not equal 1)."""
    return isinstance(a, bool) == isinstance(b, bool)


@dataclass(frozen=True)
class Requirement:
    """One compiled term: ``key op value`` over a device's attributes."""

    key: str
    op: str  # Eq | Ne | Ge | Le | Gt | Lt | In | Exists | DoesNotExist | Truthy | Falsy
    values: tuple = ()

    def matches(self, attrs: dict) -> bool:
        present = self.key in attrs
        if self.op == "Exists":
            return present
        if self.op == "DoesNotExist":
            return not present
        if not present:
            return False  # CEL errors on missing attrs → device no-match
        v = attrs[self.key]
        if self.op == "Truthy":
            return v is True
        if self.op == "Falsy":
            return v is False
        # CEL is type-strict: bool-vs-int comparisons type-error, which the
        # allocator reads as no-match (Python's True == 1 must not leak in,
        # and a type-error makes Ne false too, not true).
        if self.op == "Eq":
            return _same_kind(v, self.values[0]) and v == self.values[0]
        if self.op == "Ne":
            return _same_kind(v, self.values[0]) and v != self.values[0]
        if self.op == "In":
            return any(_same_kind(v, w) and v == w for w in self.values)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            return False  # ordered ops need numbers
        w = self.values[0]
        return (
            v >= w if self.op == "Ge"
            else v <= w if self.op == "Le"
            else v > w if self.op == "Gt"
            else v < w
        )


def _parse_literal(m: re.Match):
    if m.group("num") is not None:
        return int(m.group("num"))
    if m.group("str") is not None:
        return m.group("str")
    if m.group("bool") is not None:
        return m.group("bool") == "true"
    inner = m.group("list")[1:-1].strip()
    vals = []
    for part in re.findall(r'-?\d+|"[^"]*"', inner):
        vals.append(part[1:-1] if part.startswith('"') else int(part))
    return tuple(vals)


_OPS = {"==": "Eq", "!=": "Ne", ">=": "Ge", "<=": "Le", ">": "Gt", "<": "Lt", "in": "In"}


def compile_selector(expr: str) -> tuple[Requirement, ...]:
    """Compile one CEL selector expression into requirement tuples.
    Raises ValueError outside the supported subset."""
    reqs: list[Requirement] = []
    for raw in _split_conjunction(expr):
        term = raw.strip()
        if not term:
            raise ValueError(f"empty term in CEL selector {expr!r}")
        m = _TERM_CMP.match(term)
        if m:
            lit = _parse_literal(m)
            op = _OPS[m.group("op")]
            if op == "In":
                if not isinstance(lit, tuple):
                    raise ValueError(f"'in' needs a list literal: {term!r}")
                reqs.append(Requirement(m.group("key"), "In", lit))
            else:
                acc = m.group("acc")
                if acc == "int" and not isinstance(lit, int):
                    raise ValueError(f".int compared to non-int: {term!r}")
                if acc == "string" and not isinstance(lit, str):
                    raise ValueError(f".string compared to non-string: {term!r}")
                if acc == "bool" and not isinstance(lit, bool):
                    raise ValueError(f".bool compared to non-bool: {term!r}")
                if op in ("Ge", "Le", "Gt", "Lt") and not isinstance(lit, int):
                    raise ValueError(f"ordered compare needs an int: {term!r}")
                reqs.append(Requirement(m.group("key"), op, (lit,)))
            continue
        m = _TERM_EXISTS.match(term)
        if m:
            reqs.append(
                Requirement(
                    m.group("key"),
                    "DoesNotExist" if m.group("neg") else "Exists",
                )
            )
            continue
        m = _TERM_TRUTHY.match(term)
        if m:
            if m.group("acc") not in (None, "bool"):
                raise ValueError(f"bare attribute term must be bool: {term!r}")
            reqs.append(
                Requirement(m.group("key"), "Falsy" if m.group("neg") else "Truthy")
            )
            continue
        raise ValueError(
            f"CEL selector term outside the vectorizable subset: {term!r}"
        )
    return tuple(reqs)


def _split_conjunction(expr: str) -> list[str]:
    """Split on && outside quotes/brackets (no precedence — the subset has
    no ||)."""
    if "||" in expr:
        raise ValueError(f"'||' is outside the vectorizable subset: {expr!r}")
    parts, depth, quote, start = [], 0, False, 0
    i = 0
    while i < len(expr):
        c = expr[i]
        if c == '"':
            quote = not quote
        elif not quote and c in "([":
            depth += 1
        elif not quote and c in ")]":
            depth -= 1
        elif not quote and depth == 0 and expr.startswith("&&", i):
            parts.append(expr[start:i])
            i += 2
            start = i
            continue
        i += 1
    parts.append(expr[start:])
    return parts


def canonical(selectors: tuple[str, ...]) -> str:
    """Canonical signature of a selector set for pool interning: the sorted
    requirement tuples, so differently-written equivalent selectors share a
    pool."""
    reqs: list[Requirement] = []
    for s in selectors:
        reqs.extend(compile_selector(s))
    return ";".join(
        f"{r.key}\x00{r.op}\x00{','.join(map(repr, r.values))}"
        for r in sorted(reqs, key=lambda r: (r.key, r.op, r.values))
    )


def matches(reqs: tuple[Requirement, ...], attrs: dict) -> bool:
    return all(r.matches(attrs) for r in reqs)
