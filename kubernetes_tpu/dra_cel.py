"""CEL device-selector compilation — the vectorizable subset, in DNF.

The reference evaluates request selectors as CEL programs over
``device.attributes`` / ``device.capacity``
(staging/src/k8s.io/dynamic-resource-allocation/cel/compile.go;
expressions like ``device.attributes["gpu.example.com/memory"].int >= 40``
or ``device.capacity["mem"].isGreaterThan(quantity("10Gi"))`` — see
cel/compile_test.go and dynamicresources_test.go:117).  Full CEL cannot
run on device; this build takes the NodeAffinity playbook (compiled
requirement programs): the selector grammar below compiles once into a
DISJUNCTIVE NORMAL FORM — a union of conjunction branches — evaluated
host-side per DEVICE when selector POOLS are (re)computed, so the
per-pod/per-node hot path only reads pool count columns.  ``||`` maps
onto the pool machinery as the union of compilable branches (a device
matches when ANY branch's requirements all hold); parentheses group.

Grammar:

    or_expr  := and_expr ("||" and_expr)*
    and_expr := unit ("&&" unit)*
    unit     := "(" or_expr ")" | term
    term     := attr [accessor] op literal
              | attr [".bool"]                  (truthy)
              | "!" attr [".bool"]
              | STRING "in" "device.attributes"
              | "!(" STRING "in device.attributes" ")"
              | cap ".isGreaterThan(" qty ")"   (likewise isLessThan,
                                                 isEqualTo)
              | cap op qty                      (==, !=, >=, <=, >, <)
              | STRING "in" "device.capacity"
              | "!(" STRING "in device.capacity" ")"
    attr     := device.attributes["KEY"]
    cap      := device.capacity["KEY"]
    qty      := quantity("QUANTITY")
    accessor := .bool | .int | .string
    op       := == | != | >= | <= | > | < | in
    literal  := int | "string" | true | false | [literal, ...]

Capacity values are canonical integers (types.parse_quantity units — the
same canonicalization every quantity in the object model gets), stored
beside attributes under reserved ``capacity://KEY`` keys ("//" never
appears in attribute names), so capacity terms reuse the ordered
requirement machinery unchanged.

Residue — still hard config errors, deliberately (the reference
likewise fails allocation on CEL compile errors, allocator.go:159):
``semver()`` comparisons, string functions (startsWith/endsWith/matches),
``cel.bind``, ``device.driver``, nested domain access
(``device.attributes["domain"].field``), and arithmetic.  These do not
appear in the scheduler-perf/dynamicresources test workloads; the common
capacity/attribute/disjunction forms above all compile.

CEL semantics note: a missing attribute makes the reference's expression
error, which the allocator treats as the device not matching; here a term
over a missing key evaluates false, the same observable outcome."""

from __future__ import annotations

import re
from dataclasses import dataclass

from .api.types import parse_quantity

_ATTR = r'device\.attributes\["(?P<key>[^"\]]+)"\](?:\.(?P<acc>bool|int|string))?'
_LIT = r"""(?P<num>-?\d+)|"(?P<str>[^"]*)"|(?P<bool>true|false)|(?P<list>\[[^\]]*\])"""
_TERM_CMP = re.compile(
    rf"^{_ATTR}\s*(?P<op>==|!=|>=|<=|>|<|\bin\b)\s*(?:{_LIT})$"
)
_TERM_TRUTHY = re.compile(rf"^(?P<neg>!\s*)?{_ATTR}$")
_TERM_EXISTS = re.compile(
    r'^(?P<neg>!\s*\(\s*)?"(?P<key>[^"]+)"\s+in\s+device\.attributes\s*(?(neg)\))$'
)
_CAP = r'device\.capacity\["(?P<key>[^"\]]+)"\]'
_QTY = r'quantity\(\s*"(?P<qty>[^"]+)"\s*\)'
_TERM_CAP_CMP = re.compile(
    rf"^{_CAP}\s*(?P<op>==|!=|>=|<=|>|<)\s*{_QTY}$"
)
_TERM_CAP_FN2 = re.compile(
    rf"^{_CAP}\.(?P<fn>isGreaterThan|isLessThan|isEqualTo)\(\s*{_QTY}\s*\)$"
)
_TERM_CAP_EXISTS = re.compile(
    r'^(?P<neg>!\s*\(\s*)?"(?P<key>[^"]+)"\s+in\s+device\.capacity\s*(?(neg)\))$'
)

# Reserved key prefix for capacity entries in the merged per-device dict
# (dra.py add_slice): attribute names never contain "//".
CAPACITY_PREFIX = "capacity://"

# DNF expansion bound: branches multiply across &&-joined groups; past
# this the expression is adversarial, not a workload.
MAX_BRANCHES = 64


def _same_kind(a, b) -> bool:
    """bool and int are distinct CEL types (True must not equal 1)."""
    return isinstance(a, bool) == isinstance(b, bool)


@dataclass(frozen=True)
class Requirement:
    """One compiled term: ``key op value`` over a device's attributes
    (capacity terms carry the ``capacity://`` key prefix)."""

    key: str
    op: str  # Eq | Ne | Ge | Le | Gt | Lt | In | Exists | DoesNotExist | Truthy | Falsy
    values: tuple = ()

    def matches(self, attrs: dict) -> bool:
        present = self.key in attrs
        if self.op == "Exists":
            return present
        if self.op == "DoesNotExist":
            return not present
        if not present:
            return False  # CEL errors on missing attrs → device no-match
        v = attrs[self.key]
        if self.op == "Truthy":
            return v is True
        if self.op == "Falsy":
            return v is False
        # CEL is type-strict: bool-vs-int comparisons type-error, which the
        # allocator reads as no-match (Python's True == 1 must not leak in,
        # and a type-error makes Ne false too, not true).
        if self.op == "Eq":
            return _same_kind(v, self.values[0]) and v == self.values[0]
        if self.op == "Ne":
            return _same_kind(v, self.values[0]) and v != self.values[0]
        if self.op == "In":
            return any(_same_kind(v, w) and v == w for w in self.values)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            return False  # ordered ops need numbers
        w = self.values[0]
        return (
            v >= w if self.op == "Ge"
            else v <= w if self.op == "Le"
            else v > w if self.op == "Gt"
            else v < w
        )


def _parse_literal(m: re.Match):
    if m.group("num") is not None:
        return int(m.group("num"))
    if m.group("str") is not None:
        return m.group("str")
    if m.group("bool") is not None:
        return m.group("bool") == "true"
    inner = m.group("list")[1:-1].strip()
    vals = []
    for part in re.findall(r'-?\d+|"[^"]*"', inner):
        vals.append(part[1:-1] if part.startswith('"') else int(part))
    return tuple(vals)


_OPS = {"==": "Eq", "!=": "Ne", ">=": "Ge", "<=": "Le", ">": "Gt", "<": "Lt", "in": "In"}
_CAP_FNS = {"isGreaterThan": "Gt", "isLessThan": "Lt", "isEqualTo": "Eq"}

Branch = tuple  # tuple[Requirement, ...]


def _compile_term(term: str) -> Requirement:
    m = _TERM_CMP.match(term)
    if m:
        lit = _parse_literal(m)
        op = _OPS[m.group("op")]
        if op == "In":
            if not isinstance(lit, tuple):
                raise ValueError(f"'in' needs a list literal: {term!r}")
            return Requirement(m.group("key"), "In", lit)
        acc = m.group("acc")
        if acc == "int" and not isinstance(lit, int):
            raise ValueError(f".int compared to non-int: {term!r}")
        if acc == "string" and not isinstance(lit, str):
            raise ValueError(f".string compared to non-string: {term!r}")
        if acc == "bool" and not isinstance(lit, bool):
            raise ValueError(f".bool compared to non-bool: {term!r}")
        if op in ("Ge", "Le", "Gt", "Lt") and not isinstance(lit, int):
            raise ValueError(f"ordered compare needs an int: {term!r}")
        return Requirement(m.group("key"), op, (lit,))
    m = _TERM_CAP_FN2.match(term)
    if m:
        q = parse_quantity(m.group("qty"))
        return Requirement(
            CAPACITY_PREFIX + m.group("key"), _CAP_FNS[m.group("fn")], (q,)
        )
    m = _TERM_CAP_CMP.match(term)
    if m:
        q = parse_quantity(m.group("qty"))
        return Requirement(CAPACITY_PREFIX + m.group("key"), _OPS[m.group("op")], (q,))
    m = _TERM_CAP_EXISTS.match(term)
    if m:
        return Requirement(
            CAPACITY_PREFIX + m.group("key"),
            "DoesNotExist" if m.group("neg") else "Exists",
        )
    m = _TERM_EXISTS.match(term)
    if m:
        return Requirement(
            m.group("key"), "DoesNotExist" if m.group("neg") else "Exists"
        )
    m = _TERM_TRUTHY.match(term)
    if m:
        if m.group("acc") not in (None, "bool"):
            raise ValueError(f"bare attribute term must be bool: {term!r}")
        return Requirement(m.group("key"), "Falsy" if m.group("neg") else "Truthy")
    raise ValueError(
        f"CEL selector term outside the vectorizable subset: {term!r}"
    )


def _split_top(expr: str, sep: str) -> list[str]:
    """Split on ``sep`` (&& or ||) outside quotes/brackets/parens."""
    parts, depth, quote, start = [], 0, False, 0
    i = 0
    while i < len(expr):
        c = expr[i]
        if c == '"':
            quote = not quote
        elif not quote and c in "([":
            depth += 1
        elif not quote and c in ")]":
            depth -= 1
        elif not quote and depth == 0 and expr.startswith(sep, i):
            parts.append(expr[start:i])
            i += 2
            start = i
            continue
        i += 1
    parts.append(expr[start:])
    return parts


def _is_group(s: str) -> bool:
    """True when ``s`` is one parenthesized group: "(...)" with the
    opening paren matching the final char."""
    if not (s.startswith("(") and s.endswith(")")):
        return False
    depth, quote = 0, False
    for i, c in enumerate(s):
        if c == '"':
            quote = not quote
        elif not quote and c == "(":
            depth += 1
        elif not quote and c == ")":
            depth -= 1
            if depth == 0:
                return i == len(s) - 1
    return False


def _parse_or(expr: str) -> tuple[Branch, ...]:
    branches: list[Branch] = []
    for part in _split_top(expr, "||"):
        branches.extend(_parse_and(part.strip()))
    return tuple(branches)


def _parse_and(expr: str) -> tuple[Branch, ...]:
    branches: list[Branch] = [()]
    for part in _split_top(expr, "&&"):
        unit = part.strip()
        if not unit:
            raise ValueError(f"empty term in CEL selector: {expr!r}")
        sub = (
            _parse_or(unit[1:-1].strip())
            if _is_group(unit)
            else ((_compile_term(unit),),)
        )
        branches = [b1 + b2 for b1 in branches for b2 in sub]
        if len(branches) > MAX_BRANCHES:
            raise ValueError(
                f"CEL selector expands past {MAX_BRANCHES} DNF branches: {expr!r}"
            )
    return tuple(branches)


def _req_key(r: Requirement):
    # Values can mix int and str across requirements (e.g. an int-vs-str
    # disjunction on one attribute); tag by type so sorting never
    # compares across types.
    return (r.key, r.op, tuple((type(v).__name__, repr(v)) for v in r.values))


def _canonical_branch(b: Branch) -> Branch:
    return tuple(sorted(set(b), key=_req_key))


def compile_selector(expr: str) -> tuple[Branch, ...]:
    """Compile one CEL selector expression into DNF: a union of
    requirement-conjunction branches (a device matches when any branch's
    requirements all hold).  Raises ValueError outside the supported
    subset."""
    if not expr.strip():
        raise ValueError("empty CEL selector")
    branches = _parse_or(expr.strip())
    # Canonical: sorted, duplicate branches collapsed.
    seen: dict[Branch, None] = {}
    for b in branches:
        seen.setdefault(_canonical_branch(b))
    return tuple(sorted(seen, key=lambda b: tuple(_req_key(r) for r in b)))


def compile_selectors(selectors: tuple[str, ...]) -> tuple[Branch, ...]:
    """DNF of the CONJUNCTION of several selector expressions (a request's
    ``selectors`` list ANDs them, allocator.go selectorsMatch)."""
    branches: tuple[Branch, ...] = ((),)
    for s in selectors:
        sub = compile_selector(s)
        merged = [b1 + b2 for b1 in branches for b2 in sub]
        if len(merged) > MAX_BRANCHES:
            raise ValueError(
                f"CEL selector set expands past {MAX_BRANCHES} DNF branches"
            )
        branches = tuple(merged)
    seen: dict[Branch, None] = {}
    for b in branches:
        seen.setdefault(_canonical_branch(b))
    return tuple(sorted(seen, key=lambda b: tuple(_req_key(r) for r in b)))


def canonical(selectors: tuple[str, ...]) -> str:
    """Canonical signature of a selector set for pool interning: the sorted
    DNF, so differently-written equivalent selectors share a pool."""
    return "|".join(
        ";".join(
            f"{r.key}\x00{r.op}\x00{','.join(map(repr, r.values))}" for r in b
        )
        for b in compile_selectors(tuple(selectors))
    )


def matches(branches: tuple[Branch, ...], attrs: dict) -> bool:
    """True when any DNF branch's requirements all hold for the device
    (attrs carries capacity entries under CAPACITY_PREFIX keys)."""
    return any(all(r.matches(attrs) for r in b) for b in branches)
