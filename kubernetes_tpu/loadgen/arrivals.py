"""Open-loop arrival processes for the traffic generator.

Every number the repo recorded before this PR came from one-shot replays:
a fixed pod population drained as fast as the scheduler can go.  A
production control plane is not drained — it is *arrived at*: pods show
up on their own clock, whether or not the scheduler is keeping up.  The
difference is the whole point of an OPEN-LOOP generator (the
methodology scheduler_perf's closed drains cannot express, and the one
robust-scheduling work evaluates against — a policy's value shows under
shifted arrival distributions, not a single trace): the arrival schedule
is drawn AHEAD OF TIME from the process below, so a slow scheduler
builds backlog and its latency percentiles degrade honestly instead of
the load politely waiting.

Determinism contract (enforced by tpulint's determinism family, which
covers this package): every schedule is a pure function of its
``(seed, parameters)`` — seeded ``numpy.random.Generator`` only, no wall
clocks, no ambient entropy.  Re-running a soak with the same seed
replays the exact same arrival offsets, which is what makes a soak's
final bindings reproducible end to end.

Two processes:

- ``poisson_offsets``: homogeneous Poisson at ``rate_per_s`` —
  exponential inter-arrival gaps, the memoryless baseline.
- ``diurnal_offsets``: a non-homogeneous Poisson whose rate swings
  sinusoidally between ``base_rate`` and ``peak_rate`` over ``period_s``
  (the day/night curve of real traffic), realized by Lewis-Shedler
  thinning: draw candidates at the peak rate, keep each with probability
  ``rate(t)/peak`` — exact, and still a pure function of the seed.
"""

from __future__ import annotations

import math

import numpy as np


def _rng(seed: int) -> np.random.Generator:
    """The one RNG constructor every loadgen module uses: an explicit
    PCG64 stream keyed by the seed, so schedules are stable across numpy
    versions that re-tune ``default_rng``."""
    return np.random.Generator(np.random.PCG64(int(seed)))


def poisson_offsets(
    rate_per_s: float, duration_s: float, seed: int
) -> list[float]:
    """Arrival offsets (seconds from phase start, ascending) of a
    homogeneous Poisson process over ``[0, duration_s)``."""
    if rate_per_s <= 0 or duration_s <= 0:
        return []
    rng = _rng(seed)
    out: list[float] = []
    t = 0.0
    # Draw gaps in chunks (vectorized) until the horizon is passed; the
    # draw COUNT consumed from the stream depends only on the draws
    # themselves, so the schedule stays a pure function of the seed.
    chunk = max(16, int(rate_per_s * duration_s * 1.25) + 16)
    while True:
        for gap in rng.exponential(1.0 / rate_per_s, size=chunk):
            t += float(gap)
            if t >= duration_s:
                return out
            out.append(round(t, 9))
        chunk = max(16, chunk // 4)


def diurnal_rate(
    t: float, base_rate: float, peak_rate: float, period_s: float
) -> float:
    """The instantaneous rate of the diurnal curve: ``base`` at t=0,
    cresting to ``peak`` half a period in."""
    swing = 0.5 * (1.0 - math.cos(2.0 * math.pi * t / period_s))
    return base_rate + (peak_rate - base_rate) * swing


def diurnal_offsets(
    base_rate: float,
    peak_rate: float,
    period_s: float,
    duration_s: float,
    seed: int,
) -> list[float]:
    """Arrival offsets of the diurnally-modulated Poisson process
    (Lewis-Shedler thinning at ``peak_rate``)."""
    if peak_rate <= 0 or duration_s <= 0:
        return []
    if peak_rate < base_rate:
        raise ValueError("peak_rate must be >= base_rate")
    rng = _rng(seed)
    out: list[float] = []
    t = 0.0
    while True:
        t += float(rng.exponential(1.0 / peak_rate))
        if t >= duration_s:
            return out
        accept = diurnal_rate(t, base_rate, peak_rate, period_s) / peak_rate
        if float(rng.random()) < accept:
            out.append(round(t, 9))


def burst_offsets(
    base_rate: float,
    burst_rate: float,
    burst_start_s: float,
    burst_end_s: float,
    duration_s: float,
    seed: int,
) -> list[float]:
    """Arrival offsets of a piecewise-constant-rate Poisson process:
    ``base_rate`` outside ``[burst_start_s, burst_end_s)``, ``burst_rate``
    inside — the one-tenant-bursts shape the tenant-starvation scenario
    drives (realized by Lewis-Shedler thinning at the max rate, so the
    schedule stays a pure function of the seed like every other
    process here)."""
    peak = max(base_rate, burst_rate)
    if peak <= 0 or duration_s <= 0:
        return []
    rng = _rng(seed)
    out: list[float] = []
    t = 0.0
    while True:
        t += float(rng.exponential(1.0 / peak))
        if t >= duration_s:
            return out
        rate = burst_rate if burst_start_s <= t < burst_end_s else base_rate
        if float(rng.random()) < rate / peak:
            out.append(round(t, 9))


def coalesce(
    offsets: list[float], window_s: float
) -> list[tuple[float, list[int]]]:
    """Group arrival indices into hint-coalescing windows: one
    ``(window_start, [arrival indices])`` entry per non-empty window.
    This is the flusher-goroutine shape the sidecar's ``PendingPods``
    frame exists for — the informer fires per pod, but hints ship as one
    array frame per window."""
    if window_s <= 0:
        return [(off, [i]) for i, off in enumerate(offsets)]
    windows: dict[int, list[int]] = {}
    for i, off in enumerate(offsets):
        windows.setdefault(int(off / window_s), []).append(i)
    return [(w * window_s, idxs) for w, idxs in sorted(windows.items())]
