"""Open-loop traffic generator + soak subsystem.

``arrivals`` draws seeded Poisson/diurnal arrival schedules,
``workloads`` mixes the sweep families' pod shapes, ``scenarios``
scripts fault/churn/invalidation events, and ``soak`` drives the real
deployment (two-process ``serve --journal-dir --speculate`` or an
in-process server) against them, recording SLO latency percentiles, the
speculation miss-rate knee, and journal growth.  Everything is a pure
function of the seed — tpulint's determinism family covers this package.
"""

from .arrivals import coalesce, diurnal_offsets, poisson_offsets
from .scenarios import build_events
from .soak import PushConsumer, SoakConfig, run_soak, strip_private
from .workloads import MIXES, WorkloadMix

__all__ = [
    "MIXES",
    "PushConsumer",
    "SoakConfig",
    "WorkloadMix",
    "build_events",
    "coalesce",
    "diurnal_offsets",
    "poisson_offsets",
    "run_soak",
    "strip_private",
]
