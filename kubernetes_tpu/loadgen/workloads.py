"""Workload mixes for the traffic generator, drawn from the sweep families.

The benchmark harness already defines the pod shapes the whole recorded
trajectory is built on (benchmarks/harness.py: the BASELINE configs and
the upstream performance-config.yaml ports).  The generator reuses those
exact templates — a soak should stress the same constraint families the
one-shot sweeps measure, not a new ad-hoc shape — and mixes them by
seeded draw, so a mix is as replayable as the arrival schedule feeding
it.

Two deliberate deltas from the sweep shapes:

- pods are renamed into the generator's own ``lg-{index}`` namespace
  (indices are globally unique across a soak's phases, so a 5-minute
  stream never collides with itself or the warmup wave);
- the default requests are scaled DOWN (``small_requests``): an
  unbounded stream against a fixed fleet must not throttle on capacity
  before the retirement churn (soak.py's live-pod cap) starts freeing
  it.
"""

from __future__ import annotations

from ..api import types as t
from ..framework.metrics import TENANT_LABEL_KEY

# The sweep families this module draws from (benchmarks/harness.py is
# the single source of the shapes; importing it keeps the soak's pods
# byte-identical to the sweep's).
from ..benchmarks.harness import (
    _pod_affinity,
    _pod_basic,
    _pod_node_affinity,
    _pod_pref_anti,
    _pod_spread,
)
from ..ops.throughput import DEFAULT_THROUGHPUT_MATRIX, WORKLOAD_CLASS_LABEL_KEY
from .arrivals import _rng


def _hetero_template(wclass: str):
    """A per-workload-class pod template (ISSUE 14): the basic shape plus
    the ``scheduler.tpu/workload-class`` label the ThroughputAware /
    LearnedScorer profiles read.  SchedulerName is stamped by the
    WorkloadMix (the driver decides which registered profile serves the
    stream), so one template set serves both hetero profiles."""

    def tmpl(i: int) -> t.Pod:
        pod = _pod_basic(i)
        pod.metadata.labels = dict(pod.metadata.labels or {})
        pod.metadata.labels[WORKLOAD_CLASS_LABEL_KEY] = wclass
        return pod

    return tmpl


TEMPLATES = {
    "basic": _pod_basic,
    "spread": _pod_spread,
    "affinity": _pod_affinity,
    "pref_anti": _pod_pref_anti,
    "node_affinity": _pod_node_affinity,
}
# One template per throughput-matrix workload class:
# hetero_train-large / hetero_train-small / hetero_serve / hetero_batch.
HETERO_TEMPLATES = {
    f"hetero_{wclass}": _hetero_template(wclass)
    for wclass, _row in DEFAULT_THROUGHPUT_MATRIX
}
TEMPLATES.update(HETERO_TEMPLATES)

# name → ((template, weight), ...).  Weights normalize at draw time.
MIXES: dict[str, tuple[tuple[str, float], ...]] = {
    # The headline shape: BASELINE #4's basic pods.
    "basic": (("basic", 1.0),),
    # MixedSchedulingBasePod's spirit under sustained traffic: mostly
    # basic pods with a constraint-carrying minority (the minority is
    # what keeps the speculative frontend's domain-dependency scoping
    # honest — an affinity-free soak would never exercise it).
    "mixed": (
        ("basic", 0.70),
        ("spread", 0.10),
        ("pref_anti", 0.10),
        ("node_affinity", 0.10),
    ),
    # Adversarial for the decision cache: every pod carries terms, so
    # every domain event intersects every cached decision.
    "domains": (("affinity", 0.40), ("spread", 0.30), ("pref_anti", 0.30)),
    # Heterogeneous-cluster stream (ISSUE 14): a class-labeled majority
    # over mixed accelerator pools — every matrix row stays hot, a
    # class-less minority keeps the class-inactive program path warm.
    "hetero": (
        ("basic", 0.20),
        ("hetero_train-large", 0.20),
        ("hetero_train-small", 0.20),
        ("hetero_serve", 0.25),
        ("hetero_batch", 0.15),
    ),
}


class WorkloadMix:
    """A seeded pod factory over one mix: ``pod(i)`` builds arrival i's
    pod, choosing its template by a seeded draw (a pure function of
    ``(seed, i)`` order — the factory must be called in arrival order,
    which the driver does by construction).

    Tenants (ISSUE 12): ``tenants`` turns the factory into a
    tenant-tagged stream — each pod carries the canonical
    ``scheduler.tpu/tenant`` label, drawn from the weighted tenant set
    by its own seeded stream (so adding tenants never perturbs the
    template draw sequence), or forced per pod via ``pod(i, tenant=…)``
    (the starvation scenario's per-tenant arrival streams)."""

    def __init__(
        self,
        mix: str,
        seed: int,
        small_requests: bool = True,
        tenants: tuple[tuple[str, float], ...] = (),
        scheduler_name: str = "",
    ):
        if mix not in MIXES:
            raise ValueError(f"unknown mix {mix!r}; have {sorted(MIXES)}")
        self.mix = mix
        # Non-empty: every pod of the stream selects this registered
        # profile by schedulerName (the hetero soak's profile selection
        # — scheduler.py _profile_for routes it to the profile's own
        # compiled program family).
        self.scheduler_name = scheduler_name
        entries = MIXES[mix]
        total = sum(w for _n, w in entries)
        self._names = [n for n, _w in entries]
        self._weights = [w / total for _n, w in entries]
        self._rng = _rng(seed)
        self.small_requests = small_requests
        self.counts: dict[str, int] = {n: 0 for n in self._names}
        self.tenants = tuple((str(n), float(w)) for n, w in tenants)
        self._tenant_rng = _rng(seed * 69_061 + 5) if self.tenants else None
        if self.tenants:
            tw = sum(w for _n, w in self.tenants)
            self._tenant_names = [n for n, _w in self.tenants]
            self._tenant_weights = [w / tw for _n, w in self.tenants]
        self.tenant_counts: dict[str, int] = {}

    def pod(self, i: int, tenant: str | None = None) -> t.Pod:
        name = (
            self._names[0]
            if len(self._names) == 1
            else str(self._rng.choice(self._names, p=self._weights))
        )
        self.counts[name] += 1
        pod = TEMPLATES[name](i)
        # The generator's own naming space; rename BEFORE any uid access
        # (Pod.uid memoizes on first read).
        pod.metadata.name = f"lg-{i}"
        if self.scheduler_name:
            pod.spec.scheduler_name = self.scheduler_name
        if tenant is None and self.tenants:
            tenant = (
                self._tenant_names[0]
                if len(self._tenant_names) == 1
                else str(
                    self._tenant_rng.choice(
                        self._tenant_names, p=self._tenant_weights
                    )
                )
            )
        if tenant:
            # Labels may be shared with the template — copy before
            # tagging so tenants never alias across pods.
            pod.metadata.labels = dict(pod.metadata.labels or {})
            pod.metadata.labels[TENANT_LABEL_KEY] = tenant
            self.tenant_counts[tenant] = (
                self.tenant_counts.get(tenant, 0) + 1
            )
        if self.small_requests:
            # A sustained stream must not exhaust the fleet before the
            # retirement churn frees capacity; tiny requests put the
            # binding pressure on pods-per-node, where the live-pod cap
            # governs.
            pod.spec.containers[0].requests = {
                "cpu": t.parse_quantity("50m", "cpu"),
                "memory": t.parse_quantity("64Mi", "memory"),
            }
        return pod
