"""Fault/churn scenario scripts for the soak driver.

A soak that only arrives pods proves throughput, not robustness.  These
scripts inject the events a production control plane actually sees —
node flaps, consumers restarting cold, and mutation mixes chosen to be
ADVERSARIAL to the speculative frontend's decision cache
(sidecar/speculate.py's scoped-invalidation rules) — as a seeded,
replayable event list the driver merges into the arrival schedule.

Invalidation kinds, by blast radius against the cache:

- ``inv_label``    — re-add a node with a changed label value.  Labels
  remap topology domains, so the frontend's documented fallback is a
  FULL rollback: every cached decision recomputes.  This is the
  worst-case event the miss-rate knee is measured against.
- ``inv_capacity`` — re-add a node with its allocatable cpu nudged.  A
  capacity-only change invalidates decisions ON that node plus
  unschedulable verdicts — the scoped path.
- ``inv_ns``       — flip a namespace label.  Stales domain-dependent
  decisions and unschedulable verdicts (namespaceSelector matching);
  affinity-free mixes shrug it off, which is exactly the scoping the
  knee curve should show.

Every script is a pure function of ``(seed, parameters)`` (seeded
``numpy.random.Generator``; offsets derive from the same arrival
machinery), so a re-run replays the identical event sequence.
"""

from __future__ import annotations

from dataclasses import dataclass

from .arrivals import _rng, poisson_offsets

# Invalidation mix: (kind, weight).  Label rewrites are deliberately the
# minority — one full rollback stales everything, so an even mix would
# drown the scoped kinds' signal.
DEFAULT_INV_MIX: tuple[tuple[str, float], ...] = (
    ("inv_capacity", 0.6),
    ("inv_label", 0.25),
    ("inv_ns", 0.15),
)


@dataclass(frozen=True)
class Event:
    """One scripted occurrence at ``t`` seconds into the phase.
    ``data`` is kind-specific: node index for flaps/invalidations, a
    counter for namespace flips and cold consumers."""

    t: float
    kind: str
    data: int = 0


def invalidation_events(
    rate_per_s: float,
    duration_s: float,
    seed: int,
    *,
    nodes: int,
    mix: tuple[tuple[str, float], ...] = DEFAULT_INV_MIX,
) -> list[Event]:
    """A Poisson stream of invalidation events at ``rate_per_s`` — the
    knob the miss-rate knee sweep turns."""
    offsets = poisson_offsets(rate_per_s, duration_s, seed)
    if not offsets:
        return []
    rng = _rng(seed + 1)  # kind/target stream, distinct from the offsets
    kinds = [k for k, _w in mix]
    total = sum(w for _k, w in mix)
    weights = [w / total for _k, w in mix]
    out = []
    for off in offsets:
        kind = str(rng.choice(kinds, p=weights))
        target = int(rng.integers(0, nodes))
        out.append(Event(t=off, kind=kind, data=target))
    return out


def node_flap_events(
    period_s: float,
    down_s: float,
    duration_s: float,
    *,
    churn_nodes: int,
) -> list[Event]:
    """Periodic node flaps over a dedicated churn pool: every
    ``period_s`` one churn node goes down (its bound pods vanish with
    it — the engine's remove contract) and returns ``down_s`` later.
    Round-robin over the pool, so flaps never overlap on one node."""
    if period_s <= 0 or churn_nodes <= 0:
        return []
    out = []
    k = 0
    t = period_s
    while t < duration_s:
        node = k % churn_nodes
        out.append(Event(t=t, kind="flap_down", data=node))
        if t + down_s < duration_s:
            out.append(Event(t=t + down_s, kind="flap_up", data=node))
        k += 1
        t += period_s
    return out


def node_death_events(
    period_s: float,
    down_s: float,
    duration_s: float,
    *,
    churn_nodes: int,
) -> list[Event]:
    """Periodic node DEATHS over the churn pool — unlike ``flap_down``
    (an informer delete: the node object vanishes), a death leaves the
    Node object in place and silences its heartbeat: the node-lifecycle
    controller must DETECT the staleness, write the NotReady/Unreachable
    taints, and the eviction/requeue machinery must move its pods to
    survivors.  ``node_revive`` resumes the heartbeat (taints clear).
    Round-robin over the pool so at most one churn node is dead at a
    time (the logical Lease clock keeps advancing on the others)."""
    if period_s <= 0 or churn_nodes <= 0:
        return []
    out = []
    k = 0
    t = period_s
    while t < duration_s:
        node = k % churn_nodes
        out.append(Event(t=t, kind="node_death", data=node))
        if t + down_s < duration_s:
            out.append(Event(t=t + down_s, kind="node_revive", data=node))
        k += 1
        t += period_s
    return out


def lease_tick_events(interval_s: float, duration_s: float) -> list[Event]:
    """The heartbeat schedule: every ``interval_s`` the driver renews the
    Leases of every currently-alive lease-tracked node, stamping the
    SCENARIO clock — node liveness becomes a pure function of the event
    stream (deterministic in both pacing modes)."""
    if interval_s <= 0:
        return []
    out = []
    k = 0
    t = interval_s
    while t < duration_s:
        out.append(Event(t=t, kind="lease_tick", data=k))
        k += 1
        t += interval_s
    return out


def autoscale_tick_events(interval_s: float, duration_s: float) -> list[Event]:
    """The elastic-fleet control-loop cadence: every ``interval_s`` the
    driver ticks the shard autoscaler at the SCENARIO clock — the
    resize decision stream is a pure function of the op schedule (the
    same logical-clock discipline the lease ticks follow), so same-seed
    soaks replay the identical split/merge history."""
    if interval_s <= 0:
        return []
    out = []
    k = 0
    t = interval_s
    while t < duration_s:
        out.append(Event(t=t, kind="autoscale_tick", data=k))
        k += 1
        t += interval_s
    return out


def cold_consumer_events(period_s: float, duration_s: float) -> list[Event]:
    """Periodic push-consumer restarts: the driver drops its decision
    map mid-stream and subscribes a fresh (cold) connection — the
    plugin-process-restart shape.  A cold consumer misses to the wire
    until the push stream re-warms its map; the soak's hit rate carries
    the cost honestly."""
    if period_s <= 0:
        return []
    out = []
    k = 0
    t = period_s
    while t < duration_s:
        out.append(Event(t=t, kind="cold_consumer", data=k))
        k += 1
        t += period_s
    return out


def one_shot_events(spec) -> list[Event]:
    """Scripted one-shot events from a ``((t, kind, data), ...)`` spec —
    the production-day composition's hand-placed incidents (a cold
    router restart at a known second, a node death during the diurnal
    crest) merged into the generated stream by the fleet soak.  The spec
    is part of the config, so the merged schedule stays a pure function
    of (config, seed)."""
    return [Event(t=float(t), kind=str(k), data=int(d)) for t, k, d in spec]


def build_events(
    duration_s: float,
    seed: int,
    *,
    nodes: int,
    churn_nodes: int = 0,
    invalidation_rate_per_s: float = 0.0,
    inv_mix: tuple[tuple[str, float], ...] = DEFAULT_INV_MIX,
    node_flap_period_s: float = 0.0,
    flap_down_s: float = 1.0,
    cold_consumer_period_s: float = 0.0,
    node_death_period_s: float = 0.0,
    node_death_down_s: float = 8.0,
    lease_interval_s: float = 0.0,
    autoscale_interval_s: float = 0.0,
) -> list[Event]:
    """One phase's full scenario script, merged and time-ordered.
    Ties break by (kind, data) so the order is total and seed-stable."""
    events = (
        invalidation_events(
            invalidation_rate_per_s, duration_s, seed, nodes=nodes,
            mix=inv_mix,
        )
        + node_flap_events(
            node_flap_period_s, flap_down_s, duration_s,
            churn_nodes=churn_nodes,
        )
        + cold_consumer_events(cold_consumer_period_s, duration_s)
        + node_death_events(
            node_death_period_s, node_death_down_s, duration_s,
            churn_nodes=churn_nodes,
        )
        + lease_tick_events(lease_interval_s, duration_s)
        + autoscale_tick_events(autoscale_interval_s, duration_s)
    )
    return sorted(events, key=lambda e: (e.t, e.kind, e.data))
