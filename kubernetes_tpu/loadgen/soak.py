"""The soak harness: drive the deployment with open-loop traffic and
measure it like a service.

One-shot replays answer "how fast can it drain"; this answers the
production questions the ROADMAP's sustained-traffic item asks:

- **SLO percentiles** — per-decision serving latency (p50/p99/p999)
  against a configured budget, measured open-loop: arrivals come on
  their own schedule (arrivals.py), so a scheduler falling behind
  accrues backlog and its tail degrades honestly.
- **The speculation miss-rate knee** — a decision-cache miss costs a
  full wire round trip + device pass (~195 ms in the recorded
  integrated_serial row) while a hit costs a local map pop.  The knee
  sweep ramps the invalidation intensity (scenarios.py) across phases
  and records where the hit rate collapses and the latency crosses the
  miss cost — the number nothing measured before this PR.
- **Journal growth under an unbounded stream** — the driver retires old
  bound pods (the live-pod cap) so binds+deletes append forever; the
  WAL must stay bounded through snapshot+truncate compaction cycles
  (journal.py), observed directly as the sampled ``journal.wal`` size.

Determinism: the full wire-operation sequence (hints, per-pod decisions,
retirements, scenario events) is a pure function of the seed — events
execute in pre-computed schedule order, and real-time pacing only delays
WHEN an operation is issued, never which or in what order.  Re-running
with one seed therefore reproduces the arrival schedule exactly and
lands bit-identical final bindings, in either pacing mode.  The
deterministic push consumer below is part of that contract: pushes are
written to the subscriber socket under the dispatch lock BEFORE the
triggering call's response, so once a wire call returns, every frame it
caused is already buffered — a non-blocking drain sees a deterministic
prefix of the stream (the threaded ``DecisionCache`` trades that for
always-on draining; the single-threaded driver doesn't need it).

Deployments: ``two_process=True`` spawns the real ``serve
--journal-dir --speculate`` CLI as a child and drives it over the unix
socket (the acceptance configuration); ``two_process=False`` hosts the
SidecarServer in-process (tier-1 smoke, bench.py's slo block).
"""

from __future__ import annotations

import hashlib
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import zlib
from collections import deque
from dataclasses import asdict, dataclass, field

import numpy as np

from ..api.wrappers import make_node, make_pod
from ..framework.config import named_extra_profiles, profile_scheduler_name
from ..framework.flight import merge_fleet
from ..framework.metrics import (
    TENANT_FALLBACK,
    MetricsRegistry,
    TenantMetrics,
    pod_tenant,
)
from ..journal import Journal
from ..sidecar.host import DecisionCache, ResyncingClient
from ..sidecar.server import SidecarClient
from .arrivals import (
    _rng,
    burst_offsets,
    coalesce,
    diurnal_offsets,
    poisson_offsets,
)
from .checkpoint import CheckpointWriter, load_checkpoint, state_digest
from .scenarios import DEFAULT_INV_MIX, build_events, one_shot_events
from .workloads import WorkloadMix


@dataclass
class SoakConfig:
    seed: int = 6
    # Fleet: `nodes` serving nodes + `churn_nodes` flap targets.
    nodes: int = 200
    zones: int = 10
    churn_nodes: int = 8
    # Arrivals (open-loop).
    rate_pods_per_s: float = 60.0
    diurnal: bool = False
    diurnal_peak_factor: float = 2.0  # peak = factor × base rate
    diurnal_period_s: float = 120.0
    hint_coalesce_s: float = 0.25
    mix: str = "basic"
    # Phases: one sustained phase (the SLO source), then the knee sweep.
    duration_s: float = 60.0
    knee_points: tuple[float, ...] = (0.5, 2.0, 8.0, 32.0, 128.0)
    knee_phase_s: float = 20.0
    # Background churn during EVERY phase.
    invalidation_rate_per_s: float = 0.1
    node_flap_period_s: float = 30.0
    flap_down_s: float = 2.0
    cold_consumer_period_s: float = 0.0
    # Node-DEATH scenario (ISSUE 9): a churn node stops heartbeating
    # (the object stays), the server's node-lifecycle controller writes
    # the NotReady/Unreachable taints, its pods evict + requeue +
    # reschedule on survivors, and a revive clears the taints.  Armed by
    # node_grace_s > 0; Leases renew every lease_interval_s stamping the
    # SCENARIO clock (liveness is a pure function of the op stream).
    node_death_period_s: float = 0.0
    node_death_down_s: float = 8.0
    lease_interval_s: float = 1.0
    node_grace_s: float = 0.0  # 0 = lifecycle disarmed (pre-ISSUE-9 soak)
    node_unreachable_s: float = 0.0  # 0 = grace × 2.5
    gc_horizon_s: float = 0.0  # 0 = grace × 6
    # Elastic-fleet autoscaler (ISSUE 11; fleet soak only).  armed by
    # autoscale=True: the driver ticks the shard autoscaler every
    # autoscale_interval_s of SCENARIO time, and hot_fraction of
    # arrivals carry a node selector only the hot pool (the serving
    # nodes shard 0 owns at build time) satisfies — the diurnal crest
    # concentrates their load on one shard until a split trips.
    autoscale: bool = False
    hot_fraction: float = 0.0
    autoscale_interval_s: float = 5.0
    autoscale_split_hi: float = 1.6
    autoscale_merge_lo: float = 0.25
    autoscale_cooldown_s: float = 30.0
    autoscale_window_s: float = 60.0
    autoscale_budget: int = 2
    autoscale_min_decisions: int = 12
    autoscale_max_shards: int = 4
    # A deterministic pre-bound population scheduled BEFORE the measured
    # window (hot-marked like the stream): the owners' stores start
    # saturated, so the per-owner snapshot pause — the tail-latency
    # mechanism the split halves — is in force from the first window
    # instead of only materializing late in the run.
    preload_bound: int = 0
    # Pre/post comparison window for the split-recovery evidence block,
    # and the settle gap that separates the RESIZE TRANSITION (the
    # journaled import re-fsyncs every moved binding — a real, bounded,
    # one-time cost the artifact reports explicitly) from the
    # steady-state window the recovery claim compares.
    autoscale_compare_window_s: float = 30.0
    autoscale_compare_settle_s: float = 10.0
    # The unbounded-stream bound: completed (bound) pods beyond this cap
    # retire oldest-first, so capacity recycles and the journal sees a
    # perpetual bind+delete append stream.
    live_pod_cap: int = 2000
    # SLO.
    slo_budget_ms: float = 250.0
    # Engine shape.
    batch_size: int = 512
    chunk_size: int = 64
    warm_pods: int = 256
    # Software pipeline (ISSUE 15): depth 1 = serial parity; depth 2
    # overlaps the group-committed journal drain with the next batch's
    # in-flight device pass (bindings bit-identical either way).
    pipeline_depth: int = 1
    # Deployment.
    two_process: bool = False
    journal_dir: str = ""  # empty → a temp dir (two-process always journals)
    journal_fsync: str = "always"
    snapshot_every: int = 64
    # "real" paces operations to the arrival schedule's wall deadlines
    # (latency includes backlog); "virtual" issues them back to back
    # (latency = service time) — same operation sequence either way.
    pace: str = "real"
    # Artifact directory (flight dumps, final flight ring); empty → temp.
    out_dir: str = ""
    # -- tenant attribution (ISSUE 12) ----------------------------------
    # Weighted tenant draw for the stream: ((name, weight), ...) — every
    # arrival carries the scheduler.tpu/tenant label, drawn by its own
    # seeded stream (the template draw sequence is untouched).
    tenants: tuple = ()
    # Per-tenant arrival STREAMS (the tenant_starvation scenario; fleet
    # soak only): tuple of dicts {"name", "rate_pods_per_s", and
    # optionally "burst_factor"/"burst_start_s"/"burst_end_s", plus
    # "workload_class" — the throughput-matrix row its fairness weight
    # derives from when admission is armed} — each tenant arrives on its
    # own seeded schedule (steady Poisson, or a piecewise burst), merged
    # time-ordered.  Non-empty replaces the single
    # rate_pods_per_s/diurnal schedule.
    tenant_streams: tuple = ()
    # Weighted-fair admission (ISSUE 17): arm framework/fairness on the
    # fleet router's queue.  Dict of FairAdmission knobs —
    # {"rate_pods_per_s", "burst", "aging_max_wait_s",
    # "slo_wait_budget_s"}; weights derive from the synthetic throughput
    # matrix over the tenant_streams' workload_class mapping (uniform
    # when unmapped).  None ⇒ UNARMED: the pre-fairness FIFO admission,
    # bit-identical to pre-PR runs.
    admission: dict | None = None
    # Hashed tail tier for the tenant labeler (TenantLabeler
    # hash_buckets): 0 keeps pure top-K + "-" overflow; > 0 routes
    # over-cap tenants into that many crc32 buckets (~NN labels) — the
    # thousands-of-tenants leg's bounded-cardinality contract.
    tenant_hash_buckets: int = 0
    # Master observability switch: tenant attribution, fleet tracing and
    # flight logical-clock stamping.  Decisions are bit-identical with
    # it on or off — the tenant artifact's obs-off leg asserts exactly
    # that (observability must observe, never steer).
    observability: bool = True
    # -- heterogeneous clusters (ISSUE 14) ------------------------------
    # Accelerator-class pools for the serving/churn fleet:
    # ((accel_class, int_weight), ...) — nodes deal their
    # ``scheduler.tpu/accel`` label deterministically by index.  Empty ⇒
    # homogeneous (the pre-ISSUE-14 fleet).
    hetero_pools: tuple = ()
    # Extra registered profile served beside the default ("" |
    # "throughput-aware" | "learned-scorer"); the stream selects it by
    # schedulerName (WorkloadMix.scheduler_name).  Pair with
    # mix="hetero" + hetero_pools for the heterogeneous soak.
    profile: str = ""
    # -- warm-standby owner pool (ISSUE 18; fleet soak only) ------------
    # > 0 arms fleet/standby.py: that many pre-forked, pre-warmed serve
    # children (XLA compiled against the live featurization schema,
    # journal dir pre-created, lease unclaimed) kept behind the
    # autoscaler's owner_provider and revive_owner's takeover path —
    # promotion is a journaled handoff + lease claim (O(handoff)), not a
    # ~15s cold boot.  0 ⇒ unarmed: both paths cold-spawn exactly as
    # before, byte-identical to the pre-ISSUE-18 soak.
    standby_pool: int = 0
    standby_dir: str = ""  # pool WAL + mirror dir; empty → tmp/standby
    # -- resumable driver (ISSUE 18) ------------------------------------
    # Non-empty arms loadgen/checkpoint.py: every checkpoint_every_ops
    # executed ops the driver atomically checkpoints its FULL
    # deterministic state (op cursor, logical clock, RNG generator
    # states, SLO/latency accumulators, per-tenant ledgers) plus the
    # wall-derived observability accumulators.  resume=True replays the
    # checkpointed op prefix in virtual pace against fresh journal dirs,
    # verifies the regenerated state digest, restores the observability
    # accumulators, and continues — bit-identical to an uninterrupted
    # same-seed run.
    checkpoint_path: str = ""
    checkpoint_every_ops: int = 0
    resume: bool = False
    # Test hook (run_fault_matrix.py --standby-kill; tests/test_soak.py):
    # SIGKILL the driver process immediately after executing op N
    # (post-checkpoint-write when N lands on a boundary).  0 = disarmed.
    kill_after_op: int = 0
    # Extra scripted one-shot scenario events merged into the generated
    # stream: ((t, kind, data), ...) — the production-day composition
    # uses this for the scripted cold router restart and node deaths.
    scripted_events: tuple = ()


def _accel_label(cfg: SoakConfig, w, i: int):
    """Deal the accelerator-class label over the configured pools
    (ISSUE 14) — the SAME weighted deal the bench fleets use
    (benchmarks.harness.hetero_accel_for), so soak and sweep node
    distributions can never drift apart.  Deterministic by node index:
    a re-add mid-soak (capacity toggle, epoch label, fleet re-feed)
    reproduces the node's class.  No-op without hetero_pools."""
    pools = tuple((a, int(wt)) for a, wt in cfg.hetero_pools)
    if not pools:
        return w
    from ..benchmarks.harness import hetero_accel_for
    from ..ops.throughput import ACCEL_LABEL_KEY

    return w.label(ACCEL_LABEL_KEY, hetero_accel_for(i, pools))


def _sha(obj) -> str:
    return hashlib.sha256(
        json.dumps(obj, sort_keys=True).encode()
    ).hexdigest()


def _pct(values: list[float], q: float) -> float:
    if not values:
        return 0.0
    return float(np.percentile(np.asarray(values, np.float64), q))


def _lat_summary(values: list[float]) -> dict:
    return {
        "decisions": len(values),
        "p50_ms": round(_pct(values, 50) * 1e3, 3),
        "p99_ms": round(_pct(values, 99) * 1e3, 3),
        "p999_ms": round(_pct(values, 99.9) * 1e3, 3),
        "mean_ms": round(
            float(np.mean(values)) * 1e3 if values else 0.0, 3
        ),
        "max_ms": round(max(values) * 1e3 if values else 0.0, 3),
    }


def _slo_families(registry: MetricsRegistry, budget_ms: float):
    """The soak SLO families — ONE construction site shared by the
    single-scheduler driver and the fleet soak (metrics hygiene: one
    registration per name).  Both latency families carry the bounded
    ``tenant`` label next to ``phase`` (ISSUE 12: whose p99 blew up)."""
    hist = registry.histogram(
        "scheduler_slo_decision_latency_seconds",
        "Per-decision serving latency of the open-loop soak driver "
        "(arrival deadline to decision), by phase, tenant and component "
        "(total = queue_wait + service: queue_wait is time spent waiting "
        "for admission — driver backlog or a fairness rate cap — and "
        "service is the scheduler's own time, so a capped tenant's "
        "self-inflicted wait is attributed to the cap, not to "
        "scheduler slowness).",
    )
    violations = registry.counter(
        "scheduler_slo_violations_total",
        "Soak decisions whose serving latency exceeded the SLO "
        "budget, by phase and tenant.",
    )
    registry.gauge(
        "scheduler_slo_budget_seconds",
        "Configured SLO latency budget for the soak driver.",
    ).set(budget_ms / 1e3)
    return hist, violations


def _tenant_summary(phases: list["_PhaseResult"]) -> dict:
    """Aggregate the phases' per-tenant splits into the artifact's
    tenants block: decisions/bound/violations + the latency percentile
    split, keyed by raw tenant id ("-" = untagged)."""
    lat: dict[str, list] = {}
    cnt: dict[str, int] = {}
    bound: dict[str, int] = {}
    viol: dict[str, int] = {}
    for p in phases:
        for k, v in p.tenant_latencies.items():
            lat.setdefault(k, []).extend(v)
        for k, v in p.tenant_counts.items():
            cnt[k] = cnt.get(k, 0) + v
        for k, v in p.tenant_bound.items():
            bound[k] = bound.get(k, 0) + v
        for k, v in p.tenant_violations.items():
            viol[k] = viol.get(k, 0) + v
    return {
        k: dict(
            _lat_summary(lat[k]),
            arrivals=cnt.get(k, 0),
            bound=bound.get(k, 0),
            violations=viol.get(k, 0),
        )
        for k in sorted(lat)
    }


class PushConsumer:
    """Single-threaded push-stream consumer (the deterministic sibling
    of ``DecisionCache``): subscribes its own connection and drains
    whatever is already buffered, non-blocking.  Apply semantics are the
    stream contract shared with DecisionCache._apply — invalidations
    first, then the epoch, then the frame's decisions."""

    def __init__(self, path: str):
        self.client = SidecarClient(path)
        self.client.subscribe()
        self.sock = self.client.sock
        self.sock.setblocking(False)
        self.buf = bytearray()
        self.map: dict = {}
        self.epoch = 0
        self.frames = 0
        self.dead = False

    def drain_available(self) -> int:
        """Apply every complete frame currently buffered (never blocks).
        Frames a completed wire call emitted are guaranteed present —
        the sidecar wrote them before that call's response."""
        if self.dead:
            return 0
        while True:
            try:
                chunk = self.sock.recv(1 << 20)
            except BlockingIOError:
                break
            except OSError:
                self.dead = True
                break
            if not chunk:  # EOF: the stream is a dead epoch
                self.dead = True
                break
            self.buf += chunk
        frames, self.buf = DecisionCache._frames_from(self.buf)
        for push in frames:
            if push.invalidate_all:
                self.map.clear()
            for uid in push.invalidate_uids:
                self.map.pop(uid, None)
            self.epoch = push.epoch
            for d in push.decisions:
                self.map[d.pod_uid] = d
        self.frames += len(frames)
        return len(frames)

    def pop(self, uid: str):
        return self.map.pop(uid, None)

    def close(self) -> None:
        self.client.close()


@dataclass
class _PhaseResult:
    name: str
    invalidation_rate_per_s: float
    wall_s: float = 0.0
    decisions: int = 0
    bound: int = 0
    hits: int = 0
    misses: int = 0
    latencies: list = field(default_factory=list)
    miss_latencies: list = field(default_factory=list)
    violations: int = 0
    retired: int = 0
    events_applied: dict = field(default_factory=dict)
    # Per-tenant split (raw tenant id → samples/counts; "-" = untagged).
    tenant_latencies: dict = field(default_factory=dict)
    tenant_counts: dict = field(default_factory=dict)
    tenant_bound: dict = field(default_factory=dict)
    tenant_violations: dict = field(default_factory=dict)


class _Driver:
    """One soak run's host side: the ResyncingClient, the push consumer,
    the retirement window, and the journal-size sampler."""

    def __init__(self, cfg: SoakConfig, sock: str, journal_dir: str):
        self.cfg = cfg
        self.registry = MetricsRegistry()
        # The SLO families (README metrics catalog): per-decision serving
        # latency by phase AND tenant, violations against the budget, the
        # budget gauge.
        self._slo_hist, self._slo_violations = _slo_families(
            self.registry, cfg.slo_budget_ms
        )
        # Driver-side tenant attribution (bounded labeler + admission
        # counters mirroring the server's); None with observability off.
        self.tenant_metrics = (
            TenantMetrics(self.registry) if cfg.observability else None
        )
        self.client = ResyncingClient(
            sock, deadline_s=120.0, seed=cfg.seed, registry=self.registry
        )
        self.consumer = PushConsumer(sock)
        self.cold_consumers = 0
        self.journal_dir = journal_dir
        self.wal_samples: list[int] = []
        self.compactions_observed = 0
        self._wal_prev = 0
        # Node objects by name (re-adds must diff against the live shape).
        self.node_objs: dict[str, object] = {}
        self._cap_toggle: dict[int, int] = {}
        self._label_epoch: dict[int, int] = {}
        self._ns_epoch = 0
        self.mix = WorkloadMix(
            cfg.mix,
            seed=cfg.seed * 7919 + 11,
            tenants=cfg.tenants,
            scheduler_name=profile_scheduler_name(cfg.profile),
        )
        # Node-death bookkeeping: churn nodes currently silenced, the
        # cumulative scenario-clock offset (Lease stamps must stay
        # monotone across phases), and event counts.
        self.dead: set[str] = set()
        self.time_base = 0.0
        self.node_deaths = 0
        self.node_revives = 0
        self.lease_renewals = 0
        self.pods_by_uid: dict[str, object] = {}
        # Bound uids, oldest first.  A deque: the retirement window
        # front-pops once per decision at steady state, and an O(n)
        # list.pop(0) over live_pod_cap entries would tax the paced
        # serving path itself.
        self.live: deque[str] = deque()
        self.retired = 0

    # -- fleet -------------------------------------------------------------

    def _accel_label(self, w, i: int):
        return _accel_label(self.cfg, w, i)

    def _serving_node(self, i: int, cpu: str = "16", label_epoch: int = 0):
        w = (
            make_node(f"lgn-{i}")
            .capacity({"cpu": cpu, "memory": "64Gi", "pods": 110})
            .zone(f"zone-{i % self.cfg.zones}")
            .region("region-1")
        )
        w = self._accel_label(w, i)
        if label_epoch:
            w = w.label("loadgen.tpu/epoch", str(label_epoch))
        return w.obj()

    def _churn_node(self, i: int):
        return self._accel_label(
            make_node(f"churn-{i}")
            .capacity({"cpu": "16", "memory": "64Gi", "pods": 110})
            .zone(f"zone-{i % self.cfg.zones}")
            .region("region-1"),
            i,
        ).obj()

    def build_fleet(self) -> None:
        for i in range(self.cfg.nodes):
            n = self._serving_node(i)
            self.node_objs[n.metadata.name] = n
            self.client.add("Node", n)
        for i in range(self.cfg.churn_nodes):
            n = self._churn_node(i)
            self.node_objs[n.metadata.name] = n
            self.client.add("Node", n)
        if self.cfg.node_grace_s > 0:
            from ..api import types as t
            from ..controllers import (
                NODE_NOT_READY,
                NODE_UNREACHABLE,
                lifecycle_taints,
            )

            # Pre-seed the lifecycle taint keys into the featurization
            # vocab BEFORE warmup compiles the device programs: the
            # first mid-soak transition would otherwise grow the taint
            # schema and pay a full XLA recompile inside the measured
            # window (the same trap the fleet soak's label-epoch
            # pre-seeding closes).
            import dataclasses

            probe = self.node_objs["churn-0"]
            tainted = dataclasses.replace(
                probe,
                spec=dataclasses.replace(
                    probe.spec,
                    taints=lifecycle_taints(NODE_NOT_READY)
                    + lifecycle_taints(NODE_UNREACHABLE),
                ),
            )
            self.client.add("Node", tainted)
            self.client.add("Node", probe)
            # Only churn nodes carry Leases: the lifecycle controller
            # governs exactly the pool the death scenario targets, and
            # the serving fleet stays exempt (unleased nodes are never
            # tainted).
            for i in range(self.cfg.churn_nodes):
                self.client.add("Lease", t.Lease(f"churn-{i}", 0.0))

    def _renew_alive_leases(self, ts: float) -> None:
        from ..api import types as t

        for i in range(self.cfg.churn_nodes):
            name = f"churn-{i}"
            if name not in self.dead and name in self.node_objs:
                self.client.add("Lease", t.Lease(name, ts))
                self.lease_renewals += 1

    def warmup(self) -> None:
        """Compile the device programs and the speculative machinery out
        of the measured window, then retire the warm wave so phase 0
        starts from an empty live set (and the deletes are exercised
        before anything is measured)."""
        from ..framework.metrics import TENANT_LABEL_KEY

        # Tenant labels grow the pod-label vocab — warm them too, or the
        # first tagged arrival recompiles inside the measured window.
        warm_tenants = [name for name, _w in self.cfg.tenants]
        warm = []
        if self.cfg.profile:
            # Heterogeneous warm wave (ISSUE 14): one pod per MIX
            # TEMPLATE round-robin, so every (label set, workload class)
            # group — and the class-active compiled program — lands in
            # warmup.  This is the wire-side half of the accel-vocab
            # pre-seed: the first hetero pod's featurize interns the
            # matrix's accelerator classes and backfills the labeled
            # node rows' topo slots HERE, not inside the measured
            # window (the PR 9/PR 10 taint-vocab trap).
            from ..api import types as t
            from .workloads import MIXES, TEMPLATES

            names = [n for n, _w in MIXES[self.cfg.mix]]
            sched_name = profile_scheduler_name(self.cfg.profile)
            for i in range(self.cfg.warm_pods):
                p = TEMPLATES[names[i % len(names)]](10**6 + i)
                p.metadata.name = f"lgwarm-{i}"
                p.metadata.labels = dict(p.metadata.labels or {})
                if sched_name:
                    p.spec.scheduler_name = sched_name
                if warm_tenants:
                    p.metadata.labels[TENANT_LABEL_KEY] = warm_tenants[
                        i % len(warm_tenants)
                    ]
                p.spec.containers[0].requests = {
                    "cpu": t.parse_quantity("50m", "cpu"),
                    "memory": t.parse_quantity("64Mi", "memory"),
                }
                warm.append(p)
        else:
            for i in range(self.cfg.warm_pods):
                w = make_pod(f"lgwarm-{i}").req({"cpu": "50m", "memory": "64Mi"})
                if warm_tenants:
                    w = w.label(
                        TENANT_LABEL_KEY, warm_tenants[i % len(warm_tenants)]
                    )
                warm.append(w.obj())
        half = len(warm) // 2
        self.client.add_pending_batch(warm[:half])
        for p in warm[:half]:
            self.client.schedule([p], drain=False)
        if len(warm) > half:
            self.client.schedule(warm[half:], drain=True)
        for p in warm:
            self.client.remove("Pod", p.uid)
        self.consumer.drain_available()
        self.consumer.map.clear()

    # -- scenario application ----------------------------------------------

    def apply_event(self, ev) -> None:
        if ev.kind == "inv_capacity":
            i = ev.data % self.cfg.nodes
            self._cap_toggle[i] = 1 - self._cap_toggle.get(i, 0)
            n = self._serving_node(
                i,
                cpu="15" if self._cap_toggle[i] else "16",
                label_epoch=self._label_epoch.get(i, 0),
            )
            self.node_objs[n.metadata.name] = n
            self.client.add("Node", n)
        elif ev.kind == "inv_label":
            i = ev.data % self.cfg.nodes
            self._label_epoch[i] = self._label_epoch.get(i, 0) + 1
            n = self._serving_node(
                i,
                cpu="15" if self._cap_toggle.get(i) else "16",
                label_epoch=self._label_epoch[i],
            )
            self.node_objs[n.metadata.name] = n
            self.client.add("Node", n)
        elif ev.kind == "inv_ns":
            self._ns_epoch += 1
            self.client.set_namespace_labels(
                "loadgen-churn", {"epoch": str(self._ns_epoch)}
            )
        elif ev.kind == "flap_down":
            name = f"churn-{ev.data}"
            # The node's bound pods vanish with it (engine contract);
            # drop them from the retirement window too.
            gone = {
                uid
                for uid in self.live
                if getattr(
                    self.pods_by_uid.get(uid), "_lg_node", None
                ) == name
            }
            if gone:
                self.live = deque(
                    u for u in self.live if u not in gone
                )
                for u in gone:
                    self.pods_by_uid.pop(u, None)
            self.client.remove("Node", name)
        elif ev.kind == "flap_up":
            n = self._churn_node(ev.data)
            self.node_objs[n.metadata.name] = n
            self.client.add("Node", n)
        elif ev.kind == "cold_consumer":
            # The push consumer restarts cold mid-stream: decision map
            # gone, fresh subscription, misses until the stream re-warms.
            self.consumer.close()
            self.consumer = PushConsumer(self.client.path)
            self.cold_consumers += 1
        elif ev.kind == "node_death":
            # The node object STAYS; its heartbeat goes silent.  The
            # server's lifecycle controller must detect the staleness,
            # taint, evict, and reschedule its pods — nothing else in
            # the op stream touches the dead node.
            self.dead.add(f"churn-{ev.data % max(1, self.cfg.churn_nodes)}")
            self.node_deaths += 1
        elif ev.kind == "node_revive":
            from ..api import types as t

            name = f"churn-{ev.data % max(1, self.cfg.churn_nodes)}"
            self.dead.discard(name)
            # A fresh renewal at the current scenario clock clears the
            # lifecycle taints (the node rejoined).
            self.client.add("Lease", t.Lease(name, self.time_base + ev.t))
            self.lease_renewals += 1
            self.node_revives += 1
        elif ev.kind == "lease_tick":
            self._renew_alive_leases(self.time_base + ev.t)
        else:
            raise ValueError(f"unknown scenario event {ev.kind!r}")

    # -- decisions ----------------------------------------------------------

    def decide(self, pod, res: _PhaseResult, deadline: float | None) -> None:
        """Serve one arrival: local map first (the plugin's PreFilter
        path), wire on miss.  Latency is measured from the arrival's
        schedule deadline (real pace — backlog included) or from issue
        (virtual pace)."""
        uid = pod.uid
        t_issue = time.perf_counter()
        self.consumer.drain_available()
        d = self.consumer.pop(uid)
        node = None
        if d is None:
            res.misses += 1
            results = self.client.schedule([pod], drain=False)
            for r in results:
                if r.pod_uid == uid and r.node_name:
                    node = r.node_name
            self.consumer.drain_available()
            t_done = time.perf_counter()
            res.miss_latencies.append(t_done - t_issue)
        else:
            res.hits += 1
            node = d.node_name or None
            t_done = time.perf_counter()
        base = t_issue if deadline is None else min(deadline, t_issue)
        lat = t_done - base
        res.latencies.append(lat)
        tenant = pod_tenant(pod)
        tlabel = (
            self.tenant_metrics.labeler.label_for(tenant)
            if self.tenant_metrics is not None
            else TENANT_FALLBACK
        )
        tkey = tenant or "-"
        res.tenant_latencies.setdefault(tkey, []).append(lat)
        res.tenant_counts[tkey] = res.tenant_counts.get(tkey, 0) + 1
        if self.tenant_metrics is not None:
            # The driver-side mirror of the server's admission counter
            # (one arrival = one admission in the open-loop stream).
            self.tenant_metrics.note("admitted", tenant)
            if node:
                self.tenant_metrics.note("bound", tenant)
        # Component split: total = queue_wait + service.  queue_wait is
        # the pre-service wait (driver backlog under real pace — the
        # deadline predating issue), service the serving call itself.
        self._slo_hist.observe(
            lat, phase=res.name, tenant=tlabel, component="total"
        )
        self._slo_hist.observe(
            max(0.0, t_issue - base),
            phase=res.name, tenant=tlabel, component="queue_wait",
        )
        self._slo_hist.observe(
            t_done - t_issue,
            phase=res.name, tenant=tlabel, component="service",
        )
        if lat > self.cfg.slo_budget_ms / 1e3:
            res.violations += 1
            res.tenant_violations[tkey] = (
                res.tenant_violations.get(tkey, 0) + 1
            )
            self._slo_violations.inc(phase=res.name, tenant=tlabel)
        res.decisions += 1
        if node:
            res.bound += 1
            res.tenant_bound[tkey] = res.tenant_bound.get(tkey, 0) + 1
            pod._lg_node = node
            self.pods_by_uid[uid] = pod
            self.live.append(uid)
            while len(self.live) > self.cfg.live_pod_cap:
                old = self.live.popleft()
                self.pods_by_uid.pop(old, None)
                self.client.remove("Pod", old)
                res.retired += 1
                self.retired += 1

    # -- journal growth ------------------------------------------------------

    def sample_wal(self) -> None:
        if not self.journal_dir:
            return
        try:
            size = os.path.getsize(
                os.path.join(self.journal_dir, Journal.WAL)
            )
        except OSError:
            size = 0
        if size < self._wal_prev:
            # Truncation happened between samples: one observed
            # compaction cycle (snapshot + truncate).
            self.compactions_observed += 1
        self._wal_prev = size
        self.wal_samples.append(size)

    def close(self) -> None:
        try:
            self.consumer.close()
        except OSError:
            pass
        self.client.close()


def _phase_specs(cfg: SoakConfig) -> list[tuple[str, float, float]]:
    specs = [("sustained", cfg.duration_s, cfg.invalidation_rate_per_s)]
    for k, rate in enumerate(cfg.knee_points):
        specs.append((f"knee-{k}", cfg.knee_phase_s, float(rate)))
    return specs


def _run_phase(
    driver: _Driver,
    cfg: SoakConfig,
    phase_index: int,
    name: str,
    duration_s: float,
    inv_rate: float,
    arrival_base: int,
) -> tuple[_PhaseResult, list[float]]:
    """Merge the phase's arrival schedule, hint windows, and scenario
    script into one time-ordered operation list and execute it."""
    seed = cfg.seed * 1_000_003 + phase_index
    if cfg.diurnal:
        offsets = diurnal_offsets(
            cfg.rate_pods_per_s,
            cfg.rate_pods_per_s * cfg.diurnal_peak_factor,
            cfg.diurnal_period_s,
            duration_s,
            seed,
        )
    else:
        offsets = poisson_offsets(cfg.rate_pods_per_s, duration_s, seed)
    pods = [driver.mix.pod(arrival_base + i) for i in range(len(offsets))]
    armed = cfg.node_grace_s > 0
    scenario = build_events(
        duration_s,
        seed + 500_009,
        nodes=cfg.nodes,
        churn_nodes=cfg.churn_nodes,
        invalidation_rate_per_s=inv_rate,
        inv_mix=DEFAULT_INV_MIX,
        node_flap_period_s=cfg.node_flap_period_s,
        flap_down_s=cfg.flap_down_s,
        cold_consumer_period_s=cfg.cold_consumer_period_s,
        node_death_period_s=cfg.node_death_period_s if armed else 0.0,
        node_death_down_s=cfg.node_death_down_s,
        lease_interval_s=cfg.lease_interval_s if armed else 0.0,
    )
    # Merge: (t, class, idx) — hints flush at their window start ahead
    # of same-instant decisions; scenario events order between them by
    # their own timestamps.  The tuple sort is total and seed-stable.
    ops: list[tuple[float, int, int, object]] = []
    for w_start, idxs in coalesce(offsets, cfg.hint_coalesce_s):
        ops.append((w_start, 0, idxs[0], idxs))
    for j, ev in enumerate(scenario):
        ops.append((ev.t, 1, j, ev))
    for i, off in enumerate(offsets):
        ops.append((off, 2, i, i))
    ops.sort(key=lambda e: (e[0], e[1], e[2]))

    res = _PhaseResult(name=name, invalidation_rate_per_s=inv_rate)
    t0 = time.perf_counter()
    for t_ev, klass, _idx, payload in ops:
        if cfg.pace == "real":
            delay = (t0 + t_ev) - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
        if klass == 0:
            driver.client.add_pending_batch(
                [pods[i] for i in payload]
            )
            driver.sample_wal()
        elif klass == 1:
            driver.apply_event(payload)
            res.events_applied[payload.kind] = (
                res.events_applied.get(payload.kind, 0) + 1
            )
            driver.sample_wal()
        else:
            deadline = t0 + t_ev if cfg.pace == "real" else None
            driver.decide(pods[payload], res, deadline)
    driver.sample_wal()
    # Lease stamps must stay monotone across phases: advance the
    # scenario-clock base by this phase's span.
    driver.time_base += duration_s
    res.wall_s = round(time.perf_counter() - t0, 3)
    return res, offsets


def _knee_analysis(
    phases: list[_PhaseResult], miss_cost_ms: float
) -> dict:
    """The knee curve: hit rate and latency per invalidation intensity,
    plus the located knee — the first intensity where the hit rate
    drops below 0.5 (the cache serves less than it misses) or the
    median decision costs more than a miss (speculation stopped
    paying)."""
    points = []
    knee = None
    for p in phases:
        total = p.hits + p.misses
        hit_rate = p.hits / total if total else 0.0
        point = {
            "intensity_per_s": p.invalidation_rate_per_s,
            "hit_rate": round(hit_rate, 4),
            "decisions": total,
            "p50_ms": round(_pct(p.latencies, 50) * 1e3, 3),
            "p99_ms": round(_pct(p.latencies, 99) * 1e3, 3),
            "mean_ms": round(
                float(np.mean(p.latencies)) * 1e3 if p.latencies else 0.0,
                3,
            ),
        }
        points.append(point)
        collapsed = hit_rate < 0.5 or (
            miss_cost_ms > 0 and point["p50_ms"] > miss_cost_ms
        )
        if knee is None and collapsed:
            knee = p.invalidation_rate_per_s
    return {
        "miss_cost_ms": round(miss_cost_ms, 3),
        "points": points,
        "knee_intensity_per_s": knee,
    }


def _lifecycle_argv(cfg: SoakConfig) -> list[str]:
    """The `serve` lifecycle-arming flags a node-loss soak needs (shared
    by the single-process and fleet child spawns)."""
    if cfg.node_grace_s <= 0:
        return []
    return [
        "--node-grace-s", str(cfg.node_grace_s),
        "--node-unreachable-s",
        str(cfg.node_unreachable_s or cfg.node_grace_s * 2.5),
        "--gc-horizon-s", str(cfg.gc_horizon_s or cfg.node_grace_s * 6),
    ]


def _launch_serve(
    argv: list[str], out_dir: str, sock: str, label: str,
    deadline_s: float,
):
    """Spawn one `serve` child and wait for its socket.  Output goes to
    a per-child LOG FILE in the artifact directory, never an unread
    PIPE — a chatty child (cycle-span logging, takeover restarts) would
    otherwise block on a full pipe mid-soak and read as a hung owner."""
    log_path = os.path.join(out_dir, f"{label}.log")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["TPU_FLIGHT_DIR"] = out_dir
    log = open(log_path, "a", encoding="utf-8")
    try:
        proc = subprocess.Popen(
            argv,
            stdout=log,
            stderr=subprocess.STDOUT,
            text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)
            ))),
            env=env,
        )
    finally:
        log.close()  # the child holds its own dup
    deadline = time.monotonic() + deadline_s
    while not os.path.exists(sock):
        if proc.poll() is not None:
            try:
                with open(log_path, encoding="utf-8") as f:
                    out = f.read()
            except OSError:
                out = ""
            raise RuntimeError(
                f"{label} exited rc={proc.returncode}: {out[-2000:]}"
            )
        if time.monotonic() > deadline:
            proc.kill()
            raise RuntimeError(f"{label} never bound its socket")
        time.sleep(0.05)
    return proc


def _spawn_serve(cfg: SoakConfig, sock: str, journal_dir: str, out_dir: str):
    """The real deployment: ``python -m kubernetes_tpu serve`` as a
    child process, journaled and speculative, flight dumps into the
    artifact directory."""
    argv = [
        sys.executable, "-m", "kubernetes_tpu", "serve",
        "--socket", sock,
        "--speculate",
        "--batch-size", str(cfg.batch_size),
        "--chunk-size", str(cfg.chunk_size),
        "--journal-dir", journal_dir,
        "--journal-fsync", cfg.journal_fsync,
        "--snapshot-every", str(cfg.snapshot_every),
        "--pipeline-depth", str(cfg.pipeline_depth),
    ] + (["--profile", cfg.profile] if cfg.profile else []) + _lifecycle_argv(cfg)
    return _launch_serve(argv, out_dir, sock, "serve", deadline_s=180.0)


def run_soak(cfg: SoakConfig) -> dict:
    """Execute one soak and return the artifact document (the
    ``SOAK_rNN.json`` schema README documents)."""
    tmp = tempfile.TemporaryDirectory(prefix="tpu-soak-")
    out_dir = cfg.out_dir or tmp.name
    os.makedirs(out_dir, exist_ok=True)
    # Only dumps shed by THIS run count as its incidents — a persistent
    # out_dir may hold earlier runs' flight dumps (names embed the
    # child's pid, so they are never overwritten).
    pre_existing = set(os.listdir(out_dir))
    journal_dir = cfg.journal_dir or os.path.join(tmp.name, "journal")
    sock = os.path.join(tmp.name, "soak.sock")
    proc = None
    srv = None
    t_setup = time.perf_counter()
    if cfg.two_process:
        proc = _spawn_serve(cfg, sock, journal_dir, out_dir)
    else:
        from ..framework.leaderelection import FileLease, read_epoch
        from ..sidecar.server import SidecarServer

        os.makedirs(journal_dir, exist_ok=True)
        lease_path = os.path.join(journal_dir, "lease")
        lease = FileLease(lease_path, identity=f"soak-{os.getpid()}")
        lease.acquire(block=True)
        journal = Journal(
            journal_dir,
            epoch=lease.epoch,
            fence=lambda: read_epoch(lease_path),
            fsync=cfg.journal_fsync == "always",
        )
        srv = SidecarServer(
            sock,
            batch_size=cfg.batch_size,
            chunk_size=cfg.chunk_size,
            pipeline_depth=cfg.pipeline_depth,
            profiles=named_extra_profiles(cfg.profile),
            speculate=True,
            journal=journal,
            snapshot_every_batches=cfg.snapshot_every,
        )
        if cfg.node_grace_s > 0:
            srv.scheduler.node_lifecycle.arm(
                grace_period_s=cfg.node_grace_s,
                unreachable_after_s=(
                    cfg.node_unreachable_s or cfg.node_grace_s * 2.5
                ),
            )
            srv.scheduler.pod_gc.arm(
                gc_horizon_s=cfg.gc_horizon_s or cfg.node_grace_s * 6
            )
        srv.serve_background()

    driver = None
    phases: list[_PhaseResult] = []
    arrival_hashes: list[str] = []
    all_offsets: list[list[float]] = []
    try:
        driver = _Driver(cfg, sock, journal_dir)
        driver.build_fleet()
        driver.warmup()
        setup_s = round(time.perf_counter() - t_setup, 3)
        arrival_base = 0
        for k, (name, dur, rate) in enumerate(_phase_specs(cfg)):
            res, offsets = _run_phase(
                driver, cfg, k, name, dur, rate, arrival_base
            )
            arrival_base += len(offsets)
            phases.append(res)
            arrival_hashes.append(_sha([round(o, 9) for o in offsets]))
            all_offsets.append(offsets)
        if cfg.node_grace_s > 0:
            # Run to quiescence before measuring loop closure: requeued
            # eviction victims still in flight — or rolled back by the
            # final phase's invalidation churn — get their final
            # placements, so `reschedules` counts completed loops, not
            # the instant's pool state.  (Deterministic: the drain is
            # part of the op sequence in both same-seed runs.)
            driver.client.schedule([], drain=True)
        dump = driver.client.dump()
        bindings = {
            uid: rec["node"]
            for uid, rec in dump.get("pods", {}).items()
            if rec.get("node")
        }
        flight = driver.client.flight()
        flight_path = os.path.join(out_dir, "soak-flight.json")
        with open(flight_path, "w", encoding="utf-8") as f:
            json.dump(flight, f, indent=1, sort_keys=True)
    finally:
        if driver is not None:
            driver.close()
        if srv is not None:
            srv.close()
            lease.release()
        if proc is not None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=60)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=30)

    sustained = phases[0]
    knee_phases = phases[1:]
    miss_cost_ms = round(
        float(np.mean(sustained.miss_latencies)) * 1e3
        if sustained.miss_latencies
        else 0.0,
        3,
    )
    slo = dict(
        _lat_summary(sustained.latencies),
        budget_ms=cfg.slo_budget_ms,
        violations=sustained.violations,
        violation_rate=round(
            sustained.violations / max(1, sustained.decisions), 4
        ),
    )
    spec_stats = dump.get("speculation") or {}
    total_hits = sum(p.hits for p in phases)
    total_misses = sum(p.misses for p in phases)
    incidents = sorted(
        f
        for f in os.listdir(out_dir)
        if f.startswith("flight-")
        and f.endswith(".json")
        and f not in pre_existing
    )
    wal_max = max(driver.wal_samples) if driver.wal_samples else 0
    journal_stats = dump.get("journal") or {}
    node_loss = None
    if cfg.node_grace_s > 0:
        # Evictions counted by the server (taint eviction + GC); a
        # RESCHEDULE is a live pod whose final binding differs from the
        # node the driver first saw it bound to.
        lifecycle = dump.get("node_lifecycle") or {}
        gc_stats = dump.get("pod_gc") or {}
        moved = sum(
            1
            for uid, node in bindings.items()
            if uid in driver.pods_by_uid
            and getattr(driver.pods_by_uid[uid], "_lg_node", node) != node
        )
        gc_collected = sum(
            (gc_stats.get("collected") or {}).values()
        )
        ev = dump.get("evictions") or {}
        node_loss = {
            "node_deaths": driver.node_deaths,
            "node_revives": driver.node_revives,
            "lease_renewals": driver.lease_renewals,
            "lifecycle": lifecycle,
            "pod_gc": gc_stats,
            "evictions": ev.get("total", 0),
            "gc_collected": gc_collected,
            # Loop closure per pod (server-counted): distinct evicted
            # uids, and how many of them are bound AGAIN at the end —
            # eviction → requeue → reschedule completed.
            "evicted_uids": ev.get("evicted_uids", 0),
            "reschedules": ev.get("rebound", 0),
            # Broader churn: live pods whose final placement differs
            # from the first-delivered decision (includes speculative
            # full-rollback re-placements, not just evictions).
            "placements_moved": moved,
        }
    artifact = {
        "metric": "soak_slo_knee_journal",
        "seed": cfg.seed,
        "config": asdict(cfg),
        "setup_s": setup_s,
        "wall_s": round(sum(p.wall_s for p in phases), 3),
        "slo": slo,
        "sustained_pods_per_sec": round(
            sustained.decisions / sustained.wall_s
            if sustained.wall_s
            else 0.0,
            1,
        ),
        "speculation": {
            "hits": total_hits,
            "misses": total_misses,
            "miss_rate": round(
                total_misses / max(1, total_hits + total_misses), 4
            ),
            "sidecar": spec_stats,
        },
        "knee": _knee_analysis(knee_phases, miss_cost_ms),
        "journal": {
            "dir_sampled": bool(driver.wal_samples),
            "wal_bytes_max": wal_max,
            "wal_bytes_final": (
                driver.wal_samples[-1] if driver.wal_samples else 0
            ),
            "compactions_observed": driver.compactions_observed,
            # Bounded = compaction cycled repeatedly AND the final size
            # sits strictly below the high-water mark (a WAL that grows
            # monotonically to the end compacted too early to count).
            "bounded": bool(
                driver.compactions_observed >= 2
                and driver.wal_samples
                and driver.wal_samples[-1] < wal_max
            ),
            "stats": journal_stats,
        },
        "phases": [
            {
                "name": p.name,
                "invalidation_rate_per_s": p.invalidation_rate_per_s,
                "wall_s": p.wall_s,
                "decisions": p.decisions,
                "bound": p.bound,
                "hits": p.hits,
                "misses": p.misses,
                "retired": p.retired,
                "violations": p.violations,
                "events": dict(sorted(p.events_applied.items())),
                "latency": _lat_summary(p.latencies),
            }
            for p in phases
        ],
        "workload_mix": dict(driver.mix.counts),
        "tenants": (
            dict(
                per_tenant=_tenant_summary(phases),
                counters=(
                    driver.tenant_metrics.snapshot()
                    if driver.tenant_metrics is not None
                    else {}
                ),
                mix=dict(driver.mix.tenant_counts),
            )
            if cfg.tenants
            else None
        ),
        "node_loss": node_loss,
        "cold_consumers": driver.cold_consumers,
        "retired_total": driver.retired,
        "bound_final": len(bindings),
        "determinism": {
            "arrival_sha256": _sha(arrival_hashes),
            "bindings_sha256": _sha(sorted(bindings.items())),
            "arrivals_total": sum(len(o) for o in all_offsets),
        },
        "incidents": incidents,
        "flight": os.path.basename(flight_path),
        "pace": cfg.pace,
    }
    # Keep the raw offsets available to callers (the determinism smoke
    # compares them across runs) without bloating the JSON artifact.
    artifact["_arrival_offsets"] = all_offsets
    return artifact


# -- the partitioned-fleet soak ---------------------------------------------

FLEET_INV_MIX: tuple[tuple[str, float], ...] = (
    # The fleet feed has no namespace-label op (owners take the KINDS
    # surface only), so the churn budget splits over the two node-shaped
    # invalidations.
    ("inv_capacity", 0.7),
    ("inv_label", 0.3),
)


def _spawn_shard_serve(
    cfg: SoakConfig,
    shard: int,
    shards: int,
    sock: str,
    map_path: str,
    journal_dir: str,
    out_dir: str,
):
    """One REAL fleet owner: ``python -m kubernetes_tpu serve --shard-of
    k/N`` as a child process — its own journal, the shared shard-map
    file, the lifecycle flags armed per owner when the soak injects node
    deaths, flight dumps + the child's log into the artifact
    directory."""
    argv = [
        sys.executable, "-m", "kubernetes_tpu", "serve",
        "--socket", sock,
        "--shard-of", f"{shard}/{shards}",
        "--shard-map", map_path,
        "--batch-size", str(cfg.batch_size),
        "--chunk-size", "1",
        "--journal-dir", journal_dir,
        "--journal-fsync", cfg.journal_fsync,
        "--snapshot-every", str(cfg.snapshot_every),
    ] + ([] if cfg.observability else ["--no-observability"]) \
      + (["--profile", cfg.profile] if cfg.profile else []) \
      + _lifecycle_argv(cfg)
    return _launch_serve(
        argv, out_dir, sock, f"serve-shard{shard}", deadline_s=300.0
    )


def _spawn_standby_serve(cfg: SoakConfig, sock: str, out_dir: str, slot: int):
    """One warm-standby fleet child: ``serve --standby`` — engine booted
    and compiled, no shard, no journal, lease unclaimed — parked until a
    promotion's adopt_shard frame (fleet/standby.py).  Lifecycle knobs
    ride the adopt payload, not the argv: a slot is shard-agnostic."""
    argv = [
        sys.executable, "-m", "kubernetes_tpu", "serve",
        "--socket", sock,
        "--standby",
        "--batch-size", str(cfg.batch_size),
        "--chunk-size", "1",
    ] + ([] if cfg.observability else ["--no-observability"]) \
      + (["--profile", cfg.profile] if cfg.profile else [])
    return _launch_serve(
        argv, out_dir, sock, f"standby{slot}", deadline_s=300.0
    )


def _standby_warm_objs(
    cfg: SoakConfig, warm_tenants, hot: bool, armed: bool, epoch_hi: int = 4
):
    """The standby warm wave (ISSUE 18): every label-schema axis the
    live stream can reach — zones, accelerator classes, epoch labels,
    the hot selector, lifecycle taints, tenant/template label combos —
    built as objects a parked child exercises BEFORE promotion, so
    adoption never pays an XLA recompile mid-incident.  Mirrors
    run_fleet_soak's own warmup (same WorkloadMix template space,
    disjoint index range + ``sbwarm-`` node names: everything here is
    removed again after compiling, leaving only the grown vocab)."""
    nodes = []
    for i in range(max(cfg.zones, 12)):
        w = (
            make_node(f"sbwarm-{i}")
            .capacity({"cpu": "16", "memory": "64Gi", "pods": 110})
            .zone(f"zone-{i % max(cfg.zones, 1)}")
            .region("region-1")
        )
        w = _accel_label(cfg, w, i)
        if hot:
            w = w.label("loadgen.tpu/hot", "1")
        nodes.append(w.obj())
    epoch_nodes = []
    for epoch in range(1, epoch_hi + 1):
        w = (
            make_node("sbwarm-0")
            .capacity({"cpu": "16", "memory": "64Gi", "pods": 110})
            .zone("zone-0")
            .region("region-1")
            .label("loadgen.tpu/epoch", str(epoch))
        )
        if hot:
            w = w.label("loadgen.tpu/hot", "1")
        epoch_nodes.append(_accel_label(cfg, w, 0).obj())
    tainted = []
    if armed:
        import dataclasses

        from ..controllers import (
            NODE_NOT_READY,
            NODE_UNREACHABLE,
            lifecycle_taints,
        )

        probe = nodes[0]
        tainted.append(
            dataclasses.replace(
                probe,
                spec=dataclasses.replace(
                    probe.spec,
                    taints=lifecycle_taints(NODE_NOT_READY)
                    + lifecycle_taints(NODE_UNREACHABLE),
                ),
            )
        )
    warm_mix = WorkloadMix(
        cfg.mix,
        seed=cfg.seed * 104_729 + 31,
        scheduler_name=profile_scheduler_name(cfg.profile),
    )
    n_warm = min(cfg.warm_pods, 48)
    pods = [
        warm_mix.pod(
            30_000_000 + i,
            # Block-assigned tenants — the same combo-coverage argument
            # as the fleet warmup's own wave.
            tenant=(
                warm_tenants[
                    min(
                        (i * len(warm_tenants)) // max(n_warm, 1),
                        len(warm_tenants) - 1,
                    )
                ]
                if warm_tenants
                else None
            ),
        )
        for i in range(n_warm)
    ]
    if hot:
        for j, p in enumerate(pods):
            if j % 2 == 0:
                p.spec.node_selector["loadgen.tpu/hot"] = "1"
    preemptor = (
        make_pod("sbwarm-preemptor").req({"cpu": "12"}).priority(100).obj()
    )
    probe_pod = warm_mix.pod(
        30_900_000, tenant=warm_tenants[0] if warm_tenants else None
    )
    return nodes, epoch_nodes, tainted, pods, preemptor, probe_pod


def _warm_standby_sched(
    cfg: SoakConfig, sched, warm_tenants, hot: bool, armed: bool,
    epoch_hi: int = 4,
) -> None:
    """Warm an IN-PROCESS standby scheduler: add every schema-growing
    node variant, bind + delete a combo-covering pod wave, dry-run the
    preemptor, remove the warm nodes, and absorb the dirty-row flush
    with one eval-only probe — the promoted owner's journal recovery
    then replays real objects into an already-compiled engine."""
    nodes, epoch_nodes, tainted, pods, preemptor, probe = _standby_warm_objs(
        cfg, warm_tenants, hot, armed, epoch_hi
    )
    for n in nodes:
        sched.add_node(n)
    for n in epoch_nodes:
        sched.add_node(n)
    sched.add_node(nodes[0])  # restore sbwarm-0's epoch-free shape
    for n in tainted:
        sched.add_node(n)
    if tainted:
        sched.add_node(nodes[0])
    for p in pods:
        sched.update_pod(p)
    sched.schedule_all_pending()
    sched.preempt_propose(preemptor)
    for p in pods:
        sched.delete_pod(p.uid)
    for n in nodes:
        sched.remove_node(n.metadata.name)
    sched.propose_pod(probe)


def _warm_standby_wire(
    cfg: SoakConfig, sock: str, warm_tenants, hot: bool, armed: bool,
    epoch_hi: int = 4,
) -> None:
    """The two-process twin of ``_warm_standby_sched``: drive the same
    warm wave into a parked `serve --standby` child over its socket
    (the preempt dry-run rides the fleet frame, which StandbyServe
    allows pre-adoption for exactly this)."""
    from ..api import serialize

    nodes, epoch_nodes, tainted, pods, preemptor, probe = _standby_warm_objs(
        cfg, warm_tenants, hot, armed, epoch_hi
    )
    client = SidecarClient(sock, deadline_s=300.0)
    try:
        for n in nodes:
            client.add("Node", n)
        for n in epoch_nodes:
            client.add("Node", n)
        client.add("Node", nodes[0])
        for n in tainted:
            client.add("Node", n)
        if tainted:
            client.add("Node", nodes[0])
        client.schedule(pods, drain=True)
        client.fleet("preempt_propose", {"pod": serialize.to_dict(preemptor)})
        for p in pods:
            client.remove("Pod", p.uid)
        for n in nodes:
            client.remove("Node", n.metadata.name)
        client.schedule([probe], drain=True)
        client.remove("Pod", probe.uid)
    finally:
        client.close()


def run_fleet_soak(cfg: SoakConfig, shards: int = 2) -> dict:
    """Soak the PARTITIONED fleet (kubernetes_tpu/fleet): open-loop
    arrivals scatter-gathered by the router over ``shards`` journaled
    shard owners, with the existing loadgen scenarios re-aimed at the
    fleet's failure surfaces —

    - **node flaps hit ONE shard**: the churn pool is pinned to shard 0
      by shard-map overrides, so a flapping shard's SLO degrades while
      the others' hold (visible in the per-shard percentiles);
    - **node DEATHS inside a shard** (``node_grace_s > 0``): churn-node
      heartbeats go silent, the OWNING shard's lifecycle controller
      writes the taints and evicts, and the router requeues the evicted
      pods to rebind on whichever shard has room — the cross-shard half
      of the failure-response loop, counted per shard;
    - **cold router restarts** (the fleet's cold-consumer analog): the
      ``cold_consumer`` scenario event tears the router down mid-stream
      and rebuilds it from the owners' truth (adopt_bindings) — pending
      pods re-feed, bound pods must not double-schedule, absorbed-but-
      unbound evictions re-adopt;
    - **per-shard SLO percentiles + WAL growth**: each decision's latency
      is attributed to the shard that committed it, and every owner's
      journal is sampled for bounded-compaction evidence.

    ``cfg.two_process=True`` runs the REAL multi-process fleet: N
    ``serve --shard-of k/N`` children over the unix-socket wire, driven
    through ``WireShardOwner`` with per-call deadlines — a hung or dead
    owner degrades to TAKEOVER (the child restarts, recovers its own
    journal before its first frame, and the router re-adopts) instead of
    wedging scatter-gather.

    Same determinism contract as run_soak: the operation sequence is a
    pure function of the seed, so same-seed runs land bit-identical
    final bindings (the --shards determinism cross-check in
    scripts/run_soak.py asserts exactly that)."""
    from ..fleet import (
        AutoscalerConfig,
        FleetAutoscaler,
        FleetOwnerUnreachable,
        FleetRouter,
        ShardMap,
        ShardOwner,
        WireShardOwner,
    )
    from ..scheduler import TPUScheduler

    ckpt_prior = None
    resume_from = 0
    if cfg.resume:
        if not cfg.checkpoint_path:
            raise ValueError("SoakConfig.resume requires checkpoint_path")
        ckpt_prior = load_checkpoint(cfg.checkpoint_path)
        if ckpt_prior is None:
            raise RuntimeError(
                f"resume requested but no checkpoint at {cfg.checkpoint_path}"
            )
        resume_from = int(ckpt_prior["state"]["det"]["op_index"])
    tmp = tempfile.TemporaryDirectory(prefix="tpu-fleet-soak-")
    out_dir = cfg.out_dir or tmp.name
    os.makedirs(out_dir, exist_ok=True)
    journal_root = cfg.journal_dir or os.path.join(tmp.name, "journal")
    if cfg.resume:
        # Replay regenerates every owner journal from op 0 — recovering a
        # prior run's journals UNDERNEATH the replay would double-apply
        # its state, so a resumed run always writes fresh shard journals,
        # keyed by the checkpoint generation it resumed from.
        journal_root = os.path.join(
            journal_root, f"resume-g{int(ckpt_prior['generation'])}"
        )
    armed = cfg.node_grace_s > 0
    lifecycle = (
        {
            "node_grace_s": cfg.node_grace_s,
            "node_unreachable_s": cfg.node_unreachable_s,
            "gc_horizon_s": cfg.gc_horizon_s,
        }
        if armed
        else None
    )
    smap = ShardMap(n_shards=shards)
    for i in range(cfg.churn_nodes):
        smap.assign(f"churn-{i}", 0)  # flaps/deaths land on shard 0 only
    # The hot pool (ISSUE 11's hot-spot scenario): the serving nodes the
    # INITIAL map buckets onto shard 0 carry the hot label, and
    # hot_fraction of arrivals select on it — their load concentrates
    # there until the autoscaler's split moves half the pool (bucketed,
    # not pinned: pins survive splits by design and would anchor it).
    hot_serving = (
        {i for i in range(cfg.nodes) if smap.owner_of(f"lgn-{i}") == 0}
        if cfg.hot_fraction > 0
        else set()
    )
    registry = MetricsRegistry()
    owners: dict[int, object] = {}
    procs: dict[int, object] = {}
    socks: dict[int, str] = {}
    map_path = os.path.join(tmp.name, "shardmap.json")

    def spawn_owner(k: int):
        if not cfg.two_process:
            return ShardOwner(
                k,
                TPUScheduler(
                    batch_size=cfg.batch_size,
                    chunk_size=1,
                    tenant_attribution=cfg.observability,
                    profiles=named_extra_profiles(cfg.profile),
                ),
                smap,
                state_dir=os.path.join(journal_root, f"shard{k}"),
                journal_fsync=cfg.journal_fsync == "always",
                snapshot_every_batches=cfg.snapshot_every,
                lifecycle=lifecycle,
                observability=cfg.observability,
            )
        socks[k] = os.path.join(tmp.name, f"shard{k}.sock")
        procs[k] = _spawn_shard_serve(
            cfg, k, shards, socks[k], map_path,
            os.path.join(journal_root, f"shard{k}"), out_dir,
        )
        return WireShardOwner(
            path=socks[k],
            deadline_s=120.0,
            max_retries=2,
            registry=registry,
            shard_id=k,
        )

    if cfg.two_process:
        smap.save(map_path)  # shared ownership record, before any child
    for k in range(shards):
        owners[k] = spawn_owner(k)
    # Children die with the run, success or not: any exception out of
    # the warmup or the op loop (a protocol desync, an assertion, a
    # KeyboardInterrupt) must not leak N serve processes holding
    # journal leases and sockets.
    standby = None
    ckpt = None
    try:
        mix = WorkloadMix(
            cfg.mix,
            seed=cfg.seed * 7919 + 11,
            tenants=cfg.tenants,
            scheduler_name=profile_scheduler_name(cfg.profile),
        )
        slo_hist, slo_violations = _slo_families(
            registry, cfg.slo_budget_ms
        )
        tenant_metrics = (
            TenantMetrics(registry, hash_buckets=cfg.tenant_hash_buckets)
            if cfg.observability
            else None
        )
        node_objs: dict[str, object] = {}
        feed_order: list[str] = []
        router_restarts = 0
        owner_takeovers = 0
        # Durable admission order across router rebuilds: a cold restart
        # rebuilds the router (fresh fairness ledger — deterministic, the
        # restart is a seeded scenario event), so the run-wide order is
        # the concatenation of every router generation's admitted_log.
        admission_order: list[str] = []

        def mk_admission_policy():
            """One FairAdmission per router generation: weights are
            accelerator-time shares from the synthetic throughput matrix
            over the streams' workload_class mapping and the configured
            hetero pools (uniform fallback when unmapped); clock is the
            router's logical clock (arm_admission injects it); metrics
            ride the soak registry when observability is on — and only
            observe: decisions are identical with it off."""
            from ..framework.fairness import FairAdmission, weights_from_matrix
            from ..ops.throughput import DEFAULT_THROUGHPUT_MATRIX

            a = dict(cfg.admission or {})
            classes = {
                str(ts["name"]): str(ts["workload_class"])
                for ts in cfg.tenant_streams
                if ts.get("workload_class")
            }
            pools = (
                {str(ac): int(wt) for ac, wt in cfg.hetero_pools} or None
            )
            return FairAdmission(
                weights=weights_from_matrix(
                    DEFAULT_THROUGHPUT_MATRIX, classes, pools
                ),
                rate_pods_per_s=float(a.get("rate_pods_per_s", 0.0)),
                burst=float(a.get("burst", 8.0)),
                aging_max_wait_s=float(a.get("aging_max_wait_s", 30.0)),
                slo_wait_budget_s=float(a.get("slo_wait_budget_s", 60.0)),
                registry=registry if tenant_metrics is not None else None,
                labeler=(
                    tenant_metrics.labeler
                    if tenant_metrics is not None
                    else None
                ),
            )

        def mk_router() -> FleetRouter:
            r = FleetRouter(
                owners, smap, batch_size=cfg.batch_size, registry=registry,
                observability=cfg.observability,
            )
            if cfg.two_process:
                from ..framework.config import DEFAULT_PROFILE

                r.profile_filters = tuple(DEFAULT_PROFILE.filters)
            else:
                r.profile_filters = tuple(owners[0].sched.profile.filters)
            return r

        def feed_node(r: FleetRouter, n) -> None:
            name = n.metadata.name
            if name not in node_objs:
                feed_order.append(name)
            node_objs[name] = n
            r.add_object("Node", n)

        router = mk_router()
        # Build/warmup flight records sort ahead of the measured window
        # on the logical axis.
        router.note_logical_time(-1.0)
        autoscaler = None  # built below, once the sampling dicts exist
        for i in range(cfg.nodes):
            w = (
                make_node(f"lgn-{i}")
                .capacity({"cpu": "16", "memory": "64Gi", "pods": 110})
                .zone(f"zone-{i % cfg.zones}")
                .region("region-1")
            )
            w = _accel_label(cfg, w, i)
            if i in hot_serving:
                w = w.label("loadgen.tpu/hot", "1")
            feed_node(router, w.obj())
        for i in range(cfg.churn_nodes):
            feed_node(
                router,
                _accel_label(
                    cfg,
                    make_node(f"churn-{i}")
                    .capacity({"cpu": "16", "memory": "64Gi", "pods": 110})
                    .zone(f"zone-{i % cfg.zones}")
                    .region("region-1"),
                    i,
                ).obj(),
            )
        if armed:
            from ..api import types as t
            from ..controllers import (
                NODE_NOT_READY,
                NODE_UNREACHABLE,
                lifecycle_taints,
            )

            # Pre-seed the lifecycle taint keys into EVERY owner's
            # featurization vocab BEFORE warmup compiles the device
            # programs.  Two traps close here: (1) the first mid-soak
            # transition would otherwise grow the taint schema and pay a
            # full XLA recompile inside the measured window (PR 9's
            # single-scheduler trap); (2) TaintToleration's is_active gate
            # keys on the LOCAL vocab — a shard that never interned a taint
            # would skip the op while the churn shard runs it, skewing the
            # reverse-normalized baseline (+MaxNodeScore×weight on the
            # tainted shard's nodes) and funnelling every decision there.
            # With the vocab uniform, lifecycle taints carry exactly
            # upstream's score semantics: none (only PreferNoSchedule
            # counts), so per-shard normalization agrees.
            import dataclasses

            def preseed(name: str) -> None:
                probe = node_objs[name]
                tainted = dataclasses.replace(
                    probe,
                    spec=dataclasses.replace(
                        probe.spec,
                        taints=lifecycle_taints(NODE_NOT_READY)
                        + lifecycle_taints(NODE_UNREACHABLE),
                    ),
                )
                router.add_object("Node", tainted)
                router.add_object("Node", probe)

            preseed("churn-0")  # shard 0 (the pinned churn pool)
            seeded = {smap.owner_of("churn-0")}
            for i in range(cfg.nodes):
                name = f"lgn-{i}"
                k = smap.owner_of(name)
                if k not in seeded:
                    seeded.add(k)
                    preseed(name)
                if len(seeded) == shards:
                    break
            # Only churn nodes carry Leases: the per-owner lifecycle loop
            # governs exactly the death-eligible pool; the serving fleet
            # stays exempt (unleased nodes are never tainted).
            for i in range(cfg.churn_nodes):
                router.add_object("Lease", t.Lease(f"churn-{i}", 0.0))

        # Warm the compiled eval passes out of the measured window.  Two
        # things force a recompile mid-stream if not warmed here: a pod
        # class whose active-op set first appears inside the window, and the
        # inv_label scenario's epoch labels growing the node-label vocab
        # (a new schema keys a new compiled pass — one ~20s CPU-box compile
        # lands squarely on the measured percentiles).  So the warm wave
        # draws from the SAME WorkloadMix templates (renamed far outside the
        # stream's index space) and the vocab is pre-seeded with the epoch
        # label values the scenario can reach, then the node is restored.
        warm_mix = WorkloadMix(
            cfg.mix,
            seed=cfg.seed * 104_729 + 31,
            scheduler_name=profile_scheduler_name(cfg.profile),
        )
        for epoch in range(1, 5):
            w = (
                make_node("lgn-0")
                .capacity({"cpu": "16", "memory": "64Gi", "pods": 110})
                .zone("zone-0")
                .region("region-1")
                .label("loadgen.tpu/epoch", str(epoch))
            )
            if 0 in hot_serving:
                w = w.label("loadgen.tpu/hot", "1")
            feed_node(router, w.obj())
        # Tenant labels grow the pod-label vocab: the warm wave must
        # carry every tenant the stream will, or the first tenant-tagged
        # arrival pays a full XLA recompile inside the measured window
        # (the same trap the epoch/hot-label pre-seeds close).
        warm_tenants = [
            str(ts["name"]) for ts in cfg.tenant_streams
        ] or [name for name, _w in mix.tenants]
        n_warm = min(cfg.warm_pods, 48)
        warm = [
            warm_mix.pod(
                10_000_000 + i,
                # BLOCK-assigned (not cycled): the group vocab interns
                # label SETS, so every (template-label, tenant) combo
                # must appear in warmup — a cycled assignment correlates
                # tenant with the template's i%10 label and covers only
                # half the combos, leaving a schema growth (and its XLA
                # recompile) for the first unlucky mid-window arrival.
                tenant=(
                    warm_tenants[
                        min(
                            (i * len(warm_tenants)) // max(n_warm, 1),
                            len(warm_tenants) - 1,
                        )
                    ]
                    if warm_tenants
                    else None
                ),
            )
            for i in range(n_warm)
        ]
        if hot_serving:
            # Half the warm wave carries the hot selector so the
            # NodeAffinity op and its selector schema compile OUTSIDE
            # the measured window (a first hot arrival would otherwise
            # pay the XLA compile mid-soak).
            for j, p in enumerate(warm):
                if j % 2 == 0:
                    p.spec.node_selector["loadgen.tpu/hot"] = "1"
        for p in warm:
            router.add_pod(p)
        router.schedule_all_pending()
        # Compile the preemption dry-run programs too (they otherwise first
        # fire when the cluster fills, deep inside the measured window).
        # preempt_propose is eval-only: nothing is deleted or nominated.
        from ..api import serialize

        warm_preemptor = (
            make_pod("lgwarm-preemptor").req({"cpu": "12"}).priority(100).obj()
        )
        for owner in owners.values():
            owner.call(
                "preempt_propose", {"pod": serialize.to_dict(warm_preemptor)}
            )
        for p in warm:
            if p.uid in router._pod_shard:
                router.remove_object("Pod", p.uid)
            else:
                router.queue.delete(p.uid)
        # Restore lgn-0 to its serving shape (epoch label cleared).
        w = (
            make_node("lgn-0")
            .capacity({"cpu": "16", "memory": "64Gi", "pods": 110})
            .zone("zone-0")
            .region("region-1")
        )
        if 0 in hot_serving:
            w = w.label("loadgen.tpu/hot", "1")
        feed_node(router, w.obj())
        # The warm deletions above marked node rows dirty: the NEXT eval
        # pass pays the dirty-row scatter-flush XLA compile (~0.5s/owner
        # on this box — the single scheduler's warm_tail covers this,
        # fleet owners never call it).  One throwaway propose per owner
        # absorbs it outside the measured window; propose is eval-only.
        flush_probe = warm_mix.pod(
            10_900_000,
            tenant=warm_tenants[0] if warm_tenants else None,
        )
        for owner in owners.values():
            owner.call("propose", {"pod": serialize.to_dict(flush_probe)})
        if cfg.admission is not None:
            # Arm AFTER warmup: the warm wave must flood through
            # unthrottled (finite burst credits at a frozen logical clock
            # would starve half the label-combo compiles out of the warm
            # window) and the measured window must open on a clean
            # fairness ledger.
            router.arm_admission(mk_admission_policy())

        # -- warm-standby owner pool (ISSUE 18) ------------------------
        # Built AFTER warmup so the slots compile against the same live
        # schema the fleet just finished growing.  The schema version is
        # a crc32 over every axis the warm wave covers — when the live
        # vocab outgrows it mid-run (an epoch label past the warm range),
        # stale slots are retired + respawned against the wider range,
        # never promoted.
        standby_promotions: list[dict] = []
        standby_cold = 0
        warm_epoch_hi = [4]

        def _live_schema() -> int:
            return zlib.crc32(
                json.dumps(
                    [
                        sorted(warm_tenants),
                        sorted(str(a) for a, _w in cfg.hetero_pools),
                        cfg.profile,
                        bool(hot_serving),
                        cfg.admission is not None,
                        armed,
                        warm_epoch_hi[0],
                    ],
                    sort_keys=True,
                ).encode("utf-8")
            )

        if cfg.standby_pool > 0:
            from ..fleet.standby import StandbyPool

            def _standby_factory(slot_id: int):
                if not cfg.two_process:
                    sb_sched = TPUScheduler(
                        batch_size=cfg.batch_size,
                        chunk_size=1,
                        tenant_attribution=cfg.observability,
                        profiles=named_extra_profiles(cfg.profile),
                    )
                    _warm_standby_sched(
                        cfg, sb_sched, warm_tenants, bool(hot_serving),
                        armed, warm_epoch_hi[0],
                    )
                    return {"sched": sb_sched}
                sb_sock = os.path.join(tmp.name, f"standby{slot_id}.sock")
                sb_proc = _spawn_standby_serve(cfg, sb_sock, out_dir, slot_id)
                _warm_standby_wire(
                    cfg, sb_sock, warm_tenants, bool(hot_serving), armed,
                    warm_epoch_hi[0],
                )
                return {"sock": sb_sock, "proc": sb_proc}

            def _standby_retire(payload) -> None:
                sb_proc = payload.get("proc")
                if sb_proc is not None and sb_proc.poll() is None:
                    sb_proc.send_signal(signal.SIGTERM)
                sb_sock = payload.get("sock")
                if sb_sock and os.path.exists(sb_sock):
                    os.unlink(sb_sock)

            standby = StandbyPool(
                cfg.standby_dir or os.path.join(tmp.name, "standby"),
                _standby_factory,
                size=cfg.standby_pool,
                schema_version=_live_schema(),
                registry=registry,
                retire=_standby_retire,
                mirror_path=(
                    f"{map_path}.standby.json" if cfg.two_process else None
                ),
            )

        def promote_owner(k: int, reason: str):
            """Draw a warm child from the standby pool for shard ``k``
            (autoscale split or takeover revive): journaled claim +
            adopt_shard handoff + lease claim — O(handoff), not a cold
            boot.  A pool miss falls back to the cold spawn path the
            fleet always had (counted, never hidden)."""
            nonlocal standby_cold
            t0p = time.perf_counter()
            payload = standby.promote(k, reason)
            if payload is None:
                standby_cold += 1
                o = spawn_owner(k)
                standby_promotions.append(
                    {
                        "shard": k, "reason": reason, "from_pool": False,
                        "latency_s": round(time.perf_counter() - t0p, 4),
                        "t": round(router.lc() if router else -1.0, 3),
                    }
                )
                return o
            sdir = os.path.join(journal_root, f"shard{k}")
            if not cfg.two_process:
                o = ShardOwner(
                    k,
                    payload["sched"],
                    smap,
                    state_dir=sdir,
                    journal_fsync=cfg.journal_fsync == "always",
                    snapshot_every_batches=cfg.snapshot_every,
                    lifecycle=lifecycle,
                    observability=cfg.observability,
                )
            else:
                socks[k] = payload["sock"]
                procs[k] = payload["proc"]
                o = WireShardOwner(
                    path=socks[k],
                    deadline_s=120.0,
                    max_retries=2,
                    registry=registry,
                    shard_id=k,
                )
                o.call(
                    "adopt_shard",
                    {
                        "shard_id": k,
                        "map_path": map_path,
                        "journal_dir": sdir,
                        "journal_fsync": cfg.journal_fsync == "always",
                        "snapshot_every": cfg.snapshot_every,
                        "lifecycle": lifecycle,
                    },
                )
            standby_promotions.append(
                {
                    "shard": k, "reason": reason, "from_pool": True,
                    "latency_s": round(time.perf_counter() - t0p, 4),
                    "t": round(router.lc() if router else -1.0, 3),
                }
            )
            return o

        cap_toggle: dict[int, int] = {}
        label_epoch: dict[int, int] = {}
        live: deque[str] = deque()
        pods_by_uid: dict[str, object] = {}
        pending: dict[str, object] = {}  # decided-but-unbound, for restarts
        dead: set[str] = set()  # churn nodes with silenced heartbeats
        node_deaths = 0
        node_revives = 0
        lease_renewals = 0
        per_shard_lat: dict[int, list[float]] = {k: [] for k in owners}
        wal_prev: dict[int, int] = {k: 0 for k in owners}
        wal_samples: dict[int, list[int]] = {k: [] for k in owners}
        compactions: dict[int, int] = {k: 0 for k in owners}

        def sample_wal() -> None:
            for k in owners:
                try:
                    size = os.path.getsize(
                        os.path.join(journal_root, f"shard{k}", Journal.WAL)
                    )
                except OSError:
                    size = 0
                if size < wal_prev[k]:
                    compactions[k] += 1
                wal_prev[k] = size
                wal_samples[k].append(size)

        autoscale_actions: list[dict] = []
        lat_trace: list[tuple[float, int, float]] = []  # (t, shard, lat)

        def autoscale_provider(k: int):
            """Owner for a split-created shard: the same spawn path the
            build uses (a real `serve --shard-of k/N` child in the
            multi-process fleet — the map file may predate the split;
            the router's set_map push closes that before the import),
            plus fresh sampling slots.  With the standby pool armed the
            owner comes pre-warmed from the pool instead (ISSUE 18) —
            the split's new shard skips the child's cold boot."""
            o = (
                promote_owner(k, "autoscale-split")
                if standby is not None
                else spawn_owner(k)
            )
            owners[k] = o
            wal_prev.setdefault(k, 0)
            wal_samples.setdefault(k, [])
            compactions.setdefault(k, 0)
            per_shard_lat.setdefault(k, [])
            return o

        def autoscale_retirer(k: int, owner) -> None:
            """A merged-away shard's owner drains and stops; its serve
            child (if any) terminates now and is reaped with the rest."""
            owners.pop(k, None)
            try:
                owner.close()
            except OSError:
                pass
            proc = procs.get(k)
            if proc is not None and proc.poll() is None:
                proc.send_signal(signal.SIGTERM)

        if cfg.autoscale:
            autoscaler = FleetAutoscaler(
                router,
                AutoscalerConfig(
                    split_imbalance_hi=cfg.autoscale_split_hi,
                    merge_imbalance_lo=cfg.autoscale_merge_lo,
                    decide_every_s=cfg.autoscale_interval_s,
                    cooldown_s=cfg.autoscale_cooldown_s,
                    window_s=cfg.autoscale_window_s,
                    max_actions_per_window=cfg.autoscale_budget,
                    min_window_decisions=cfg.autoscale_min_decisions,
                    max_shards=cfg.autoscale_max_shards,
                ),
                map_path=map_path if cfg.two_process else None,
                owner_provider=autoscale_provider,
                owner_retirer=autoscale_retirer,
                registry=registry,
                state_path=os.path.join(out_dir, "autoscaler.json"),
            )

        if cfg.preload_bound:
            # The pre-bound population: seeded, hot-marked like the
            # stream, scheduled through the real router path (journals
            # and all) before the window opens.  Rides the live-pod cap
            # like any stream binding, so retirement churns it.
            pre_mix = WorkloadMix(
                cfg.mix,
                seed=cfg.seed * 31 + 7,
                scheduler_name=profile_scheduler_name(cfg.profile),
            )
            pre_rng = _rng(cfg.seed * 1_000_003 + 313_131)
            pre_draws = pre_rng.random(cfg.preload_bound)
            for i in range(cfg.preload_bound):
                p = pre_mix.pod(20_000_000 + i)
                if cfg.hot_fraction > 0 and pre_draws[i] < cfg.hot_fraction:
                    p.spec.node_selector["loadgen.tpu/hot"] = "1"
                router.add_pod(p)
            for o in router.schedule_all_pending():
                if o.node_name:
                    o.pod._lg_node = o.node_name
                    pods_by_uid[o.pod.uid] = o.pod
                    live.append(o.pod.uid)
            if autoscaler is not None:
                # Preload binds are setup, not window signal: the first
                # decision window opens at the stream.
                autoscaler.rebind_router(router)

        def serving_node(i: int):
            w = (
                make_node(f"lgn-{i}")
                .capacity(
                    {
                        "cpu": "15" if cap_toggle.get(i) else "16",
                        "memory": "64Gi",
                        "pods": 110,
                    }
                )
                .zone(f"zone-{i % cfg.zones}")
                .region("region-1")
            )
            if label_epoch.get(i):
                w = w.label("loadgen.tpu/epoch", str(label_epoch[i]))
            if i in hot_serving:
                # Hot-pool membership is fixed at build time — an
                # invalidation re-feed must not quietly shrink it.
                w = w.label("loadgen.tpu/hot", "1")
            return w.obj()

        def rebuild_router() -> FleetRouter:
            """A fresh front door over the owners' truth (cold restart or
            post-takeover re-adopt): node positions re-derive from the
            recorded feed order (the row-allocator mirror must land where
            the dead router's did), parked journal bindings re-apply,
            bindings re-adopt, crash-surfaced evictions drain, the dead
            router's absorbed-but-unbound evictions re-adopt, and
            still-pending pods re-feed."""
            prior_evicted = dict(router.evicted_pending) if router else {}
            if router and router.queue.admission is not None:
                # Harvest the dying generation's admitted order before the
                # fresh ledger starts from zero: the run-wide admission
                # order is the concatenation across generations.
                admission_order.extend(router.queue.admission.admitted_log)
            r = mk_router()
            if cfg.admission is not None:
                # Mid-run rebuilds arm at build (no warm wave to protect):
                # the re-fed pending pods below enqueue straight into the
                # fresh generation's ledger.
                r.arm_admission(mk_admission_policy())
            # The logical clock follows the front door: adoption-time
            # flight records keep the scenario axis.
            r.note_logical_time(router.lc() if router else -1.0)
            for name in feed_order:
                if name in node_objs:
                    r.add_object("Node", node_objs[name])
            if armed:
                # The owners keep their own heartbeat state; the router only
                # needs its clock high-water mark back so the next renewal's
                # broadcast gate behaves — harmless extra ticks otherwise.
                r._lifecycle_hw = router._lifecycle_hw if router else 0.0
            r.reconcile_recovered()
            r.adopt_bindings()
            r.drain_evictions()
            r.readopt_evictions(prior_evicted)
            for uid in sorted(pending):
                r.add_pod(pending[uid])
            if autoscaler is not None:
                # The control loop follows the front door: fresh commit
                # counters mean the next window starts at the restart.
                autoscaler.rebind_router(r)
            return r

        def revive_owner(k: int) -> None:
            """Bounded-retry exhausted on shard ``k`` (hung or dead child):
            TAKEOVER — kill whatever is left, restart the serve child (it
            recovers its own journal before the first frame), and rebuild
            the router over the recovered truth."""
            nonlocal router, owner_takeovers
            proc = procs.get(k)
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
            try:
                owners[k].close()
            except OSError:
                pass
            if socks.get(k) and os.path.exists(socks[k]):
                os.unlink(socks[k])
            # With the standby pool armed, the replacement comes WARM
            # (ISSUE 18): promotion = journaled handoff + lease claim
            # over the dead owner's journal dir, and the recovery replay
            # lands in an already-compiled engine — the ~15s boot the
            # takeover used to pay mid-incident disappears.
            owners[k] = (
                promote_owner(k, "revive")
                if standby is not None
                else spawn_owner(k)
            )
            owner_takeovers += 1
            router = rebuild_router()

        def apply_event(ev) -> None:
            nonlocal router, router_restarts, node_deaths, node_revives
            nonlocal lease_renewals
            if ev.kind == "inv_capacity":
                i = ev.data % cfg.nodes
                cap_toggle[i] = 1 - cap_toggle.get(i, 0)
                feed_node(router, serving_node(i))
            elif ev.kind == "inv_label":
                i = ev.data % cfg.nodes
                label_epoch[i] = label_epoch.get(i, 0) + 1
                feed_node(router, serving_node(i))
                if standby is not None and label_epoch[i] > warm_epoch_hi[0]:
                    # The epoch label grew past the warm range: the live
                    # featurization schema is now ahead of the pool's
                    # compiled programs.  Stale slots retire + respawn
                    # against the widened range — NEVER promote — so a
                    # later promotion still lands in a current engine.
                    warm_epoch_hi[0] = label_epoch[i]
                    standby.sync_schema(_live_schema())
            elif ev.kind == "node_death":
                # The Node object STAYS; its heartbeat goes silent.  The
                # OWNING shard's lifecycle controller must detect the
                # staleness, taint, evict — and the router must rebind the
                # evicted pods on surviving shards.
                dead.add(f"churn-{ev.data % max(1, cfg.churn_nodes)}")
                node_deaths += 1
            elif ev.kind == "node_revive":
                from ..api import types as t

                name = f"churn-{ev.data % max(1, cfg.churn_nodes)}"
                dead.discard(name)
                router.add_object("Lease", t.Lease(name, ev.t))
                lease_renewals += 1
                node_revives += 1
            elif ev.kind == "lease_tick":
                from ..api import types as t

                for i in range(cfg.churn_nodes):
                    name = f"churn-{i}"
                    if name not in dead and name in node_objs:
                        router.add_object("Lease", t.Lease(name, ev.t))
                        lease_renewals += 1
            elif ev.kind == "flap_down":
                name = f"churn-{ev.data}"
                gone = sorted(
                    uid
                    for uid in live
                    if getattr(pods_by_uid.get(uid), "_lg_node", None) == name
                )
                if gone:
                    gone_set = set(gone)
                    for u in gone:
                        pods_by_uid.pop(u, None)
                    live_kept = deque(u for u in live if u not in gone_set)
                    live.clear()
                    live.extend(live_kept)
                if name in node_objs and name in router._node_pos:
                    router.remove_object("Node", name)
            elif ev.kind == "flap_up":
                feed_node(router, node_objs[f"churn-{ev.data}"])
            elif ev.kind == "cold_consumer":
                # Cold ROUTER restart: the front door is rebuilt from the
                # owners' truth mid-stream — bound pods must not
                # double-schedule, and absorbed-but-unbound evictions
                # survive the restart (readopt_evictions).
                router = rebuild_router()
                router_restarts += 1
            elif ev.kind == "autoscale_tick":
                # The elastic control loop, on the scenario clock: the
                # binding-rate window is a pure function of the op
                # stream, so the split/merge history replays same-seed.
                if autoscaler is not None:
                    for act in autoscaler.tick(ev.t):
                        autoscale_actions.append(dict(act, t=ev.t))
            elif ev.kind == "owner_kill":
                # Scripted owner SIGKILL (the production-day incident
                # schedule): a serve child dies mid-stream.  Two-process,
                # the NEXT op that touches its shard exhausts bounded
                # retry and takes over — drawing the replacement from
                # the standby pool when armed; in-process the takeover
                # is synchronous (there is no child to die under us).
                k = sorted(owners)[ev.data % len(owners)]
                if cfg.two_process:
                    proc = procs.get(k)
                    if proc is not None and proc.poll() is None:
                        proc.kill()
                else:
                    revive_owner(k)
            else:
                raise ValueError(f"unknown fleet scenario event {ev.kind!r}")

        res = _PhaseResult(
            name="fleet-sustained",
            invalidation_rate_per_s=cfg.invalidation_rate_per_s,
        )
        # The burst window (first bursting tenant stream), for the
        # in-burst/off-burst per-tenant split: FIFO queueing is shared,
        # so the honest starvation evidence is WHERE the queueing lands
        # (the burst window) and WHOSE traffic dominates it.
        burst_win = next(
            (
                (float(ts["burst_start_s"]), float(ts["burst_end_s"]))
                for ts in cfg.tenant_streams
                if float(ts.get("burst_factor", 1.0)) != 1.0
            ),
            None,
        )
        burst_lat: dict[tuple[str, bool], list] = {}

        # Arrival metadata of decided-but-unbound pods (rate-capped or
        # unschedulable): uid → (deadline, arrival t_ev, arrival issue
        # stamp, raw tenant).  When a LATER decide's scheduling round
        # finally binds one, its full latency is accounted from the
        # ORIGINAL arrival — queue_wait for the capped span, service for
        # the round that bound it.
        pending_meta: dict[str, tuple] = {}

        def _observe_split(
            tlabel: str, total: float, qwait: float, svc: float
        ) -> None:
            slo_hist.observe(
                total, phase=res.name, tenant=tlabel, component="total"
            )
            slo_hist.observe(
                qwait, phase=res.name, tenant=tlabel, component="queue_wait"
            )
            slo_hist.observe(
                svc, phase=res.name, tenant=tlabel, component="service"
            )

        def _retire_overflow() -> None:
            while len(live) > cfg.live_pod_cap:
                old = live.popleft()
                pods_by_uid.pop(old, None)
                pending.pop(old, None)
                pending_meta.pop(old, None)
                if old in router._pod_shard:
                    router.remove_object("Pod", old)
                res.retired += 1

        def decide(pod, deadline: float | None, t_ev: float = 0.0) -> None:
            uid = pod.uid
            t_issue = time.perf_counter()
            router.add_pod(pod)
            outs = router.schedule_all_pending()
            node = None
            late_binds: list[tuple[str, str]] = []
            for o in outs:
                if o.pod.uid == uid and o.node_name:
                    node = o.node_name
                elif o.node_name and o.pod.uid in pending:
                    # A deferred pod (rate-capped on an earlier arrival)
                    # bound in THIS round: full accounting below, from
                    # its original arrival stamps.
                    late_binds.append((o.pod.uid, o.node_name))
                elif o.node_name and o.pod.uid in pods_by_uid:
                    # A rebind (an evicted pod rescheduled mid-decision):
                    # keep the live-window's node attribution current, or a
                    # later flap of the DEAD node would prune the survivor.
                    pods_by_uid[o.pod.uid]._lg_node = o.node_name
            shard = router._pod_shard.get(uid)
            t_done = time.perf_counter()
            base = t_issue if deadline is None else min(deadline, t_issue)
            lat = t_done - base
            tenant = pod_tenant(pod)
            tlabel = (
                tenant_metrics.labeler.label_for(tenant)
                if tenant_metrics is not None
                else TENANT_FALLBACK
            )
            tkey = tenant or "-"
            res.tenant_counts[tkey] = res.tenant_counts.get(tkey, 0) + 1
            # Armed admission defers an unbound pod's SLO sample to its
            # BIND (the exactly-once accounting below) — sampling the
            # arrival attempt too would double-count the pod and bury
            # the capped span's queue_wait.  Unarmed keeps the pre-
            # fairness accounting bit for bit.
            sample_now = node is not None or router.queue.admission is None
            if sample_now:
                res.latencies.append(lat)
                res.tenant_latencies.setdefault(tkey, []).append(lat)
                if burst_win is not None:
                    in_burst = burst_win[0] <= t_ev < burst_win[1]
                    burst_lat.setdefault((tkey, in_burst), []).append(lat)
                _observe_split(
                    tlabel, lat, max(0.0, t_issue - base), t_done - t_issue
                )
                if shard is not None:
                    per_shard_lat.setdefault(shard, []).append(lat)
                    if autoscaler is not None:
                        autoscaler.note_latency(shard, lat)
                    lat_trace.append((t_ev, shard, lat))
                if lat > cfg.slo_budget_ms / 1e3:
                    res.violations += 1
                    res.tenant_violations[tkey] = (
                        res.tenant_violations.get(tkey, 0) + 1
                    )
                    slo_violations.inc(phase=res.name, tenant=tlabel)
            res.decisions += 1
            if node:
                res.bound += 1
                res.tenant_bound[tkey] = res.tenant_bound.get(tkey, 0) + 1
                pod._lg_node = node
                pods_by_uid[uid] = pod
                pending.pop(uid, None)
                pending_meta.pop(uid, None)
                live.append(uid)
                _retire_overflow()
            else:
                pending[uid] = pod
                pending_meta[uid] = (deadline, t_ev, t_issue, tenant)
            for buid, bnode in late_binds:
                bpod = pending.pop(buid, None)
                meta = pending_meta.pop(buid, None)
                if bpod is None:
                    continue
                res.bound += 1
                bpod._lg_node = bnode
                pods_by_uid[buid] = bpod
                live.append(buid)
                if meta is not None:
                    b_deadline, b_t_ev, b_issue, b_tenant = meta
                    b_base = (
                        b_issue
                        if b_deadline is None
                        else min(b_deadline, b_issue)
                    )
                    # The capped span (arrival → this round) is
                    # queue_wait; only this round's scheduling time is
                    # service — the cap's cost lands on the cap.
                    b_qwait = max(0.0, t_issue - b_base)
                    b_svc = t_done - t_issue
                    b_lat = b_qwait + b_svc
                    b_tkey = b_tenant or "-"
                    b_tlabel = (
                        tenant_metrics.labeler.label_for(b_tenant)
                        if tenant_metrics is not None
                        else TENANT_FALLBACK
                    )
                    res.latencies.append(b_lat)
                    res.tenant_latencies.setdefault(b_tkey, []).append(
                        b_lat
                    )
                    res.tenant_bound[b_tkey] = (
                        res.tenant_bound.get(b_tkey, 0) + 1
                    )
                    if burst_win is not None:
                        b_in = burst_win[0] <= b_t_ev < burst_win[1]
                        burst_lat.setdefault((b_tkey, b_in), []).append(
                            b_lat
                        )
                    _observe_split(b_tlabel, b_lat, b_qwait, b_svc)
                    if b_lat > cfg.slo_budget_ms / 1e3:
                        res.violations += 1
                        res.tenant_violations[b_tkey] = (
                            res.tenant_violations.get(b_tkey, 0) + 1
                        )
                        slo_violations.inc(
                            phase=res.name, tenant=b_tlabel
                        )
            if late_binds:
                _retire_overflow()

        seed = cfg.seed * 1_000_003
        tenant_of_arrival: list[str | None] = []
        if cfg.tenant_streams:
            # The tenant-starvation shape: each tenant arrives on its
            # OWN seeded schedule (steady Poisson or a piecewise burst),
            # merged time-ordered — (t, stream index, intra-stream
            # index) is a total, seed-stable order.
            streams: list[tuple[str, list[float]]] = []
            for j, ts in enumerate(cfg.tenant_streams):
                rate = float(ts["rate_pods_per_s"])
                factor = float(ts.get("burst_factor", 1.0))
                sseed = seed + 8_627 + j * 1_009
                if factor != 1.0:
                    offs = burst_offsets(
                        rate,
                        rate * factor,
                        float(ts.get("burst_start_s", 0.0)),
                        float(ts.get("burst_end_s", 0.0)),
                        cfg.duration_s,
                        sseed,
                    )
                else:
                    offs = poisson_offsets(rate, cfg.duration_s, sseed)
                streams.append((str(ts["name"]), offs))
            merged_arrivals = sorted(
                (t_off, j, k)
                for j, (_name, offs) in enumerate(streams)
                for k, t_off in enumerate(offs)
            )
            offsets = [a[0] for a in merged_arrivals]
            tenant_of_arrival = [streams[a[1]][0] for a in merged_arrivals]
            pods = [
                mix.pod(i, tenant=tenant_of_arrival[i])
                for i in range(len(offsets))
            ]
        else:
            if cfg.diurnal:
                offsets = diurnal_offsets(
                    cfg.rate_pods_per_s,
                    cfg.rate_pods_per_s * cfg.diurnal_peak_factor,
                    cfg.diurnal_period_s,
                    cfg.duration_s,
                    seed,
                )
            else:
                offsets = poisson_offsets(
                    cfg.rate_pods_per_s, cfg.duration_s, seed
                )
            pods = [mix.pod(i) for i in range(len(offsets))]
        if cfg.hot_fraction > 0:
            # A dedicated seeded stream marks hot arrivals (a pure
            # function of (seed, arrival schedule) — the hot-spot skew
            # replays).  Under diurnal arrivals the hot PROBABILITY
            # rides the same day/night swing as the rate: off-crest
            # traffic spreads fleet-wide (imbalance in-band), the crest
            # concentrates on the hot pool — so the split trips exactly
            # when the skew hurts, not at the first quiet tick.
            from .arrivals import diurnal_rate

            hot_rng = _rng(seed + 424_243)
            draws = hot_rng.random(len(offsets))
            for i, p in enumerate(pods):
                p_hot = (
                    diurnal_rate(
                        offsets[i], 0.0, cfg.hot_fraction,
                        cfg.diurnal_period_s,
                    )
                    if cfg.diurnal
                    else cfg.hot_fraction
                )
                if draws[i] < p_hot:
                    p.spec.node_selector["loadgen.tpu/hot"] = "1"
        scenario = build_events(
            cfg.duration_s,
            seed + 500_009,
            nodes=cfg.nodes,
            churn_nodes=cfg.churn_nodes,
            invalidation_rate_per_s=cfg.invalidation_rate_per_s,
            inv_mix=FLEET_INV_MIX,
            node_flap_period_s=cfg.node_flap_period_s,
            flap_down_s=cfg.flap_down_s,
            cold_consumer_period_s=cfg.cold_consumer_period_s,
            node_death_period_s=cfg.node_death_period_s if armed else 0.0,
            node_death_down_s=cfg.node_death_down_s,
            lease_interval_s=cfg.lease_interval_s if armed else 0.0,
            autoscale_interval_s=(
                cfg.autoscale_interval_s if cfg.autoscale else 0.0
            ),
        )
        if cfg.scripted_events:
            # Hand-placed production-day incidents (owner kills, cold
            # router restarts, node deaths at scripted seconds) merged
            # into the generated stream.  Only re-sorted when armed: the
            # legacy schedule stays byte-identical otherwise.
            scenario = sorted(
                list(scenario) + one_shot_events(cfg.scripted_events),
                key=lambda e: (e.t, e.kind, e.data),
            )
        ops: list[tuple[float, int, int, object]] = []
        for j, ev in enumerate(scenario):
            ops.append((ev.t, 1, j, ev))
        for i, off in enumerate(offsets):
            ops.append((off, 2, i, i))
        ops.sort(key=lambda e: (e[0], e[1], e[2]))

        # -- resumable-driver state (ISSUE 18) -------------------------
        # The driver is (lint-enforced) a pure function of (config,
        # seed, logical clock): every RNG draw is pre-computed above, so
        # the deterministic state is exactly the op cursor plus the
        # replayable accumulators — digest-verified on resume.  The
        # wall-derived observability accumulators ride a separate block,
        # restored verbatim (a replay cannot re-measure the past).
        def _det_state(op_index: int, clock: float) -> dict:
            adm: list[str] = []
            if router.queue.admission is not None:
                adm = list(router.queue.admission.admitted_log)
            return {
                "op_index": int(op_index),
                "clock": round(float(clock), 9),
                "decisions": res.decisions,
                "bound": res.bound,
                "retired": res.retired,
                "tenant_counts": dict(sorted(res.tenant_counts.items())),
                "tenant_bound": dict(sorted(res.tenant_bound.items())),
                "events_applied": dict(sorted(res.events_applied.items())),
                "router_restarts": router_restarts,
                "node_deaths": node_deaths,
                "node_revives": node_revives,
                "lease_renewals": lease_renewals,
                "cap_toggle": sorted(cap_toggle.items()),
                "label_epoch": sorted(label_epoch.items()),
                "dead": sorted(dead),
                "live_sha": _sha(list(live)),
                "pending_sha": _sha(sorted(pending)),
                "bindings_sha": _sha(sorted(router.bindings().items())),
                "admission_sha": _sha(list(admission_order) + adm),
                "autoscale_sha": _sha(
                    [
                        [
                            a.get("op"), a.get("from"), a.get("to"),
                            round(float(a.get("t", 0.0)), 9),
                        ]
                        for a in autoscale_actions
                    ]
                ),
                "shards": sorted(owners),
            }

        def _obs_state() -> dict:
            return {
                "latencies": list(res.latencies),
                "violations": res.violations,
                "tenant_latencies": {
                    k: list(v)
                    for k, v in sorted(res.tenant_latencies.items())
                },
                "tenant_violations": dict(
                    sorted(res.tenant_violations.items())
                ),
                "per_shard_lat": {
                    str(k): list(v)
                    for k, v in sorted(per_shard_lat.items())
                },
                "lat_trace": [[t, s, l] for t, s, l in lat_trace],
                "burst_lat": {
                    f"{tk}\x1f{int(b)}": list(v)
                    for (tk, b), v in sorted(burst_lat.items())
                },
                "owner_takeovers": owner_takeovers,
                "wal_samples": {
                    str(k): list(v) for k, v in sorted(wal_samples.items())
                },
                "wal_prev": {
                    str(k): v for k, v in sorted(wal_prev.items())
                },
                "compactions": {
                    str(k): v for k, v in sorted(compactions.items())
                },
            }

        def _restore_obs(obs: dict) -> None:
            nonlocal owner_takeovers
            res.latencies[:] = [float(v) for v in obs["latencies"]]
            res.violations = int(obs["violations"])
            res.tenant_latencies.clear()
            res.tenant_latencies.update(
                {k: [float(v) for v in vs]
                 for k, vs in obs["tenant_latencies"].items()}
            )
            res.tenant_violations.clear()
            res.tenant_violations.update(
                {k: int(v) for k, v in obs["tenant_violations"].items()}
            )
            per_shard_lat.clear()
            per_shard_lat.update(
                {int(k): [float(v) for v in vs]
                 for k, vs in obs["per_shard_lat"].items()}
            )
            lat_trace[:] = [
                (float(t), int(s), float(l)) for t, s, l in obs["lat_trace"]
            ]
            burst_lat.clear()
            for key, vs in obs["burst_lat"].items():
                tk, b = key.split("\x1f")
                burst_lat[(tk, bool(int(b)))] = [float(v) for v in vs]
            owner_takeovers = int(obs["owner_takeovers"])
            wal_samples.clear()
            wal_samples.update(
                {int(k): [int(v) for v in vs]
                 for k, vs in obs["wal_samples"].items()}
            )
            wal_prev.clear()
            wal_prev.update(
                {int(k): int(v) for k, v in obs["wal_prev"].items()}
            )
            compactions.clear()
            compactions.update(
                {int(k): int(v) for k, v in obs["compactions"].items()}
            )

        digest_verified = None
        if cfg.checkpoint_path:
            ckpt = CheckpointWriter(cfg.checkpoint_path)
            if ckpt_prior is not None:
                ckpt.generation = int(ckpt_prior["generation"])
        t0 = time.perf_counter()

        def execute(klass: int, payload, t_ev: float) -> None:
            # Flight records downstream of this op (router batch, owner
            # propose/commit, handoff markers) carry the SCENARIO clock —
            # the logical axis the merged fleet timeline orders on.
            router.note_logical_time(t_ev)
            if klass == 1:
                apply_event(payload)
                res.events_applied[payload.kind] = (
                    res.events_applied.get(payload.kind, 0) + 1
                )
                sample_wal()
            else:
                deadline = (
                    t0 + t_ev
                    if cfg.pace == "real" and not replay_active[0]
                    else None
                )
                decide(pods[payload], deadline, t_ev)

        # Replay prefix (resume): ops [0, resume_from) re-execute in
        # virtual pace — deterministic regeneration of the driver and
        # fleet state, sleeps skipped — then the regenerated digest is
        # verified against the checkpoint, the wall-derived accumulators
        # restore, and the wall origin rebases so the remaining ops pace
        # exactly as the uninterrupted run's would have.
        replay_active = [resume_from > 0]
        op_i = 0
        last_t = 0.0
        for t_ev, klass, _idx, payload in ops:
            replay_active[0] = op_i < resume_from
            if cfg.pace == "real" and not replay_active[0]:
                delay = (t0 + t_ev) - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
            try:
                execute(klass, payload, t_ev)
            except FleetOwnerUnreachable as exc:
                # Bounded retry exhausted on one owner: takeover (restart
                # the serve child — it recovers its journal before the first
                # frame — and re-adopt), then re-issue the op once against
                # the recovered fleet.  Idempotent by the same contracts the
                # kill matrix proves: bound pods re-adopt, adds upsert.
                shard = getattr(exc, "shard_id", None)
                if shard is None or not cfg.two_process:
                    raise
                if autoscaler is not None:
                    # Stale stats never drive a resize: the autoscaler
                    # holds the shard out of actions while takeover
                    # owns its fate.
                    autoscaler.note_unreachable(shard)
                revive_owner(shard)
                execute(klass, payload, t_ev)
            op_i += 1
            last_t = t_ev
            if replay_active[0] and op_i == resume_from:
                # End of the replayed prefix: the regenerated driver
                # state must hash exactly to what the checkpoint
                # recorded, or the resume would silently diverge.
                want = ckpt_prior["state"]["det"]
                got = _det_state(op_i, t_ev)
                if state_digest(got) != state_digest(want):
                    diffs = [
                        k
                        for k in sorted(set(got) | set(want))
                        if got.get(k) != want.get(k)
                    ]
                    raise RuntimeError(
                        "resume digest mismatch at op "
                        f"{op_i}: replay diverged on {diffs}"
                    )
                _restore_obs(ckpt_prior["state"]["obs"])
                digest_verified = True
                t0 = time.perf_counter() - t_ev
            if (
                ckpt is not None
                and cfg.checkpoint_every_ops > 0
                and op_i > resume_from
                and op_i % cfg.checkpoint_every_ops == 0
            ):
                ckpt.write(
                    {"det": _det_state(op_i, t_ev), "obs": _obs_state()}
                )
            if (
                cfg.kill_after_op
                and op_i == cfg.kill_after_op
                and op_i > resume_from
            ):
                # Test hook (--standby-kill ckpt cells; tests/test_soak):
                # die HARD right here — after the boundary checkpoint
                # when op_i lands on one, mid-interval otherwise.
                os.kill(os.getpid(), signal.SIGKILL)
        if cfg.resume and not digest_verified:
            raise RuntimeError(
                f"resume op index {resume_from} was never reached "
                f"({op_i} ops in schedule) — checkpoint/config mismatch"
            )
        sample_wal()
        res.wall_s = round(time.perf_counter() - t0, 3)
        driver_state_sha = state_digest(_det_state(op_i, last_t))
        standby_status = standby.status() if standby is not None else None

        bindings = router.bindings()
        stats = router.stats()
        autoscale = None
        if cfg.autoscale and autoscaler is not None:
            W = cfg.autoscale_compare_window_s

            def _win_p99(shard_ids, lo: float, hi: float) -> dict:
                lats = [
                    lat
                    for t, s, lat in lat_trace
                    if lo <= t < hi and (shard_ids is None or s in shard_ids)
                ]
                return {
                    "decisions": len(lats),
                    "p99_ms": round(_pct(lats, 99) * 1e3, 3),
                    "p50_ms": round(_pct(lats, 50) * 1e3, 3),
                }

            # Split-recovery evidence: for each split, the SPLIT shard's
            # SLO in the window before vs the strictest honest "after" —
            # the worst of the two shards now sharing its load, measured
            # AFTER the settle gap (the transition window, where the
            # journaled import re-fsyncs every moved binding, is
            # reported separately — a resize is not free, it is bounded
            # and crash-safe).
            settle = cfg.autoscale_compare_settle_s
            recovery = []
            for act in autoscale_actions:
                if act["op"] != "split":
                    continue
                ts = act["t"]
                src, dst = act["from"], act["to"]
                pre = _win_p99({src}, ts - W, ts)
                post_src = _win_p99({src}, ts + settle, ts + settle + W)
                post_dst = _win_p99({dst}, ts + settle, ts + settle + W)
                post = max(
                    (post_src, post_dst), key=lambda d: d["p99_ms"]
                )
                recovery.append(
                    {
                        "t_split": round(ts, 3),
                        "shard": src,
                        "new_shard": dst,
                        "window_s": W,
                        "settle_s": settle,
                        "pre": pre,
                        "transition": _win_p99(
                            {src, dst}, ts, ts + settle
                        ),
                        "post_worst_of_pair": post,
                        "post_src": post_src,
                        "post_new": post_dst,
                        "global_pre": _win_p99(None, ts - W, ts),
                        "global_post": _win_p99(
                            None, ts + settle, ts + settle + W
                        ),
                        "p99_recovered": (
                            post["p99_ms"] < pre["p99_ms"]
                            if pre["decisions"] and post["decisions"]
                            else None
                        ),
                    }
                )
            autoscale = {
                "enabled": True,
                "hot_fraction": cfg.hot_fraction,
                "hot_serving_nodes": len(hot_serving),
                "actions": autoscale_actions,
                "splits": sum(
                    1 for a in autoscale_actions if a["op"] == "split"
                ),
                "merges": sum(
                    1 for a in autoscale_actions if a["op"] == "merge"
                ),
                "deferrals": dict(sorted(autoscaler.deferrals.items())),
                "split_recovery": recovery,
                "status": autoscaler.status(),
            }
        node_loss = None
        if armed:
            lc = router.lifecycle_stats()
            node_loss = {
                "node_deaths": node_deaths,
                "node_revives": node_revives,
                "lease_renewals": lease_renewals,
                "evictions_absorbed": lc["evictions_absorbed"],
                "rebinds": lc["rebinds"],
                "cross_shard_rebinds": lc["cross_shard_rebinds"],
                "pending_rebinds": lc["pending_rebinds"],
                "per_shard_lifecycle": lc["per_shard"],
            }
        fleet_timeline = None
        merged_sha = None
        if cfg.observability:
            # The federated flight merge: every owner's ring (over the
            # wire for serve children) + the router's, folded into one
            # fleet timeline on the scenario clock with per-phase
            # overlap and critical-path attribution.  The deterministic
            # timeline hash rides the determinism block — two same-seed
            # runs must merge byte-identically.
            snaps, names = router.fleet_flight_snapshots()
            merged = merge_fleet(snaps, names)
            merged["slow_spans"] = list(router.slow_spans)
            merged_sha = merged["timeline_sha256"]
            # The Perfetto twin: the merged timeline rendered as
            # trace-event JSON on the logical timebase (wall fields
            # stripped — same-seed runs export byte-identically), written
            # next to the merged doc and stamped into it so the fleet
            # renderer (scripts/profile_report.py) can link the artifact.
            from ..framework import trace_export

            trace_name = "fleet-trace.json"
            merged["perfetto"] = trace_name
            merged_path = os.path.join(out_dir, "fleet-flight-merged.json")
            with open(merged_path, "w", encoding="utf-8") as f:
                json.dump(merged, f, indent=1, sort_keys=True)
            with open(
                os.path.join(out_dir, trace_name), "w", encoding="utf-8"
            ) as f:
                f.write(trace_export.render(merged, timebase="logical"))
            # Flight-derived measured throughput over the same rings
            # (empty matrix when the scenario has no hetero classes).
            mt = router.measured_throughput()
            fleet_timeline = {
                "file": os.path.basename(merged_path),
                "perfetto": trace_name,
                "timeline_sha256": merged_sha,
                "events": merged["timeline_events"],
                "components": merged["components"],
                "wall": merged["wall"],
                "critical_path_top": merged["critical_path"][:8],
                "measured_throughput": {
                    "matrix": mt["matrix"],
                    "binds": mt["window"]["binds"],
                    "source_sha256": mt["source"]["sha256"],
                },
            }
        registry_summary = router.registry.summary()
    finally:
        if standby is not None:
            try:
                standby.close()  # retires (SIGTERMs) un-promoted slots
            except OSError:
                pass
        if ckpt is not None:
            ckpt.close()
        for owner in owners.values():
            try:
                owner.close()
            except OSError:
                pass
        for proc in procs.values():
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        for proc in procs.values():
            try:
                proc.wait(timeout=60)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=30)
    slo = dict(
        _lat_summary(res.latencies),
        budget_ms=cfg.slo_budget_ms,
        violations=res.violations,
        violation_rate=round(res.violations / max(1, res.decisions), 4),
    )
    artifact = {
        "metric": "fleet_soak_slo_per_shard",
        "seed": cfg.seed,
        "shards": shards,
        "config": asdict(cfg),
        "wall_s": res.wall_s,
        "decisions": res.decisions,
        "bound": res.bound,
        "retired": res.retired,
        "sustained_pods_per_sec": round(
            res.decisions / res.wall_s if res.wall_s else 0.0, 1
        ),
        "slo": slo,
        "per_shard": {
            str(k): {
                "slo": _lat_summary(per_shard_lat[k]),
                "wal_bytes_max": max(wal_samples[k], default=0),
                "wal_bytes_final": (
                    wal_samples[k][-1] if wal_samples[k] else 0
                ),
                "compactions_observed": compactions[k],
                "owner": stats["shards"][str(k)],
            }
            for k in sorted(owners)
        },
        "events": dict(sorted(res.events_applied.items())),
        "router_restarts": router_restarts,
        "owner_takeovers": owner_takeovers,
        "deployment": (
            "multi-process" if cfg.two_process else "in-process"
        ),
        "autoscale": autoscale,
        "node_loss": node_loss,
        "tenants": (
            dict(
                per_tenant=_tenant_summary([res]),
                counters=(
                    tenant_metrics.snapshot()
                    if tenant_metrics is not None
                    else {}
                ),
                per_shard_commits={
                    str(k): (stats["shards"][str(k)].get("tenants") or {})
                    for k in sorted(owners)
                },
                burst_split=(
                    {
                        "window_s": list(burst_win),
                        "per_tenant": {
                            tkey: {
                                "in_burst": _lat_summary(
                                    burst_lat.get((tkey, True), [])
                                ),
                                "off_burst": _lat_summary(
                                    burst_lat.get((tkey, False), [])
                                ),
                            }
                            for tkey in sorted(
                                {k for k, _b in burst_lat}
                            )
                        },
                        # Whose traffic the burst window's queueing
                        # lands on: each tenant's share of the window's
                        # decisions.
                        "in_burst_share": {
                            tkey: round(
                                len(burst_lat.get((tkey, True), []))
                                / max(
                                    1,
                                    sum(
                                        len(v)
                                        for (_k, b), v in burst_lat.items()
                                        if b
                                    ),
                                ),
                                4,
                            )
                            for tkey in sorted(
                                {k for k, b in burst_lat if b}
                            )
                        },
                    }
                    if burst_win is not None
                    else None
                ),
            )
            if (cfg.tenants or cfg.tenant_streams)
            else None
        ),
        "fleet_timeline": fleet_timeline,
        "fleet_metrics": registry_summary,
        "admission": (
            dict(
                armed=True,
                status=router.queue.admission.status(),
                # Run-wide admission order: every dead generation's
                # harvested log plus the final router's — the cross-run
                # determinism oracle for WFQ ordering.
                admission_order_sha256=_sha(
                    list(admission_order)
                    + list(router.queue.admission.admitted_log)
                ),
                admitted_total=(
                    len(admission_order)
                    + len(router.queue.admission.admitted_log)
                ),
            )
            if cfg.admission is not None
            and router.queue.admission is not None
            else None
        ),
        "standby": (
            dict(
                enabled=True,
                pool=standby_status,
                promotions=standby_promotions,
                served_from_pool=sum(
                    1 for p in standby_promotions if p["from_pool"]
                ),
                cold_fallbacks=standby_cold,
                promotion_latency=_lat_summary(
                    [
                        p["latency_s"]
                        for p in standby_promotions
                        if p["from_pool"]
                    ]
                ),
            )
            if standby is not None
            else None
        ),
        "resume": (
            dict(
                enabled=True,
                resumed=bool(cfg.resume),
                resume_op_index=resume_from,
                checkpoint_generation=(
                    ckpt.generation if ckpt is not None else 0
                ),
                checkpoint_every_ops=cfg.checkpoint_every_ops,
                digest_verified=digest_verified,
            )
            if cfg.checkpoint_path
            else None
        ),
        "determinism": {
            "arrival_sha256": _sha([round(o, 9) for o in offsets]),
            "bindings_sha256": _sha(sorted(bindings.items())),
            "timeline_sha256": merged_sha,
            # The driver's own final-state digest (ISSUE 18): the same
            # function the resume checkpoint verifies — equal between a
            # --resume'd run and its uninterrupted same-seed twin.
            "driver_state_sha256": driver_state_sha,
            "arrivals_total": len(offsets),
        },
        "bound_final": len(bindings),
        "pace": cfg.pace,
    }
    artifact["_arrival_offsets"] = [list(offsets)]
    # Raw (t, shard, latency) samples for callers that window SLOs
    # around incidents (run_soak.py --prod's per-phase evidence) —
    # underscore-keyed: strip_private drops it from the committed JSON.
    artifact["_lat_trace"] = [[t, s, lat] for t, s, lat in lat_trace]
    return artifact


def strip_private(artifact: dict) -> dict:
    """The committed-artifact view: drop the underscore-keyed raw data
    callers use in-process, and normalize to JSON-native types (config
    tuples become lists) so the document round-trips byte-stable."""
    return json.loads(
        json.dumps(
            {k: v for k, v in artifact.items() if not k.startswith("_")}
        )
    )
