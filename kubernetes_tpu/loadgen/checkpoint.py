"""Resumable soak driver: atomic checkpoints of the driver's full
deterministic state (ISSUE 18).

Hour-scale runs die with the harness — a SIGKILLed soak driver used to
mean starting the hour over.  The driver is (lint-enforced) a pure
function of (config, seed, logical clock), so its state is exactly
checkpointable: arrival cursors, scenario op index, RNG generator
states, SLO/latency accumulators, per-tenant ledgers, logical clock.
This module is the soak-driver twin of the scheduler's own WAL
discipline:

- ``CheckpointWriter.write(state)`` — serialize to a temp file, fsync,
  append the generation record (digest) to the writer's own journal,
  then ``finish_checkpoint``: os.replace + directory fsync (the
  shardmap discipline).  The ``mid-checkpoint`` crash point sits between
  the journal append and the apply — a SIGKILL there leaves the
  PREVIOUS complete checkpoint live, never a torn half.
- ``load_checkpoint(path)`` — reads the live file and verifies the
  embedded digest over the state block, so a corrupt file is a loud
  error, not a silently divergent resume.

`run_soak.py --resume` then replays the op prefix `[0, op_index)` in
virtual pace (deterministic regeneration — sleeps skipped), asserts the
regenerated driver digest matches the checkpoint's, and continues the
remaining ops at the configured pace: the final artifact is
bit-identical to an uninterrupted same-seed run
(tests/test_soak.py; run_fault_matrix.py --standby-kill's ckpt cells)."""

from __future__ import annotations

import hashlib
import json
import os

from .. import journal as _journal


def state_digest(state: dict) -> str:
    """Canonical digest of a checkpoint state block (sort_keys JSON →
    sha256) — the bit-identity witness resume verifies against."""
    blob = json.dumps(state, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


class CheckpointWriter:
    """Atomic generation-journaled checkpoint writer for one soak run."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._dir = d
        self.generation = 0
        self._jf = open(f"{path}.journal", "a", encoding="utf-8")
        self.journal = self  # receiver alias: self.journal.append(...)

    def append(self, rec: dict) -> None:
        """Fsync'd JSONL append to the generation journal — the WAL half
        that precedes every ``finish_checkpoint`` apply."""
        self._jf.write(json.dumps(rec, sort_keys=True) + "\n")
        self._jf.flush()
        os.fsync(self._jf.fileno())

    def write(self, state: dict) -> str:
        """Write one checkpoint generation; returns its digest."""
        self.generation += 1
        digest = state_digest(state)
        doc = {
            "generation": self.generation,
            "digest": digest,
            "state": state,
        }
        tmp = f"{self.path}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, sort_keys=True)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        self.journal.append(
            {"op": "checkpoint", "generation": self.generation,
             "digest": digest, "op_index": state.get("op_index")}
        )
        _journal._crash("mid-checkpoint")
        self.finish_checkpoint(tmp)
        return digest

    def finish_checkpoint(self, tmp: str) -> None:
        """The apply half (WAL marker — journaled first by ``write``):
        the new generation becomes the live checkpoint atomically."""
        os.replace(tmp, self.path)
        dfd = os.open(self._dir, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)

    def close(self) -> None:
        try:
            self._jf.close()
        except OSError:
            pass


def load_checkpoint(path: str) -> dict | None:
    """The live checkpoint's verified document, or None when absent.
    A present-but-corrupt file (torn write would need a torn os.replace,
    i.e. a broken filesystem; digest mismatch means tampering or a
    divergent writer) raises ValueError — resuming from it would
    silently break the bit-identity promise."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except OSError:
        return None
    except ValueError as exc:
        raise ValueError(f"corrupt checkpoint {path}: {exc}") from exc
    got = state_digest(doc.get("state", {}))
    want = doc.get("digest")
    if got != want:
        raise ValueError(
            f"checkpoint {path} digest mismatch: state hashes to "
            f"{got[:12]}… but records {str(want)[:12]}…"
        )
    return doc
