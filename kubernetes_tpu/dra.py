"""Dynamic Resource Allocation: host-side claim catalog + allocation.

The host half of the DynamicResources plugin
(pkg/scheduler/framework/plugins/dynamicresources/, wired at
scheduler.go:298–302 through the claim assume-cache) with STRUCTURED
PARAMETERS (staging/src/k8s.io/dynamic-resource-allocation/structured/
allocator.go): ResourceSlices publish named devices with typed attributes;
claims carry device requests narrowed by CEL selectors (the vectorizable
subset, dra_cel.py).  Allocation is delayed (the scheduler allocates at
Reserve/PreBind, like WaitForFirstConsumer volume binding), pins the claim
to one node and names the chosen devices; deallocation happens when the
last reserving pod goes away.

TPU-first split: requests intern into SELECTOR POOLS — one pool per
distinct (class, canonical-selector) — and the device tensors carry
per-pool per-node cap/alloc count columns (ClusterState.dra_cap/dra_alloc),
so the compiled pass filters ``alloc + need ≤ cap`` per pool exactly like
the counted form (ops/dynamicresources.py).  Pool counts OVER-approximate
feasibility when pools overlap on devices (a device taken under pool A
still counts free under an overlapping pool B until the host re-check);
this catalog's exact named-device allocator is authoritative at Reserve —
a lost race forgets the pod and retries, the same assume-cache pattern as
volumes.VolumeCatalog.bind_pod_volumes."""

from __future__ import annotations

from dataclasses import dataclass, field

from .api import types as t
from . import dra_cel


def pool_sig(device_class: str, selectors: tuple[str, ...]) -> str:
    """Pool signature: the class itself for selector-less requests, else
    class + canonical selector form (equivalent spellings share a pool)."""
    if not selectors:
        return device_class
    return f"{device_class}|{dra_cel.canonical(selectors)}"


@dataclass
class ClaimCatalog:
    claims: dict[str, t.ResourceClaim] = field(default_factory=dict)
    # (node, device_class) → published device count.
    slices: dict[tuple[str, str], int] = field(default_factory=dict)
    # (node, device_class) → devices consumed by allocated claims
    # (named local allocations + count-only external charges).
    allocated: dict[tuple[str, str], int] = field(default_factory=dict)
    # (node, device_class) → {device name → attributes} (ResourceSlice
    # devices; counted slices synthesize anonymous attribute-less ones).
    devices: dict[tuple[str, str], dict[str, dict]] = field(default_factory=dict)
    # (node, device_class) → {device name → owning claim uid}.
    device_owner: dict[tuple[str, str], dict[str, str]] = field(default_factory=dict)
    # Selector pools: sig → (device_class, compiled requirements).  Bare
    # class pools have empty requirements.
    pools: dict[str, tuple[str, tuple]] = field(default_factory=dict)
    pools_by_class: dict[str, list[str]] = field(default_factory=dict)
    # Pools registered since the scheduler last collected them (their cap
    # columns need backfilling for existing nodes).
    new_pools: list[str] = field(default_factory=list)
    epoch: int = 0  # featurization cache token
    # External-allocation row charges (see add_claim): claims whose phantom
    # reservation is applied to node rows, and those waiting for their
    # node to appear (the claim-before-node informer race — the same one
    # add_node replays CSINode/ResourceSlices for).  Values are per-request
    # charge lists [(node, pool sig, count)].
    row_charged: dict[str, list[tuple[str, str, int]]] = field(default_factory=dict)
    pending_external: dict[str, list[tuple[str, str, int]]] = field(default_factory=dict)
    # claim uid → pod uids reserved IN-PROCESS (allocate_pod_claims).  The
    # assume-cache stale-echo guard keys off these, not off the informer's
    # status.reservedFor — external consumers releasing a claim is a real
    # deallocation, not an echo.
    local_reserved: dict[str, set[str]] = field(default_factory=dict)
    # Pool-overlap CORRECTIONS.  A claim's reservation transition charges
    # its REQUEST pools; once allocation names the devices, every OTHER
    # pool those devices match must charge too (a device taken under
    # "bigmem" is no longer free under the bare class pool).  corrections
    # holds each allocated claim's extra per-pool charges (reversed at
    # deallocation); corr_events queues (node, [(sig, cnt)], ±1) row
    # adjustments for the scheduler to apply (TPUScheduler.
    # _drain_dra_corrections).  Within one batch the scan still sees the
    # uncorrected counts — same-batch overlap races lose the host Reserve
    # re-check and retry against the corrected state.
    corrections: dict[str, list[tuple[str, int]]] = field(default_factory=dict)
    corr_events: list[tuple[str, list[tuple[str, int]], int]] = field(default_factory=list)
    # Corrections whose applied row charges died with a removed node —
    # parked like pending_external, replayed when the node returns (for
    # external claims, whose base charges replay too; a local claim's stay
    # parked until deallocation clears them, matching its vaporized pods).
    pending_corr: dict[str, list[tuple[str, int]]] = field(default_factory=dict)

    # -- pools ---------------------------------------------------------------

    def ensure_pool(self, device_class: str, selectors: tuple[str, ...]) -> str:
        """Intern a (class, selectors) pool; compile errors propagate (the
        reference fails allocation on CEL compile errors, allocator.go:159)."""
        sig = pool_sig(device_class, selectors)
        if sig not in self.pools:
            # DNF: a union of requirement-conjunction branches (`||` maps
            # onto the pool machinery as branch union — one pool, one
            # count column, matching = any branch holds).
            self.pools[sig] = (
                device_class, dra_cel.compile_selectors(tuple(selectors))
            )
            self.pools_by_class.setdefault(device_class, []).append(sig)
            self.new_pools.append(sig)
        return sig

    def request_pools(self, claim: t.ResourceClaim) -> list[tuple[str, int, t.DeviceRequest]]:
        """[(pool sig, count, request)] for the claim's device requests."""
        return [
            (self.ensure_pool(r.device_class, r.selectors), r.count, r)
            for r in claim.device_requests()
        ]

    def charge_pools(self, claim: t.ResourceClaim) -> list[tuple[str, int]]:
        """The pools a claim's transition charges: each request's own pool
        PLUS the bare class pool for selector requests — every selector
        pool is a subset of its class pool, so charging both keeps
        bare-vs-selector availability exact on device (only
        selector-vs-selector overlap is left to the corrections)."""
        out: list[tuple[str, int]] = []
        for sig, cnt, req in self.request_pools(claim):
            out.append((sig, cnt))
            if req.selectors:
                self.ensure_pool(req.device_class, ())
                out.append((req.device_class, cnt))
        return out

    def pool_cap(self, node: str, sig: str) -> int:
        """Devices on ``node`` matching the pool (allocated or not)."""
        cls, reqs = self.pools[sig]
        if not reqs or reqs == ((),):
            # Selector-less pool (compile_selectors(()) is the vacuous
            # single empty branch): every device matches — O(1) count.
            return self.slices.get((node, cls), 0)
        devs = self.devices.get((node, cls), {})
        return sum(1 for attrs in devs.values() if dra_cel.matches(reqs, attrs))

    def new_pool_alloc(self, node: str, sig: str) -> int:
        """The alloc value for a JUST-registered pool's column on ``node``:
        per owning claim, max(devices actually matching, what the claim's
        own transition already charges this pool) — corrections record only
        the EXCESS over the transition charge, so deallocation (transition
        discharge + correction reversal) nets to exactly this value.
        Count-only external charges keep their recorded per-pool amounts
        (devices unknown — the host re-check covers the slack)."""
        cls, reqs = self.pools[sig]
        owners = self.device_owner.get((node, cls), {})
        attrs_of = self.devices.get((node, cls), {})
        actual_by_uid: dict[str, int] = {}
        for dev, uid in owners.items():
            if dra_cel.matches(reqs, attrs_of.get(dev, {})):
                actual_by_uid[uid] = actual_by_uid.get(uid, 0) + 1
        total = 0
        seen_uids = set(actual_by_uid)
        for uid, actual in actual_by_uid.items():
            claim = self.claims.get(uid)
            charged = (
                sum(c for s2, c in self.charge_pools(claim) if s2 == sig)
                if claim is not None
                else 0
            )
            charged += sum(
                c for s2, c in self.corrections.get(uid, ()) if s2 == sig
            )
            contribution = max(actual, charged)
            if contribution > charged:
                self.corrections.setdefault(uid, []).append(
                    (sig, contribution - charged)
                )
            total += contribution
        # External claims charged on this node for this pool whose devices
        # did not land in actual_by_uid (count-only, or named but
        # non-matching) keep their applied charge in the column.
        for uid, charges in self.row_charged.items():
            if uid in seen_uids:
                continue
            total += sum(
                c for n2, s2, c in charges if n2 == node and s2 == sig
            )
        return total

    # -- object events -------------------------------------------------------

    def add_claim(
        self, claim: t.ResourceClaim
    ) -> list[tuple[str, str, int, int]]:
        """Upsert a claim (informer).  Returns row-charge deltas
        [(node, pool sig, count, ±1)] for EXTERNAL allocation changes — an
        allocation written by another scheduler (or a restart replay)
        consumes devices the moment it arrives, exactly as the reference's
        claim assume-cache sees it.  The charge rides a PHANTOM
        reservation (SnapshotBuilder.apply_external_claim) so a local pod
        later reserving the same claim cannot double-charge.

        Assume-cache semantics (the reference accepts only informer
        objects newer than its assumed version): an upsert that would
        DE-allocate a claim with live local reservations is a stale watch
        echo of the pre-allocation object and is dropped; an upsert whose
        allocation matches the current record replaces the object without
        touching accounting (local reservations carry over)."""
        # Register the claim's selector pools up front (compile errors
        # surface at the informer edge, not mid-featurize) — the scheduler
        # backfills new pools' cap columns right after this call.
        for r in claim.device_requests():
            self.ensure_pool(r.device_class, r.selectors)
        old = self.claims.get(claim.uid)
        if old is not None:
            local = self.local_reserved.get(claim.uid, ())
            if local and not claim.allocated_node:
                return []  # stale echo: local truth wins until released
            # Local reservations survive the object replacement; an
            # external consumer vanishing from status.reservedFor does not
            # get resurrected from the old object.
            merged = tuple(dict.fromkeys(
                claim.reserved_for
                + tuple(u for u in old.reserved_for if u in local)
            ))
            claim.reserved_for = merged
        deltas: list[tuple[str, str, int, int]] = []
        old_key = (
            (old.allocated_node, tuple(old.device_requests()))
            if old is not None and old.allocated_node
            else None
        )
        new_key = (
            (claim.allocated_node, tuple(claim.device_requests()))
            if claim.allocated_node
            else None
        )
        if old_key != new_key:
            if old_key is not None:
                deltas += self._external_charge(old, -1)
            if new_key is not None:
                self.claims[claim.uid] = claim  # request_pools needs it
                deltas += self._external_charge(claim, +1)
        self.claims[claim.uid] = claim
        self.epoch += 1
        return deltas

    def _external_charge(self, claim: t.ResourceClaim, sign: int):
        """Counter + named-device bookkeeping for an externally-allocated
        claim; returns the per-request row deltas."""
        node = claim.allocated_node
        for req in claim.device_requests():
            key = (node, req.device_class)
            self.allocated[key] = self.allocated.get(key, 0) + sign * req.count
        out = [
            (node, sig, cnt, sign) for sig, cnt in self.charge_pools(claim)
        ]
        if sign < 0:
            # Corrections recorded for this claim (new-pool backfill over
            # its named devices) reverse with the external deallocation;
            # node-removal-parked ones die unapplied (their charges went
            # with the row) — never replay them against a later allocation.
            corr = self.corrections.pop(claim.uid, None)
            if corr:
                self.corr_events.append((node, corr, -1))
            self.pending_corr.pop(claim.uid, None)
        if claim.allocated_devices:
            # The allocation result names its devices: own/free them so
            # selector pools see exact availability.
            for _rname, dev in claim.allocated_devices:
                owners = self.device_owner.setdefault(
                    (node, self._device_class_of(claim, _rname)), {}
                )
                if sign > 0:
                    owners[dev] = claim.uid
                elif owners.get(dev) == claim.uid:
                    del owners[dev]
        return out

    @staticmethod
    def _device_class_of(claim: t.ResourceClaim, request_name: str) -> str:
        for r in claim.device_requests():
            if r.name == request_name:
                return r.device_class
        return claim.device_requests()[0].device_class

    def add_slice(self, s: t.ResourceSlice) -> None:
        key = (s.node_name, s.device_class)
        devs = self.devices.setdefault(key, {})
        if s.devices:
            for d in s.devices:
                # Capacity quantities live beside the attributes under
                # reserved "capacity://" keys (dra_cel.CAPACITY_PREFIX),
                # so capacity terms reuse the requirement machinery.
                attrs = d.attributes
                if d.capacity:
                    attrs = dict(attrs)
                    for ck, cv in d.capacity.items():
                        # Normalize quantity strings ("40Gi") to canonical
                        # ints here — a raw string would silently fail
                        # every capacity comparison (ordered ops require
                        # numbers), the exact silent-mismatch class this
                        # subsystem turns into loud errors.
                        attrs[dra_cel.CAPACITY_PREFIX + ck] = (
                            cv
                            if isinstance(cv, int) and not isinstance(cv, bool)
                            else t.parse_quantity(cv)
                        )
                devs[d.name] = attrs
        else:
            base = len(devs)
            for i in range(s.count):
                devs[f"{s.device_class}-{base + i}"] = {}
        self.slices[key] = len(devs)
        self.epoch += 1

    def pod_claims(self, pod: t.Pod) -> list[t.ResourceClaim | None]:
        return [
            self.claims.get(f"{pod.namespace}/{name}")
            for name in pod.spec.resource_claims
        ]

    def free(self, node: str, device_class: str) -> int:
        key = (node, device_class)
        return self.slices.get(key, 0) - self.allocated.get(key, 0)

    def _free_matching(self, node: str, req: t.DeviceRequest) -> list[str]:
        """Unowned device names on ``node`` matching the request's
        selectors, in sorted order (deterministic pick — the scalar oracle
        mirrors it)."""
        key = (node, req.device_class)
        owners = self.device_owner.get(key, {})
        # The interned pool holds the compiled requirements — no re-parse.
        _cls, reqs = self.pools[self.ensure_pool(req.device_class, req.selectors)]
        return sorted(
            name
            for name, attrs in self.devices.get(key, {}).items()
            if name not in owners and dra_cel.matches(reqs, attrs)
        )

    def allocate_pod_claims(self, pod: t.Pod, node: str) -> list | None:
        """Allocate/reserve the pod's claims on ``node`` (the Reserve step,
        dynamicresources' claim assume + API write; the exact named-device
        allocator, structured/allocator.go).  Returns undo records, or None
        when a claim can no longer be satisfied there (allocation race
        lost — the caller forgets the pod and retries)."""
        # Validate first (all-or-nothing): pick devices for every request
        # of every still-unallocated claim against a working owner view.
        taken: dict[tuple[str, str], set[str]] = {}
        need_counter: dict[str, int] = {}
        picks: dict[str, list[tuple[str, str, str]]] = {}  # claim → [(req, cls, dev)]
        seen_claims: set[str] = set()
        for claim in self.pod_claims(pod):
            if claim is None:
                return None
            if claim.uid in seen_claims:
                continue  # a pod may reference the same claim twice
            seen_claims.add(claim.uid)
            if claim.allocated_node:
                if claim.allocated_node != node:
                    return None
                continue
            for req in claim.device_requests():
                free_names = [
                    n
                    for n in self._free_matching(node, req)
                    if n not in taken.get((node, req.device_class), set())
                ]
                if len(free_names) < req.count:
                    return None
                chosen = free_names[: req.count]
                taken.setdefault((node, req.device_class), set()).update(chosen)
                picks.setdefault(claim.uid, []).extend(
                    (req.name, req.device_class, d) for d in chosen
                )
                need_counter[req.device_class] = (
                    need_counter.get(req.device_class, 0) + req.count
                )
        # Counter guard: count-only EXTERNAL charges consume capacity
        # without naming devices, so named availability over-states.
        for cls, cnt in need_counter.items():
            if self.free(node, cls) < cnt:
                return None
        undo: list[tuple[str, t.ResourceClaim, str]] = []
        committed: set[str] = set()
        for claim in self.pod_claims(pod):
            if claim.uid in committed:
                continue
            committed.add(claim.uid)
            if not claim.allocated_node:
                claim.allocated_node = node
                claim.allocated_devices = tuple(
                    (rname, dev) for rname, _cls, dev in picks.get(claim.uid, ())
                )
                for rname, cls, dev in picks.get(claim.uid, ()):
                    self.device_owner.setdefault((node, cls), {})[dev] = claim.uid
                for req in claim.device_requests():
                    key = (node, req.device_class)
                    self.allocated[key] = self.allocated.get(key, 0) + req.count
                self._record_corrections(claim, node, picks.get(claim.uid, ()))
                undo.append(("allocated", claim, ""))
            if pod.uid not in claim.reserved_for:
                claim.reserved_for += (pod.uid,)
                self.local_reserved.setdefault(claim.uid, set()).add(pod.uid)
                undo.append(("reserved", claim, pod.uid))
        if undo:
            self.epoch += 1
        return undo

    def _record_corrections(self, claim, node: str, picks) -> None:
        """Per-pool overlap charges for a freshly-named allocation: for
        every pool of the devices' classes, (devices actually matching) −
        (what the claim's request-pool transitions charge)."""
        by_class: dict[str, list[str]] = {}
        for _rname, cls, dev in picks:
            by_class.setdefault(cls, []).append(dev)
        charged: dict[str, int] = {}
        for sig, cnt in self.charge_pools(claim):
            charged[sig] = charged.get(sig, 0) + cnt
        corr: list[tuple[str, int]] = []
        for cls, devs in by_class.items():
            attrs_of = self.devices.get((node, cls), {})
            for sig in self.pools_by_class.get(cls, ()):
                _c, reqs = self.pools[sig]
                actual = sum(
                    1 for d in devs if dra_cel.matches(reqs, attrs_of.get(d, {}))
                )
                delta = actual - charged.get(sig, 0)
                if delta:
                    corr.append((sig, delta))
        if corr:
            self.corrections[claim.uid] = corr
            self.corr_events.append((node, corr, +1))

    def _deallocate(self, claim: t.ResourceClaim) -> None:
        node = claim.allocated_node
        for req in claim.device_requests():
            key = (node, req.device_class)
            self.allocated[key] = self.allocated.get(key, 0) - req.count
        for rname, dev in claim.allocated_devices:
            owners = self.device_owner.get(
                (node, self._device_class_of(claim, rname)), {}
            )
            if owners.get(dev) == claim.uid:
                del owners[dev]
        corr = self.corrections.pop(claim.uid, None)
        if corr:
            self.corr_events.append((node, corr, -1))
        self.pending_corr.pop(claim.uid, None)  # never re-applied: no event
        claim.allocated_node = ""
        claim.allocated_devices = ()

    def unallocate(self, undo: list) -> None:
        """Revert allocate_pod_claims (gang rollback)."""
        for kind, claim, uid in undo:
            if kind == "reserved":
                claim.reserved_for = tuple(
                    u for u in claim.reserved_for if u != uid
                )
                self.local_reserved.get(claim.uid, set()).discard(uid)
            else:
                self._deallocate(claim)
        if undo:
            self.epoch += 1

    def release_pod(self, pod_uid: str) -> list[tuple[str, str, str, int]]:
        """Drop the pod's reservations; deallocate claims nobody reserves
        (the resourceclaim controller's cleanup, in-process).  Returns row
        discharges [(uid, node, pool sig, count)] for deallocated claims
        whose charge was EXTERNAL (row_charged) — locally-charged claims
        discharge through the removing pod's own delta transition."""
        changed = False
        discharges: list[tuple[str, str, str, int]] = []
        for claim in self.claims.values():
            if pod_uid in claim.reserved_for:
                claim.reserved_for = tuple(
                    u for u in claim.reserved_for if u != pod_uid
                )
                self.local_reserved.get(claim.uid, set()).discard(pod_uid)
                changed = True
                if not claim.reserved_for and claim.allocated_node:
                    node = claim.allocated_node
                    charged = self.row_charged.pop(claim.uid, None)
                    self.pending_external.pop(claim.uid, None)
                    if charged is not None:
                        discharges.extend(
                            (claim.uid, n, sig, cnt) for n, sig, cnt in charged
                        )
                    self._deallocate(claim)
        if changed:
            self.epoch += 1
        return discharges
