"""Dynamic Resource Allocation: host-side claim catalog + allocation.

The host half of the DynamicResources plugin
(pkg/scheduler/framework/plugins/dynamicresources/, wired at
scheduler.go:298–302 through the claim assume-cache), reduced to the
counted-device form of structured parameters: a ResourceClaim asks for N
devices of a device class; ResourceSlices publish per-node per-class device
counts.  Allocation is delayed (the scheduler allocates at PreBind, like
WaitForFirstConsumer volume binding) and pins the claim to one node;
deallocation happens when the last reserving pod goes away.

Device-side accounting lives in ClusterState.dra_cap/dra_alloc (per-class
per-node counts) committed per-reservation by the engine; this catalog is
the allocation truth the PreBind re-check runs against (the assume-cache
race pattern shared with volumes.VolumeCatalog.bind_pod_volumes)."""

from __future__ import annotations

from dataclasses import dataclass, field

from .api import types as t


@dataclass
class ClaimCatalog:
    claims: dict[str, t.ResourceClaim] = field(default_factory=dict)
    # (node, device_class) → published device count.
    slices: dict[tuple[str, str], int] = field(default_factory=dict)
    # (node, device_class) → devices consumed by allocated claims.
    allocated: dict[tuple[str, str], int] = field(default_factory=dict)
    epoch: int = 0  # featurization cache token
    # External-allocation row charges (see add_claim): claims whose phantom
    # reservation is applied to a node row, and those waiting for their
    # node to appear (the claim-before-node informer race — the same one
    # add_node replays CSINode/ResourceSlices for).
    row_charged: dict[str, tuple[str, str, int]] = field(default_factory=dict)
    pending_external: dict[str, tuple[str, str, int]] = field(default_factory=dict)
    # claim uid → pod uids reserved IN-PROCESS (allocate_pod_claims).  The
    # assume-cache stale-echo guard keys off these, not off the informer's
    # status.reservedFor — external consumers releasing a claim is a real
    # deallocation, not an echo.
    local_reserved: dict[str, set[str]] = field(default_factory=dict)

    def add_claim(
        self, claim: t.ResourceClaim
    ) -> list[tuple[str, str, int, int]]:
        """Upsert a claim (informer).  Returns row-charge deltas
        [(node, class, count, ±1)] for EXTERNAL allocation changes — an
        allocation written by another scheduler (or a restart replay)
        consumes devices the moment it arrives, exactly as the reference's
        claim assume-cache sees it.  The charge rides a PHANTOM
        reservation (SnapshotBuilder.apply_external_claim) so a local pod
        later reserving the same claim cannot double-charge.

        Assume-cache semantics (the reference accepts only informer
        objects newer than its assumed version): an upsert that would
        DE-allocate a claim with live local reservations is a stale watch
        echo of the pre-allocation object and is dropped; an upsert whose
        allocation matches the current record replaces the object without
        touching accounting (local reservations carry over)."""
        old = self.claims.get(claim.uid)
        if old is not None:
            local = self.local_reserved.get(claim.uid, ())
            if local and not claim.allocated_node:
                return []  # stale echo: local truth wins until released
            # Local reservations survive the object replacement; an
            # external consumer vanishing from status.reservedFor does not
            # get resurrected from the old object.
            merged = tuple(dict.fromkeys(
                claim.reserved_for
                + tuple(u for u in old.reserved_for if u in local)
            ))
            claim.reserved_for = merged
        old_alloc = (
            (old.allocated_node, old.device_class, old.count)
            if old is not None and old.allocated_node
            else None
        )
        new_alloc = (
            (claim.allocated_node, claim.device_class, claim.count)
            if claim.allocated_node
            else None
        )
        deltas: list[tuple[str, str, int, int]] = []
        if old_alloc != new_alloc:
            if old_alloc is not None:
                node, cls, cnt = old_alloc
                self.allocated[(node, cls)] = (
                    self.allocated.get((node, cls), 0) - cnt
                )
                deltas.append((node, cls, cnt, -1))
            if new_alloc is not None:
                node, cls, cnt = new_alloc
                self.allocated[(node, cls)] = (
                    self.allocated.get((node, cls), 0) + cnt
                )
                deltas.append((node, cls, cnt, +1))
        self.claims[claim.uid] = claim
        self.epoch += 1
        return deltas

    def add_slice(self, s: t.ResourceSlice) -> None:
        key = (s.node_name, s.device_class)
        self.slices[key] = self.slices.get(key, 0) + s.count
        self.epoch += 1

    def pod_claims(self, pod: t.Pod) -> list[t.ResourceClaim | None]:
        return [
            self.claims.get(f"{pod.namespace}/{name}")
            for name in pod.spec.resource_claims
        ]

    def free(self, node: str, device_class: str) -> int:
        key = (node, device_class)
        return self.slices.get(key, 0) - self.allocated.get(key, 0)

    def allocate_pod_claims(self, pod: t.Pod, node: str) -> list | None:
        """Allocate/reserve the pod's claims on ``node`` (the PreBind step,
        dynamicresources' claim assume + API write).  Returns undo records,
        or None when a claim can no longer be satisfied there (allocation
        race lost — the caller forgets the pod and retries)."""
        # Validate first (all-or-nothing): per-class demand of the pod's
        # still-unallocated claims vs free devices.
        need: dict[str, int] = {}
        for claim in self.pod_claims(pod):
            if claim is None:
                return None
            if claim.allocated_node:
                if claim.allocated_node != node:
                    return None
                continue
            need[claim.device_class] = need.get(claim.device_class, 0) + claim.count
        for cls, cnt in need.items():
            if self.free(node, cls) < cnt:
                return None
        undo: list[tuple[str, t.ResourceClaim, str]] = []
        for claim in self.pod_claims(pod):
            if not claim.allocated_node:
                claim.allocated_node = node
                key = (node, claim.device_class)
                self.allocated[key] = self.allocated.get(key, 0) + claim.count
                undo.append(("allocated", claim, ""))
            if pod.uid not in claim.reserved_for:
                claim.reserved_for += (pod.uid,)
                self.local_reserved.setdefault(claim.uid, set()).add(pod.uid)
                undo.append(("reserved", claim, pod.uid))
        if undo:
            self.epoch += 1
        return undo

    def unallocate(self, undo: list) -> None:
        """Revert allocate_pod_claims (gang rollback)."""
        for kind, claim, uid in undo:
            if kind == "reserved":
                claim.reserved_for = tuple(
                    u for u in claim.reserved_for if u != uid
                )
                self.local_reserved.get(claim.uid, set()).discard(uid)
            else:
                key = (claim.allocated_node, claim.device_class)
                self.allocated[key] = self.allocated.get(key, 0) - claim.count
                claim.allocated_node = ""
        if undo:
            self.epoch += 1

    def release_pod(self, pod_uid: str) -> list[tuple[str, str, str, int]]:
        """Drop the pod's reservations; deallocate claims nobody reserves
        (the resourceclaim controller's cleanup, in-process).  Returns row
        discharges [(uid, node, class, count)] for deallocated claims whose
        charge was EXTERNAL (row_charged) — locally-charged claims
        discharge through the removing pod's own delta transition."""
        changed = False
        discharges: list[tuple[str, str, str, int]] = []
        for claim in self.claims.values():
            if pod_uid in claim.reserved_for:
                claim.reserved_for = tuple(
                    u for u in claim.reserved_for if u != pod_uid
                )
                self.local_reserved.get(claim.uid, set()).discard(pod_uid)
                changed = True
                if not claim.reserved_for and claim.allocated_node:
                    key = (claim.allocated_node, claim.device_class)
                    self.allocated[key] = (
                        self.allocated.get(key, 0) - claim.count
                    )
                    charged = self.row_charged.pop(claim.uid, None)
                    self.pending_external.pop(claim.uid, None)
                    if charged is not None:
                        discharges.append(
                            (claim.uid, claim.allocated_node,
                             claim.device_class, claim.count)
                        )
                    claim.allocated_node = ""
        if changed:
            self.epoch += 1
        return discharges
