"""Scheduler extenders: out-of-process filter/prioritize/bind webhooks.

Mirrors the reference's HTTP extender (pkg/scheduler/extender.go) and its
wire types (staging/src/k8s.io/kube-scheduler/extender/v1/types.go:73–124):
an extender is an external service consulted AFTER the in-process filter
pass (findNodesThatPassExtenders, schedule_one.go:704) and alongside score
aggregation (prioritizeNodes, schedule_one.go:799–857).  Extender scores are
0..MaxExtenderPriority (10) and are rescaled by weight onto the node-score
range.

TPU note: extenders serialize a host round-trip per pod, so a profile with
extenders schedules through the eval-only device pass (filter+score masks
come back to the host, the extender chain runs, the host commits the pick).
That is the same position the reference is in — its extender calls are
synchronous HTTP inside the cycle — so the A/B comparison stays honest;
profiles without extenders keep the fully on-device batch path.
"""

from __future__ import annotations

import json
import urllib.request
from dataclasses import dataclass, field
from typing import Protocol

from .api import types as t

MAX_EXTENDER_PRIORITY = 10  # extender/v1/types.go:29
MAX_NODE_SCORE = 100


@dataclass
class ExtenderArgs:
    """extender/v1 ExtenderArgs (types.go:73)."""

    pod: t.Pod
    node_names: list[str]

    def to_json(self) -> dict:
        return {
            "Pod": {
                "metadata": {
                    "name": self.pod.metadata.name,
                    "namespace": self.pod.namespace,
                    "labels": dict(self.pod.metadata.labels),
                },
                "spec": {"priority": self.pod.spec.priority},
            },
            "NodeNames": self.node_names,
        }


@dataclass
class ExtenderFilterResult:
    """extender/v1 ExtenderFilterResult (types.go:88)."""

    node_names: list[str] = field(default_factory=list)
    failed_nodes: dict[str, str] = field(default_factory=dict)
    failed_and_unresolvable_nodes: dict[str, str] = field(default_factory=dict)
    error: str = ""


@dataclass
class HostPriority:
    """extender/v1 HostPriority (types.go:124)."""

    host: str
    score: int


class Extender(Protocol):
    """The scheduler-side extender surface (framework.Extender)."""

    name: str
    weight: int
    ignorable: bool  # errors don't fail the cycle (extender.go IsIgnorable)

    def filter(self, pod: t.Pod, nodes: list[str]) -> ExtenderFilterResult: ...

    def prioritize(self, pod: t.Pod, nodes: list[str]) -> list[HostPriority]: ...

    def bind(self, pod: t.Pod, node: str) -> bool: ...

    def is_interested(self, pod: t.Pod) -> bool: ...


@dataclass
class HTTPExtender:
    """HTTP+JSON extender client (pkg/scheduler/extender.go HTTPExtender):
    POSTs ExtenderArgs to url_prefix/<verb>."""

    url_prefix: str
    filter_verb: str = ""
    prioritize_verb: str = ""
    bind_verb: str = ""
    preempt_verb: str = ""
    weight: int = 1
    ignorable: bool = False
    timeout_s: float = 5.0
    # Pods with no resource request in managed_resources skip this extender
    # (extender.go IsInterested).
    managed_resources: tuple[str, ...] = ()

    @property
    def name(self) -> str:
        return self.url_prefix

    def _post(self, verb: str, payload: dict) -> dict:
        req = urllib.request.Request(
            f"{self.url_prefix.rstrip('/')}/{verb}",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            return json.load(resp)

    def is_interested(self, pod: t.Pod) -> bool:
        if not self.managed_resources:
            return True
        req = pod.resource_request()
        return any(req.get(r, 0) > 0 for r in self.managed_resources)

    def filter(self, pod: t.Pod, nodes: list[str]) -> ExtenderFilterResult:
        if not self.filter_verb:
            return ExtenderFilterResult(node_names=list(nodes))
        out = self._post(self.filter_verb, ExtenderArgs(pod, nodes).to_json())
        return ExtenderFilterResult(
            node_names=list(out.get("NodeNames") or []),
            failed_nodes=dict(out.get("FailedNodes") or {}),
            failed_and_unresolvable_nodes=dict(
                out.get("FailedAndUnresolvableNodes") or {}
            ),
            error=out.get("Error") or "",
        )

    def prioritize(self, pod: t.Pod, nodes: list[str]) -> list[HostPriority]:
        if not self.prioritize_verb:
            return []
        out = self._post(self.prioritize_verb, ExtenderArgs(pod, nodes).to_json())
        return [
            HostPriority(h["Host"], int(h["Score"])) for h in out or []
        ]

    def bind(self, pod: t.Pod, node: str) -> bool:
        if not self.bind_verb:
            return True
        out = self._post(
            self.bind_verb,
            {"PodName": pod.metadata.name, "PodNamespace": pod.namespace, "Node": node},
        )
        return not (out or {}).get("Error")

    @property
    def supports_preemption(self) -> bool:
        # extender.go SupportsPreemption: declared by a preempt verb.
        return bool(self.preempt_verb)

    def process_preemption(
        self, pod: t.Pod, node_to_victims: dict[str, list[t.Pod]]
    ) -> dict[str, list[str]]:
        """ProcessPreemption (extender.go, wire types extender/v1
        ExtenderPreemptionArgs/Result): POST the candidate victim map as
        NodeNameToMetaVictims ({node: {Pods: [{UID}]}}), get back the
        subset of nodes (with victim uids) the extender accepts."""
        payload = {
            "Pod": ExtenderArgs(pod, []).to_json()["Pod"],
            "NodeNameToMetaVictims": {
                node: {"Pods": [{"UID": v.uid} for v in victims]}
                for node, victims in node_to_victims.items()
            },
        }
        out = self._post(self.preempt_verb, payload)
        result = out.get("NodeNameToMetaVictims") or {}
        return {
            node: [p.get("UID", "") for p in (meta or {}).get("Pods", [])]
            for node, meta in result.items()
        }


def run_extender_chain(
    extenders: list, pod: t.Pod, feasible: list[str], scores: dict[str, int]
) -> tuple[list[str], dict[str, int], set[str]]:
    """Filter + prioritize through the chain.

    Filtering is sequential and shrinking (findNodesThatPassExtenders);
    prioritize results are weighted and ADDED to the in-process scores
    (prioritizeNodes: extender scores × weight on top of plugin scores).
    Returns (surviving nodes, combined scores, unresolvable nodes)."""
    nodes = list(feasible)
    unresolvable: set[str] = set()
    for ex in extenders:
        if not nodes:
            break
        if not ex.is_interested(pod):
            continue
        try:
            res = ex.filter(pod, nodes)
        except Exception:
            if ex.ignorable:
                continue
            raise
        if res.error and not ex.ignorable:
            raise RuntimeError(f"extender {ex.name}: {res.error}")
        unresolvable |= set(res.failed_and_unresolvable_nodes)
        nodes = [n for n in res.node_names if n not in unresolvable]
    combined = {n: scores.get(n, 0) for n in nodes}
    for ex in extenders:
        if not ex.is_interested(pod):
            continue
        try:
            prios = ex.prioritize(pod, nodes)
        except Exception:
            if ex.ignorable:
                continue
            raise
        for hp in prios:
            if hp.host in combined:
                # Extender scores are 0..10, rescaled by weight
                # (prioritizeNodes: score * weight; the reference adds the
                # raw product to the MaxNodeScore-normalized plugin sum).
                combined[hp.host] += hp.score * ex.weight
    return nodes, combined, unresolvable
