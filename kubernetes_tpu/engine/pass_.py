"""The batched scheduling pass: one device dispatch schedules a whole batch.

This replaces both reference hot loops — the goroutine-parallel Filter over
nodes (schedule_one.go:591 findNodesThatPassFilters) and the 3-pass parallel
Score (runtime/framework.go:1101) — with vectorized ops over the node axis,
and replaces the serialized one-pod-at-a-time outer loop (scheduler.go:470)
with a `lax.scan` over the pod batch.

Chunking: each scan step schedules a CHUNK of `chunk` pods.  Filter, score,
and selectHost are vmapped over the chunk (one set of vectorized ops services
the whole chunk — on TPU the per-op dispatch overhead inside a compiled loop
dominates these small tensors, so C pods per step is ~C× cheaper than C
steps).  Correctness is restored by on-device conflict resolution:

  * Pods whose decision could depend on an earlier chunk-mate's commit
    (writer's pod-group or affinity terms intersect the reader's selector
    masks; shared host-port keys; any volume use) are DEFERRED (pick = -2) —
    the scheduler re-runs them through a strict chunk=1 pass against the
    committed state, preserving the sequential outcome for every interacting
    pod.
  * Resource/pod-count fit is checked EXACTLY within the chunk: cumulative
    same-node demand in chunk order must fit, else the pod defers.

With chunk=1 the pass is the strictly sequential-equivalent scan: each step
is one reference scheduling cycle — filter → score → selectHost → commit —
with the assume's row-delta applied to the carried ClusterState so the next
pod observes it (the reference gets the same effect through its cache assume
protocol, cache.go:361).  Chunk>1 trades one documented divergence for
throughput: non-interacting chunk-mates score against the chunk-start state,
so resource-driven score drift (e.g. LeastAllocated) within a chunk does not
influence their relative placement.  Hard constraints are never violated —
anything that could be is in the defer classes above — and the reference
itself exhibits analogous drift across its async binding goroutines.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.config import Profile
from ..ops import common as opcommon
from ..ops.helpers import make_topo_onehot
from ..snapshot import ClusterState, Schema


class PassResult(NamedTuple):
    picks: jax.Array  # (K,) i32 — chosen node row, -1 = unschedulable
    scores: jax.Array  # (K,) i64 — winning node's total score
    feasible_counts: jax.Array  # (K,) i32 — nodes passing all filters
    # (K,) i32 — nodes examined this cycle in truncated (parity) mode: the
    # rotation increment (schedule_one.go:519 processedNodes).  Zero when
    # percentage_of_nodes_to_score == 100 (full evaluation).
    processed: jax.Array
    # (K,) u32 — bit b set ⟺ filter op b rejected ≥1 node that passed every
    # earlier filter: the batch analog of Diagnosis.UnschedulablePlugins
    # (the reference records each node's FIRST failing plugin,
    # runtime/framework.go:861 RunFilterPlugins).  Bit order =
    # filter_op_names(profile, active).
    fail_masks: jax.Array


def filter_op_names(profile: Profile, active: frozenset[str] | None) -> list[str]:
    """Filter-op bit order of PassResult.fail_masks for one compiled pass."""
    return [
        n
        for n in profile.filters
        if (active is None or n in active) and opcommon.get(n).filter is not None
    ]


class DomTables(NamedTuple):
    """Per-domain aggregate tables, the device analog of the reference's
    ``topologyToMatchedTermCount`` maps (interpodaffinity/filtering.go:86).

    The expensive reductions over the node axis are computed ONCE per pass
    (build_dom) and then maintained INCREMENTALLY by the scan's commit — the
    hoist that VERDICT r1 called out: rebuilding the (N, TK, DV) one-hot and
    its einsum every scan step was the anti-affinity 1.5× bottleneck.

    ``onehot``/``et_vals`` are scan-invariant (node topology never changes
    mid-batch); ``group_dom``/``et_dom`` are part of the scan carry."""

    onehot: jax.Array  # (N, TK, DV) f32 — topo one-hot, scan-invariant
    group_dom: jax.Array  # (G, TK, DV) f32 — pods of group g in domain (k, d)
    et_dom: jax.Array  # (ET, DV) f32 — carriers of term t in its own key's domain d
    et_vals: jax.Array  # (ET, N) i32 — node's domain id at term t's topo slot
    et_slot: jax.Array  # (ET,) i32 — term t's topology-key slot
    et_host: jax.Array  # (ET,) bool — term t's key is the hostname key


def _dom_aggregates(
    state: ClusterState, onehot: jax.Array, et_slot: jax.Array, dv: int
) -> tuple[jax.Array, jax.Array]:
    """(group_dom, et_dom): the expensive per-domain aggregate matmuls —
    the piece a carried-over DomTables skips (see build_pass carry_dom)."""
    group_dom = jnp.einsum(
        "gn,nkd->gkd", state.group_counts.astype(jnp.float32), onehot
    )
    et_f = state.et_counts.astype(jnp.float32)  # (ET, N)
    tk = state.topo_vals.shape[1]
    et_dom = jnp.zeros((et_f.shape[0], dv), jnp.float32)
    for k in range(tk):  # static TK, unrolled: TK small (ET,N)x(N,DV) matmuls
        sel = jnp.where((et_slot == k)[:, None], et_f, 0.0)
        et_dom = et_dom + sel @ onehot[:, k, :]
    return group_dom, et_dom


def build_dom(state: ClusterState, et_slot: jax.Array, et_host: jax.Array, dv: int) -> DomTables:
    """Full rebuild of the domain tables from the cluster state — one set of
    MXU matmuls per device pass (amortized over the whole pod batch)."""
    onehot = make_topo_onehot(state.topo_vals, dv)  # (N, TK, DV)
    group_dom, et_dom = _dom_aggregates(state, onehot, et_slot, dv)
    et_vals = jnp.take(state.topo_vals, et_slot, axis=1).T  # (ET, N)
    return DomTables(onehot, group_dom, et_dom, et_vals, et_slot, et_host)


def _hash_u32(x: jax.Array) -> jax.Array:
    """splitmix32-style avalanche; deterministic counter-based tie-break RNG.

    The reference breaks score ties with reservoir sampling over math/rand
    (schedule_one.go:888–899).  For cross-run determinism (and Go↔device
    parity) we instead pick the h(seed, step)-th tie in snapshot row order —
    still uniform over ties, but a pure function of (seed, step)."""
    x = x.astype(jnp.uint32)
    x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
    x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return x


def select_host(
    feasible: jax.Array, total: jax.Array, tie_rand: jax.Array,
    pos: jax.Array | None = None,
):
    """argmax with uniform tie-break among max-score feasible nodes.

    Mirrors selectHost (schedule_one.go:873): highest TotalScore wins;
    ties broken uniformly (see _hash_u32 docstring for the parity rule).
    With ``pos`` (truncated/parity mode) ties enumerate in rotated scan
    order — the order the reference's feasible list is built in — instead
    of snapshot row order."""
    neg = jnp.int64(-(2**62))
    masked = jnp.where(feasible, total, neg)
    best = jnp.max(masked)
    ties = feasible & (masked == best)
    m = jnp.sum(ties.astype(jnp.int32))
    kth = (tie_rand % jnp.maximum(m, 1).astype(jnp.uint32)).astype(jnp.int32)
    if pos is None:
        # Index of the (kth+1)-th True in `ties`, row order.
        order = jnp.cumsum(ties.astype(jnp.int32)) - 1
        pick = jnp.argmax(ties & (order == kth)).astype(jnp.int32)
    else:
        big = jnp.int32(2**30)
        tpos = jnp.where(ties, pos, big)
        thr = jnp.sort(tpos)[jnp.clip(kth, 0, tpos.shape[0] - 1)]
        pick = jnp.argmax(ties & (tpos == thr)).astype(jnp.int32)
    pick = jnp.where(m > 0, pick, -1)
    return pick, best, m


def _commit_chunk(
    state: ClusterState, dom: DomTables, pf: dict, picks: jax.Array, do: jax.Array
) -> tuple[ClusterState, DomTables]:
    """Apply a chunk's row-deltas on device (NodeInfo.AddPodInfo,
    framework/types.go:990).  ``pf`` leaves are (C, …), ``picks``/``do`` (C,).
    All updates are predicated on `do` so padded, unschedulable, or deferred
    pods commit nothing; scatter-adds accumulate duplicates, so several pods
    landing on one node commit correctly in one op.  The domain tables get
    the SAME delta (each pod joins its group's/terms' domains at its node's
    topology values) so the next chunk's affinity lookups stay consistent."""
    rows = jnp.where(do, picks, 0)  # (C,)
    zero64 = jnp.int64(0)
    c = rows.shape[0]
    new = dict(
        req=state.req.at[rows].add(jnp.where(do[:, None], pf["req"], zero64)),
        nonzero_req=state.nonzero_req.at[rows].add(
            jnp.where(do[:, None], pf["nonzero"], zero64)
        ),
        num_pods=state.num_pods.at[rows].add(do.astype(jnp.int32)),
        group_counts=state.group_counts.at[pf["group"], rows].add(do.astype(jnp.int32)),
    )
    # Domain tables: each chosen node's per-slot topology values.
    dvals = state.topo_vals[rows]  # (C, TK)
    tk = dvals.shape[1]
    inc_k = (do[:, None] & (dvals >= 0)).astype(jnp.float32)
    group_dom = dom.group_dom.at[
        pf["group"][:, None], jnp.arange(tk)[None, :], jnp.clip(dvals, 0)
    ].add(inc_k)
    et_dom = dom.et_dom
    if "port_triples" in pf:
        inc = (do[:, None] & (pf["port_triples"] >= 0)).astype(jnp.int32)
        safe_t = jnp.maximum(pf["port_triples"], 0)
        safe_k = jnp.maximum(pf["port_keys"], 0)
        new["port_counts"] = state.port_counts.at[safe_t, rows[:, None]].add(inc)
        new["portkey_counts"] = state.portkey_counts.at[safe_k, rows[:, None]].add(inc)
    if "ipa_own_terms" in pf:
        own = pf["ipa_own_terms"]  # (C, A)
        inc = (do[:, None] & (own >= 0)).astype(jnp.int32)
        safe_a = jnp.maximum(own, 0)
        new["et_counts"] = state.et_counts.at[safe_a, rows[:, None]].add(inc)
        # Term t's domain at this node: the value at the term's own topo slot.
        d_a = dvals[jnp.arange(c)[:, None], dom.et_slot[safe_a]]  # (C, A)
        inc_a = (do[:, None] & (own >= 0) & (d_a >= 0)).astype(jnp.float32)
        et_dom = et_dom.at[safe_a, jnp.clip(d_a, 0)].add(inc_a)
    if "vol_dev_ids" in pf:
        inc = (do[:, None] & (pf["vol_dev_ids"] >= 0)).astype(jnp.int32)
        safe_d = jnp.maximum(pf["vol_dev_ids"], 0)
        new["dev_counts"] = state.dev_counts.at[safe_d, rows[:, None]].add(inc)
        new["dev_rw_counts"] = state.dev_rw_counts.at[safe_d, rows[:, None]].add(
            inc * pf["vol_dev_rw"].astype(jnp.int32)
        )
    if "vol_csi_ids" in pf:
        # Distinct-volume accounting (nodevolumelimits/csi.go:219): a volume
        # counts against the driver limit only when its per-node pod count
        # crosses 0→1.  Safe to read-before-scatter: volume-using pods are a
        # conflict class in _conflict_pairs, so at most one commits per chunk.
        ids = pf["vol_csi_ids"]  # (C, S)
        act = do[:, None] & (ids >= 0)
        safe_v = jnp.maximum(ids, 0)
        prev = state.csivol_counts[safe_v, rows[:, None]]  # (C, S)
        new["csivol_counts"] = state.csivol_counts.at[safe_v, rows[:, None]].add(
            act.astype(jnp.int32)
        )
        newly = act & (prev == 0)  # (C, S)
        drv_oh = (
            pf["vol_csi_drv"][:, :, None] == jnp.arange(state.csi_used.shape[0])[None, None, :]
        ) & newly[:, :, None]  # (C, S, DR)
        new["csi_used"] = state.csi_used.at[:, rows].add(
            drv_oh.sum(axis=1).astype(jnp.int32).T
        )
    if "dra_claim_ids" in pf:
        # DRA distinct-claim accounting (the csivol pattern): a claim's
        # devices charge dra_alloc only on its 0→1 reservation transition
        # on the node.  Safe to read-before-scatter: DRA pods are a
        # conflict class, at most one commits per chunk.
        kids = pf["dra_claim_ids"]  # (C, S)
        act = do[:, None] & (kids >= 0)
        safe_k = jnp.maximum(kids, 0)
        prev = state.dra_claim_counts[safe_k, rows[:, None]]  # (C, S)
        # Slots are per device REQUEST; only a claim's `first` slot moves
        # its count (the others charge their own selector pools below).
        # prev reads pre-scatter state, so same-claim slots agree on the
        # 0↔1 transition.
        new["dra_claim_counts"] = state.dra_claim_counts.at[
            safe_k, rows[:, None]
        ].add((act & pf["dra_claim_first"]).astype(jnp.int32))
        newly = act & (prev == 0)
        dc = state.dra_alloc.shape[0]
        cls_oh = (
            pf["dra_claim_cls"][:, :, None] == jnp.arange(dc)[None, None, :]
        ) & newly[:, :, None]  # (C, S, DC)
        inc_dc = (cls_oh * pf["dra_claim_cnt"][:, :, None]).sum(axis=1)  # (C, DC)
        new["dra_alloc"] = state.dra_alloc.at[:, rows].add(
            inc_dc.astype(jnp.int32).T
        )
    return dataclasses.replace(state, **new), dom._replace(
        group_dom=group_dom, et_dom=et_dom
    )


def _conflict_pairs(pf: dict, schema: Schema) -> jax.Array:
    """(C, C) bool: does pod i's commit possibly affect pod j's decision?

    pairs[i, j] = (i's pod group ∈ j's selector-mask reads) ∨ (i's own
    affinity terms ∩ j's matched terms) ∨ (shared host-port keys) ∨ (both
    touch volumes).  This is the batch analog of "which earlier scheduling
    cycles could this cycle observe": any such reader is deferred to a strict
    pass.  Conservative by construction — extra pairs only cost a deferral,
    never correctness.  Reads are assembled from the ops' own feature masks
    (tps_*_groups, ipa_*), so an inactive op contributes nothing."""
    group_oh = (
        pf["group"][:, None] == jnp.arange(schema.G)[None, :]
    )  # (C, G) — what each pod writes
    # Only HARD (filter) reads defer: score-only terms (preferred affinity,
    # ScheduleAnyway spread) drift within a chunk exactly like
    # LeastAllocated resource scores — the documented chunked-mode drift —
    # while hard constraints stay sequential-exact.
    reads_g = jnp.zeros(group_oh.shape, jnp.bool_)
    if "ipa_ra_allmask" in pf:
        reads_g = reads_g | pf["ipa_ra_allmask"]
        reads_g = reads_g | pf["ipa_rs_groups"].any(axis=1)
    if "tps_h_groups" in pf:
        reads_g = reads_g | pf["tps_h_groups"].any(axis=1)
    pairs = jnp.einsum(
        "ig,jg->ij", group_oh.astype(jnp.float32), reads_g.astype(jnp.float32)
    ) > 0.5
    if "ipa_et_match" in pf:
        own = pf["ipa_own_terms"]  # (C, A)
        writes_t = (
            (own[:, :, None] == jnp.arange(schema.ET)[None, None, :]) & (own >= 0)[:, :, None]
        ).any(axis=1)  # (C, ET)
        hard_reads_t = pf["ipa_et_match"] & pf["ipa_et_anti"]  # (C, ET)
        pairs = pairs | (
            jnp.einsum(
                "it,jt->ij",
                writes_t.astype(jnp.float32),
                hard_reads_t.astype(jnp.float32),
            )
            > 0.5
        )
    if "port_keys" in pf:
        pk = pf["port_keys"]  # (C, S)
        ports_oh = (
            (pk[:, :, None] == jnp.arange(schema.PK)[None, None, :]) & (pk >= 0)[:, :, None]
        ).any(axis=1)  # (C, PK)
        pairs = pairs | (
            jnp.einsum(
                "ip,jp->ij", ports_oh.astype(jnp.float32), ports_oh.astype(jnp.float32)
            )
            > 0.5
        )
    # Volume/DRA conflicts by IDENTITY, not any-vs-any (the old rule
    # deferred every volume pod behind every other, strict-tailing whole PV
    # workloads):
    #  - shared in-tree device id or shared CSI volume (same claim);
    #  - both have UNBOUND WaitForFirstConsumer claims (their PreBinds race
    #    over the same candidate PV / provisioner pool);
    #  - shared DRA claim, or both demanding unallocated claims (allocation
    #    races over the same free-device pool).
    def _id_overlap(ids: jax.Array) -> jax.Array:
        valid = ids >= 0
        eq = (ids[:, None, :, None] == ids[None, :, None, :]) & (
            valid[:, None, :, None] & valid[None, :, None, :]
        )
        return eq.any(axis=(2, 3))

    pairs = pairs | _id_overlap(pf["vol_dev_ids"]) | _id_overlap(pf["vol_csi_ids"])
    if "vol_unbound" in pf:
        pairs = pairs | (pf["vol_unbound"][:, None] & pf["vol_unbound"][None, :])
    if "dra_claim_ids" in pf:
        pairs = pairs | _id_overlap(pf["dra_claim_ids"])
        # Only UNALLOCATED claims race over the free-device pool; allocated
        # claims pin to their node and consume nothing new.
        need = pf["dra_claim_unalloc"].any(axis=1)
        pairs = pairs | (need[:, None] & need[None, :])
    c = pairs.shape[0]
    return pairs & ~jnp.eye(c, dtype=jnp.bool_)


def build_pass(
    profile: Profile,
    schema: Schema,
    builder_res_col: dict[str, int],
    active: frozenset[str] | None = None,
    chunk: int = 1,
    carry_dom: bool = False,
):
    """Compile the batch pass for one (profile, schema, active-op-set, chunk).

    Returns run(state, batch, inv, seed_base) → (state, PassResult), where
    ``inv`` holds the batch-invariant term→slot tables
    (SnapshotBuilder.batch_invariants). Recompiles
    only when the profile, a bucketed schema capacity, the batch-active
    op set, or the chunk size changes — the analog of building a
    frameworkImpl per profile (profile/profile.go:50) with per-cycle Skip
    sets, plus XLA compilation.  Result picks: node row ≥ 0, -1
    unschedulable, -2 deferred to a strict pass (see module docstring).

    ``batch["step_offset"]`` (optional, (K,) i32): per-pod tie-break step
    offsets — the scheduler ships each pod's ORIGINAL dispatch position so
    the selectHost tie seed rides the pod, not the slot.  A packed
    (reordered) batch and its strict-tail re-runs then draw the exact seed
    the chunk_size=1 sequential scan would have drawn, which is what keeps
    packed bindings bit-identical to the parity oracle.  Absent (direct
    callers), positions default to arange — the pre-packing behavior.

    ``carry_dom=True`` changes the signature to
    run(state, batch, inv, seed_base, dom_group, dom_et, dom_valid)
    → (state, PassResult, (group_dom, et_dom)): when ``dom_valid`` the
    expensive domain-aggregate rebuild (``_dom_aggregates``) is skipped and
    the carried tables are used (the scan maintained them incrementally
    last batch); the final tables ride back so the scheduler can carry
    them batch to batch, rebuilding only on host-side invalidation (see
    scheduler._dom_carry_valid).  The carry is derivable state — recovery
    never persists it."""
    filter_ops = [
        opcommon.get(n)
        for n in profile.filters
        if active is None or n in active
    ]
    score_ops = [
        (opcommon.get(n), w)
        for n, w in profile.scorers
        if active is None or n in active
    ]
    static: dict = {}
    for op in {o.name: o for o in filter_ops + [o for o, _ in score_ops]}.values():
        if op.static is not None:
            static.update(op.static(profile, schema, builder_res_col))
    ctx = opcommon.PassContext(profile=profile, schema=schema, static=static)
    c = chunk
    # Fused strict tail eligibility (see the tail block in run): every
    # active op node-axis-only, chunked, not parity mode.
    _effective = frozenset(o.name for o in filter_ops) | frozenset(
        o.name for o, _ in score_ops
    )
    fuse_tail = (
        chunk > 1
        and profile.percentage_of_nodes_to_score == 100
        and _effective <= PINNED_SAFE_OPS
    )

    # Truncated (parity) mode — percentage_of_nodes_to_score != 100:
    # reproduce the reference's adaptive search truncation semantics
    # sequentially: numFeasibleNodesToFind (schedule_one.go:676, formula
    # 50 − nodes/125 clamped to ≥5% when unset, floor 100 nodes), the
    # zone-interleaved scan order (node_tree.go:119 via inv["order_pos"]),
    # and the rotating start index (schedule_one.go:628, carried through
    # the scan; the per-cycle increment is processedNodes, :519).  The
    # reference's parallel checkNode makes WHICH feasible nodes win the
    # race nondeterministic; the deterministic parity semantic is the
    # sequential scan (parallelism=1), which is what a batch scan step is.
    truncated = profile.percentage_of_nodes_to_score != 100
    pct_cfg = profile.percentage_of_nodes_to_score
    if truncated:
        assert c == 1, "truncation/parity mode requires chunk_size=1"

    def _num_to_find(nvalid: jax.Array) -> jax.Array:
        """numFeasibleNodesToFind (schedule_one.go:676–702)."""
        if pct_cfg:
            percentage = jnp.int32(pct_cfg)
        else:  # unset → adaptive formula, min 5%
            percentage = jnp.maximum(50 - nvalid // 125, 5).astype(jnp.int32)
        num = jnp.maximum(nvalid * percentage // 100, 100)
        return jnp.where(nvalid < 100, nvalid, num)

    def _run(
        state: ClusterState,
        batch: dict,
        inv: dict,
        seed_base: jax.Array,
        dom_group: jax.Array | None = None,
        dom_et: jax.Array | None = None,
        dom_valid: jax.Array | None = None,
    ):
        # Domain tables: rebuilt once per pass, maintained incrementally by
        # the scan's commit.  The one-hot and per-term value gathers are
        # scan-invariant, so the scan body closes over them instead of
        # recomputing per step (the r1 anti-affinity bottleneck).  With
        # carry_dom the aggregate rebuild itself is skipped whenever the
        # caller carried last batch's tables (dom_valid) — the cond keeps
        # ONE compiled program either way.
        if carry_dom:
            onehot = make_topo_onehot(state.topo_vals, schema.DV)
            group0, et0 = lax.cond(
                dom_valid,
                lambda _: (dom_group, dom_et),
                lambda _: _dom_aggregates(state, onehot, inv["et_slot"], schema.DV),
                None,
            )
            et_vals = jnp.take(state.topo_vals, inv["et_slot"], axis=1).T
            dom0 = DomTables(
                onehot, group0, et0, et_vals, inv["et_slot"], inv["et_host"]
            )
        else:
            dom0 = build_dom(state, inv["et_slot"], inv["et_host"], schema.DV)
        # Nominated-pod overlay for the fit filter (framework.go:973
        # RunFilterPluginsWithNominatedPods); the scheduler always ships it
        # (zeros when no pods are nominated, so the compiled program is
        # stable); direct callers (tests/profiling) may omit it.
        ctx_nom = dataclasses.replace(
            ctx,
            nom=(
                (inv["nom_req"], inv["nom_cnt"], inv["nom_prio"])
                if "nom_req" in inv
                else None
            ),
        )
        k = batch["valid"].shape[0]
        assert k % c == 0, f"batch size {k} not a multiple of chunk {c}"
        batch = dict(batch)
        # Scalar flag (not a per-pod feature): every pod in the batch is
        # featurization-identical.  Popped before the chunk reshape.
        uniform_all = batch.pop("uniform_all", None)
        # Tie-break step offsets ride the POD (its original dispatch
        # position), not the slot — a packed batch's seeds match the
        # sequential scan's.  Popped before the reshape (no op reads it).
        step_off = batch.pop("step_offset", None)
        cbatch = jax.tree_util.tree_map(
            lambda x: x.reshape((k // c, c) + x.shape[1:]), batch
        )
        offs = (
            jnp.arange(k, dtype=jnp.uint32)
            if step_off is None
            else step_off.astype(jnp.uint32)
        )
        steps = (seed_base.astype(jnp.uint32) + offs).reshape(k // c, c)

        def eval_pod(state, dctx, pf, step_idx, start):
            """One reference scheduling cycle's decision (no commit)."""
            feasible = state.valid
            fail_mask = jnp.uint32(0)
            bit = 0
            for op in filter_ops:
                if op.filter is not None:
                    ok = op.filter(state, pf, dctx)
                    newly = feasible & ~ok
                    fail_mask = fail_mask | jnp.where(
                        newly.any(), jnp.uint32(1 << bit), jnp.uint32(0)
                    )
                    bit += 1
                    feasible &= ok
            pos = None
            processed = jnp.int32(0)
            if truncated:
                # Truncate the feasible set to the first `limit` feasible
                # nodes in rotated zone-interleaved order (the sequential
                # findNodesThatPassFilters semantics): positions sort, the
                # limit-th smallest is the cutoff; processedNodes is the
                # (limit+1)-th feasible position (the node whose check
                # tripped the cancel) or the whole list.
                nvalid = jnp.sum(state.valid.astype(jnp.int32))
                nv = jnp.maximum(nvalid, 1)
                limit = _num_to_find(nvalid)
                big = jnp.int32(2**30)
                pos = jnp.where(
                    state.valid,
                    (inv["order_pos"] - start.astype(jnp.int32)) % nv,
                    big,
                )
                total_feas = jnp.sum(feasible.astype(jnp.int32))
                fpos = jnp.sort(jnp.where(feasible, pos, big))
                n = fpos.shape[0]
                over = total_feas > limit
                cutoff = fpos[jnp.clip(limit - 1, 0, n - 1)]
                feasible = jnp.where(over, feasible & (pos <= cutoff), feasible)
                processed = jnp.where(over, fpos[jnp.clip(limit, 0, n - 1)], nvalid)
            total = jnp.zeros(schema.N, jnp.int64)
            for op, weight in score_ops:
                if op.score is not None:
                    # Plugin scores are pre-normalized to [0, MaxNodeScore]
                    # over the feasible (post-truncation) set; the framework
                    # applies the weight (runtime/framework.go:1188).
                    total += op.score(state, pf, dctx, feasible) * jnp.int64(weight)
            tie_rand = _hash_u32(
                jnp.uint32(profile.tie_break_seed) * jnp.uint32(2654435761)
                + step_idx.astype(jnp.uint32)
            )
            pick, best, _ties = select_host(feasible, total, tie_rand, pos)
            # Nominated-node fast path (schedule_one.go:491–502): a pod
            # whose preemption nominated a node takes it whenever it is
            # feasible, without re-ranking the whole cluster.
            nomr = pf.get("nominated_row")
            if nomr is not None:
                safe_nom = jnp.maximum(nomr, 0)
                use_nom = (nomr >= 0) & feasible[safe_nom]
                pick = jnp.where(use_nom, safe_nom, pick)
                best = jnp.where(use_nom, total[safe_nom], best)
            return pick, best, jnp.sum(feasible.astype(jnp.int32)), fail_mask, processed

        def step(carry, xs):
            state, group_dom, et_dom, start = carry
            pf, step_idx = xs  # pf leaves (C, …)
            dom = dom0._replace(group_dom=group_dom, et_dom=et_dom)
            dctx = dataclasses.replace(ctx_nom, dom=dom)
            picks, bests, feas, fails, processed = jax.vmap(
                lambda p, si: eval_pod(state, dctx, p, si, start)
            )(pf, step_idx)
            if truncated:
                # Rotation advances only for real pods (padding must not
                # skew the start index across batches).
                inc = jnp.where(pf["valid"], processed, 0).sum().astype(jnp.uint32)
                nv = jnp.maximum(jnp.sum(state.valid.astype(jnp.int32)), 1)
                start = (start + inc) % nv.astype(jnp.uint32)
            att = pf["valid"] & (picks >= 0)  # attempting placement
            defer = jnp.zeros((c,), jnp.bool_)
            if c > 1:
                # (a) Interaction deferral: reader pods behind any attempting
                # writer re-run strictly (module docstring).
                pairs = _conflict_pairs(pf, schema)
                # before[i, j] ⟺ i precedes j in chunk order.  A reader
                # behind an attempting writer defers even when its own pick
                # failed (-1): the writer's commit may make it feasible
                # (e.g. required pod affinity to the writer's group).
                before = jnp.triu(jnp.ones((c, c), jnp.bool_), k=1)
                defer = (pairs & before & att[:, None]).any(axis=0) & pf["valid"]
                att = att & ~defer
                # (b) Exact cumulative resource fit at each picked node in
                # chunk order (fitsRequest semantics over the chunk prefix).
                samei = (
                    (picks[:, None] == picks[None, :])
                    & att[:, None]
                    & att[None, :]
                    & jnp.triu(jnp.ones((c, c), jnp.bool_))  # i ≤ j, incl. self
                )
                # i64 dot_general has no TPU lowering; masked-sum instead.
                cum_req = jnp.where(
                    samei[:, :, None], pf["req"][:, None, :], jnp.int64(0)
                ).sum(axis=0)  # (C, R)
                cum_cnt = samei.sum(axis=0).astype(jnp.int32)  # (C,)
                rows = jnp.where(att, picks, 0)
                free = (state.alloc - state.req)[rows]  # (C, R)
                # Per-resource escape mirrors noderesources.filter_fn: a
                # resource the pod does not request is never checked (the
                # node may legitimately be over-committed on it).
                ok = ((pf["req"] == 0) | (cum_req <= free)).all(axis=-1) & (
                    state.num_pods[rows] + cum_cnt <= state.allowed_pods[rows]
                )
                overflow = att & ~ok
                # Per-node CSI attach limits interact only on the SAME node:
                # a later chunk-mate whose limit-scoped claims land where an
                # earlier mate's did defers (distinct volumes still consume
                # one shared per-driver budget; cross-node claims don't).
                if "vol_csi_lim" in pf:
                    lim = pf["vol_csi_lim"]  # (C,) carries a limited-driver claim
                    prev_same = samei & ~jnp.eye(c, dtype=jnp.bool_)
                    lim_clash = (
                        prev_same & lim[:, None] & lim[None, :]
                    ).any(axis=0)
                    overflow = overflow | (att & lim_clash)
                defer = defer | overflow
                att = att & ~overflow
            state, dom = _commit_chunk(state, dom, pf, picks, att)
            out_picks = jnp.where(defer, -2, jnp.where(pf["valid"], picks, -1))
            return (state, dom.group_dom, dom.et_dom, start), PassResult(
                picks=out_picks, scores=bests, feasible_counts=feas,
                fail_masks=fails,
                processed=jnp.where(pf["valid"], processed, 0),
            )

        start0 = (
            inv["scan_start"].astype(jnp.uint32) if truncated else jnp.uint32(0)
        )

        def _run_scan(st0):
            carry_, out_ = lax.scan(
                step, (st0, dom0.group_dom, dom0.et_dom, start0), (cbatch, steps)
            )
            return carry_, out_

        uniform = uniform_all if fuse_tail else None
        if uniform is not None:
            # Template-batch all-fail shortcut: when every pod in the
            # batch is featurization-identical (the scheduler ships the
            # flag) and the REPRESENTATIVE is feasible nowhere, every pod
            # fails identically — the scan would commit nothing and each
            # chunk would reproduce the same verdict k/c times.  One
            # evaluation replaces the whole scan (the full-cluster
            # preemption shape: the main pass exists only to prove
            # failure before the chained dry-run does the real work).
            # Sound under the fused-tail gating (node-axis-only ops) —
            # no domain reads, no commits, so pod order cannot matter.
            pf0 = {kk: v[0, 0] for kk, v in cbatch.items()}
            dctx0 = dataclasses.replace(ctx_nom, dom=dom0)
            _p0, _b0, feas0, fail0, _pr0 = eval_pod(
                state, dctx0, pf0, steps[0, 0], start0
            )
            allfail = uniform & (feas0 == 0) & batch["valid"][0]

            def fail_branch(st0):
                carry_ = (st0, dom0.group_dom, dom0.et_dom, start0)
                valid = cbatch["valid"]  # (k//c, c)
                out_ = PassResult(
                    picks=jnp.full(valid.shape, -1, _p0.dtype),
                    scores=jnp.zeros(valid.shape, _b0.dtype),
                    feasible_counts=jnp.zeros(valid.shape, feas0.dtype),
                    fail_masks=jnp.where(valid, fail0, jnp.zeros((), fail0.dtype)),
                    processed=jnp.zeros(valid.shape, _pr0.dtype),
                )
                return carry_, out_

            carry, out = lax.cond(allfail, fail_branch, _run_scan, state)
        else:
            carry, out = _run_scan(state)
        out = jax.tree_util.tree_map(
            lambda x: x.reshape((k,) + x.shape[2:]), out
        )
        if fuse_tail:
            # FUSED strict tail (VERDICT r4 missing-2): chunk-deferred pods
            # (pick == -2) re-run against the committed state INSIDE this
            # program, so their verdicts ride the main fetch instead of a
            # second host→device round trip (the tunnel RTT was a third of
            # the preemption row's wall time).  Sound exactly when the
            # host tail's re-featurization would be an identity: every
            # active op reads only node-axis state (PINNED_SAFE_OPS — no
            # domain tables, no vocab-order-dependent features), so the
            # original feature rows are still correct against the
            # post-commit state.  Residual re-deferrals (chunk-mates
            # colliding again) still drain to the host tail.
            deferred1 = out.picks == -2
            batch2 = dict(batch)
            batch2["valid"] = batch["valid"] & deferred1
            cbatch2 = jax.tree_util.tree_map(
                lambda x: x.reshape((k // c, c) + x.shape[1:]), batch2
            )
            # Pod-identity seeds: the tail re-evaluation IS the pod's real
            # decision (the deferred first-round result is discarded), so
            # it draws the pod's own step seed — exactly the seed the
            # sequential scan would have used.
            steps2 = steps

            def step_tail(carry2, xs):
                pf, _si = xs
                # Chunks with no deferred rows skip the whole evaluation
                # (typically all but one): the deferral clusters in the
                # chunk whose mates collided.
                return lax.cond(
                    pf["valid"].any(),
                    lambda c_: step(c_, xs),
                    lambda c_: (
                        c_,
                        PassResult(
                            picks=jnp.full((c,), -1, out.picks.dtype),
                            scores=jnp.zeros((c,), out.scores.dtype),
                            feasible_counts=jnp.zeros(
                                (c,), out.feasible_counts.dtype
                            ),
                            fail_masks=jnp.zeros((c,), out.fail_masks.dtype),
                            processed=jnp.zeros((c,), out.processed.dtype),
                        ),
                    ),
                    carry2,
                )

            carry, out2 = lax.scan(step_tail, carry, (cbatch2, steps2))
            out2 = jax.tree_util.tree_map(
                lambda x: x.reshape((k,) + x.shape[2:]), out2
            )
            out = PassResult(
                picks=jnp.where(deferred1, out2.picks, out.picks),
                scores=jnp.where(deferred1, out2.scores, out.scores),
                feasible_counts=jnp.where(
                    deferred1, out2.feasible_counts, out.feasible_counts
                ),
                fail_masks=jnp.where(deferred1, out2.fail_masks, out.fail_masks),
                processed=out.processed,
            )
        state = carry[0]
        return state, out, (carry[1], carry[2])

    if carry_dom:
        return jax.jit(_run)

    @jax.jit
    def run(state: ClusterState, batch: dict, inv: dict, seed_base: jax.Array):
        st, out, _dom = _run(state, batch, inv, seed_base)
        return st, out

    return run


def build_eval_pass(
    profile: Profile,
    schema: Schema,
    builder_res_col: dict[str, int],
    active: frozenset[str] | None = None,
):
    """Eval-only single-pod pass: filter + score masks with NO commit.

    The extender scheduling path (extender.py) needs the full per-node
    verdicts on the host — the extender chain filters/prioritizes between
    the in-process pass and selectHost, so the pick cannot be made on
    device.  Returns run(state, pf, inv) → (feasible (N,) bool,
    total (N,) i64)."""
    filter_ops = [
        opcommon.get(n) for n in profile.filters if active is None or n in active
    ]
    score_ops = [
        (opcommon.get(n), w)
        for n, w in profile.scorers
        if active is None or n in active
    ]
    static: dict = {}
    for op in {o.name: o for o in filter_ops + [o for o, _ in score_ops]}.values():
        if op.static is not None:
            static.update(op.static(profile, schema, builder_res_col))
    ctx = opcommon.PassContext(profile=profile, schema=schema, static=static)

    @jax.jit
    def run(state: ClusterState, pf: dict, inv: dict):
        dom = build_dom(state, inv["et_slot"], inv["et_host"], schema.DV)
        dctx = dataclasses.replace(
            ctx,
            dom=dom,
            nom=(
                (inv["nom_req"], inv["nom_cnt"], inv["nom_prio"])
                if "nom_req" in inv
                else None
            ),
        )
        feasible = state.valid
        for op in filter_ops:
            if op.filter is not None:
                feasible &= op.filter(state, pf, dctx)
        total = jnp.zeros(schema.N, jnp.int64)
        for op, weight in score_ops:
            if op.score is not None:
                total += op.score(state, pf, dctx, feasible) * jnp.int64(weight)
        return feasible, total

    return run


def score_op_names(
    profile: Profile, active: frozenset[str] | None
) -> list[tuple[str, int]]:
    """Score-op (name, weight) column order of build_attribution_pass's
    score stack for one compiled pass — the scorer analog of
    filter_op_names."""
    return [
        (n, w)
        for n, w in profile.scorers
        if (active is None or n in active) and opcommon.get(n).score is not None
    ]


def build_attribution_pass(
    profile: Profile,
    schema: Schema,
    builder_res_col: dict[str, int],
    active: frozenset[str] | None = None,
):
    """Attribution variant of build_eval_pass (decision provenance):
    the SAME op calls in the SAME order with the SAME dtypes, but every
    intermediate column is returned instead of folded away.

    Returns run(state, pf, inv) →
      (ok_cols  (F, N) bool — each filter op's independent verdict,
                row order = filter_op_names(profile, active);
       feasible (N,)  bool — the conjunction, as eval computes it;
       score_cols (S, N) i64 — each scorer's NORMALIZED column over the
                final feasible set (pre-weight), row order =
                score_op_names(profile, active);
       total    (N,)  i64 — the weighted sum, bit-identical to the
                commit pass's TotalScore vector).

    Debug/read path only — never dispatched from the hot loop, so the
    extra outputs cost nothing when provenance is unarmed."""
    filter_ops = [
        opcommon.get(n) for n in profile.filters if active is None or n in active
    ]
    score_ops = [
        (opcommon.get(n), w)
        for n, w in profile.scorers
        if active is None or n in active
    ]
    static: dict = {}
    for op in {o.name: o for o in filter_ops + [o for o, _ in score_ops]}.values():
        if op.static is not None:
            static.update(op.static(profile, schema, builder_res_col))
    ctx = opcommon.PassContext(profile=profile, schema=schema, static=static)

    @jax.jit
    def run(state: ClusterState, pf: dict, inv: dict):
        dom = build_dom(state, inv["et_slot"], inv["et_host"], schema.DV)
        dctx = dataclasses.replace(
            ctx,
            dom=dom,
            nom=(
                (inv["nom_req"], inv["nom_cnt"], inv["nom_prio"])
                if "nom_req" in inv
                else None
            ),
        )
        feasible = state.valid
        ok_cols = []
        for op in filter_ops:
            if op.filter is not None:
                ok = op.filter(state, pf, dctx)
                ok_cols.append(ok)
                feasible &= ok
        total = jnp.zeros(schema.N, jnp.int64)
        score_cols = []
        for op, weight in score_ops:
            if op.score is not None:
                col = op.score(state, pf, dctx, feasible)
                score_cols.append(col)
                total += col * jnp.int64(weight)
        ok_stack = (
            jnp.stack(ok_cols)
            if ok_cols
            else jnp.zeros((0, schema.N), jnp.bool_)
        )
        sc_stack = (
            jnp.stack(score_cols)
            if score_cols
            else jnp.zeros((0, schema.N), jnp.int64)
        )
        return ok_stack, feasible, sc_stack, total

    return run


# Ops whose filter/score read ONLY node-axis state (no domain tables, no
# cross-pod conflict classes) — the op subset the pinned fast path handles.
PINNED_SAFE_OPS = frozenset({
    "NodeUnschedulable", "NodeName", "TaintToleration", "NodeAffinity",
    "NodeResourcesFit", "NodeResourcesBalancedAllocation", "ImageLocality",
    # Heterogeneity scorers (ISSUE 14): per-node gathers of topo_vals /
    # alloc / num_pods — node-axis state only, no domain tables, no
    # feasible-set normalization.
    "ThroughputAware", "LearnedScorer",
})


def build_pinned_pass(
    profile: Profile,
    schema: Schema,
    builder_res_col: dict[str, int],
    active: frozenset[str] | None = None,
):
    """Pinned-batch fast path: every pod arrives pre-resolved to ONE
    candidate row (``batch["pin_row"]``) — the TPU analog of PreFilter
    node-set reduction (nodeaffinity.go PreFilter returns the name set for
    metadata.name matchFields; NodeName via spec.nodeName;
    schedule_one.go:504 evaluates only those nodes).  The (K, N) matrix
    scan collapses to one vmapped own-row evaluation: each pod's active
    filters/scorers run against a single-row slice of the state, and
    same-row capacity interaction is a closed-form segmented prefix — no
    sequential scan; placed pods commit in ONE _commit_chunk scatter (a
    per-row host flush of thousands of dirty rows costs more than the
    whole evaluation).

    Decision-identical to the full pass for eligible batches: a pinned
    pod's only feasible node IS its pin (the NodeName/NodeAffinity filters
    guarantee it), so filter verdicts, the pick, and even the normalized
    score (over a single-node feasible set either way) agree.  Same-row
    mates whose cumulative demand overflows defer (pick -2) to the strict
    tail, exactly like the chunked scan's overflow rule.  Eligibility
    (every pod pinned, active ⊆ PINNED_SAFE_OPS, not truncated) is the
    scheduler's job."""
    filter_ops = [
        opcommon.get(n) for n in profile.filters if active is None or n in active
    ]
    score_ops = [
        (opcommon.get(n), w)
        for n, w in profile.scorers
        if active is None or n in active
    ]
    static: dict = {}
    for op in {o.name: o for o in filter_ops + [o for o, _ in score_ops]}.values():
        if op.static is not None:
            static.update(op.static(profile, schema, builder_res_col))
    ctx = opcommon.PassContext(profile=profile, schema=schema, static=static)

    @jax.jit
    def run(state: ClusterState, batch: dict, inv: dict):
        from ..snapshot import _NODE_AXIS

        rows = batch["pin_row"]  # (K,) i32; -1 ⇒ pin names no live node
        k = rows.shape[0]
        safe = jnp.maximum(rows, 0)
        # Per-pod single-row state slices: node-axis gathered to the front,
        # then a kept axis of size 1 so every op sees its usual layout.
        sliced = {}
        for f in dataclasses.fields(ClusterState):
            a = getattr(state, f.name)
            if _NODE_AXIS[f.name] == 0:
                sliced[f.name] = jnp.expand_dims(a[safe], 1)
            else:  # (X, N) fields
                sliced[f.name] = jnp.expand_dims(
                    jnp.moveaxis(a[:, safe], 1, 0), 2
                )
        state_k = ClusterState(**sliced)
        if "nom_req" in inv:
            nom_k = (
                jnp.expand_dims(inv["nom_req"][safe], 1),
                jnp.expand_dims(inv["nom_cnt"][safe], 1),
                jnp.expand_dims(inv["nom_prio"][safe], 1),
            )
        else:
            nom_k = None
        # The fit filter's nominated self-exclusion indexes LOCAL rows.
        batch2 = dict(batch)
        if "nominated_row" in batch2:
            batch2["nominated_row"] = jnp.where(
                batch2["nominated_row"] == rows, 0, -1
            ).astype(jnp.int32)

        def eval_own(st1: ClusterState, pf: dict, nom1):
            dctx = dataclasses.replace(ctx, dom=None, nom=nom1)
            feasible = st1.valid  # (1,)
            fail_mask = jnp.uint32(0)
            bit = 0
            for op in filter_ops:
                if op.filter is not None:
                    ok = op.filter(st1, pf, dctx)
                    newly = feasible & ~ok
                    fail_mask = fail_mask | jnp.where(
                        newly.any(), jnp.uint32(1 << bit), jnp.uint32(0)
                    )
                    bit += 1
                    feasible &= ok
            total = jnp.zeros(1, jnp.int64)
            for op, weight in score_ops:
                if op.score is not None:
                    total += op.score(st1, pf, dctx, feasible) * jnp.int64(weight)
            return feasible[0], total[0], fail_mask

        if nom_k is None:
            feas_k, score_k, fail_k = jax.vmap(
                lambda st1, pf: eval_own(st1, pf, None)
            )(state_k, batch2)
        else:
            feas_k, score_k, fail_k = jax.vmap(eval_own)(state_k, batch2, nom_k)
        feas_k &= (rows >= 0) & batch["valid"]

        # Same-row sequential capacity: segmented inclusive prefixes over
        # feasible mates in lane order (the chunked scan's cumulative-fit
        # rule (b), in closed form).  Later mates whose prefix overflows
        # DEFER to the strict tail rather than fail — an earlier mate's
        # failure could have freed the room.
        order = jnp.argsort(rows, stable=True)
        r_s = rows[order]
        req_s = batch["req"][order]  # (K, R)
        feas_s = feas_k[order]
        idx = jnp.arange(k)
        segnew = jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), r_s[1:] != r_s[:-1]]
        )
        start = lax.cummax(jnp.where(segnew, idx, 0))  # segment-start index
        contrib = jnp.where(feas_s[:, None], req_s, 0)
        csum = jnp.cumsum(contrib, axis=0)
        within = csum - csum[start] + contrib[start]  # inclusive prefix
        cnt = (
            jnp.cumsum(feas_s.astype(jnp.int32))
            - jnp.cumsum(feas_s.astype(jnp.int32))[start]
            + feas_s[start].astype(jnp.int32)
        )
        r_safe = jnp.maximum(r_s, 0)
        free_s = (state.alloc - state.req)[r_safe]
        fit_s = ((req_s == 0) | (within <= free_s)).all(axis=-1) & (
            state.num_pods[r_safe] + cnt <= state.allowed_pods[r_safe]
        )
        place_s = feas_s & fit_s
        picks_s = jnp.where(
            place_s, r_s, jnp.where(feas_s, jnp.int32(-2), jnp.int32(-1))
        )
        picks = jnp.zeros(k, jnp.int32).at[order].set(picks_s)
        picks = jnp.where(batch["valid"], picks, -1)
        att = picks >= 0
        # One whole-batch commit (duplicate rows scatter-accumulate; -2
        # deferrals commit nothing and retry next batch).
        dom0 = build_dom(state, inv["et_slot"], inv["et_host"], schema.DV)
        new_state, _dom = _commit_chunk(state, dom0, batch2, picks, att)
        return new_state, PassResult(
            picks=picks,
            scores=score_k.astype(jnp.int64),
            feasible_counts=feas_k.astype(jnp.int32),
            fail_masks=fail_k,
            processed=jnp.zeros(k, jnp.int32),
        )

    return run


class PassCache:
    """Compiled-pass cache keyed by (profile, schema, resource columns,
    batch-active op set, chunk)."""

    def __init__(self) -> None:
        self._cache: dict = {}

    def __len__(self) -> int:
        """Built program variants held — the scheduler_jax_compiled_programs gauge
        (each entry traced+compiled its own XLA program family)."""
        return len(self._cache)

    def get(
        self,
        profile: Profile,
        schema: Schema,
        res_col: dict[str, int],
        active: frozenset[str] | None = None,
        chunk: int = 1,
        carry_dom: bool = False,
    ):
        key = (
            profile, schema, tuple(sorted(res_col.items())), active, chunk,
            carry_dom,
        )
        fn = self._cache.get(key)
        if fn is None:
            fn = build_pass(profile, schema, res_col, active, chunk, carry_dom)
            self._cache[key] = fn
        return fn

    def get_pinned(
        self,
        profile: Profile,
        schema: Schema,
        res_col: dict[str, int],
        active: frozenset[str] | None = None,
    ):
        key = (profile, schema, tuple(sorted(res_col.items())), active, "pin")
        fn = self._cache.get(key)
        if fn is None:
            fn = build_pinned_pass(profile, schema, res_col, active)
            self._cache[key] = fn
        return fn
