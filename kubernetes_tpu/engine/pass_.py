"""The batched scheduling pass: one device dispatch schedules a whole batch.

This replaces both reference hot loops — the goroutine-parallel Filter over
nodes (schedule_one.go:591 findNodesThatPassFilters) and the 3-pass parallel
Score (runtime/framework.go:1101) — with vectorized ops over the node axis,
and replaces the serialized one-pod-at-a-time outer loop (scheduler.go:470)
with a `lax.scan` over the pod batch.  Each scan step is sequential-equivalent
to one reference scheduling cycle: filter → score → selectHost → assume, with
the assume's row-delta applied to the carried ClusterState so the next pod in
the batch observes it (the reference gets the same effect through its cache
assume protocol, cache.go:361).

Why scan and not vmap: pod placements are not independent — pod i+1 must see
pod i's resources committed.  The scan keeps the dependency chain on device,
which is what makes batch size ≈ free (no host↔device round trip per pod).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.config import Profile
from ..ops import common as opcommon
from ..snapshot import ClusterState, Schema


class PassResult(NamedTuple):
    picks: jax.Array  # (K,) i32 — chosen node row, -1 = unschedulable
    scores: jax.Array  # (K,) i64 — winning node's total score
    feasible_counts: jax.Array  # (K,) i32 — nodes passing all filters


def _hash_u32(x: jax.Array) -> jax.Array:
    """splitmix32-style avalanche; deterministic counter-based tie-break RNG.

    The reference breaks score ties with reservoir sampling over math/rand
    (schedule_one.go:888–899).  For cross-run determinism (and Go↔device
    parity) we instead pick the h(seed, step)-th tie in snapshot row order —
    still uniform over ties, but a pure function of (seed, step)."""
    x = x.astype(jnp.uint32)
    x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
    x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return x


def select_host(feasible: jax.Array, total: jax.Array, tie_rand: jax.Array):
    """argmax with uniform tie-break among max-score feasible nodes.

    Mirrors selectHost (schedule_one.go:873): highest TotalScore wins;
    ties broken uniformly (see _hash_u32 docstring for the parity rule)."""
    neg = jnp.int64(-(2**62))
    masked = jnp.where(feasible, total, neg)
    best = jnp.max(masked)
    ties = feasible & (masked == best)
    m = jnp.sum(ties.astype(jnp.int32))
    kth = (tie_rand % jnp.maximum(m, 1).astype(jnp.uint32)).astype(jnp.int32)
    # Index of the (kth+1)-th True in `ties`.
    order = jnp.cumsum(ties.astype(jnp.int32)) - 1
    pick = jnp.argmax(ties & (order == kth)).astype(jnp.int32)
    pick = jnp.where(m > 0, pick, -1)
    return pick, best, m


def _commit(state: ClusterState, pf: dict, pick: jax.Array, do: jax.Array) -> ClusterState:
    """Apply the chosen pod's row-delta on device (NodeInfo.AddPodInfo,
    framework/types.go:990). All updates are predicated on `do` so padded or
    unschedulable pods commit nothing."""
    row = jnp.where(do, pick, 0)
    zero64 = jnp.int64(0)
    new = dict(
        req=state.req.at[row].add(jnp.where(do, pf["req"], zero64)),
        nonzero_req=state.nonzero_req.at[row].add(jnp.where(do, pf["nonzero"], zero64)),
        num_pods=state.num_pods.at[row].add(do.astype(jnp.int32)),
        group_counts=state.group_counts.at[pf["group"], row].add(do.astype(jnp.int32)),
    )
    if "port_triples" in pf:
        inc = (do & (pf["port_triples"] >= 0)).astype(jnp.int32)
        safe_t = jnp.maximum(pf["port_triples"], 0)
        safe_k = jnp.maximum(pf["port_keys"], 0)
        new["port_counts"] = state.port_counts.at[safe_t, row].add(inc)
        new["portkey_counts"] = state.portkey_counts.at[safe_k, row].add(inc)
    if "ipa_own_terms" in pf:
        inc = (do & (pf["ipa_own_terms"] >= 0)).astype(jnp.int32)
        safe_a = jnp.maximum(pf["ipa_own_terms"], 0)
        new["et_counts"] = state.et_counts.at[safe_a, row].add(inc)
    if "vol_dev_ids" in pf:
        inc = (do & (pf["vol_dev_ids"] >= 0)).astype(jnp.int32)
        safe_d = jnp.maximum(pf["vol_dev_ids"], 0)
        new["dev_counts"] = state.dev_counts.at[safe_d, row].add(inc)
        new["dev_rw_counts"] = state.dev_rw_counts.at[safe_d, row].add(
            inc * pf["vol_dev_rw"].astype(jnp.int32)
        )
    if "vol_drivers" in pf:
        new["csi_used"] = state.csi_used.at[:, row].add(
            jnp.where(do, pf["vol_drivers"], 0)
        )
    return dataclasses.replace(state, **new)


def build_pass(
    profile: Profile,
    schema: Schema,
    builder_res_col: dict[str, int],
    active: frozenset[str] | None = None,
):
    """Compile the batch pass for one (profile, schema, active-op-set).

    Returns run(state, batch, seed_base) → (state, PassResult). Recompiles
    only when the profile, a bucketed schema capacity, or the batch-active
    op set changes — the analog of building a frameworkImpl per profile
    (profile/profile.go:50) with per-cycle Skip sets, plus XLA compilation."""
    filter_ops = [
        opcommon.get(n)
        for n in profile.filters
        if active is None or n in active
    ]
    score_ops = [
        (opcommon.get(n), w)
        for n, w in profile.scorers
        if active is None or n in active
    ]
    static: dict = {}
    for op in {o.name: o for o in filter_ops + [o for o, _ in score_ops]}.values():
        if op.static is not None:
            static.update(op.static(profile, schema, builder_res_col))
    ctx = opcommon.PassContext(profile=profile, schema=schema, static=static)

    def step(state: ClusterState, xs):
        pf, step_idx = xs
        feasible = state.valid
        for op in filter_ops:
            if op.filter is not None:
                feasible &= op.filter(state, pf, ctx)
        total = jnp.zeros(schema.N, jnp.int64)
        for op, weight in score_ops:
            if op.score is not None:
                # Plugin scores are pre-normalized to [0, MaxNodeScore] over
                # the feasible set; the framework applies the weight
                # (runtime/framework.go:1188).
                total += op.score(state, pf, ctx, feasible) * jnp.int64(weight)
        tie_rand = _hash_u32(
            jnp.uint32(profile.tie_break_seed) * jnp.uint32(2654435761) + step_idx.astype(jnp.uint32)
        )
        pick, best, _ties = select_host(feasible, total, tie_rand)
        do = pf["valid"] & (pick >= 0)
        state = _commit(state, pf, pick, do)
        return state, PassResult(
            picks=jnp.where(pf["valid"], pick, -1),
            scores=best,
            feasible_counts=jnp.sum(feasible.astype(jnp.int32)),
        )

    @jax.jit
    def run(state: ClusterState, batch: dict, seed_base: jax.Array):
        k = batch["valid"].shape[0]
        steps = seed_base.astype(jnp.uint32) + jnp.arange(k, dtype=jnp.uint32)
        state, out = lax.scan(step, state, (batch, steps))
        return state, out

    return run


class PassCache:
    """Compiled-pass cache keyed by (profile, schema, resource columns,
    batch-active op set)."""

    def __init__(self) -> None:
        self._cache: dict = {}

    def get(
        self,
        profile: Profile,
        schema: Schema,
        res_col: dict[str, int],
        active: frozenset[str] | None = None,
    ):
        key = (profile, schema, tuple(sorted(res_col.items())), active)
        fn = self._cache.get(key)
        if fn is None:
            fn = build_pass(profile, schema, res_col, active)
            self._cache[key] = fn
        return fn
