"""Software-pipelined batch commit: staged binds, group-commit drain,
and the predispatch double buffer (ISSUE 15).

The serial batch loop interleaves three kinds of work that have no data
dependence on each other once the device pass has been dispatched:

- **featurize(k+1)** — host CPU building the next batch's feature rows
  (already overlapped by the scheduler's prefetch since PR 6);
- **device(k)** — the compiled pass, running asynchronously on the
  accelerator from dispatch until the completion fetch;
- **commit/journal(k-1)** — host bookkeeping plus the write-ahead
  journal's durability barrier (the fsync bill BENCH_r06 measured at
  37.8s of a 76.2s wall).

This module supplies the two pieces that turn the loop into a real
pipeline (the generalization of PR 6's ``post_dispatch_hook``
amortization into a stage engine):

- :class:`CommitTicket` / :func:`drain_commit` — the commit stage is
  SPLIT.  ``_complete_batch`` stages every bind (reserve plugins run,
  cache assumed, outcome built) into a ticket; ``drain_commit`` then
  journals the whole ticket inside ONE ``journal.group()`` barrier and
  applies the binds only after the group's single fsync has returned —
  journal-before-apply preserved strictly, at group scope (tpulint's
  WAL family checks this file).  At pipeline depth 1 the drain runs at
  exactly the point the serial loop applied binds inline; at depth >= 2
  the scheduler dispatches batch k+1 FIRST, so the fsync and the apply
  loop execute under the in-flight device pass.

- :class:`Predispatch` / :func:`predispatch_valid` — the double buffer
  for the dispatch stage: batch k+1 (already featurized by the
  prefetch) is dispatched at the END of batch k's cycle, before the
  drain, so the device is never idle while the host commits.  The
  predispatched pass ran against the host state visible at dispatch
  time; ``predispatch_valid`` re-checks every token that state could
  have changed under (feature version, mutation epoch, schema, dirty
  rows, live nominations) when the next cycle picks the pass up — a
  mismatch discards the pass, rolls the tie-break cycle counter back,
  and re-dispatches exactly as the serial loop would have, so bindings
  stay bit-identical to pipeline depth 1 (the parity oracle).

Determinism: this module decides nothing — staging order is the
serial loop's entry order, the drain applies in that order, and every
validity token is a pure function of scheduler state (the determinism
lint family covers this file like the rest of ``engine/``).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ..framework.events import NORMAL
from ..journal import _crash


@dataclass
class StagedBind:
    """One bind that passed Permit + Reserve and awaits its group's
    durability barrier.  ``outcome`` is the ScheduleOutcome already in
    the batch's outcome list (node set optimistically at stage time; a
    same-batch race rollback clears it and unstages the bind)."""

    qp: object  # QueuedPodInfo
    node_name: str
    outcome: object  # ScheduleOutcome
    # Once-only accounting ran (gang quorum credit, counters, events):
    # a resumed drain may replay a partially applied bind's idempotent
    # state steps, but must never credit it twice.
    counted: bool = False


@dataclass
class CommitTicket:
    """The staged commit group of one batch: binds whose journal records
    and applies drain together under one group fsync."""

    staged: list = field(default_factory=list)
    # Batch commit clock (time.monotonic at phase 1) — latency samples
    # and first/last-scheduled stamps use it so a deferred drain reports
    # the same numbers the inline apply would have.
    now: float = 0.0
    drained: bool = False
    # Drain progress: staged[:journaled] have records WRITTEN to the
    # log, barriered means the group's fsync RETURNED (written is not
    # durable), staged[:applied] are live.  A drain interrupted by an
    # exception (deposed-writer fence, fsync OSError) leaves drained
    # False with these markers on the completed prefix, so the recovery
    # drain resumes exactly what remains — never re-journaling, never
    # silently abandoning the group, and never applying ahead of a
    # barrier that has not actually returned.
    journaled: int = 0
    barriered: bool = False
    applied: int = 0
    # Weighted-fair admission debits of THIS batch's pops (framework/
    # fairness intent records, pop order).  Captured at ticket creation
    # so a depth-2 prefetch pop for batch k+1 can never smuggle its
    # debits into batch k's group.  Journaled as one "admission" record
    # FIRST inside the group (a bind is only durable together with the
    # debit that admitted it), applied to the durable ledger after the
    # barrier; the two flags make an interrupted drain resume without
    # re-journaling or double-debiting.
    admission: list | None = None
    admission_journaled: bool = False
    admission_applied: bool = False
    # Membership index (never iterated): rollback paths and the
    # scheduler's metrics loop ask "is this uid staged?".
    _uids: set = field(default_factory=set)

    def stage(self, qp, node_name: str, outcome) -> None:
        self.staged.append(StagedBind(qp, node_name, outcome))
        self._uids.add(qp.pod.uid)

    def unstage(self, uid: str) -> None:
        """Remove a bind a same-batch race rolled back (its record was
        never journaled; nothing to undo on the log)."""
        self._uids.discard(uid)
        self.staged = [sb for sb in self.staged if sb.qp.pod.uid != uid]

    def holds(self, uid: str) -> bool:
        return uid in self._uids

    def __len__(self) -> int:
        return len(self.staged)


def drain_commit(sched, ticket: CommitTicket) -> float:
    """Journal + apply one staged commit group.  Returns the drain's
    host seconds (the flight recorder's ``drain`` stage segment).

    Ordering contract (the WAL family's apply sites live here):

    1. every staged bind's record is appended inside ONE
       ``journal.group()`` — written and flushed, fsync deferred;
    2. the group barrier returns — all records durable in one fsync;
    3. only then does any bind apply (spec mutation, finish_binding,
       queue bookkeeping, events/metrics), in stage order.

    A crash before or inside the barrier applied nothing; recovery
    replays the durable prefix and reschedules the rest — the
    pipeline cells of scripts/run_fault_matrix.py probe exactly these
    windows (stage-boundary / mid-group-fsync / post-group-fsync /
    torn-group-tail).

    An in-process EXCEPTION mid-drain (epoch fence, fsync error) leaves
    ``drained`` False with the ticket's journaled/applied counters
    marking the completed prefix: the group's `__exit__` has already
    made that prefix durable, and a retry (the recovery path's
    ``_drain_pending``) resumes from the counters — never re-journaling
    a record, never reporting an unapplied bind as committed.
    """
    if ticket.drained:
        return 0.0
    if not ticket.staged and not ticket.admission:
        ticket.drained = True
        return 0.0
    t0 = time.perf_counter()
    # The commit stage is fully staged, nothing journaled yet — the
    # stage-boundary crash window (at depth >= 2 a device pass for the
    # NEXT batch is typically in flight right now).
    _crash("stage-boundary")
    journal = sched.journal
    if journal is not None and not ticket.barriered:
        need_admission = bool(ticket.admission) and not ticket.admission_journaled
        if ticket.journaled < len(ticket.staged) or need_admission:
            with journal.group():
                if need_admission:
                    # The batch's fairness debits ride the SAME barrier
                    # as its binds, ahead of them: a crash either loses
                    # the whole group (restored pods re-pop through the
                    # identical ledger) or recovers debits + binds
                    # together — admission order replays bit-identical.
                    sched._journal_append(
                        "admission", debits=ticket.admission
                    )
                    ticket.admission_journaled = True
                for sb in ticket.staged[ticket.journaled :]:
                    sched._journal_bind(sb.qp.pod, sb.node_name)
                    ticket.journaled += 1
        else:
            # Every record is already written; only the group's fsync
            # raised on the last attempt.  Re-entering group() would see
            # zero pending appends and skip the fsync — re-run the
            # barrier explicitly instead.
            journal.barrier()
        ticket.barriered = True
    # Group fsync returned: every record in the group is durable.
    if ticket.admission and not ticket.admission_applied:
        # Debits are durable (journaled above, inside the barrier) —
        # advance the DURABLE fairness ledger to match the effective
        # ledger's pop-time debits.  Flag-guarded so an in-process
        # resume of an interrupted drain never double-debits.
        sched.queue.admission.apply_admission(ticket.admission)
        ticket.admission_applied = True
    # Apply in stage order — identical to the serial loop's inline
    # order, just batched behind the single barrier.
    m = sched.metrics
    now = ticket.now
    for sb in ticket.staged[ticket.applied :]:
        qp, node_name = sb.qp, sb.node_name
        # State steps — each idempotent, so a resume may replay a
        # partially applied bind from the top.
        qp.pod.spec.node_name = node_name
        sched.cache.finish_binding(qp.pod.uid)
        # Self-placed pods get their NoExecute judgment at bind (the
        # reference's handlePodUpdate fires on the binding update).
        sched.taint_eviction.handle_pod_assigned(qp.pod, node_name)
        sched.queue.done(qp.pod.uid)
        if not sb.counted:
            sb.counted = True
            # Gang quorum credit first (state-critical), observational
            # accounting after — a fault below loses at most one bind's
            # metrics, never credit, and a resume never double-counts.
            if qp.pod.spec.pod_group:
                sched.gang_bound[qp.pod.spec.pod_group] = (
                    sched.gang_bound.get(qp.pod.spec.pod_group, 0) + 1
                )
            if m.scheduled == 0:
                m.first_scheduled_ts = now
            m.scheduled += 1
            m.last_scheduled_ts = now
            sched._note_bound(qp.pod, node_name)
            sched.recorder.event(
                qp.pod.uid, NORMAL, "Scheduled",
                f"Successfully assigned {qp.pod.uid} to {node_name}",
            )
            lat = now - qp.initial_attempt_timestamp
            m.e2e_latency_samples.append(lat)
            m.registry.scheduling_sli.observe(lat)
        ticket.applied += 1
    ticket.drained = True
    # Stage flight fields: deterministic drain counts on the current
    # batch's flight record — the trace exporter sizes/labels the drain
    # slice from these, never from wall seconds (which differ run to
    # run).  A recovery drain outside a batch has no accumulator; the
    # guard inside _flight_add keeps this a no-op there.
    sched._flight_add("drained", ticket.applied)
    if journal is not None:
        sched._flight_add("group_fsyncs", 1)
    return time.perf_counter() - t0


@dataclass
class Predispatch:
    """A device pass dispatched one cycle early (the double buffer).

    ``infos`` is the batch in its ORIGINAL pop order (the packer may
    have permuted ``ctx['infos']``; an invalidated predispatch must
    re-dispatch from the unpermuted order or the re-pack would see
    pre-permuted input and diverge from the serial loop)."""

    infos: list
    ctx: dict
    profile: object
    # Validity tokens, captured at dispatch:
    version: tuple  # builder.feature_version()
    mutation_epoch: int
    schema: object
    nominator_token: tuple
    cycle0: int  # _cycle before the dispatch (rollback target)
    t_dispatch: float = 0.0


def nominator_token(sched) -> tuple:
    """Stable fingerprint of the live nominations a dispatch read
    (_full_inv's nom_* arrays and _inject_nomrows both depend on them):
    any change between predispatch and pickup must invalidate."""
    return tuple(
        sorted(
            (uid, node, prio)
            for uid, (node, _delta, prio) in sched.nominator.items()
        )
    )


def predispatch_valid(sched, pd: Predispatch) -> bool:
    """True when nothing the predispatched pass read has changed since
    dispatch — the pass's decisions are exactly what a fresh dispatch
    would compute, so the pipeline may complete it as-is."""
    b = sched.builder
    return (
        pd.version == b.feature_version()
        and pd.mutation_epoch == b.mutation_epoch
        and pd.schema == b.schema
        and not b._dirty_all
        and not b._dirty_rows
        and pd.nominator_token == nominator_token(sched)
    )
