"""Conflict-aware chunk packing: reorder a featurized pod batch so that
same-interaction-class pods land in DIFFERENT chunk slices of the scan.

The chunked pass (pass_.py) defers a pod whose decision could depend on an
earlier chunk-mate's commit (``_conflict_pairs``) to a strict chunk=1 tail —
sequential-correct, but a batch whose interaction classes are DENSE (the
affinity-heavy BASELINE #3 shape: every chunk holds several pods of the same
label group) turns the tail into the dominant cost, and the old mitigation
(halve the chunk size until a host-side duplicate count looked tame) shrank
device parallelism exactly when those workloads needed it most.

This module replaces that heuristic with an exact plan built from the same
signals the device pass derives conflicts from:

1. **Conflict classes** (`conflict_classes`): pods are connected-component
   grouped over the hard write→read relations the device defers on — pod
   label-group writes vs hard group reads (required (anti-)affinity /
   DoNotSchedule spread selector masks), own-affinity-term writes vs
   existing-term hard anti reads, shared host-port keys, volume/DRA identity
   overlaps and the any-vs-any unbound-claim / unallocated-claim /
   limited-CSI classes.  The closure is conservative: merging two pods that
   would not actually conflict only costs parallelism, never correctness.
   A group read by pods but WRITTEN by nobody in the batch creates no edge
   (the readers race nothing — bound-pod state is already in the snapshot),
   and vice versa.

2. **Width choice** (`plan_packing`): the largest chunk width (from the
   configured width's halving ladder) whose chunk count can host every
   class without same-chunk collisions (small residuals tolerated — they
   drain in one strict-tail invocation).  A batch whose biggest class
   exceeds every width's capacity degrades to the sequential chunk=1 pass,
   exactly like the old dense fallback — but only when truly dense, not
   whenever a duplicate count crossed a threshold.

3. **Placement** (`pack_batch`): classes are dealt column-major over the
   (chunks × width) grid, largest class first, then each class's cells are
   re-sorted into scan order so that same-class pods evaluate in their
   ORIGINAL relative order — the invariant that keeps the packed scan
   sequential-equivalent: an interacting reader always evaluates after its
   writer's commit, with the tie-break seed riding the pod (the scheduler
   ships per-pod ``step_offset``), so bindings stay bit-identical to the
   chunk_size=1 parity oracle.  Pods in different classes do not interact
   through hard state; reordering them exposes only the score drift the
   chunked mode already documents (pass_.py module docstring).

Everything here is host-side NumPy on already-featurized arrays — the
packer replaced a Python double loop that re-walked every pod per halving
iteration on the dispatch hot path.  Determinism: pure function of the
batch arrays; ties break on original position (tpulint's determinism family
covers this module).
"""

from __future__ import annotations

import dataclasses

import numpy as np

# Residual same-chunk collisions tolerated per batch before stepping the
# width down, as a cap: a residue this size drains in a single strict-tail
# invocation (scheduler.tail_size), cheaper than doubling the scan length
# for one outlier class.  The effective tolerance scales down with the
# batch (npods // 16) so small batches don't accept whole-batch residues.
COLLISION_TOLERANCE = 64


@dataclasses.dataclass(frozen=True)
class PackPlan:
    """One batch's packing decision.

    ``perm`` maps packed row → original batch position (None = identity
    order); ``width`` is the chosen chunk width (≤ the configured chunk);
    ``collisions`` counts pods sharing a chunk with an earlier same-class
    pod under this plan — each is an expected strict-tail deferral."""

    perm: np.ndarray | None
    width: int
    n_classes: int
    max_class: int
    collisions: int
    class_sizes: np.ndarray  # descending


def _hard_group_reads(batch: dict, npods: int) -> np.ndarray | None:
    """(P, G) bool — groups each pod's HARD filters read (the exact masks
    pass_.py ``_conflict_pairs`` unions); None when no group-reading op is
    active in this batch."""
    reads = None
    if "ipa_ra_allmask" in batch:
        reads = np.asarray(batch["ipa_ra_allmask"][:npods], np.bool_).copy()
        reads |= np.asarray(batch["ipa_rs_groups"][:npods]).any(axis=1)
    if "tps_h_groups" in batch:
        h = np.asarray(batch["tps_h_groups"][:npods]).any(axis=1)
        reads = h.copy() if reads is None else (reads | h)
    return reads


def conflict_classes(batch: dict, npods: int) -> np.ndarray:
    """(P,) int32 dense class ids: connected components of the batch's
    possible-conflict graph (see module docstring).  Pure NumPy — edges are
    (pod, shared-key) pairs; components resolve by min-label propagation
    (deterministic: labels are original positions)."""
    pod_edges: list[np.ndarray] = []
    key_edges: list[np.ndarray] = []
    next_key = 0

    def add_edges(pods: np.ndarray, keys: np.ndarray, space: int) -> None:
        nonlocal next_key
        if pods.size:
            pod_edges.append(pods.astype(np.int64))
            key_edges.append(keys.astype(np.int64) + next_key)
        next_key += space

    # -- label-group write→read crossings -----------------------------------
    groups = np.asarray(batch["group"][:npods], np.int64)
    reads_g = _hard_group_reads(batch, npods)
    if reads_g is not None and reads_g.any():
        g_cap = reads_g.shape[1]
        write_any = np.zeros(g_cap, np.bool_)
        write_any[np.clip(groups, 0, g_cap - 1)] = True
        read_any = reads_g.any(axis=0)
        active_g = write_any & read_any
        if active_g.any():
            # Writers touch their own group's key; readers touch every
            # active group their masks select.
            own_active = active_g[np.clip(groups, 0, g_cap - 1)]
            add_pods = np.nonzero(own_active)[0]
            pod_edges.append(add_pods.astype(np.int64))
            key_edges.append(groups[add_pods] + next_key)
            rp, rg = np.nonzero(reads_g & active_g[None, :])
            pod_edges.append(rp.astype(np.int64))
            key_edges.append(rg.astype(np.int64) + next_key)
        next_key += reads_g.shape[1]

    # -- existing-term write→hard-read crossings ----------------------------
    if "ipa_et_match" in batch:
        own = np.asarray(batch["ipa_own_terms"][:npods], np.int64)  # (P, A)
        hard_reads_t = np.asarray(batch["ipa_et_match"][:npods], np.bool_) & np.asarray(
            batch["ipa_et_anti"][:npods], np.bool_
        )  # (P, ET)
        et_cap = hard_reads_t.shape[1]
        write_any_t = np.zeros(et_cap, np.bool_)
        valid_own = own >= 0
        if valid_own.any():
            write_any_t[np.clip(own[valid_own], 0, et_cap - 1)] = True
        read_any_t = hard_reads_t.any(axis=0)
        active_t = write_any_t & read_any_t
        if active_t.any():
            wp, ws = np.nonzero(valid_own & active_t[np.clip(own, 0, et_cap - 1)])
            add_edges(wp, own[wp, ws], 0)
            rp, rt = np.nonzero(hard_reads_t & active_t[None, :])
            add_edges(rp, rt, 0)
        next_key += et_cap

    # -- symmetric identity overlaps (ports, volumes, DRA claims) -----------
    for key in ("port_keys", "vol_dev_ids", "vol_csi_ids", "dra_claim_ids"):
        if key not in batch:
            continue
        ids = np.asarray(batch[key][:npods], np.int64)  # (P, S)
        vp, vs = np.nonzero(ids >= 0)
        space = int(ids.max(initial=-1)) + 1
        add_edges(vp, ids[vp, vs], max(space, 0))

    # -- any-vs-any classes (racing pools, per-node shared budgets) ---------
    for key, reduce_axis in (
        ("vol_unbound", False),
        ("vol_csi_lim", False),
        ("dra_claim_unalloc", True),
    ):
        if key not in batch:
            continue
        flags = np.asarray(batch[key][:npods], np.bool_)
        if reduce_axis and flags.ndim > 1:
            flags = flags.any(axis=1)
        add_edges(np.nonzero(flags)[0], np.zeros(int(flags.sum()), np.int64), 1)

    if not pod_edges:
        return np.arange(npods, dtype=np.int32)
    e_pod = np.concatenate(pod_edges)
    e_key = np.concatenate(key_edges)

    # Min-label propagation over the bipartite pod↔key graph: converges in
    # O(component diameter) rounds — a handful for the star-shaped unions
    # real workloads produce, but a CHAIN (pod i sharing a key with pod
    # i+1 only) needs diameter rounds, so the bound must be npods: a
    # truncated propagation would split one component into several
    # classes and let the packer reorder directly-conflicting pods
    # across chunks (code-review finding, reproduced with a 200-pod
    # port-key chain under the old 64-round cap).
    labels = np.arange(npods, dtype=np.int64)
    for _ in range(npods + 1):
        key_lab = np.full(next_key, npods, np.int64)
        np.minimum.at(key_lab, e_key, labels[e_pod])
        new = labels.copy()
        np.minimum.at(new, e_pod, key_lab[e_key])
        if np.array_equal(new, labels):
            break
        labels = new
    _, dense = np.unique(labels, return_inverse=True)
    return dense.astype(np.int32)


def _width_ladder(chunk: int) -> list[int]:
    out = []
    w = chunk
    while w >= 1:
        out.append(w)
        w //= 2
    return out


def plan_packing(
    classes: np.ndarray,
    npods: int,
    chunk: int,
    tolerance: int | None = None,
) -> tuple[int, np.ndarray]:
    """(width, class_sizes): the largest width from the halving ladder whose
    chunk count hosts every class with ≤ ``tolerance`` forced collisions.
    Width 1 (the sequential pass) always qualifies."""
    if tolerance is None:
        tolerance = min(COLLISION_TOLERANCE, npods // 16)
    sizes = np.bincount(classes, minlength=1)
    for w in _width_ladder(chunk):
        if w == 1:
            return 1, sizes
        m = -(-npods // w)  # chunk count at this width
        if npods % w:
            m = max(m - 1, 1)  # the partial last chunk shortens the cycle
        coll = int(np.maximum(sizes - m, 0).sum())
        if coll <= tolerance:
            return w, sizes
    return 1, sizes


def pack_batch(batch: dict, npods: int, chunk: int) -> PackPlan:
    """Compute the batch's packing plan: conflict classes → width → the
    order-preserving round-robin permutation (see module docstring)."""
    classes = conflict_classes(batch, npods)
    width, sizes = plan_packing(classes, npods, chunk)
    n_classes = int(sizes.shape[0])
    max_class = int(sizes.max(initial=0))
    sizes_desc = np.sort(sizes)[::-1].copy()
    if width <= 1 or max_class <= 1:
        # Sequential fallback (no packing can help) or no interactions at
        # all (identity order is already collision-free at full width).
        return PackPlan(
            perm=None,
            width=width if max_class > 1 else chunk,
            n_classes=n_classes,
            max_class=max_class,
            collisions=0,
            class_sizes=sizes_desc,
        )

    # Class blocks: largest first (ties → earliest first appearance, which
    # np.lexsort's stable original-position key provides), members inside a
    # block keep original order.
    first_pos = np.full(n_classes, npods, np.int64)
    np.minimum.at(first_pos, classes, np.arange(npods))
    block_rank = np.lexsort((first_pos, -sizes))  # class id → dealt order
    block_of_class = np.empty(n_classes, np.int64)
    block_of_class[block_rank] = np.arange(n_classes)
    blk = block_of_class[classes]  # (P,)
    seq = np.lexsort((np.arange(npods), blk))  # block-major, original-minor

    # Column-major cells over the (M × width) grid; the last chunk may be
    # partial (real pods stay contiguous in the batch rows), so columns
    # past its fill skip it.
    m = -(-npods // width)
    last = npods - (m - 1) * width  # rows in the last chunk (1..width)
    s = np.arange(npods, dtype=np.int64)
    in_full = s < last * m
    c_full = s % max(m, 1)
    l_full = s // max(m, 1)
    s2 = s - last * m
    m1 = max(m - 1, 1)
    c_part = s2 % m1
    l_part = last + s2 // m1
    chunk_of = np.where(in_full, c_full, c_part)
    slice_of = np.where(in_full, l_full, l_part)
    rows = chunk_of * width + slice_of  # scan position == batch row

    # Re-sort each block's cells into scan order so same-class pods keep
    # their original relative order in the scan.
    cell_order = np.lexsort((rows, blk[seq]))
    perm = np.empty(npods, np.int64)
    perm[rows[cell_order]] = seq

    # Exact residual collisions under this layout (reported + counted into
    # scheduler_chunk metrics; each is an expected strict-tail deferral).
    cls_at_row = classes[perm]
    chunk_idx = np.arange(npods) // width
    uniq = np.unique(np.stack([chunk_idx, cls_at_row.astype(np.int64)]), axis=1)
    collisions = int(npods - uniq.shape[1])

    if np.array_equal(perm, np.arange(npods)):
        return PackPlan(
            perm=None,
            width=width,
            n_classes=n_classes,
            max_class=max_class,
            collisions=collisions,
            class_sizes=sizes_desc,
        )
    return PackPlan(
        perm=perm,
        width=width,
        n_classes=n_classes,
        max_class=max_class,
        collisions=collisions,
        class_sizes=sizes_desc,
    )


def residual_collisions(classes: np.ndarray, npods: int, width: int) -> int:
    """Forced same-chunk collisions at ``width`` under an optimal deal —
    the per-width pack-quality number scripts/profile_ipa_pieces.py
    reports (``Σ max(0, class_size − chunk_count)``)."""
    if width <= 1:
        return 0
    sizes = np.bincount(classes, minlength=1)
    m = -(-npods // width)
    if npods % width:
        m = max(m - 1, 1)
    return int(np.maximum(sizes - m, 0).sum())
