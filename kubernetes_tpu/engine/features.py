"""Pod-batch featurization: list[Pod] → padded device feature tensors.

The host-side analog of the reference's PreFilter extension point
(runtime/framework.go:698): everything about a pod that the device pass needs
is computed once per pod here (resource vectors, interned ids, compiled
selector programs) and shipped as one (K, …) batch.  Padding rows carry
valid=False and are ignored by the engine's commit."""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from ..api import types as t
from ..framework.config import Profile
from ..ops import common as opcommon
from ..snapshot import POD_PORT_SLOTS, SnapshotBuilder, _bucket
from ..utils import const_array

opcommon.feature_fill("ipa_own_terms", -1)
opcommon.feature_fill("vol_dev_ids", -1)
opcommon.feature_fill("vol_dev_rw", 0)
opcommon.feature_fill("vol_csi_ids", -1)
opcommon.feature_fill("vol_csi_drv", -1)
opcommon.feature_fill("vol_unbound", 0)
opcommon.feature_fill("vol_csi_lim", 0)
opcommon.feature_fill("dra_claim_ids", -1)
opcommon.feature_fill("dra_claim_cls", -1)
opcommon.feature_fill("dra_claim_cnt", 0)
opcommon.feature_fill("dra_claim_first", False)
opcommon.feature_fill("dra_claim_unalloc", 0)
# Injected by the scheduler AFTER featurization (nomination lives in pod
# STATUS; the featurize cache keys on spec only).
opcommon.feature_fill("nominated_row", -1)

# The empty-case singletons (hoisted: building even a cache key per pod
# costs more than it saves at millions of pods).
_PORTS_EMPTY = const_array(POD_PORT_SLOTS, -1, np.int32)
_I32_NEG1 = const_array(1, -1, np.int32)
_I32_ZERO = const_array(1, 0, np.int32)
_BOOL_FALSE = const_array(1, 0, np.bool_)


def pin_name(pod: t.Pod):
    """The single node a pod's own constraints reduce its candidate set to,
    or None: a required node affinity of exactly one term with one
    metadata.name In [one value] matchFields (nodeaffinity.go PreFilter's
    PreFilterResult.NodeNames)."""
    aff = pod.spec.affinity
    na = aff.node_affinity if aff else None
    if na is not None and na.required is not None and len(na.required.terms) == 1:
        term = na.required.terms[0]
        if not term.match_expressions and len(term.match_fields) == 1:
            mf = term.match_fields[0]
            if (
                mf.key == "metadata.name"
                and mf.operator == t.OP_IN
                and len(mf.values) == 1
            ):
                return mf.values[0]
    return None


def pod_sig(pod: t.Pod):
    """The featurization cache key for an in-process pod.  Workload pods
    are stamped from templates, so (namespace, labels, spec) collapses
    thousands of pods onto a handful of signatures (names/uids excluded:
    featurization never reads them).  Built through the ONE shared key
    constructor (serialize.featsig_from_data — the same function that
    stamps wire pods), so wire-fed and in-process copies of one template
    share cache entries by string equality."""
    from ..api import serialize

    return serialize.featsig_from_data(
        pod.namespace,
        pod.metadata.labels,
        serialize._codegen().dumper(t.PodSpec)(pod.spec),
    )


_PODSPEC_FIELDS: tuple[str, ...] = ()


def _spec_eq_mod_pin(a: t.PodSpec, b: t.PodSpec) -> bool:
    """Structural equality of two PIN-SHAPED pod specs modulo the pinned
    node name (both already passed pin_name, so the affinity shape is
    exactly one required term with one single-value matchField).  Direct
    field comparison — no tree hashing: for the daemonset template this is
    ~20 mostly-None comparisons, an order of magnitude cheaper than a
    canonical signature walk."""
    global _PODSPEC_FIELDS
    if not _PODSPEC_FIELDS:
        # node_name excluded: pods are always UNASSIGNED when featurized,
        # but a stored template's spec mutates at bind (the in-place
        # spec.node_name write) — comparing it would kill every later hit.
        _PODSPEC_FIELDS = tuple(
            f.name
            for f in dataclasses.fields(t.PodSpec)
            if f.name not in ("affinity", "node_name")
        )
    for name in _PODSPEC_FIELDS:
        if getattr(a, name) != getattr(b, name):
            return False
    aa, bb = a.affinity, b.affinity
    if (
        aa.pod_affinity != bb.pod_affinity
        or aa.pod_anti_affinity != bb.pod_anti_affinity
    ):
        return False
    na, nb = aa.node_affinity, bb.node_affinity
    if na.preferred != nb.preferred:
        return False
    ta, tb = na.required.terms[0], nb.required.terms[0]
    if ta.match_expressions != tb.match_expressions:
        return False
    ma, mb = ta.match_fields[0], tb.match_fields[0]
    return ma.key == mb.key and ma.operator == mb.operator


def build_pod_batch(
    pods: list[t.Pod],
    builder: SnapshotBuilder,
    profile: Profile,
    k: int,
    force_active: frozenset[str] | None = None,
    sample_into: dict | None = None,
) -> tuple[dict, list[dict], frozenset[str]]:
    """Featurize up to ``k`` pods into a dict of (k, …) numpy arrays, plus the
    per-pod commit deltas (reused by the cache's assume step so pods are
    featurized exactly once) and the batch's ACTIVE op set — ops whose
    ``is_active`` predicate is False for every pod are skipped here and
    compiled out of the batch's pass (the batch analog of PreFilter Skip).

    Featurization may grow vocabularies/schema (new scalar resources, label
    pairs, topology keys), which is why it must run before the device state is
    flushed for the pass."""
    assert len(pods) <= k
    fctx = opcommon.FeaturizeContext(builder=builder, profile=profile)
    all_ops = [opcommon.get(name) for name in dict.fromkeys(
        list(profile.filters) + [s for s, _ in profile.scorers]
    )]
    # Cache keys first (memoized on the pod object — hashing the spec tree
    # is ~half of featurize cost; a pod's spec/labels only change by
    # arriving as a NEW object on the informer path; bind's in-place
    # spec.node_name write happens after the pod's last featurization).
    # NAME-PINNED pods (the daemonset shape — thousands of pods differing
    # only in the matchFields node name) skip signatures entirely: they
    # match against pin TEMPLATES by direct field comparison, and a hit
    # stamps only the interned pin id (see the template block below).
    # Pinned pods whose NodeAffinity featurize would take the general path
    # (addedAffinity / preferred terms embed the name id in program
    # tensors a patch can't reach) are featurized per pod, uncached.
    templatable = profile.added_affinity is None
    keys: list = []
    pins: list = []
    for pod in pods:
        # The memo is profile-independent (the cache's version token, not
        # the key, carries the profile); wire-built pods arrive with it
        # pre-stamped from the raw JSON (serialize.pod_from_data).
        memo = getattr(pod, "_featsig", None)
        if memo is not None:
            keys.append(memo)
            pins.append(None)
            continue
        pin = pin_name(pod)
        if pin is not None:
            keys.append(None)
            pins.append(
                pin
                if templatable and not pod.spec.affinity.node_affinity.preferred
                else None
            )
            continue
        key = pod_sig(pod)
        pod._featsig = key
        keys.append(key)
        pins.append(None)
    if force_active is not None:
        # Rebuild for the strict tail: the pass is already compiled for this
        # op set; features must match it exactly.
        ops = [op for op in all_ops if op.name in force_active]
    else:
        # is_active reads only (labels, spec) and builder catalogs, so one
        # REPRESENTATIVE per distinct key/template suffices — template
        # workloads collapse 4096 predicate scans to a handful (the
        # O(ops × pods) inactive-op scan was a measured featurize cost).
        seen: dict = {}
        pin_reps: list = []
        pin_buckets: dict = {}  # (ns, labels-items) → candidate reps
        for pod, key, pin in zip(pods, keys, pins):
            if key is not None:
                seen.setdefault(key, pod)
            elif pin is not None:
                bkey = (pod.namespace, tuple(sorted(pod.metadata.labels.items())))
                bucket = pin_buckets.setdefault(bkey, [])
                # Spec-distinct pods within a bucket are rare; past the cap
                # just take every pod as a rep (the pre-optimization
                # behavior — only extra is_active calls, never wrong).
                if len(bucket) > 16 or not any(
                    _spec_eq_mod_pin(pod.spec, rep.spec) for rep in bucket
                ):
                    bucket.append(pod)
                    pin_reps.append(pod)
            else:
                pin_reps.append(pod)  # unique-featurized pinned pod
        reps = list(seen.values()) + pin_reps
        ops = [
            op
            for op in all_ops
            if op.is_active is None or any(op.is_active(p, fctx) for p in reps)
        ]
    active = frozenset(op.name for op in ops)
    fctx.active = active
    per_pod: list[dict] = []
    deltas: list[dict] = []
    # Featurization cache: identical (namespace, labels, spec) pods produce
    # identical features/deltas as long as nothing featurization reads has
    # changed (vocabularies, schema, volumes, namespace labels — the version
    # token).  An entry whose own featurization grew a vocabulary is NOT
    # cached: a pod featurized before term/group T was interned legitimately
    # lacks T's feature bits only because every pod of T's group schedules
    # after it — reusing those features for a later pod would break that
    # ordering invariant.
    version = (builder.feature_version(), profile, active)
    if builder.feat_cache is None or builder.feat_cache[0] != version:
        builder.feat_cache = (version, {}, [])
    store = builder.feat_cache[1]
    # Uniform-batch stack cache: a template workload's whole batch is ONE
    # signature, so the stacked (k, …) tensors are a pure function of
    # (signature, count, k) under the version token — tile once, reuse
    # across batches (the per-pod stack/pad loop was the residual
    # featurize cost after the row cache).  The returned dict is shallow-
    # copied per use: consumers assign fresh keys (nominated_row,
    # uniform_all, pin_row) but never mutate the arrays.
    uniform_key = None
    uniform_version = version
    if (
        sample_into is None
        and force_active is None
        and pods
        and keys[0] is not None
        and all(k2 == keys[0] for k2 in keys)
    ):
        # Count-independent: every row is the template row (broadcast
        # views), so a 1-pod warm batch and a 1000-pod measured batch share
        # the entry; only `valid` depends on the count and is built fresh.
        uniform_key = ("#stacked", keys[0], k)
        hit = store.get(uniform_key)
        if hit is not None:
            tmpl_batch, delta0 = hit
            batch = dict(tmpl_batch)
            valid = np.zeros(k, np.bool_)
            valid[: len(pods)] = True
            batch["valid"] = valid
            return (
                batch,
                [dict(delta0) for _ in range(len(pods))],
                active,
            )
    # Pin templates: (ns, labels, spec, feats, delta) per distinct pinned
    # template, living beside the key store under the same version token.
    templates = builder.feat_cache[2]
    for pod, key, pin in zip(pods, keys, pins):
        if key is not None:
            hit = store.get(key)
            if hit is not None:
                deltas.append(dict(hit[1]))
                per_pod.append(dict(hit[0]))
                continue
        elif pin is not None:
            tmpl = None
            for cand in templates:
                if (
                    pod.namespace == cand[0]
                    and pod.metadata.labels == cand[1]
                    and _spec_eq_mod_pin(pod.spec, cand[2])
                ):
                    tmpl = cand
                    break
            if tmpl is not None:
                feats = dict(tmpl[3])
                # The ONLY pin-dependent feature is the interned name id
                # (the NodeAffinity pin fast path's (1,1,1) value tensor).
                # Present only when NodeAffinity is in the profile — a
                # NodeAffinity-less profile still pins via the host-side
                # pin_row, and its dicts must stay homogeneous.
                if "na_req_vals" in feats:
                    vals = np.empty((1, 1, 1), np.int32)
                    vals[0, 0, 0] = fctx.interns.node_names.id(pin)
                    feats["na_req_vals"] = vals
                deltas.append(dict(tmpl[4]))
                per_pod.append(feats)
                continue
        delta = builder.pod_delta_vectors(pod)
        deltas.append(delta)
        # Host ports are base commit features: the scan's _commit and the host
        # apply_pod_delta must apply the *same* delta or the mirrors desync.
        # Empty-case arrays are shared immutable singletons (const_array):
        # most pods carry no ports/devices/claims, and per-pod allocation of
        # all-pad arrays was a measurable slice of featurize cost.
        if delta["ports"]:
            port_triples = np.full(POD_PORT_SLOTS, -1, np.int32)
            port_keys = np.full(POD_PORT_SLOTS, -1, np.int32)
            for j, (triple, pk) in enumerate(delta["ports"][:POD_PORT_SLOTS]):
                port_triples[j] = triple
                port_keys[j] = pk
        else:
            port_triples = port_keys = _PORTS_EMPTY
        own = delta["own_terms"]
        if own:
            own_terms = np.full(_bucket(len(own), 1), -1, np.int32)
            own_terms[: len(own)] = own
        else:
            own_terms = _I32_NEG1
        devs = delta["devices"]
        if devs:
            dev_ids = np.full(_bucket(len(devs), 1), -1, np.int32)
            dev_rw = np.zeros(dev_ids.shape[0], np.bool_)
            for j, (vid, rw) in enumerate(devs):
                dev_ids[j] = vid
                dev_rw[j] = rw
        else:
            dev_ids = _I32_NEG1
            dev_rw = _BOOL_FALSE
        dcl = delta["dra_claims"]
        if dcl:
            # One slot per device REQUEST (structured parameters); slots of
            # a claim share kid, `first` marks the count-moving one.
            dra_ids = np.full(_bucket(len(dcl), 1), -1, np.int32)
            dra_cls = np.full(dra_ids.shape[0], -1, np.int32)
            dra_cnt = np.zeros(dra_ids.shape[0], np.int32)
            dra_unalloc = np.zeros(dra_ids.shape[0], np.bool_)
            dra_first = np.zeros(dra_ids.shape[0], np.bool_)
            for j, (kid, cid, cnt, unalloc, first) in enumerate(dcl):
                dra_ids[j] = kid
                dra_cls[j] = cid
                dra_cnt[j] = cnt
                dra_unalloc[j] = unalloc
                dra_first[j] = first
        else:
            dra_ids = dra_cls = _I32_NEG1
            dra_cnt = _I32_ZERO
            dra_unalloc = _BOOL_FALSE
            dra_first = _BOOL_FALSE
        cvols = delta["csivols"]
        if cvols:
            csi_ids = np.full(_bucket(len(cvols), 1), -1, np.int32)
            csi_drv = np.full(csi_ids.shape[0], -1, np.int32)
            for j, (vid, did) in enumerate(cvols):
                csi_ids[j] = vid
                csi_drv[j] = did
        else:
            csi_ids = csi_drv = _I32_NEG1
        feats = {
            "ipa_own_terms": own_terms,
            "vol_dev_ids": dev_ids,
            "vol_dev_rw": dev_rw,
            "vol_csi_ids": csi_ids,
            "vol_csi_drv": csi_drv,
            "req": delta["req"],
            "nonzero": delta["nonzero"],
            "group": np.int32(delta["group"]),
            "priority": np.int32(pod.spec.priority),
            "port_triples": port_triples,
            "port_keys": port_keys,
            "dra_claim_ids": dra_ids,
            "dra_claim_cls": dra_cls,
            "dra_claim_cnt": dra_cnt,
            "dra_claim_first": dra_first,
            "dra_claim_unalloc": dra_unalloc,
            # Chunked-pass conflict classes (engine/pass_.py _conflict_pairs):
            # only PreBind-racing claims (unbound WFC) conflict any-vs-any;
            # bound claims conflict only on SHARED volume/device ids.
            "vol_unbound": np.bool_(delta["vol_unbound"]),
            "vol_csi_lim": np.bool_(delta["vol_csi_lim"]),
        }
        # plugin_execution_duration_seconds{plugin, Featurize}: the
        # per-plugin measurable unit of the batch engine (the device pass
        # fuses the rest), recorded only on ~10% of batches like the
        # reference (schedule_one.go:48 pluginMetricsSamplePercent).
        for op in ops:
            if op.featurize is not None:
                if sample_into is None:
                    feats.update(op.featurize(pod, fctx))
                else:
                    t0 = time.perf_counter()
                    feats.update(op.featurize(pod, fctx))
                    sample_into[op.name] = (
                        sample_into.get(op.name, 0.0)
                        + time.perf_counter() - t0
                    )
        per_pod.append(feats)
        v2 = (builder.feature_version(), profile, active)
        if v2 != version:  # this pod grew a vocabulary — new cache generation
            version = v2
            store = {}
            templates = []
            builder.feat_cache = (version, store, templates)
        elif key is not None:
            if len(store) > 8192:
                store.clear()
            store[key] = (dict(feats), dict(delta))
        elif pin is not None and len(templates) < 8:
            templates.append(
                (pod.namespace, dict(pod.metadata.labels), pod.spec,
                 dict(feats), dict(delta))
            )

    if not per_pod:
        raise ValueError("empty pod batch")

    # Stack + pad. Schema/vocab growth during featurization means early pods
    # may have shorter feature arrays than late ones — pad every key to the
    # per-key max shape with its registered fill (0 for counts, -1 for ids).
    keys = per_pod[-1].keys()
    for key in keys:
        shapes = {f[key].shape for f in per_pod}
        if len(shapes) > 1:
            target = tuple(max(dims) for dims in zip(*shapes))
            fill = opcommon.FEATURE_FILLS.get(key, 0)
            for f in per_pod:
                a = f[key]
                if a.shape != target:
                    pad = [(0, tgt - cur) for cur, tgt in zip(a.shape, target)]
                    f[key] = np.pad(a, pad, constant_values=fill)
    if (
        uniform_key is not None
        and (builder.feature_version(), profile, active) == uniform_version
    ):
        # Uniform fast path — no stack at all: every row (including the
        # padding region, which `valid` gates) is a zero-copy broadcast
        # view of the template row.  Version compared against the capture
        # from BEFORE featurizing: a batch whose first pod grew a
        # vocabulary must not be cached (its row legitimately lacks the
        # new feature bits — the same ordering invariant the per-pod
        # store honors above).  The cached arrays are read-only views;
        # consumers assign fresh keys but never write rows.
        f0 = per_pod[0]
        batch = {
            key: np.broadcast_to(
                np.asarray(val), (k,) + np.asarray(val).shape
            )
            for key, val in f0.items()
        }
        store[uniform_key] = (dict(batch), dict(deltas[0]))
        valid = np.zeros(k, np.bool_)
        valid[: len(pods)] = True
        batch["valid"] = valid
        return batch, deltas, active
    batch = {}
    for key in keys:
        rows = [f[key] for f in per_pod]
        stacked = np.stack(rows)
        pad_width = [(0, k - len(pods))] + [(0, 0)] * (stacked.ndim - 1)
        batch[key] = np.pad(stacked, pad_width)
    batch["valid"] = np.zeros(k, np.bool_)
    batch["valid"][: len(pods)] = True
    return batch, deltas, active
