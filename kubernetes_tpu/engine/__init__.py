from .features import build_pod_batch  # noqa: F401
from .pass_ import PassCache, PassResult, build_pass, select_host  # noqa: F401
