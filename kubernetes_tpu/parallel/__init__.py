from .mesh import make_mesh, shard_cluster_state, shard_pod_batch  # noqa: F401
