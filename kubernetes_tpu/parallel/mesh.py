"""Multi-chip scaling: shard the node axis across a device mesh.

The reference scales its Filter/Score hot loop with a 16-goroutine pool over
the node list (parallelize/parallelism.go). The TPU-native equivalent shards
the node axis of ClusterState across chips with `jax.sharding` — every
vectorized op is elementwise or a reduction over N, so GSPMD partitions them
for free and inserts the ICI collectives (the argmax/cumsum in select_host
become cross-chip reductions; see SURVEY.md §2.3). Nothing in the ops needs to
change: this module only places the data.

Pod batches are replicated (the scan is a sequential dependency chain — its
parallelism is across the node axis, not pods)."""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..snapshot import _NODE_AXIS, ClusterState

NODE_AXIS_NAME = "nodes"


def make_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.array(devs[:n]), (NODE_AXIS_NAME,))


def _spec_for(field: str) -> P:
    if _NODE_AXIS[field] == 0:
        return P(NODE_AXIS_NAME)
    return P(None, NODE_AXIS_NAME)


def shard_cluster_state(state: ClusterState, mesh: Mesh) -> ClusterState:
    """Place every field with its node axis split across the mesh."""
    out = {}
    for f in dataclasses.fields(ClusterState):
        arr = getattr(state, f.name)
        sharding = NamedSharding(mesh, _spec_for(f.name))
        out[f.name] = jax.device_put(arr, sharding)
    return ClusterState(**out)


def shard_pod_batch(batch: dict, mesh: Mesh) -> dict:
    """Replicate the pod batch on every chip."""
    repl = NamedSharding(mesh, P())
    return {k: jax.device_put(v, repl) for k, v in batch.items()}
