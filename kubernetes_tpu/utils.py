"""Small host-side utilities."""

from __future__ import annotations

import jax
import numpy as np


def const_array(shape, fill, dtype) -> np.ndarray:
    """Shared immutable constant array: allocate ONCE at module scope and
    reuse per pod.  Per-pod feature dicts are full of all-pad arrays (a pod
    with no host ports still carries port slots, a pod with no claims still
    carries claim slots…) — allocating them per pod is a measurable slice
    of featurize cost.  Read-only; np.stack copies it into the batch."""
    a = np.full(shape, fill, dtype)
    a.flags.writeable = False
    return a


def device_fetch(tree):
    """jax.device_get with the per-leaf round trips PIPELINED: start every
    leaf's device→host copy asynchronously, then collect.  device_get alone
    blocks one full round trip PER LEAF — through a remote-TPU tunnel
    (~35-70 ms per trip) a 5-leaf result costs ~200 ms serialized vs ~40 ms
    pipelined.  Co-located HBM→host copies see the same effect at a smaller
    scale (one DMA wait instead of N)."""
    for leaf in jax.tree.leaves(tree):
        if hasattr(leaf, "copy_to_host_async"):
            leaf.copy_to_host_async()
    return jax.device_get(tree)
