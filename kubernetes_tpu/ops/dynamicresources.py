"""DynamicResources plugin, vectorized over selector POOLS (structured
parameters).

Reference: pkg/scheduler/framework/plugins/dynamicresources/ (973 LoC, wired
via the claim assume-cache at scheduler.go:298–302) + staging
dynamic-resource-allocation/structured/allocator.go.  Scheduler-relevant
semantics:

  * A pod referencing a MISSING claim is UnschedulableAndUnresolvable until
    the claim appears (the plugin's PreEnqueue/PreFilter checks).
  * An ALLOCATED claim pins the pod to the claim's node (the allocation
    result's node selector).
  * UNALLOCATED claims demand free devices per REQUEST from the request's
    selector pool — a (device class, canonical CEL selector) column pair
    (dra.pool_sig; dra_cel compiles the vectorizable CEL subset) — AND
    from the bare class pool: dra_alloc + need ≤ dra_cap per pool.  One
    feature slot per (request × charged pool), slots of a claim sharing
    its id (snapshot.py pod delta).

Exact named-device allocation happens host-side at Reserve
(dra.ClaimCatalog.allocate_pod_claims), with the same race-recheck pattern
as volume binding; selector-vs-selector pool overlap inside one batch is
resolved there and back-propagated as correction charges
(ClaimCatalog.corr_events)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..api import types as t
from .common import FeaturizeContext, OpDef, PassContext, feature_fill, register

_PIN_SLOTS = 4  # static pin capacity; >4 distinct allocated claims per pod
# would need a bigger slot count (rejected at featurize time).


def _dra_featurize(pod: t.Pod, fctx: FeaturizeContext) -> dict:
    delta_pins = []
    missing = False
    for claim in fctx.builder.dra.pod_claims(pod):
        if claim is None:
            missing = True
        elif claim.allocated_node:
            delta_pins.append(fctx.interns.node_names.id(claim.allocated_node))
    if len(delta_pins) > _PIN_SLOTS:
        raise ValueError(f"pod {pod.uid}: >{_PIN_SLOTS} allocated claims")
    pins = np.full(_PIN_SLOTS, -1, np.int32)
    pins[: len(delta_pins)] = delta_pins
    return {"dra_pin_ids": pins, "dra_missing": np.bool_(missing)}


def _dra_filter(state, pf, ctx: PassContext):
    # Demand per class per node from the pod's claims NOT already reserved
    # on the node (distinct-claim accounting, like csivol attach limits):
    # claims someone on the node already reserves are free rides.
    kids = pf["dra_claim_ids"]  # (S,) engine base feature, -1 pad
    act = kids >= 0
    present = state.dra_claim_counts[jnp.maximum(kids, 0)] > 0  # (S, N)
    dc = state.dra_cap.shape[0]
    cls_oh = (
        pf["dra_claim_cls"][:, None] == jnp.arange(dc)[None, :]
    ) & act[:, None]  # (S, DC)
    new_cnt = (
        (cls_oh[:, :, None] & ~present[:, None, :])
        * pf["dra_claim_cnt"][:, None, None]
    ).sum(0)  # (DC, N)
    fits = ((new_cnt == 0) | (state.dra_alloc + new_cnt <= state.dra_cap)).all(0)
    pins = pf["dra_pin_ids"]  # (S,)
    pin_ok = (
        (pins[:, None] < 0) | (state.name_id[None, :] == pins[:, None])
    ).all(0)
    return ~pf["dra_missing"] & fits & pin_ok


def _dra_hard(state, pf, ctx: PassContext):
    """Missing claims and allocation pins are unresolvable by preemption
    (deleting pods moves no allocation); device shortage IS resolvable."""
    pins = pf["dra_pin_ids"]
    pin_ok = (
        (pins[:, None] < 0) | (state.name_id[None, :] == pins[:, None])
    ).all(0)
    return pf["dra_missing"] | ~pin_ok


def _dra_active(pod: t.Pod, fctx: FeaturizeContext) -> bool:
    return bool(pod.spec.resource_claims)


for _k, _fill in [("dra_pin_ids", -1), ("dra_missing", 0)]:
    feature_fill(_k, _fill)

register(
    OpDef(
        name="DynamicResources",
        featurize=_dra_featurize,
        filter=_dra_filter,
        hard_filter=_dra_hard,
        is_active=_dra_active,
    )
)
