"""ImageLocality, vectorized.

Reference (plugins/imagelocality/image_locality.go): a node scores the summed
sizes of the pod's container images it already holds, each scaled by the
image's spread across the cluster (``size × numNodesWithImage/totalNodes``,
:117 scaledImageScore, truncated per image), then clamped into
[23MB, 1000MB × numContainers] and mapped to [0, MaxNodeScore]
(:84 calculatePriority).  Image names are normalized to a tagged CRI form
(:128 normalizedImageName).

TPU design: node rows carry interned image-name slots (one per alias) with
sizes; a pod ships its container image ids and the device computes presence
masks, spread counts, and the clamp in one vector pass.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..api import types as t
from ..framework.config import MAX_NODE_SCORE
from ..snapshot import _bucket
from .common import FeaturizeContext, OpDef, PassContext, feature_fill, register

MB = 1024 * 1024
MIN_THRESHOLD = 23 * MB
MAX_CONTAINER_THRESHOLD = 1000 * MB


def normalized_image_name(name: str) -> str:
    """image_locality.go:128 — append :latest when the ref has no tag."""
    if name.rfind(":") <= name.rfind("/"):
        name = name + ":latest"
    return name


def featurize(pod: t.Pod, fctx: FeaturizeContext) -> dict:
    refs = [
        normalized_image_name(img)
        for c in list(pod.spec.init_containers) + list(pod.spec.containers)
        for img in c.images
    ]
    # Unknown images can never be on a node: leave them as -1 (scores 0).
    ids = [fctx.interns.images.get(r) for r in refs]
    dim = _bucket(max(len(ids), 1), 1)
    arr = np.full(dim, -1, np.int32)
    arr[: len(ids)] = ids
    n_containers = len(pod.spec.init_containers) + len(pod.spec.containers)
    return {"il_image_ids": arr, "il_ncontainers": np.int64(max(n_containers, 1))}


def score_fn(state, pf, ctx: PassContext, feasible):
    ids = pf["il_image_ids"]  # (CI,)
    active = ids >= 0
    # (CI, N, IM) presence of each wanted image in each node's slots.
    hit = state.image_ids[None, :, :] == ids[:, None, None]
    hit &= active[:, None, None]
    present = hit.any(-1)  # (CI, N)
    # Size of the image on the node (0 when absent); slots of one image alias
    # set never collide within a node row.
    size = jnp.where(hit, state.image_sizes[None, :, :], 0).sum(-1)  # (CI, N)
    num_nodes_with = (present & state.valid[None, :]).sum(-1)  # (CI,)
    total = jnp.maximum(state.valid.sum(), 1)
    spread = num_nodes_with.astype(jnp.float64) / total.astype(jnp.float64)
    # Per-image truncation before the sum (scaledImageScore returns int64).
    scaled = (size.astype(jnp.float64) * spread[:, None]).astype(jnp.int64)
    sum_scores = scaled.sum(0)  # (N,)

    max_threshold = MAX_CONTAINER_THRESHOLD * pf["il_ncontainers"]
    clamped = jnp.clip(sum_scores, MIN_THRESHOLD, max_threshold)
    denom = jnp.maximum(max_threshold - MIN_THRESHOLD, 1)
    return MAX_NODE_SCORE * (clamped - MIN_THRESHOLD) // denom


feature_fill("il_image_ids", -1)
feature_fill("il_ncontainers", 1)
def is_active(pod: t.Pod, fctx: FeaturizeContext) -> bool:
    # Score is 0 for every node when the pod names no images or no node
    # reports any (min-threshold clamp maps empty sums to 0).
    if len(fctx.interns.images) == 0:
        return False
    return any(
        c.images for c in list(pod.spec.init_containers) + list(pod.spec.containers)
    )


register(
    OpDef(name="ImageLocality", featurize=featurize, score=score_fn, is_active=is_active)
)
