"""TaintToleration, vectorized.

Reference (plugins/tainttoleration/taint_toleration.go):
  * Filter (:110): node is infeasible if it has any NoSchedule/NoExecute
    taint the pod does not tolerate (FindMatchingUntoleratedTaint with
    DoNotScheduleTaintsFilterFunc).
  * Score (:171): count of PreferNoSchedule taints not tolerated by the
    pod's PreferNoSchedule-effect tolerations; NormalizeScore reverses
    (DefaultNormalizeScore(MaxNodeScore, true)).

TPU design: taints are interned host-side into a (key, value, effect) vocab;
node rows carry taint-id slots.  Featurization evaluates the pod's tolerations
against the whole vocabulary once, producing two (TV,) bitmasks; the device
filter/score is then two gathers — no string ops on device, and the work is
O(vocab) per pod instead of O(nodes × taints).
"""

from __future__ import annotations

import numpy as np

from ..api import types as t
from .common import FeaturizeContext, OpDef, PassContext, feature_fill, invert_filter, register
from .helpers import default_normalize_score, gather_mask

_DO_NOT_SCHEDULE = (t.EFFECT_NO_SCHEDULE, t.EFFECT_NO_EXECUTE)


def featurize(pod: t.Pod, fctx: FeaturizeContext) -> dict:
    it = fctx.interns
    builder = fctx.builder
    builder._ensure(TV=max(len(it.taints), 1))
    tv = builder.schema.TV
    intol_hard = np.zeros(tv, np.bool_)
    intol_pref = np.zeros(tv, np.bool_)
    tols = pod.spec.tolerations
    # getAllTolerationPreferNoSchedule (taint_toleration.go:143): only
    # empty-effect / PreferNoSchedule tolerations count for scoring.
    pref_tols = tuple(
        tol for tol in tols if not tol.effect or tol.effect == t.EFFECT_PREFER_NO_SCHEDULE
    )
    for tid in range(len(it.taints)):
        key, value, effect = it.taints.value(tid)  # type: ignore[misc]
        taint = t.Taint(key, value, effect)
        if effect in _DO_NOT_SCHEDULE:
            intol_hard[tid] = not any(tol.tolerates(taint) for tol in tols)
        elif effect == t.EFFECT_PREFER_NO_SCHEDULE:
            intol_pref[tid] = not any(tol.tolerates(taint) for tol in pref_tols)
    return {"taint_intol_hard": intol_hard, "taint_intol_pref": intol_pref}


def filter_fn(state, pf, ctx: PassContext):
    return ~gather_mask(pf["taint_intol_hard"], state.taint_ids).any(axis=1)


def score_fn(state, pf, ctx: PassContext, feasible):
    import jax.numpy as jnp

    count = gather_mask(pf["taint_intol_pref"], state.taint_ids).astype(jnp.int64).sum(axis=1)
    return default_normalize_score(count, feasible, reverse=True)


feature_fill("taint_intol_hard", 0)
feature_fill("taint_intol_pref", 0)
def is_active(pod: t.Pod, fctx: FeaturizeContext) -> bool:
    # With no taints interned anywhere, the filter passes every node and the
    # score is a uniform MaxNodeScore (reverse-normalize of all-zero counts)
    # — a constant offset that cannot change any decision.
    return len(fctx.interns.taints) > 0


register(
    OpDef(
        name="TaintToleration",
        featurize=featurize,
        filter=filter_fn,
        score=score_fn,
        hard_filter=invert_filter(filter_fn),
        is_active=is_active,
    )
)
