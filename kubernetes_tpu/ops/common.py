"""Vectorized-plugin op interface.

Each op is the TPU-native re-design of one in-tree scheduling plugin
(reference: pkg/scheduler/framework/plugins/): instead of a per-node Filter /
Score callback invoked from a goroutine pool (runtime/framework.go:861,1101),
an op contributes

  featurize(pod, fctx) → per-pod feature dict (host, numpy; stacked over the
      batch by the engine; every value must have a schema-static shape), and
  filter(state, pf, ctx)  → (N,) bool feasibility over all node rows at once,
  score(state, pf, ctx, feasible) → (N,) int64 in [0, MAX_NODE_SCORE]
      (already normalized over the post-filter ``feasible`` mask — the
      engine applies the plugin weight and sums),

where `pf` is the batch feature dict sliced to one pod by `lax.scan`.  Ops are
pure jax; everything dynamic about the cluster lives in ClusterState, and
everything static (profile, schema) in PassContext so it is baked into the
compiled program.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

from ..framework.config import Profile
from ..snapshot import Schema, SnapshotBuilder


@dataclass
class FeaturizeContext:
    """Host-side context handed to op featurizers."""

    builder: SnapshotBuilder
    profile: Optional[Profile] = None
    # Batch-active op names (None until build_pod_batch resolves them) —
    # lets one op skip recomputing features another active op produces.
    active: Optional[frozenset] = None

    @property
    def interns(self):
        return self.builder.interns

    @property
    def schema(self) -> Schema:
        return self.builder.schema

    @property
    def gates(self):
        """Feature gates (the plfeature.Features analog): stamped on the
        builder by the scheduler; defaults when driving the builder bare."""
        if self.builder.feature_gates is not None:
            return self.builder.feature_gates
        from ..framework.features import DEFAULT_GATES

        return DEFAULT_GATES


@dataclass(frozen=True)
class PassContext:
    """Static (trace-time) context for op filter/score functions.  `static`
    holds per-profile resolved config (e.g. scoring-strategy resource columns)
    baked into the trace — it is never a traced value.  ``dom`` is the one
    exception: the engine rebinds it per trace (dataclasses.replace) to the
    pass's DomTables — the hoisted topology one-hot plus the incrementally
    maintained per-domain count tables (engine/pass_.py)."""

    profile: Profile
    schema: Schema
    static: dict = None  # type: ignore[assignment]
    dom: object = None  # engine.pass_.DomTables, bound per trace
    # Nominated-pod overlay, bound per trace by the engine: (nom_req (N,R)
    # i64, nom_cnt (N,) i32, nom_prio (N,) i32 = max nominated priority, or
    # INT32_MIN when none).  The batch analog of
    # RunFilterPluginsWithNominatedPods (runtime/framework.go:973): a pod
    # must fit with higher-or-equal-priority nominated pods' resources
    # counted, so a preemptor's freed node is not stolen by the next batch.
    nom: object = None


@dataclass(frozen=True)
class OpDef:
    name: str
    featurize: Optional[Callable] = None  # (pod, FeaturizeContext) -> dict[str, np.ndarray]
    filter: Optional[Callable] = None  # (state, pf, PassContext) -> (N,) bool
    # (state, pf, PassContext, feasible (N,) bool) -> (N,) i64 in
    # [0, MAX_NODE_SCORE].  `feasible` is the post-filter mask: the reference
    # scores (and normalizes over) only nodes that passed Filter
    # (schedule_one.go:755 prioritizeNodes runs on `feasibleNodes`).
    score: Optional[Callable] = None
    # Trace-time config resolver: (profile, schema, builder_res_col) -> dict,
    # merged into PassContext.static under this op's keys.
    static: Optional[Callable] = None
    # (state, pf, PassContext) -> (N,) bool of nodes whose rejection by this
    # op is UNRESOLVABLE by preemption (the reference's
    # UnschedulableAndUnresolvable status, which excludes a node from
    # preemption candidates — preemption.go:216 findCandidates /
    # nodesWherePreemptionMightHelp).  None ⇒ this op's failures are
    # resolvable (e.g. resource fit, ports, anti-affinity).
    hard_filter: Optional[Callable] = None
    # (pod, FeaturizeContext) -> bool: does this op do anything for this pod
    # in this cluster?  The batch analog of the reference's PreFilter/PreScore
    # Skip status (framework/cycle_state.go skip sets): an op inactive for an
    # ENTIRE batch is compiled out of that batch's pass.  MUST be
    # conservative — skipping an inactive op must not change any decision
    # (its filter would pass every node; its score would add a constant).
    # None ⇒ always active.
    is_active: Optional[Callable] = None


from ..snapshot import POD_PORT_SLOTS  # noqa: F401  (re-export for ops)

# Pad fill per feature key when featurization grows the schema mid-batch and
# early pods' arrays are shorter than the final schema shape (0 is correct for
# counts/requests; id slots pad with -1 = "empty").
FEATURE_FILLS: dict[str, int] = {}


def feature_fill(key: str, fill: int) -> None:
    FEATURE_FILLS[key] = fill


def invert_filter(filter_fn: Callable) -> Callable:
    """hard_filter adapter for ops whose every rejection is unresolvable."""

    def hard(state, pf, ctx):
        return ~filter_fn(state, pf, ctx)

    return hard


_REGISTRY: dict[str, OpDef] = {}


def register(op: OpDef) -> OpDef:
    _REGISTRY[op.name] = op
    return op


def get(name: str) -> OpDef:
    return _REGISTRY[name]


def has(name: str) -> bool:
    return name in _REGISTRY


def all_ops() -> dict[str, OpDef]:
    return dict(_REGISTRY)


def registered_subset(profile: Profile) -> Profile:
    """Restrict a profile to plugins with registered ops (build-out aid while
    the op inventory grows; a fully-built tree is a no-op)."""
    import dataclasses

    return dataclasses.replace(
        profile,
        filters=tuple(f for f in profile.filters if f in _REGISTRY),
        scorers=tuple((s, w) for s, w in profile.scorers if s in _REGISTRY),
    )
