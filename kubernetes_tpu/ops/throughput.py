"""ThroughputAware: Gavel-style heterogeneity-aware scoring, vectorized.

Reference (Gavel, arxiv 2008.09213): heterogeneity-aware policies rank
accelerators per job class by a measured per-(job-class, accelerator-type)
throughput matrix and allocate each job the accelerator time where its
NORMALIZED effective throughput is highest.  This op is the score-plugin
projection of that objective onto the one-shot placement decision: a
candidate node scores its accelerator class's throughput for the pod's
workload class, normalized by the class's best-case throughput across the
matrix row — a profile-config constant, so the score is a pure per-node
property.

TPU design: the accelerator class rides the existing device matrix as a
TOPOLOGY KEY (``scheduler.tpu/accel`` — node pools label their class, e.g.
``tpu-v4`` / ``tpu-v5e`` / ``gpu-a100``); node rows carry the interned
class id in ``state.topo_vals`` like any zone/region value, so the
heterogeneous cluster model adds ZERO new ClusterState fields.  Pod
featurization resolves the pod's workload class (``scheduler.tpu/
workload-class`` label) against the profile's throughput matrix ONCE,
producing a (DV,) pre-normalized score table; the device score is a single
gather per node — no string ops, no host loop, O(1) per (pod, node).

Determinism/fleet contract: the normalizer is the STATIC matrix-row max
(profile config), never the feasible-set max — per-node scores are
partition-independent, so a fleet of shard owners reproduces the single
scheduler bit for bit (the Tesserae compromise documented in
fleet/router.py never engages; contrast DefaultNormalizeScore ops).
"""

from __future__ import annotations

import numpy as np

from ..api import types as t
from ..framework.config import MAX_NODE_SCORE, Profile
from .common import FeaturizeContext, OpDef, PassContext, feature_fill, register
from .helpers import gather_mask

# The accelerator-class node label (the heterogeneous cluster model's one
# knob: a node pool's class is a label, featurized as a topology key).
ACCEL_LABEL_KEY = "scheduler.tpu/accel"
# The pod-side workload class selecting the matrix row.
WORKLOAD_CLASS_LABEL_KEY = "scheduler.tpu/workload-class"

# The default per-(workload-class, accelerator-class) throughput matrix —
# integer milli-throughput (relative units; only ratios matter).  Shaped
# like Gavel's measured matrices: orderings DIFFER per class (v5e wins
# serving, v4 wins large training, the GPU wins preprocessing), which is
# exactly what a heterogeneity-UNAWARE scorer cannot express.
DEFAULT_THROUGHPUT_MATRIX: tuple[tuple[str, tuple[tuple[str, int], ...]], ...] = (
    ("train-large", (("tpu-v4", 1000), ("tpu-v5e", 520), ("gpu-a100", 410))),
    ("train-small", (("tpu-v4", 760), ("tpu-v5e", 980), ("gpu-a100", 650))),
    ("serve", (("tpu-v4", 540), ("tpu-v5e", 1000), ("gpu-a100", 720))),
    ("batch", (("tpu-v4", 330), ("tpu-v5e", 450), ("gpu-a100", 1000))),
)


def matrix_accel_classes(
    matrix: tuple[tuple[str, tuple[tuple[str, int], ...]], ...]
) -> tuple[str, ...]:
    """Every accelerator class any matrix row names, first-seen order."""
    seen: dict[str, None] = {}
    for _wclass, row in matrix:
        for accel, _tput in row:
            seen.setdefault(accel, None)
    return tuple(seen)


def pod_workload_class(pod: t.Pod) -> str | None:
    return pod.metadata.labels.get(WORKLOAD_CLASS_LABEL_KEY)


def node_accel_class(node: t.Node) -> str | None:
    return node.metadata.labels.get(ACCEL_LABEL_KEY)


def reference_scores(
    pod: t.Pod, nodes: list[t.Node], matrix=DEFAULT_THROUGHPUT_MATRIX
) -> list[int]:
    """Pure-Python oracle for the device score (tests/test_heterogeneity
    parity): per-node normalized effective throughput in
    [0, MAX_NODE_SCORE], 0 for unlabeled nodes / unknown classes."""
    row = dict(matrix).get(pod_workload_class(pod))
    if not row:
        return [0 for _ in nodes]
    best = max(max(tput for _accel, tput in row), 1)
    by_accel = dict(row)
    return [
        (by_accel.get(node_accel_class(n) or "", 0) * MAX_NODE_SCORE) // best
        for n in nodes
    ]


def preseed_hetero_vocab(builder, matrix=DEFAULT_THROUGHPUT_MATRIX) -> None:
    """Pre-seed the accelerator-class vocabulary (and the matrix's row
    keys) into the featurization vocab BEFORE warmup compiles the device
    programs — the heterogeneity analog of the lifecycle-taint/tenant
    pre-seeds (PR 9/PR 12): without it the FIRST mid-window heterogeneous
    pod or freshly-labeled node grows the topo/label vocab (and possibly
    the DV bucket) and pays a full XLA recompile inside the measured
    window.  Idempotent; safe on a builder that never sees hetero pods
    (interning adds vocabulary entries, never behavior)."""
    it = builder.interns
    builder.ensure_topo_key(ACCEL_LABEL_KEY)
    it.label_keys.id(ACCEL_LABEL_KEY)
    it.label_keys.id(WORKLOAD_CLASS_LABEL_KEY)
    for accel in matrix_accel_classes(matrix):
        it.topo_value_id(ACCEL_LABEL_KEY, accel)
        it.label_pairs.id((ACCEL_LABEL_KEY, accel))
    for wclass, _row in matrix:
        it.label_pairs.id((WORKLOAD_CLASS_LABEL_KEY, wclass))
    builder._ensure(DV=it.max_topo_vocab())


def _tp_features(pod: t.Pod, fctx: FeaturizeContext, matrix) -> dict:
    """(tp_scores (DV,) i64, tp_slot () i32): the pod's pre-normalized
    per-accelerator-class score table and the accel topology slot.
    Shared with the learned scorer's throughput input feature."""
    builder = fctx.builder
    it = fctx.interns
    slot = builder.ensure_topo_key(ACCEL_LABEL_KEY)
    row = dict(matrix).get(pod_workload_class(pod)) if matrix else None
    if row:
        # Intern every class in the row BEFORE sizing the table: a class
        # no node carries yet still gets its stable id (and the DV grow
        # happens here, host-side, not mid-pass).
        vids = {it.topo_value_id(ACCEL_LABEL_KEY, accel): tput for accel, tput in row}
        builder._ensure(DV=it.max_topo_vocab())
    else:
        vids = {}
    dv = builder.schema.DV
    scores = np.zeros(dv, np.int64)
    if vids:
        # validate_profile rejects all-zero rows; the max(…, 1) keeps an
        # unvalidated embedder-built profile at score 0 instead of a
        # schedule-time divide.
        best = max(max(vids.values()), 1)
        for vid, tput in vids.items():
            if vid < dv:
                scores[vid] = tput * MAX_NODE_SCORE // best
    return {"tp_scores": scores, "tp_slot": np.int32(slot)}


def featurize(pod: t.Pod, fctx: FeaturizeContext) -> dict:
    matrix = fctx.profile.throughput_matrix if fctx.profile is not None else ()
    return _tp_features(pod, fctx, matrix)


def score_fn(state, pf, ctx: PassContext, feasible):
    import jax.numpy as jnp

    # Node's accelerator-class id at the accel topo slot ((N,); -1 when
    # the node carries no class label → gather_mask scores it 0).
    vals = jnp.take(state.topo_vals, pf["tp_slot"], axis=1)
    return gather_mask(pf["tp_scores"], vals[:, None])[:, 0].astype(jnp.int64)


feature_fill("tp_scores", 0)
feature_fill("tp_slot", 0)


def is_active(pod: t.Pod, fctx: FeaturizeContext) -> bool:
    # All-zero scores are a constant the engine may skip: no matrix row
    # for the pod's class, or no node anywhere carries the accel label
    # (then every gather lands on -1/absent ids).  A shard whose nodes
    # are all unlabeled skipping the op is bit-identical to running it —
    # the scores it would compute are exactly zero (no feasible-set
    # normalization), so fleet shards never diverge on activation.
    profile = fctx.profile
    if profile is None or not profile.throughput_matrix:
        return False
    if pod_workload_class(pod) not in dict(profile.throughput_matrix):
        return False
    return ACCEL_LABEL_KEY in fctx.interns.label_keys


register(
    OpDef(
        name="ThroughputAware",
        featurize=featurize,
        score=score_fn,
        is_active=is_active,
    )
)


def load_matrix(path: str) -> tuple:
    """Load a measured ``measured_matrix.json`` artifact (ISSUE 16:
    framework/measured.py — schema/version/finiteness-validated) into
    the profile's tuple-of-rows form, interchangeable with
    DEFAULT_THROUGHPUT_MATRIX.  ValueError/OSError are config errors at
    the caller (configv1 ``matrixFile``, ``serve --measured-matrix``)."""
    from ..framework import measured

    return measured.matrix_rows(measured.load(path))


def throughput_aware_profile(
    matrix: tuple = DEFAULT_THROUGHPUT_MATRIX, weight: int = 3
) -> Profile:
    """The heterogeneity-aware profile: the full default plugin set plus
    the ThroughputAware scorer, selected by pods naming
    ``schedulerName: throughput-aware-scheduler``.  Registered beside the
    default via ``TPUScheduler(profiles=[throughput_aware_profile()])``
    (the multi-profile map compiles it as its own XLA program family)."""
    base = Profile()
    return Profile(
        name="throughput-aware-scheduler",
        scorers=base.scorers + (("ThroughputAware", weight),),
        throughput_matrix=tuple((w, tuple(r)) for w, r in matrix),
    )
