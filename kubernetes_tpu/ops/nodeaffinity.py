"""NodeAffinity, vectorized.

Reference (plugins/nodeaffinity/node_affinity.go):
  * Filter (:146): pod.Spec.NodeSelector (map, ANDed) AND required node
    affinity (`nodeaffinity.GetRequiredNodeAffinity`,
    component-helpers/scheduling/corev1/nodeaffinity/nodeaffinity.go):
    a NodeSelector is an OR of terms; a term is an AND of matchExpressions +
    matchFields; operators In/NotIn/Exists/DoesNotExist/Gt/Lt; the only
    supported field is metadata.name.
  * Score: sum of weights of matching preferredDuringScheduling terms,
    then DefaultNormalizeScore (not reversed).

TPU design: featurization compiles the pod's selector into a *requirement
program* — dense (T, Q) opcode/key tensors plus (T, Q, V) value-id tensors,
bucketed to powers of two so XLA sees few distinct shapes — and the device
evaluates every requirement against every node's interned label slots in one
broadcast (string matching became integer equality at intern time).  In/NotIn
compare (key, value) pair ids; Exists/DoesNotExist compare key ids; Gt/Lt use
the pre-parsed per-slot integer label values; name ops compare node-name ids.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..api import types as t
from ..snapshot import INT_SENTINEL, _bucket
from .common import FeaturizeContext, OpDef, PassContext, feature_fill, invert_filter, register
from .helpers import default_normalize_score

# Requirement opcodes. Pad slots are OP_PAD and evaluate True (AND identity).
OP_PAD = -1
OP_IN = 0
OP_NOT_IN = 1
OP_EXISTS = 2
OP_NOT_EXISTS = 3
OP_GT = 4
OP_LT = 5
OP_NAME_IN = 6
OP_NAME_NOT_IN = 7
OP_FALSE = 8  # unsupported field/operator or unparseable Gt/Lt operand

_OPCODE = {
    t.OP_IN: OP_IN,
    t.OP_NOT_IN: OP_NOT_IN,
    t.OP_EXISTS: OP_EXISTS,
    t.OP_DOES_NOT_EXIST: OP_NOT_EXISTS,
    t.OP_GT: OP_GT,
    t.OP_LT: OP_LT,
}


from ..utils import const_array as _const

_EMPTY_PROG: dict[str, dict] = {}
_EMPTY_SEL = _const(1, -1, np.int32)
# Name-pin fast-path singletons: the fixed parts of a single
# metadata.name In [v] program (only the value id differs per pod).
_NAME_PIN_OP = _const((1, 1), OP_NAME_IN, np.int32)
_NAME_PIN_KEY = _const((1, 1), -1, np.int32)
_NAME_PIN_INT = _const((1, 1), 0, np.int64)
_NAME_PIN_VALID = _const(1, 1, np.bool_)
_EMPTY_PREF = {
    "na_pref_op": _const((1, 1), OP_PAD, np.int32),
    "na_pref_key": _const((1, 1), -1, np.int32),
    "na_pref_vals": _const((1, 1, 1), -1, np.int32),
    "na_pref_int": _const((1, 1), 0, np.int64),
    "na_pref_weight": _const(1, 0, np.int64),
}


class _Program:
    """Mutable builder for a (T, Q, V) requirement program."""

    def __init__(self) -> None:
        self.terms: list[list[tuple[int, int, list[int], int]]] = []  # op,key,vals,int

    def add_term(self, term: t.NodeSelectorTerm, it) -> None:
        """Compile one NodeSelectorTerm; empty terms match nothing
        (nodeaffinity.go nodeSelectorTermsMatch skips them)."""
        reqs: list[tuple[int, int, list[int], int]] = []
        for r in term.match_expressions:
            op = _OPCODE.get(r.operator, None)
            if op is None:
                reqs.append((OP_FALSE, -1, [], 0))
                continue
            key_id = it.label_keys.id(r.key)
            if op in (OP_IN, OP_NOT_IN):
                vals = [it.label_pairs.id((r.key, v)) for v in r.values]
                reqs.append((op, key_id, vals, 0))
            elif op in (OP_GT, OP_LT):
                if len(r.values) != 1:
                    reqs.append((OP_FALSE, -1, [], 0))
                    continue
                try:
                    rhs = int(r.values[0])
                except ValueError:
                    reqs.append((OP_FALSE, -1, [], 0))
                    continue
                reqs.append((op, key_id, [], rhs))
            else:
                reqs.append((op, key_id, [], 0))
        for r in term.match_fields:
            # Only metadata.name is a valid field selector.
            if r.key != "metadata.name" or r.operator not in (t.OP_IN, t.OP_NOT_IN):
                reqs.append((OP_FALSE, -1, [], 0))
                continue
            op = OP_NAME_IN if r.operator == t.OP_IN else OP_NAME_NOT_IN
            # Unknown node names intern fine; they simply match no live row.
            vals = [it.node_names.id(v) for v in r.values]
            reqs.append((op, -1, vals, 0))
        if reqs:
            self.terms.append(reqs)

    def tensors(self, prefix: str, min_terms: int = 1) -> dict:
        """Pack into dense tensors.  A term with zero requirements is
        all-OP_PAD and evaluates True everywhere — _Program.add_term never
        produces one, but grouped volume programs use them as always-true
        entries (ops/volumes._GroupedProgram)."""
        if not self.terms and min_terms <= 1:
            # Empty program (no affinity): shared immutable all-pad tensors
            # — allocated once per prefix, not per pod (most pods have no
            # affinity of the given kind).
            cached = _EMPTY_PROG.get(prefix)
            if cached is None:
                cached = {
                    f"{prefix}_op": _const((1, 1), OP_PAD, np.int32),
                    f"{prefix}_key": _const((1, 1), -1, np.int32),
                    f"{prefix}_vals": _const((1, 1, 1), -1, np.int32),
                    f"{prefix}_int": _const((1, 1), 0, np.int64),
                    f"{prefix}_term_valid": _const(1, 0, np.bool_),
                }
                _EMPTY_PROG[prefix] = cached
            return dict(cached)
        tdim = _bucket(max(len(self.terms), min_terms, 1), 1)
        qdim = _bucket(max((len(te) for te in self.terms), default=1) or 1, 1)
        vdim = _bucket(
            max((len(v) for te in self.terms for _, _, v, _ in te), default=1) or 1, 1
        )
        ops = np.full((tdim, qdim), OP_PAD, np.int32)
        keys = np.full((tdim, qdim), -1, np.int32)
        vals = np.full((tdim, qdim, vdim), -1, np.int32)
        ints = np.zeros((tdim, qdim), np.int64)
        valid = np.zeros(tdim, np.bool_)
        for ti, te in enumerate(self.terms):
            valid[ti] = True
            for qi, (op, key, vlist, rhs) in enumerate(te):
                ops[ti, qi] = op
                keys[ti, qi] = key
                vals[ti, qi, : len(vlist)] = vlist
                ints[ti, qi] = rhs
        return {
            f"{prefix}_op": ops,
            f"{prefix}_key": keys,
            f"{prefix}_vals": vals,
            f"{prefix}_int": ints,
            f"{prefix}_term_valid": valid,
        }


def _eval_terms(state, ops, keys, vals, ints):
    """Evaluate a requirement program on every node: (T, N) term matches."""
    lk = state.label_key_ids  # (N, LS)
    lp = state.label_pair_ids  # (N, LS)
    li = state.label_int_vals  # (N, LS)
    keymatch = lk[None, None, :, :] == keys[:, :, None, None]  # (T, Q, N, LS)
    has_key = keymatch.any(-1)  # (T, Q, N)
    pair_hit = (lp[None, None, None, :, :] == vals[:, :, :, None, None]) & (
        vals >= 0
    )[:, :, :, None, None]
    pair_any = pair_hit.any((-1, -3))  # over LS and V → (T, Q, N)
    # Per-slot int label value; exactly one slot holds a given key, so a
    # masked sum extracts it (INT_SENTINEL marks non-integer values).
    label_int = jnp.sum(jnp.where(keymatch, li[None, None, :, :], 0), axis=-1)
    int_ok = has_key & (label_int != INT_SENTINEL)
    name_hit = (state.name_id[None, None, None, :] == vals[:, :, :, None]) & (
        vals >= 0
    )[:, :, :, None]
    name_any = name_hit.any(-2)  # over V → (T, Q, N)

    op = ops[:, :, None]
    result = jnp.where(op == OP_IN, pair_any, True)
    result &= jnp.where(op == OP_NOT_IN, ~pair_any, True)
    result &= jnp.where(op == OP_EXISTS, has_key, True)
    result &= jnp.where(op == OP_NOT_EXISTS, ~has_key, True)
    result &= jnp.where(op == OP_GT, int_ok & (label_int > ints[:, :, None]), True)
    result &= jnp.where(op == OP_LT, int_ok & (label_int < ints[:, :, None]), True)
    result &= jnp.where(op == OP_NAME_IN, name_any, True)
    result &= jnp.where(op == OP_NAME_NOT_IN, ~name_any, True)
    result = jnp.where(op == OP_FALSE, False, result)
    return result.all(axis=1)  # AND over Q → (T, N)


def featurize(pod: t.Pod, fctx: FeaturizeContext) -> dict:
    it = fctx.interns
    # spec.nodeSelector map: every (k, v) pair must be present on the node.
    if pod.spec.node_selector:
        sel_pairs = [
            it.label_pairs.id((k, v))
            for k, v in sorted(pod.spec.node_selector.items())
        ]
        sel = np.full(_bucket(len(sel_pairs), 1), -1, np.int32)
        sel[: len(sel_pairs)] = sel_pairs
    else:
        sel = _EMPTY_SEL

    aff = pod.spec.affinity
    na = aff.node_affinity if aff else None
    # Name-pin fast path: a required affinity of exactly one
    # metadata.name In [one value] matchFields term (the daemonset shape —
    # one unique program per pod, so the general builder's Python cost is
    # paid 15k times per workload) compiles to fixed-shape tensors with
    # just the interned name id filled in.  The SAME shape test
    # (features.pin_name) gates the pinned scheduling pass, so the two
    # definitions of "name-pinned" cannot drift.
    from ..engine.features import pin_name

    pinned_name = pin_name(pod)
    if (
        pinned_name is not None
        and (fctx.profile is None or fctx.profile.added_affinity is None)
        and not na.preferred
    ):
        name_id = it.node_names.id(pinned_name)
        feats = {"na_sel_pairs": sel, "na_has_required": np.bool_(True)}
        feats["na_req_op"] = _NAME_PIN_OP
        feats["na_req_key"] = _NAME_PIN_KEY
        vals = np.empty((1, 1, 1), np.int32)
        vals[0, 0, 0] = name_id
        feats["na_req_vals"] = vals
        feats["na_req_int"] = _NAME_PIN_INT
        feats["na_req_term_valid"] = _NAME_PIN_VALID
        feats.update(_EMPTY_PREF)
        return feats
    req_prog = _Program()
    has_required = False
    if na and na.required is not None:
        has_required = True
        for term in na.required.terms:
            req_prog.add_term(term, it)
    pref_prog = _Program()
    weights: list[int] = []
    if na:
        for p in na.preferred:
            before = len(pref_prog.terms)
            pref_prog.add_term(p.preference, it)
            if len(pref_prog.terms) > before:
                weights.append(p.weight)
    # NodeAffinityArgs.AddedAffinity (node_affinity.go:117): the profile's
    # affinity is a SEPARATE required selector ANDed with the pod's own
    # (two OR-of-term groups, both must match), and its preferred terms
    # join the pod's in Score.  Featurized per pod so the batch feature
    # cache (keyed on profile) stays coherent across profiles.
    added = fctx.profile.added_affinity if fctx.profile else None
    feats = {"na_sel_pairs": sel, "na_has_required": np.bool_(has_required)}
    feats.update(req_prog.tensors("na_req"))
    if added is not None:
        # Profile is trace-static: profiles WITHOUT addedAffinity emit no
        # na_add features and their compiled filter skips the whole added
        # branch (a per-pod program build + a (T,Q,N,LS) device broadcast
        # that regressed the daemonset workload when done unconditionally).
        add_prog = _Program()
        has_added = False
        if added.required is not None and added.required.terms:
            has_added = True
            for term in added.required.terms:
                add_prog.add_term(term, it)
        for p in added.preferred:
            before = len(pref_prog.terms)
            pref_prog.add_term(p.preference, it)
            if len(pref_prog.terms) > before:
                weights.append(p.weight)
        feats["na_has_added"] = np.bool_(has_added)
        feats.update(add_prog.tensors("na_add"))
    pref = pref_prog.tensors("na_pref")
    w = np.zeros(pref["na_pref_term_valid"].shape[0], np.int64)
    w[: len(weights)] = weights
    pref["na_pref_weight"] = w
    del pref["na_pref_term_valid"]  # weight 0 already neutralizes pad terms
    feats.update(pref)
    return feats


def filter_fn(state, pf, ctx: PassContext):
    lp = state.label_pair_ids  # (N, LS)
    sel = pf["na_sel_pairs"]  # (S,)
    sel_hit = (lp[None, :, :] == sel[:, None, None]).any(-1)  # (S, N)
    sel_ok = (sel_hit | (sel < 0)[:, None]).all(0)  # pads auto-pass

    term_match = _eval_terms(
        state, pf["na_req_op"], pf["na_req_key"], pf["na_req_vals"], pf["na_req_int"]
    )
    any_term = (term_match & pf["na_req_term_valid"][:, None]).any(0)
    affinity_ok = jnp.where(pf["na_has_required"], any_term, True)
    ok = sel_ok & affinity_ok
    if ctx.profile.added_affinity is not None:  # static trace-time branch
        add_match = _eval_terms(
            state, pf["na_add_op"], pf["na_add_key"], pf["na_add_vals"],
            pf["na_add_int"],
        )
        add_any = (add_match & pf["na_add_term_valid"][:, None]).any(0)
        ok &= jnp.where(pf["na_has_added"], add_any, True)
    return ok


def score_fn(state, pf, ctx: PassContext, feasible):
    term_match = _eval_terms(
        state, pf["na_pref_op"], pf["na_pref_key"], pf["na_pref_vals"], pf["na_pref_int"]
    )
    raw = jnp.sum(term_match * pf["na_pref_weight"][:, None], axis=0)
    return default_normalize_score(raw, feasible, reverse=False)


for _k, _fill in [
    ("na_sel_pairs", -1),
    ("na_req_op", OP_PAD),
    ("na_req_key", -1),
    ("na_req_vals", -1),
    ("na_req_int", 0),
    ("na_req_term_valid", 0),
    ("na_pref_op", OP_PAD),
    ("na_pref_key", -1),
    ("na_pref_vals", -1),
    ("na_pref_int", 0),
    ("na_pref_weight", 0),
    ("na_add_op", OP_PAD),
    ("na_add_key", -1),
    ("na_add_vals", -1),
    ("na_add_int", 0),
    ("na_add_term_valid", 0),
]:
    feature_fill(_k, _fill)

def is_active(pod: t.Pod, fctx: FeaturizeContext) -> bool:
    # No nodeSelector and no node affinity: filter passes everywhere, score
    # is uniformly zero.  A profile-level addedAffinity applies to EVERY
    # pod of the profile.
    if fctx.profile is not None and fctx.profile.added_affinity is not None:
        return True
    aff = pod.spec.affinity
    return bool(pod.spec.node_selector) or bool(aff and aff.node_affinity)


register(
    OpDef(
        name="NodeAffinity",
        featurize=featurize,
        filter=filter_fn,
        score=score_fn,
        hard_filter=invert_filter(filter_fn),
        is_active=is_active,
    )
)
