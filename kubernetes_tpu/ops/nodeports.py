"""NodePorts, vectorized.

Reference (plugins/nodeports/node_ports.go): a pod with hostPort requests is
infeasible on a node where any wanted (hostIP, protocol, hostPort) conflicts
with a port already in use (HostPortInfo.CheckConflict,
framework/types.go): a wildcard-IP want conflicts with any same
(protocol, port) use; a specific-IP want conflicts with the same triple or a
wildcard-IP use of the same (protocol, port).

TPU design: the snapshot keeps per-node usage counts keyed by interned port
ids — ``port_counts`` rows for exact (proto, ip, port) triples and
``portkey_counts`` rows for (proto, *, port) — so the filter is a handful of
row gathers compared against zero.  The engine's base features already carry
the pod's port ids (they double as commit deltas); this op adds the wildcard
triple for the specific-IP conflict rule.
"""

from __future__ import annotations

import numpy as np

from ..api import types as t
from .common import (
    POD_PORT_SLOTS,
    FeaturizeContext,
    OpDef,
    PassContext,
    feature_fill,
    register,
)


def featurize(pod: t.Pod, fctx: FeaturizeContext) -> dict:
    # Recompute the interned ids (cheap dict hits — the ids were already
    # interned by the engine's pod_delta_vectors call for this pod).
    wild_triples = np.full(POD_PORT_SLOTS, -1, np.int32)
    is_wild = np.zeros(POD_PORT_SLOTS, np.bool_)
    ports = fctx.interns.ports
    for j, (proto, ip, port) in enumerate(pod.host_ports()[:POD_PORT_SLOTS]):
        wild_triples[j] = ports.id((proto, "0.0.0.0", port))
        is_wild[j] = ip == "0.0.0.0"
    return {"port_wild_triples": wild_triples, "port_is_wild": is_wild}


def filter_fn(state, pf, ctx: PassContext):
    import jax.numpy as jnp

    triples = pf["port_triples"]  # (S,) -1 pad
    keys = pf["port_keys"]
    wilds = pf["port_wild_triples"]
    is_wild = pf["port_is_wild"]
    active = triples >= 0
    # (S, N) usage counts for each wanted port.
    exact = state.port_counts[jnp.maximum(triples, 0)]
    wild_use = state.port_counts[jnp.maximum(wilds, 0)]
    any_ip = state.portkey_counts[jnp.maximum(keys, 0)]
    # Wildcard want: conflicts with any same (proto, port) use.
    # Specific want: conflicts with same triple or wildcard-IP use.
    conflict = jnp.where(is_wild[:, None], any_ip > 0, (exact > 0) | (wild_use > 0))
    return ~(conflict & active[:, None]).any(axis=0)


feature_fill("port_wild_triples", -1)
feature_fill("port_triples", -1)
feature_fill("port_keys", -1)
def is_active(pod: t.Pod, fctx: FeaturizeContext) -> bool:
    # A pod without hostPort requests conflicts with nothing (PreFilter
    # returns Skip, node_ports.go:97).
    return bool(pod.host_ports())


register(
    OpDef(name="NodePorts", featurize=featurize, filter=filter_fn, is_active=is_active)
)
