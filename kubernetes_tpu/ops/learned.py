"""LearnedScorer: a fixed-weight MLP scoring program under the profile map.

The proof (ROADMAP's learned-scoring direction) that the config/profile
machinery hosts ARBITRARY scoring programs, not just upstream plugin
ports: a small multi-layer perceptron over featurized (pod, node) columns
evaluated INSIDE the same compiled batch pass as every other op.
Inference-only and fully deterministic — the weights are a committed
artifact (``learned_weights.json``, loaded once per profile; no training,
no entropy, no wallclock), and the forward pass is written as explicitly
associated elementwise float32 arithmetic (unrolled over the fixed
feature/hidden dims) so the reduction order is IDENTICAL whatever the
node-axis shape or sharding — a fleet shard evaluating its partition
reproduces the single scheduler's per-node scores bit for bit.

Input features per (pod, node) — all node-axis state or pod base
features, nothing cross-node (no feasible-set reductions; the fleet
contract of ops/throughput.py applies):

  0. free-cpu fraction      (alloc − req)/alloc, 0 for cpu-less rows
  1. free-memory fraction   same, memory column
  2. pod-count fraction     num_pods/allowed_pods
  3. normalized throughput  ops/throughput score table gather / 100
  4. request pressure       pod cpu request / node cpu allocatable

Output: sigmoid(tanh(x·W1 + b1)·W2 + b2) mapped to [0, MAX_NODE_SCORE]
via floor(y·MAX + 0.5) in float32 — deterministic rounding, no data-
dependent normalization.
"""

from __future__ import annotations

import json
import math
import os

import numpy as np

from ..api import types as t
from ..framework.config import MAX_NODE_SCORE, Profile
from ..snapshot import RES_CPU, RES_MEMORY
from .common import FeaturizeContext, OpDef, PassContext, feature_fill, register
from .helpers import gather_mask
from .throughput import DEFAULT_THROUGHPUT_MATRIX, _tp_features

# The committed inference artifact: weights live beside the op, loaded
# once per profile construction (never per pod / per pass).
DEFAULT_WEIGHTS_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "learned_weights.json"
)

N_FEATURES = 5


def load_weights(path: str = DEFAULT_WEIGHTS_PATH) -> tuple:
    """Load + validate the committed MLP artifact into the hashable
    nested-tuple form Profile.learned_weights carries:
    ``((w1 rows...), (b1...), (w2...), b2)`` with w1 (F, H), b1 (H,),
    w2 (H,), b2 scalar.  Strict: wrong shapes or non-finite values are
    config errors, not runtime surprises."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("version") != 1:
        raise ValueError(f"learned_weights: unsupported version {doc.get('version')!r}")
    w1 = doc["w1"]
    b1 = doc["b1"]
    w2 = doc["w2"]
    b2 = doc["b2"]
    if len(w1) != N_FEATURES:
        raise ValueError(
            f"learned_weights: w1 has {len(w1)} feature rows, want {N_FEATURES}"
        )
    hidden = len(b1)
    if hidden < 1:
        raise ValueError("learned_weights: empty hidden layer")
    for i, row in enumerate(w1):
        if len(row) != hidden:
            raise ValueError(f"learned_weights: w1[{i}] has {len(row)} cols, want {hidden}")
    if len(w2) != hidden:
        raise ValueError(f"learned_weights: w2 has {len(w2)} entries, want {hidden}")
    flat = [x for row in w1 for x in row] + list(b1) + list(w2) + [b2]
    for x in flat:
        if not math.isfinite(float(x)):
            raise ValueError("learned_weights: non-finite weight")
    return (
        tuple(tuple(float(x) for x in row) for row in w1),
        tuple(float(x) for x in b1),
        tuple(float(x) for x in w2),
        float(b2),
    )


def reference_scores(
    pod, nodes, weights, matrix=DEFAULT_THROUGHPUT_MATRIX, num_pods=None
):
    """Pure-Python float32 oracle of the device forward pass (parity
    tests): same feature extraction, same association order.
    ``num_pods`` maps node name → pods already on it (default empty)."""
    from .throughput import node_accel_class, pod_workload_class

    w1, b1, w2, b2 = weights
    row = dict(matrix).get(pod_workload_class(pod)) if matrix else None
    best = max(max((tp for _a, tp in row), default=1), 1) if row else 1
    by_accel = dict(row) if row else {}
    req = pod.resource_request()
    req_cpu = req.get(t.CPU, 0)
    req_mem = req.get(t.MEMORY, 0)
    out = []
    for n in nodes:
        alloc_cpu = n.status.allocatable.get(t.CPU, 0)
        alloc_mem = n.status.allocatable.get(t.MEMORY, 0)
        allowed = n.status.allocatable.get(t.PODS, 110)
        f32 = np.float32
        x = [
            max(f32(alloc_cpu - req_cpu) / f32(max(alloc_cpu, 1)), f32(0.0)),
            max(f32(alloc_mem - req_mem) / f32(max(alloc_mem, 1)), f32(0.0)),
            f32((num_pods or {}).get(n.name, 0)) / f32(max(allowed, 1)),
            f32(by_accel.get(node_accel_class(n) or "", 0) * MAX_NODE_SCORE // best)
            / f32(MAX_NODE_SCORE),
            f32(req_cpu) / f32(max(alloc_cpu, 1)),
        ]
        h = []
        for j in range(len(b1)):
            acc = f32(b1[j])
            for i in range(len(x)):
                acc = f32(acc + f32(f32(w1[i][j]) * f32(x[i])))
            h.append(np.tanh(acc, dtype=np.float32))
        y = f32(b2)
        for j in range(len(b1)):
            y = f32(y + f32(f32(w2[j]) * h[j]))
        y = f32(1.0) / f32(1.0 + np.exp(-y, dtype=np.float32))
        out.append(int(np.floor(f32(y * MAX_NODE_SCORE) + f32(0.5))))
    return out


def featurize(pod: t.Pod, fctx: FeaturizeContext) -> dict:
    profile = fctx.profile
    matrix = profile.throughput_matrix if profile is not None else ()
    feats = _tp_features(pod, fctx, matrix)
    req = pod.resource_request()
    return {
        "tp_scores": feats["tp_scores"],
        "tp_slot": feats["tp_slot"],
        "ls_req_cpu": np.int64(req.get(t.CPU, 0)),
        "ls_req_mem": np.int64(req.get(t.MEMORY, 0)),
    }


def _static(profile: Profile, schema, builder_res_col) -> dict:
    """Bake the weight tuples into the trace (profile config is static
    under jit — each weights artifact compiles its own program)."""
    return {"learned_weights": profile.learned_weights}


def score_fn(state, pf, ctx: PassContext, feasible):
    import jax.numpy as jnp

    weights = ctx.static.get("learned_weights")
    if not weights:
        return jnp.zeros(state.valid.shape, jnp.int64)
    w1, b1, w2, b2 = weights
    f32 = jnp.float32
    alloc_cpu = state.alloc[:, RES_CPU].astype(f32)
    alloc_mem = state.alloc[:, RES_MEMORY].astype(f32)
    safe_cpu = jnp.maximum(alloc_cpu, 1.0)
    safe_mem = jnp.maximum(alloc_mem, 1.0)
    req_cpu = pf["ls_req_cpu"].astype(f32)
    req_mem = pf["ls_req_mem"].astype(f32)
    vals = jnp.take(state.topo_vals, pf["tp_slot"], axis=1)
    tput = gather_mask(pf["tp_scores"], vals[:, None])[:, 0].astype(f32)
    x = [
        jnp.maximum((alloc_cpu - req_cpu) / safe_cpu, 0.0),
        jnp.maximum((alloc_mem - req_mem) / safe_mem, 0.0),
        state.num_pods.astype(f32) / jnp.maximum(state.allowed_pods.astype(f32), 1.0),
        tput / f32(MAX_NODE_SCORE),
        req_cpu / safe_cpu,
    ]
    # Unrolled, explicitly associated forward pass: the Python loops fix
    # the reduction order at trace time (no dot_general whose internal
    # order could vary with shape/sharding), so every shard — and the
    # single scheduler — computes bit-equal float32 per-node scores.
    hs = []
    for j in range(len(b1)):
        acc = jnp.full(alloc_cpu.shape, f32(b1[j]))
        for i in range(len(x)):
            acc = acc + f32(w1[i][j]) * x[i]
        hs.append(jnp.tanh(acc))
    y = jnp.full(alloc_cpu.shape, f32(b2))
    for j in range(len(b1)):
        y = y + f32(w2[j]) * hs[j]
    y = 1.0 / (1.0 + jnp.exp(-y))
    return jnp.floor(y * f32(MAX_NODE_SCORE) + f32(0.5)).astype(jnp.int64)


feature_fill("ls_req_cpu", 0)
feature_fill("ls_req_mem", 0)


def is_active(pod: t.Pod, fctx: FeaturizeContext) -> bool:
    # Weights are profile config: uniform across pods AND across fleet
    # shards (no per-shard vocab dependence), so activation can never
    # skew a partition.
    profile = fctx.profile
    return profile is not None and bool(profile.learned_weights)


register(
    OpDef(
        name="LearnedScorer",
        featurize=featurize,
        score=score_fn,
        static=_static,
        is_active=is_active,
    )
)


def learned_scorer_profile(
    weights_path: str = DEFAULT_WEIGHTS_PATH,
    matrix: tuple = DEFAULT_THROUGHPUT_MATRIX,
    weight: int = 3,
) -> Profile:
    """The learned-scorer profile: default plugins + the MLP scorer,
    selected by ``schedulerName: learned-scorer-scheduler``.  The matrix
    rides along so feature 3 (normalized throughput) is live — the
    learned program SUBSUMES the hand-written throughput objective."""
    base = Profile()
    return Profile(
        name="learned-scorer-scheduler",
        scorers=base.scorers + (("LearnedScorer", weight),),
        throughput_matrix=tuple((w, tuple(r)) for w, r in matrix),
        learned_weights=load_weights(weights_path),
    )
