"""InterPodAffinity, vectorized.

Reference (plugins/interpodaffinity/):
  * Filter (filtering.go:354–383 satisfy*): three checks against
    topology-pair match counts —
    (1) existing pods' required anti-affinity terms matching the incoming pod
        forbid every node sharing the term's topology pair with a carrier
        (existingAntiAffinityCounts; the node fails if ANY of its topology
        pairs has a positive count, :306);
    (2) the incoming pod's required affinity terms need, per term, a node
        whose (topologyKey, value) domain hosts a pod matching ALL terms
        (affinityCounts; all topology keys must exist on the node, with the
        lonely-first-pod self-match exception, :337–351);
    (3) the incoming pod's required anti-affinity terms forbid domains
        hosting any matching pod (antiAffinityCounts, :322).
  * Score (scoring.go:80–124 processExistingPod): per existing pod E on node
    m, weights accumulate onto m's (topologyKey, value) pairs — the incoming
    pod's preferred (anti-)affinity terms matching E contribute ±weight; E's
    required affinity terms matching the pod contribute HardPodAffinityWeight;
    E's preferred (anti-)affinity terms matching the pod contribute ±weight.
    A node's raw score sums its pairs' weights (:243); NormalizeScore maps
    [min,max] over feasible nodes to [0,100] (:265).

TPU design: existing pods' terms are interned into a term vocabulary; the
cluster state carries per-(term, node) carrier counts (et_counts), updated by
the same commit delta that moves resources.  Featurization matches the
incoming pod against every interned term once (host-side string work), and
compiles the pod's own terms to group bitmasks.

The HARD-read masks this op emits (``ipa_ra_allmask``/``ipa_rs_groups``
group reads, ``ipa_et_match ∧ ipa_et_anti`` term reads vs ``ipa_own_terms``
writes) are load-bearing twice: the chunked pass's conflict deferral
(engine/pass_.py ``_conflict_pairs``) AND the conflict-aware chunk packer's
class derivation (engine/packing.py ``conflict_classes``) both consume
them — renaming a key must update both, or packed batches silently lose
their sequential-equivalence guarantee.  On device, all domain
tallies come from the engine's DomTables (engine/pass_.py): ``group_dom``
(G, TK, DV) and ``et_dom`` (ET, DV) are built once per pass with MXU matmuls
and updated incrementally as the scan commits pods, so each step only does
tiny (T,G)×(G,DV) contractions and (N, TK) gathers — replacing the
reference's O(pods × nodes) goroutine sweep (the BASELINE config #3 worst
case) with dense linear algebra whose per-pod cost is near-constant.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..api import types as t
from ..framework.config import MAX_NODE_SCORE
from ..intern import term_key
from ..snapshot import _bucket
from .common import FeaturizeContext, OpDef, PassContext, feature_fill, register

# Existing-term categories (intern.term_id).
CAT_REQ_AFF, CAT_REQ_ANTI, CAT_PREF_AFF, CAT_PREF_ANTI = 0, 1, 2, 3


def _term_matches_pod(term_key, pod: t.Pod, ns_labels: dict[str, dict[str, str]]) -> bool:
    """AffinityTerm.Matches (framework/types.go:479): namespace membership or
    namespaceSelector over the pod's namespace labels, AND label selector."""
    _cat, _w, _topo, ns_tuple, ns_sel, selector = term_key
    ns_ok = pod.namespace in ns_tuple or (
        ns_sel is not None
        and t.label_selector_matches(ns_sel, ns_labels.get(pod.namespace, {}))
    )
    return ns_ok and t.label_selector_matches(selector, pod.metadata.labels)


def _term_group_ns_ids(term: t.PodAffinityTerm, pod: t.Pod, fctx: FeaturizeContext):
    """Namespace-id set an incoming pod's term selects."""
    it = fctx.interns
    ns = set(term.namespaces)
    if not ns and term.namespace_selector is None:
        ns = {pod.namespace}
    ids = {it.namespaces.id(n) for n in ns}
    if term.namespace_selector is not None:
        # Evaluate the selector over every namespace any group references.
        nsl = fctx.builder.namespace_labels
        for nid in range(len(it.namespaces)):
            name = it.namespaces.value(nid)
            if t.label_selector_matches(term.namespace_selector, nsl.get(name, {})):
                ids.add(nid)
    return ids


def _own_term_feats(
    terms, pod: t.Pod, fctx: FeaturizeContext, prefix: str, weights=None
) -> dict:
    """Compile the incoming pod's terms: per-term topo slot + group bitmask."""
    builder = fctx.builder
    dim = _bucket(max(len(terms), 1), 1)
    valid = np.zeros(dim, np.bool_)
    slots = np.zeros(dim, np.int32)
    masks = np.zeros((dim, builder.schema.G), np.bool_)
    wvec = np.zeros(dim, np.int64)
    for i, term in enumerate(terms):
        valid[i] = True
        slots[i] = builder.ensure_topo_key(term.topology_key)
        ns_ids = _term_group_ns_ids(term, pod, fctx)
        m = builder.group_index.match_selector(term.label_selector, ns_ids)
        masks[i, : m.shape[0]] = m
        if weights is not None:
            wvec[i] = weights[i]
    host = np.zeros(dim, np.bool_)
    for i, term in enumerate(terms):
        host[i] = term.topology_key == fctx.interns.HOSTNAME_KEY
    out = {
        f"{prefix}_valid": valid,
        f"{prefix}_slot": slots,
        f"{prefix}_groups": masks,
        f"{prefix}_host": host,
    }
    if weights is not None:
        out[f"{prefix}_w"] = wvec
    return out


def featurize(pod: t.Pod, fctx: FeaturizeContext) -> dict:
    it = fctx.interns
    builder = fctx.builder
    aff = pod.spec.affinity
    pa = aff.pod_affinity if aff else None
    paa = aff.pod_anti_affinity if aff else None
    req_aff = list(pa.required) if pa else []
    req_anti = list(paa.required) if paa else []
    pref = [(wt.term, wt.weight) for wt in (pa.preferred if pa else ())]
    pref += [(wt.term, -wt.weight) for wt in (paa.preferred if paa else ())]

    feats = _own_term_feats(req_aff, pod, fctx, "ipa_ra")
    feats.update(_own_term_feats(req_anti, pod, fctx, "ipa_rs"))
    feats.update(
        _own_term_feats(
            [term for term, _ in pref], pod, fctx, "ipa_pf", [w for _, w in pref]
        )
    )
    # Required affinity counts pods matching ALL terms (podMatchesAllAffinityTerms)
    # — intersect the per-term group masks.
    if req_aff:
        allmask = feats["ipa_ra_groups"][: len(req_aff)].all(axis=0)
    else:
        allmask = np.zeros(builder.schema.G, np.bool_)
    feats["ipa_ra_allmask"] = allmask
    # podMatchesAllAffinityTerms(pod's own terms, pod) for the lonely-first-pod
    # exception (filtering.go:345).
    feats["ipa_ra_self"] = np.bool_(
        bool(req_aff)
        and all(
            _term_matches_pod(
                term_key(CAT_REQ_AFF, 0, term, pod.namespace), pod, builder.namespace_labels
            )
            for term in req_aff
        )
    )

    # Match the pod against every interned existing-pod term: one COLUMN of
    # the incremental term↔group matrix (intern.TermIndex) — pods with
    # identical (namespace, labels) share a group, so this replaces the
    # per-pod O(ET) Python loop that dominated featurization on the
    # affinity-heavy configs (BASELINE #3).  The terms' topology slots/host
    # flags are batch-invariant and live in the engine's DomTables.
    builder._ensure(ET=max(len(it.terms), 1))
    et = builder.schema.ET
    et_match = np.zeros(et, np.bool_)
    et_anti = np.zeros(et, np.bool_)
    et_w = np.zeros(et, np.int64)
    hard_w = fctx.profile.hard_pod_affinity_weight if fctx.profile else 1
    gid = it.group_id(pod.namespace, pod.metadata.labels)
    builder.term_index.sync(builder.ns_epoch)
    col, cats, weights = builder.term_index.column(gid)
    nt = col.shape[0]
    et_match[:nt] = col
    et_anti[:nt] = col & (cats == CAT_REQ_ANTI)
    et_w[:nt] = np.where(
        col,
        np.where(
            cats == CAT_REQ_AFF,
            hard_w,
            np.where(
                cats == CAT_PREF_AFF,
                weights,
                np.where(cats == CAT_PREF_ANTI, -weights, 0),
            ),
        ),
        0,
    )
    feats.update(ipa_et_match=et_match, ipa_et_anti=et_anti, ipa_et_w=et_w)
    return feats


def _own_term_tallies(state, dom, slots, masks, host):
    """Per-term domain tallies for the incoming pod's own terms: (T, N).

    ``masks`` (T, G) group bitmasks, ``slots`` (T,) topo-key slots.  Generic
    terms contract the engine's group_dom table — (T,G)×(G,DV) per slot, no
    node-axis work; hostname terms (single-node domains, their vocabulary is
    excluded from DV) take the per-node (T,G)×(G,N) matmul fast path.
    Returns (vals (T,N), key_present (T,N), cnt_node (T,N), at_node (T,N))
    where cnt_node is the per-node matching count and at_node the term's
    domain tally at each node (0 where the key is missing)."""
    masks = masks.astype(jnp.float32)
    vals = jnp.take(state.topo_vals, slots, axis=1).T  # (T, N)
    key_present = vals >= 0
    cnt_node = masks @ state.group_counts.astype(jnp.float32)  # (T, N)
    gd = jnp.take(dom.group_dom, slots, axis=1)  # (G, T, DV)
    tbl = jnp.einsum("tg,gtd->td", masks, gd)  # (T, DV)
    # Read tbl back per node via the hoisted one-hot — an MXU contraction,
    # not a take_along_axis: node-axis gathers are the slow path on TPU
    # (this was ~60% of the IPA-active per-pod cost).  The slot one-hot
    # keeps the contraction over the shared (N, TK·DV) table — a per-pod
    # take of dom.onehot would materialize (N, T, DV) per batch lane.
    # Invalid topo values have all-zero one-hot rows, so key_present
    # masking is preserved.
    n, tk, dv = dom.onehot.shape
    slot_oh = (slots[:, None] == jnp.arange(tk)[None, :]).astype(jnp.float32)
    # Explicit order: expand tbl over its slot (tiny), then ONE flat
    # (T, TK·DV)×(TK·DV, N) MXU matmul — a single einsum here lets XLA
    # pick a contraction order that materializes (T, N, DV) per lane.
    tbl_kd = jnp.einsum("td,tk->tkd", tbl, slot_oh).reshape(-1, tk * dv)
    gathered = tbl_kd @ dom.onehot.reshape(n, tk * dv).T  # (T, N)
    at_node = jnp.where(key_present, jnp.where(host[:, None], cnt_node, gathered), 0.0)
    return vals, key_present, cnt_node, at_node, tbl


def _affinity_ok(state, pf, ctx: PassContext):
    """Incoming required-affinity check (2) — its failures are
    UnschedulableAndUnresolvable (ErrReasonAffinityRulesNotMatch)."""
    dom = ctx.dom
    ra_valid = pf["ipa_ra_valid"]  # (RA,)
    any_ra = ra_valid.any()
    host = pf["ipa_ra_host"]
    # All required terms share one intersection mask (podMatchesAllAffinityTerms).
    allmask = jnp.broadcast_to(
        pf["ipa_ra_allmask"][None, :], (ra_valid.shape[0], pf["ipa_ra_allmask"].shape[0])
    )
    _v, key_ra, cnt_node, at_ra, tbl = _own_term_tallies(
        state, dom, pf["ipa_ra_slot"], allmask, host
    )
    keys_ok = (key_ra | ~ra_valid[:, None]).all(0)
    pods_exist = ((at_ra > 0.5) | ~ra_valid[:, None]).all(0)
    # len(affinityCounts) == 0 ⟺ no key-bearing node hosts a matching pod.
    per_term_total = jnp.where(
        host,
        (key_ra.astype(jnp.float32) * cnt_node).sum(1),
        tbl.sum(1),
    )  # (T,)
    counts_empty = jnp.sum(jnp.where(ra_valid, per_term_total, 0.0)) == 0
    return ~any_ra | (keys_ok & (pods_exist | (counts_empty & pf["ipa_ra_self"])))


def _existing_anti_fail(state, pf, ctx: PassContext):
    """(1) Existing pods' required anti-affinity: a node fails if any of its
    topology domains carries a matching term (filtering.go:306).  Reduced to a
    (TK, DV) forbidden-domain table (terms merge per slot) + an (N, TK)
    gather; hostname terms check their per-node carrier counts directly."""
    dom = ctx.dom
    tk, dv = ctx.schema.TK, ctx.schema.DV
    active_e = pf["ipa_et_match"] & pf["ipa_et_anti"]  # (ET,)
    nonhost = active_e & ~dom.et_host
    slot_oh = (dom.et_slot[:, None] == jnp.arange(tk)[None, :]).astype(jnp.float32)
    forbidden_kd = jnp.einsum(
        "tk,td->kd",
        jnp.where(nonhost[:, None], slot_oh, 0.0),
        (dom.et_dom > 0.5).astype(jnp.float32),
    )  # (TK, DV)
    # Read-back as ONE flat (TK·DV) matvec against the hoisted one-hot
    # (gather-free; invalid topo values have all-zero one-hot rows, so the
    # summed hit count only sees present keys — a node fails iff any of
    # its domains is forbidden ⟺ the sum is positive).
    n, tk2, dv2 = dom.onehot.shape
    hit_sum = dom.onehot.reshape(n, tk2 * dv2) @ forbidden_kd.reshape(tk2 * dv2)
    fail_nonhost = hit_sum > 0.5
    host_active = (active_e & dom.et_host).astype(jnp.float32)
    key_e = dom.et_vals >= 0  # (ET, N)
    fail_host = (
        host_active @ ((state.et_counts > 0) & key_e).astype(jnp.float32)
    ) > 0.5
    return fail_nonhost | fail_host


def filter_fn(state, pf, ctx: PassContext):
    # (1) Existing pods' required anti-affinity.
    fail_existing = _existing_anti_fail(state, pf, ctx)

    # (2) Incoming required affinity.
    aff_ok = _affinity_ok(state, pf, ctx)

    # (3) Incoming required anti-affinity.
    rs_valid = pf["ipa_rs_valid"]
    _v, key_rs, _cnt, at_rs, _tbl = _own_term_tallies(
        state, ctx.dom, pf["ipa_rs_slot"], pf["ipa_rs_groups"], pf["ipa_rs_host"]
    )
    fail_anti = (rs_valid[:, None] & key_rs & (at_rs > 0.5)).any(0)

    return ~fail_existing & aff_ok & ~fail_anti


def hard_filter_fn(state, pf, ctx: PassContext):
    return ~_affinity_ok(state, pf, ctx)


def score_fn(state, pf, ctx: PassContext, feasible):
    dom = ctx.dom
    tk, dv = ctx.schema.TK, ctx.schema.DV

    # Incoming pod's preferred terms: ±w × (matching pods in the node's domain).
    pf_valid = pf["ipa_pf_valid"]
    _v, key_p, _cnt, at_p, _tbl = _own_term_tallies(
        state, dom, pf["ipa_pf_slot"], pf["ipa_pf_groups"], pf["ipa_pf_host"]
    )
    raw = jnp.sum(
        jnp.where(pf_valid[:, None] & key_p, at_p, 0.0)
        * pf["ipa_pf_w"][:, None].astype(jnp.float32),
        axis=0,
    )

    # Existing pods' terms matching the incoming pod: carriers in the node's
    # domain × signed weight (hard affinity / preferred ±w).  Terms collapse
    # into a (TK, DV) weighted-domain table, read back with one (N, TK)
    # gather; hostname terms use their per-node carrier counts via a matvec.
    active_e = pf["ipa_et_match"] & (pf["ipa_et_w"] != 0)
    wts = pf["ipa_et_w"].astype(jnp.float32)
    slot_oh = (dom.et_slot[:, None] == jnp.arange(tk)[None, :]).astype(jnp.float32)
    wsum_kd = jnp.einsum(
        "t,tk,td->kd",
        jnp.where(active_e & ~dom.et_host, wts, 0.0),
        slot_oh,
        dom.et_dom,
    )  # (TK, DV)
    # One flat matvec against the hoisted one-hot (see filter; invalid
    # topo values contribute zero rows, replacing the dvals>=0 mask).
    n2, tk2, dv2 = dom.onehot.shape
    raw += dom.onehot.reshape(n2, tk2 * dv2) @ wsum_kd.reshape(tk2 * dv2)
    host_w = jnp.where(active_e & dom.et_host, wts, 0.0)
    key_e = dom.et_vals >= 0  # (ET, N)
    raw += host_w @ (state.et_counts.astype(jnp.float32) * key_e)
    raw = raw.astype(jnp.int64)

    big = jnp.int64(2**62)
    mn = jnp.min(jnp.where(feasible, raw, big))
    mx = jnp.max(jnp.where(feasible, raw, -big))
    diff = mx - mn
    norm = jnp.where(
        diff > 0, MAX_NODE_SCORE * (raw - mn) // jnp.maximum(diff, 1), 0
    )
    return jnp.where(feasible, norm, 0)


for _k, _fill in [
    ("ipa_ra_valid", 0), ("ipa_ra_slot", 0), ("ipa_ra_groups", 0),
    ("ipa_ra_allmask", 0), ("ipa_ra_self", 0), ("ipa_ra_host", 0),
    ("ipa_rs_valid", 0), ("ipa_rs_slot", 0), ("ipa_rs_groups", 0), ("ipa_rs_host", 0),
    ("ipa_pf_valid", 0), ("ipa_pf_slot", 0), ("ipa_pf_groups", 0), ("ipa_pf_w", 0),
    ("ipa_pf_host", 0),
    ("ipa_et_match", 0), ("ipa_et_anti", 0), ("ipa_et_w", 0),
]:
    feature_fill(_k, _fill)

def is_active(pod: t.Pod, fctx: FeaturizeContext) -> bool:
    # Inactive only when the pod has no pod (anti-)affinity AND no existing
    # pod carries any term (existing pods' terms score/filter incoming pods
    # regardless of the incoming spec — PreFilter Skip, filtering.go:257).
    if len(fctx.interns.terms) > 0:
        return True
    aff = pod.spec.affinity
    return bool(aff and (aff.pod_affinity or aff.pod_anti_affinity))


register(
    OpDef(
        name="InterPodAffinity",
        featurize=featurize,
        filter=filter_fn,
        score=score_fn,
        hard_filter=hard_filter_fn,
        is_active=is_active,
    )
)
