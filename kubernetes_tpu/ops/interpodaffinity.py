"""InterPodAffinity, vectorized.

Reference (plugins/interpodaffinity/):
  * Filter (filtering.go:354–383 satisfy*): three checks against
    topology-pair match counts —
    (1) existing pods' required anti-affinity terms matching the incoming pod
        forbid every node sharing the term's topology pair with a carrier
        (existingAntiAffinityCounts; the node fails if ANY of its topology
        pairs has a positive count, :306);
    (2) the incoming pod's required affinity terms need, per term, a node
        whose (topologyKey, value) domain hosts a pod matching ALL terms
        (affinityCounts; all topology keys must exist on the node, with the
        lonely-first-pod self-match exception, :337–351);
    (3) the incoming pod's required anti-affinity terms forbid domains
        hosting any matching pod (antiAffinityCounts, :322).
  * Score (scoring.go:80–124 processExistingPod): per existing pod E on node
    m, weights accumulate onto m's (topologyKey, value) pairs — the incoming
    pod's preferred (anti-)affinity terms matching E contribute ±weight; E's
    required affinity terms matching the pod contribute HardPodAffinityWeight;
    E's preferred (anti-)affinity terms matching the pod contribute ±weight.
    A node's raw score sums its pairs' weights (:243); NormalizeScore maps
    [min,max] over feasible nodes to [0,100] (:265).

TPU design: existing pods' terms are interned into a term vocabulary; the
cluster state carries per-(term, node) carrier counts (et_counts), updated by
the same commit delta that moves resources.  Featurization matches the
incoming pod against every interned term once (host-side string work), and
compiles the pod's own terms to group bitmasks, so the device computes all
domain tallies with (T,G)×(G,N) matmuls plus segment reductions over interned
topology values — replacing the reference's O(pods × nodes) goroutine sweep
(the BASELINE config #3 worst case) with dense linear algebra.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..api import types as t
from ..framework.config import MAX_NODE_SCORE
from ..intern import term_key
from ..snapshot import _bucket
from .common import FeaturizeContext, OpDef, PassContext, feature_fill, register
from .helpers import domain_tables
from .podtopologyspread import groups_matching

# Existing-term categories (intern.term_id).
CAT_REQ_AFF, CAT_REQ_ANTI, CAT_PREF_AFF, CAT_PREF_ANTI = 0, 1, 2, 3


def _term_matches_pod(term_key, pod: t.Pod, ns_labels: dict[str, dict[str, str]]) -> bool:
    """AffinityTerm.Matches (framework/types.go:479): namespace membership or
    namespaceSelector over the pod's namespace labels, AND label selector."""
    _cat, _w, _topo, ns_tuple, ns_sel, selector = term_key
    ns_ok = pod.namespace in ns_tuple or (
        ns_sel is not None
        and t.label_selector_matches(ns_sel, ns_labels.get(pod.namespace, {}))
    )
    return ns_ok and t.label_selector_matches(selector, pod.metadata.labels)


def _term_group_ns_ids(term: t.PodAffinityTerm, pod: t.Pod, fctx: FeaturizeContext):
    """Namespace-id set an incoming pod's term selects."""
    it = fctx.interns
    ns = set(term.namespaces)
    if not ns and term.namespace_selector is None:
        ns = {pod.namespace}
    ids = {it.namespaces.id(n) for n in ns}
    if term.namespace_selector is not None:
        # Evaluate the selector over every namespace any group references.
        nsl = fctx.builder.namespace_labels
        for nid in range(len(it.namespaces)):
            name = it.namespaces.value(nid)
            if t.label_selector_matches(term.namespace_selector, nsl.get(name, {})):
                ids.add(nid)
    return ids


def _own_term_feats(
    terms, pod: t.Pod, fctx: FeaturizeContext, prefix: str, weights=None
) -> dict:
    """Compile the incoming pod's terms: per-term topo slot + group bitmask."""
    builder = fctx.builder
    dim = _bucket(max(len(terms), 1), 1)
    valid = np.zeros(dim, np.bool_)
    slots = np.zeros(dim, np.int32)
    masks = np.zeros((dim, builder.schema.G), np.bool_)
    wvec = np.zeros(dim, np.int64)
    for i, term in enumerate(terms):
        valid[i] = True
        slots[i] = builder.ensure_topo_key(term.topology_key)
        ns_ids = _term_group_ns_ids(term, pod, fctx)
        m = groups_matching(fctx.interns, builder.schema.G, ns_ids, term.label_selector)
        masks[i, : m.shape[0]] = m
        if weights is not None:
            wvec[i] = weights[i]
    host = np.zeros(dim, np.bool_)
    for i, term in enumerate(terms):
        host[i] = term.topology_key == fctx.interns.HOSTNAME_KEY
    out = {
        f"{prefix}_valid": valid,
        f"{prefix}_slot": slots,
        f"{prefix}_groups": masks,
        f"{prefix}_host": host,
    }
    if weights is not None:
        out[f"{prefix}_w"] = wvec
    return out


def featurize(pod: t.Pod, fctx: FeaturizeContext) -> dict:
    it = fctx.interns
    builder = fctx.builder
    aff = pod.spec.affinity
    pa = aff.pod_affinity if aff else None
    paa = aff.pod_anti_affinity if aff else None
    req_aff = list(pa.required) if pa else []
    req_anti = list(paa.required) if paa else []
    pref = [(wt.term, wt.weight) for wt in (pa.preferred if pa else ())]
    pref += [(wt.term, -wt.weight) for wt in (paa.preferred if paa else ())]

    feats = _own_term_feats(req_aff, pod, fctx, "ipa_ra")
    feats.update(_own_term_feats(req_anti, pod, fctx, "ipa_rs"))
    feats.update(
        _own_term_feats(
            [term for term, _ in pref], pod, fctx, "ipa_pf", [w for _, w in pref]
        )
    )
    # Required affinity counts pods matching ALL terms (podMatchesAllAffinityTerms)
    # — intersect the per-term group masks.
    if req_aff:
        allmask = feats["ipa_ra_groups"][: len(req_aff)].all(axis=0)
    else:
        allmask = np.zeros(builder.schema.G, np.bool_)
    feats["ipa_ra_allmask"] = allmask
    # podMatchesAllAffinityTerms(pod's own terms, pod) for the lonely-first-pod
    # exception (filtering.go:345).
    feats["ipa_ra_self"] = np.bool_(
        bool(req_aff)
        and all(
            _term_matches_pod(
                term_key(CAT_REQ_AFF, 0, term, pod.namespace), pod, builder.namespace_labels
            )
            for term in req_aff
        )
    )

    # Match the pod against every interned existing-pod term.
    builder._ensure(ET=max(len(it.terms), 1))
    et = builder.schema.ET
    et_match = np.zeros(et, np.bool_)
    et_anti = np.zeros(et, np.bool_)
    et_w = np.zeros(et, np.int64)
    et_slot = np.zeros(et, np.int32)
    et_host = np.zeros(et, np.bool_)
    hard_w = fctx.profile.hard_pod_affinity_weight if fctx.profile else 1
    for tid in range(len(it.terms)):
        key = it.terms.value(tid)
        cat, weight, topo_key = key[0], key[1], key[2]
        et_slot[tid] = builder.ensure_topo_key(topo_key)
        et_host[tid] = topo_key == it.HOSTNAME_KEY
        if not _term_matches_pod(key, pod, builder.namespace_labels):
            continue
        et_match[tid] = True
        if cat == CAT_REQ_ANTI:
            et_anti[tid] = True
        elif cat == CAT_REQ_AFF:
            et_w[tid] = hard_w
        elif cat == CAT_PREF_AFF:
            et_w[tid] = weight
        elif cat == CAT_PREF_ANTI:
            et_w[tid] = -weight
    feats.update(
        ipa_et_match=et_match,
        ipa_et_anti=et_anti,
        ipa_et_w=et_w,
        ipa_et_slot=et_slot,
        ipa_et_host=et_host,
    )
    return feats


def _domain_tables(state, slots, counts, host, dv):
    """Per-term domain tallies gathered back per node: (T, N).

    ``counts`` (T, N) f32 contributions; nodes missing the term's topology
    key contribute nothing (the reference's map update skips them).
    ``host`` (T,) marks hostname-key terms: their domains are single nodes
    (the hostname vocabulary is excluded from DV), so the tally at a node is
    the node's own count — no domain table."""
    vals, key_present, masked, tbl = domain_tables(state, slots, counts, dv)
    gathered = jnp.take_along_axis(tbl, jnp.clip(vals, 0, dv - 1), axis=1)
    at_node = jnp.where(host[:, None], masked, gathered)  # (T, N)
    return vals, key_present, masked, at_node


def _affinity_ok(state, pf, ctx: PassContext):
    """Incoming required-affinity check (2) — its failures are
    UnschedulableAndUnresolvable (ErrReasonAffinityRulesNotMatch)."""
    gc = state.group_counts.astype(jnp.float32)
    ra_valid = pf["ipa_ra_valid"]  # (RA,)
    any_ra = ra_valid.any()
    cnt_all = pf["ipa_ra_allmask"].astype(jnp.float32) @ gc  # (N,)
    ra_counts = jnp.broadcast_to(cnt_all[None, :], (ra_valid.shape[0], cnt_all.shape[0]))
    _v, key_ra, masked_ra, at_ra = _domain_tables(
        state, pf["ipa_ra_slot"], ra_counts, pf["ipa_ra_host"], ctx.schema.DV
    )
    keys_ok = (key_ra | ~ra_valid[:, None]).all(0)
    pods_exist = ((at_ra > 0.5) | ~ra_valid[:, None]).all(0)
    # len(affinityCounts) == 0 ⟺ no key-bearing node hosts a matching pod.
    counts_empty = jnp.sum(jnp.where(ra_valid[:, None], masked_ra, 0.0)) == 0
    return ~any_ra | (keys_ok & (pods_exist | (counts_empty & pf["ipa_ra_self"])))


def filter_fn(state, pf, ctx: PassContext):
    gc = state.group_counts.astype(jnp.float32)  # (G, N)
    dv = ctx.schema.DV

    # (1) Existing pods' required anti-affinity.
    active_e = pf["ipa_et_match"] & pf["ipa_et_anti"]  # (ET,)
    carriers = state.et_counts.astype(jnp.float32)  # (ET, N)
    _v, key_e, _m, at_node_e = _domain_tables(
        state, pf["ipa_et_slot"], carriers, pf["ipa_et_host"], dv
    )
    fail_existing = (active_e[:, None] & key_e & (at_node_e > 0.5)).any(0)

    # (2) Incoming required affinity.
    aff_ok = _affinity_ok(state, pf, ctx)

    # (3) Incoming required anti-affinity.
    rs_valid = pf["ipa_rs_valid"]
    cnt_rs = pf["ipa_rs_groups"].astype(jnp.float32) @ gc  # (RS, N)
    _v, key_rs, _m, at_rs = _domain_tables(
        state, pf["ipa_rs_slot"], cnt_rs, pf["ipa_rs_host"], dv
    )
    fail_anti = (rs_valid[:, None] & key_rs & (at_rs > 0.5)).any(0)

    return ~fail_existing & aff_ok & ~fail_anti


def hard_filter_fn(state, pf, ctx: PassContext):
    return ~_affinity_ok(state, pf, ctx)


def score_fn(state, pf, ctx: PassContext, feasible):
    gc = state.group_counts.astype(jnp.float32)
    dv = ctx.schema.DV

    # Incoming pod's preferred terms: ±w × (matching pods in the node's domain).
    pf_valid = pf["ipa_pf_valid"]
    cnt_p = pf["ipa_pf_groups"].astype(jnp.float32) @ gc  # (PP, N)
    _v, key_p, _m, at_p = _domain_tables(
        state, pf["ipa_pf_slot"], cnt_p, pf["ipa_pf_host"], dv
    )
    raw = jnp.sum(
        jnp.where(pf_valid[:, None] & key_p, at_p, 0.0)
        * pf["ipa_pf_w"][:, None].astype(jnp.float32),
        axis=0,
    )

    # Existing pods' terms matching the incoming pod: carriers in the node's
    # domain × signed weight (hard affinity / preferred ±w).
    active_e = pf["ipa_et_match"] & (pf["ipa_et_w"] != 0)
    carriers = state.et_counts.astype(jnp.float32)
    _v, key_e, _m, at_e = _domain_tables(
        state, pf["ipa_et_slot"], carriers, pf["ipa_et_host"], dv
    )
    raw += jnp.sum(
        jnp.where(active_e[:, None] & key_e, at_e, 0.0)
        * pf["ipa_et_w"][:, None].astype(jnp.float32),
        axis=0,
    )
    raw = raw.astype(jnp.int64)

    big = jnp.int64(2**62)
    mn = jnp.min(jnp.where(feasible, raw, big))
    mx = jnp.max(jnp.where(feasible, raw, -big))
    diff = mx - mn
    norm = jnp.where(
        diff > 0, MAX_NODE_SCORE * (raw - mn) // jnp.maximum(diff, 1), 0
    )
    return jnp.where(feasible, norm, 0)


for _k, _fill in [
    ("ipa_ra_valid", 0), ("ipa_ra_slot", 0), ("ipa_ra_groups", 0),
    ("ipa_ra_allmask", 0), ("ipa_ra_self", 0), ("ipa_ra_host", 0),
    ("ipa_rs_valid", 0), ("ipa_rs_slot", 0), ("ipa_rs_groups", 0), ("ipa_rs_host", 0),
    ("ipa_pf_valid", 0), ("ipa_pf_slot", 0), ("ipa_pf_groups", 0), ("ipa_pf_w", 0),
    ("ipa_pf_host", 0),
    ("ipa_et_match", 0), ("ipa_et_anti", 0), ("ipa_et_w", 0), ("ipa_et_slot", 0),
    ("ipa_et_host", 0),
]:
    feature_fill(_k, _fill)

def is_active(pod: t.Pod, fctx: FeaturizeContext) -> bool:
    # Inactive only when the pod has no pod (anti-)affinity AND no existing
    # pod carries any term (existing pods' terms score/filter incoming pods
    # regardless of the incoming spec — PreFilter Skip, filtering.go:257).
    if len(fctx.interns.terms) > 0:
        return True
    aff = pod.spec.affinity
    return bool(aff and (aff.pod_affinity or aff.pod_anti_affinity))


register(
    OpDef(
        name="InterPodAffinity",
        featurize=featurize,
        filter=filter_fn,
        score=score_fn,
        hard_filter=hard_filter_fn,
        is_active=is_active,
    )
)
