"""NodeResourcesFit + NodeResourcesBalancedAllocation, vectorized.

Reference semantics:
  * Filter — fitsRequest (plugins/noderesources/fit.go:488–560): pod count,
    then for each requested resource, request ≤ allocatable − requested(node).
    A resource the pod does not request never fails.
  * Score — strategy scorers (least_allocated.go / most_allocated.go /
    requested_to_capacity_ratio.go) over NonZeroRequested for cpu/memory and
    Requested for other resources (resource_allocation.go:89–114).
  * BalancedAllocation — 1 − std of resource utilization fractions
    (balanced_allocation.go:138 balancedResourceScorer), over plain Requested.

The per-node Go loop becomes a handful of (N,)/(N,R) int64 vector ops; the
whole node axis is evaluated in one shot on the VPU.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..api import types as t
from ..framework.config import (
    LEAST_ALLOCATED,
    MAX_NODE_SCORE,
    MOST_ALLOCATED,
    REQUESTED_TO_CAPACITY_RATIO,
)
from .common import FeaturizeContext, OpDef, PassContext, register

# Kind tags for strategy resource columns: where the "requested" number for a
# resource comes from (resource_allocation.go:89 calculateResourceAllocatableRequest).
_KIND_NONZERO_CPU = 0  # NodeInfo.NonZeroRequested.MilliCPU
_KIND_NONZERO_MEM = 1  # NodeInfo.NonZeroRequested.Memory
_KIND_REQ_COL = 2  # NodeInfo.Requested column


def strategy_columns(profile, builder_res_col: dict[str, int]):
    """Resolve the scoring strategy's resource list to (kind, col, weight)."""
    out = []
    for name, weight in profile.scoring_strategy.resources:
        if name == t.CPU:
            out.append((_KIND_NONZERO_CPU, 0, weight))
        elif name == t.MEMORY:
            out.append((_KIND_NONZERO_MEM, 1, weight))
        else:
            col = builder_res_col.get(name)
            if col is not None:
                out.append((_KIND_REQ_COL, col, weight))
    return tuple(out)


def featurize(pod: t.Pod, fctx: FeaturizeContext) -> dict:
    # Base req/nonzero features are provided by the engine; nothing extra here.
    return {}


def filter_fn(state, pf, ctx: PassContext):
    # Pod count check always applies (fit.go:491).
    fits = state.num_pods + 1 <= state.allowed_pods
    req = pf["req"]  # (R,) i64
    # NodeResourcesFitArgs.IgnoredResources: zero the demand in the FIT
    # check only — bind-time accounting still charges the full delta
    # (fit.go:488 skips ignoredExtendedResources in fitsRequest).
    ig = ctx.static.get("fit_ignored_cols", ()) if ctx.static else ()
    req_fit = req.at[np.array(ig, np.int32)].set(0) if ig else req
    free = state.alloc - state.req  # (N, R)
    fits &= jnp.all((req_fit[None, :] == 0) | (req_fit[None, :] <= free), axis=1)
    if ctx.nom is not None:
        # Nominated-pod accounting (RunFilterPluginsWithNominatedPods,
        # runtime/framework.go:973): the pod must ALSO fit with nominated
        # pods' resources counted on their nominated nodes.  Applied per
        # node when the pod's priority ≤ the node's max nominated priority
        # (conservative: the reference adds only the ≥-priority subset).
        # The pod's own nomination is excluded (framework.go skips same-UID).
        nom_req, nom_cnt, nom_prio = ctx.nom
        n = state.alloc.shape[0]
        own = pf["nominated_row"]
        self_mask = (jnp.arange(n) == own) & (own >= 0)
        eff_req = jnp.maximum(
            nom_req - jnp.where(self_mask[:, None], req[None, :], 0), 0
        )
        eff_cnt = jnp.maximum(nom_cnt - self_mask.astype(jnp.int32), 0)
        fits_nom = jnp.all(
            (req_fit[None, :] == 0) | (req_fit[None, :] <= free - eff_req), axis=1
        )
        fits_nom &= state.num_pods + 1 + eff_cnt <= state.allowed_pods
        applies = pf["priority"] <= nom_prio  # (N,)
        fits &= fits_nom | ~applies
    return fits


def _requested_totals(state, pf, cols):
    """Per strategy resource: (alloc (N,), requested-including-pod (N,))."""
    out = []
    for kind, col, weight in cols:
        if kind == _KIND_NONZERO_CPU:
            alloc = state.alloc[:, 0]
            reqd = state.nonzero_req[:, 0] + pf["nonzero"][0]
        elif kind == _KIND_NONZERO_MEM:
            alloc = state.alloc[:, 1]
            reqd = state.nonzero_req[:, 1] + pf["nonzero"][1]
        else:
            alloc = state.alloc[:, col]
            reqd = state.req[:, col] + pf["req"][col]
        out.append((alloc, reqd, weight))
    return out


def _least_requested(alloc, reqd):
    # least_allocated.go:97 — ((capacity-requested)*MaxNodeScore)/capacity,
    # 0 when capacity == 0 or requested > capacity. Int64 truncating division.
    ok = (alloc > 0) & (reqd <= alloc)
    safe_alloc = jnp.maximum(alloc, 1)
    return jnp.where(ok, ((alloc - reqd) * MAX_NODE_SCORE) // safe_alloc, 0)


def _most_requested(alloc, reqd):
    # most_allocated.go — requested*MaxNodeScore/capacity, 0 outside [0, cap].
    ok = (alloc > 0) & (reqd <= alloc)
    safe_alloc = jnp.maximum(alloc, 1)
    return jnp.where(ok, (reqd * MAX_NODE_SCORE) // safe_alloc, 0)


def _ratio_scorer(shape):
    """BuildBrokenLinearFunction over (utilization%, score 0..10) points,
    scaled to MaxNodeScore (requested_to_capacity_ratio.go)."""
    xs = np.array([p[0] for p in shape], np.float64)
    ys = np.array([p[1] for p in shape], np.float64)

    def f(alloc, reqd):
        util = jnp.where(
            alloc > 0, (reqd * 100.0) / jnp.maximum(alloc, 1).astype(jnp.float64), 0.0
        )
        raw = jnp.interp(util, jnp.asarray(xs), jnp.asarray(ys))
        ok = (alloc > 0) & (reqd <= alloc)
        return jnp.where(ok, (raw * (MAX_NODE_SCORE / 10)).astype(jnp.int64), 0)

    return f


def score_fn(state, pf, ctx: PassContext, feasible=None):
    cols = ctx.static["fit_strategy_cols"]
    strat = ctx.profile.scoring_strategy.type
    if strat == REQUESTED_TO_CAPACITY_RATIO:
        scorer = _ratio_scorer(ctx.profile.scoring_strategy.shape)
    elif strat == MOST_ALLOCATED:
        scorer = _most_requested
    else:
        assert strat == LEAST_ALLOCATED, strat
        scorer = _least_requested
    node_score = jnp.zeros(ctx.schema.N, jnp.int64)
    weight_sum = jnp.zeros(ctx.schema.N, jnp.int64)
    for alloc, reqd, weight in _requested_totals(state, pf, cols):
        # `if allocable[i] == 0 { continue }` skips the weight too
        # (least_allocated.go:72) — weightSum varies per node.
        present = alloc > 0
        node_score += jnp.where(present, scorer(alloc, reqd) * weight, 0)
        weight_sum += jnp.where(present, weight, 0)
    return jnp.where(weight_sum > 0, node_score // jnp.maximum(weight_sum, 1), 0)


def balanced_score_fn(state, pf, ctx: PassContext, feasible=None):
    """balancedResourceScorer: fractions of Requested/Allocatable (capped at
    1), score = (1 − std) * MaxNodeScore.  Uses plain Requested (useRequested,
    balanced_allocation.go:135) — no nonzero defaults."""
    cols = ctx.static["balanced_cols"]
    fracs = []
    present = []
    for col, in cols:
        alloc = state.alloc[:, col]
        reqd = state.req[:, col] + pf["req"][col]
        f = jnp.minimum(reqd.astype(jnp.float64) / jnp.maximum(alloc, 1).astype(jnp.float64), 1.0)
        fracs.append(jnp.where(alloc > 0, f, 0.0))
        present.append(alloc > 0)
    fr = jnp.stack(fracs)  # (C, N)
    pres = jnp.stack(present)  # (C, N)
    count = pres.sum(axis=0)
    # Exactly two resources → std = |f0 - f1| / 2 (balanced_allocation.go:155);
    # otherwise root of mean squared deviation. With per-node presence masks we
    # compute both and select.
    mean = jnp.where(count > 0, fr.sum(0) / jnp.maximum(count, 1), 0.0)
    var = jnp.where(pres, (fr - mean[None, :]) ** 2, 0.0).sum(0) / jnp.maximum(count, 1)
    std_general = jnp.sqrt(var)
    # two-resource shortcut: requires identifying the two present fractions;
    # when count == 2, sum of |f - mean| / 2 over present == |f0-f1|/2.
    std_two = jnp.where(pres, jnp.abs(fr - mean[None, :]), 0.0).sum(0) / 2.0
    std = jnp.where(count == 2, std_two, jnp.where(count > 2, std_general, 0.0))
    return ((1.0 - std) * MAX_NODE_SCORE).astype(jnp.int64)


def static_features(profile, schema, builder_res_col: dict[str, int]) -> dict:
    """Static (non-tensor) per-profile config the score fns need."""
    from ..snapshot import FIXED_RESOURCES

    ignored = set(profile.fit_ignored_resources)
    groups = set(profile.fit_ignored_resource_groups)
    return {
        "fit_strategy_cols": strategy_columns(profile, builder_res_col),
        "balanced_cols": tuple(
            (builder_res_col[name],)
            for name, _ in profile.scoring_strategy.resources
            if name in builder_res_col
        ),
        # Only EXTENDED resources may be ignored (fit.go:488; built-ins are
        # always checked).  Groups match the "<group>/<name>" prefix.
        "fit_ignored_cols": tuple(
            sorted(
                col
                for name, col in builder_res_col.items()
                if name not in FIXED_RESOURCES
                and (
                    name in ignored
                    or ("/" in name and name.split("/", 1)[0] in groups)
                )
            )
        ),
    }


register(
    OpDef(
        name="NodeResourcesFit",
        featurize=featurize,
        filter=filter_fn,
        score=score_fn,
        static=static_features,
    )
)
register(
    OpDef(
        name="NodeResourcesBalancedAllocation",
        score=balanced_score_fn,
        static=static_features,
    )
)
