"""Volume plugins, vectorized: VolumeBinding, VolumeZone,
VolumeRestrictions, NodeVolumeLimits.

Reference semantics:
  * VolumeBinding (plugins/volumebinding/volume_binding.go): bound claims
    restrict the pod to nodes matching each PV's node affinity; unbound
    claims with a WaitForFirstConsumer class need, per claim, a matching
    static PV whose affinity fits the node, or a provisioner whose
    StorageClass allowedTopologies fit; unbound Immediate claims are
    UnschedulableAndUnresolvable.  The actual binding (PreBind) happens
    host-side after the pick (volumes.VolumeCatalog.bind_pod_volumes).
  * VolumeZone (plugins/volumezone/volume_zone.go): each bound PV's
    zone/region labels (``__``-separated value sets) must match the node.
  * VolumeRestrictions (plugins/volumerestrictions/volume_restrictions.go):
    an in-tree device volume conflicts with an existing use on the node
    unless both sides are read-only; a ReadWriteOncePod claim already used
    by another pod is Unschedulable everywhere.
  * NodeVolumeLimits (plugins/nodevolumelimits/csi.go): per CSI driver,
    attached volumes + the pod's new volumes must stay within the CSINode
    allocatable count.

TPU design: all string/object work happens at featurize time against the
host VolumeCatalog.  PV affinities and zone labels compile into the same
requirement-program encoding NodeAffinity uses, with one extra *group* axis:
each claim (or bound PV) is an OR-group of terms and the node must satisfy
every group — evaluated as one broadcast + a segment-style group reduction.
Device conflicts and attach limits read per-node count tensors maintained by
the same commit deltas that move resources.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..api import types as t
from ..snapshot import _bucket
from .common import FeaturizeContext, OpDef, PassContext, feature_fill, invert_filter, register
from .nodeaffinity import _Program, _eval_terms


class _GroupedProgram(_Program):
    """Requirement program whose terms belong to AND-ed OR-groups."""

    def __init__(self) -> None:
        super().__init__()
        self.groups: list[int] = []  # group id per term
        self.n_groups = 0

    def start_group(self) -> int:
        gid = self.n_groups
        self.n_groups += 1
        return gid

    def add_group_term(self, gid: int, term: t.NodeSelectorTerm, it) -> None:
        before = len(self.terms)
        self.add_term(term, it)
        if len(self.terms) > before:
            self.groups.append(gid)

    def add_group_true(self, gid: int) -> None:
        """A term that matches every node (PV without node affinity)."""
        self.terms.append([])
        self.groups.append(gid)

    def tensors(self, prefix: str) -> dict:
        # The term axis must cover every group id so _eval_grouped's
        # existence check sees term-less (unsatisfiable) groups too.
        out = super().tensors(prefix, min_terms=self.n_groups)
        gdim = out[f"{prefix}_op"].shape[0]
        groups = np.full(gdim, -1, np.int32)
        groups[: len(self.groups)] = self.groups
        out[f"{prefix}_group"] = groups
        out[f"{prefix}_ngroups"] = np.int32(self.n_groups)
        return out


def _eval_grouped(state, pf, prefix: str) -> jnp.ndarray:
    """(N,) bool: every group has ≥1 matching valid term."""
    term_match = _eval_terms(
        state, pf[f"{prefix}_op"], pf[f"{prefix}_key"],
        pf[f"{prefix}_vals"], pf[f"{prefix}_int"],
    )  # (T, N)
    term_match &= pf[f"{prefix}_term_valid"][:, None]
    groups = pf[f"{prefix}_group"]  # (T,) -1 pad
    n_groups = pf[f"{prefix}_ngroups"]
    t_dim = groups.shape[0]
    # Group satisfaction via max over the group's terms: one-hot matmul keeps
    # shapes static (group count ≤ term count).
    onehot = (groups[:, None] == jnp.arange(t_dim)[None, :]) & (groups >= 0)[:, None]
    grp_any = (onehot[:, :, None] & term_match[:, None, :]).any(0)  # (T, N)
    grp_exists = jnp.arange(t_dim)[:, None] < n_groups
    return (grp_any | ~grp_exists).all(0)


# --------------------------------------------------------------------------
# VolumeBinding
# --------------------------------------------------------------------------


def _vb_featurize(pod: t.Pod, fctx: FeaturizeContext) -> dict:
    cat = fctx.builder.volumes
    it = fctx.interns
    prog = _GroupedProgram()
    feasible = True
    for pvc in cat.pod_pvcs(pod):
        if pvc is None:
            feasible = False
            break
        kind, *rest = cat.classify(pvc)
        if kind in ("lost", "unbound_immediate"):
            feasible = False
            break
        if kind == "bound":
            pv = rest[0]
            gid = prog.start_group()
            if pv.node_affinity is None or not pv.node_affinity.terms:
                prog.add_group_true(gid)
            else:
                for term in pv.node_affinity.terms:
                    prog.add_group_term(gid, term, it)
        else:  # delayed
            candidates, sc = rest
            gid = prog.start_group()
            for pv in candidates:
                if pv.node_affinity is None or not pv.node_affinity.terms:
                    prog.add_group_true(gid)
                else:
                    for term in pv.node_affinity.terms:
                        prog.add_group_term(gid, term, it)
            from ..volumes import NO_PROVISIONER

            if sc.provisioner != NO_PROVISIONER:
                if sc.allowed_topologies is None or not sc.allowed_topologies.terms:
                    prog.add_group_true(gid)
                else:
                    for term in sc.allowed_topologies.terms:
                        prog.add_group_term(gid, term, it)
            # No candidates and no provisioner → empty group → infeasible
            # everywhere (correct: nothing can satisfy the claim yet).
    feats = prog.tensors("vb")
    feats["vb_feasible"] = np.bool_(feasible)
    return feats


def _vb_filter(state, pf, ctx: PassContext):
    return pf["vb_feasible"] & _eval_grouped(state, pf, "vb")


def _vb_hard(state, pf, ctx: PassContext):
    # Lost/unbound-immediate claims are UnschedulableAndUnresolvable; PV
    # affinity mismatches are too (deleting pods moves no volume).
    return ~_vb_filter(state, pf, ctx)


def _vb_active(pod: t.Pod, fctx: FeaturizeContext) -> bool:
    return any(v.pvc for v in pod.spec.volumes)


# --------------------------------------------------------------------------
# VolumeZone
# --------------------------------------------------------------------------


def _vz_featurize(pod: t.Pod, fctx: FeaturizeContext) -> dict:
    cat = fctx.builder.volumes
    it = fctx.interns
    prog = _GroupedProgram()
    feasible = True
    for pvc in cat.pod_pvcs(pod):
        if pvc is None:
            feasible = False
            break
        kind, *rest = cat.classify(pvc)
        if kind in ("lost", "unbound_immediate"):
            feasible = False
            break
        if kind != "bound":
            continue  # delayed claims are VolumeBinding's business
        reqs = cat.zone_requirements(rest[0])
        if reqs:
            gid = prog.start_group()
            prog.add_group_term(
                gid, t.NodeSelectorTerm(match_expressions=tuple(reqs)), it
            )
    feats = prog.tensors("vz")
    feats["vz_feasible"] = np.bool_(feasible)
    return feats


def _vz_filter(state, pf, ctx: PassContext):
    return pf["vz_feasible"] & _eval_grouped(state, pf, "vz")


def _vz_active(pod: t.Pod, fctx: FeaturizeContext) -> bool:
    return any(v.pvc for v in pod.spec.volumes)


# --------------------------------------------------------------------------
# VolumeRestrictions
# --------------------------------------------------------------------------


def _vr_featurize(pod: t.Pod, fctx: FeaturizeContext) -> dict:
    cat = fctx.builder.volumes
    # ReadWriteOncePod: any other pod already using the claim blocks
    # scheduling everywhere (volume_restrictions.go isRWOPConflict).
    rwop_ok = True
    for pvc in cat.pod_pvcs(pod):
        if pvc is not None and t.RWOP in pvc.access_modes:
            if cat.pvc_users.get(pvc.uid, 0) > 0:
                rwop_ok = False
                break
    return {"vr_rwop_ok": np.bool_(rwop_ok)}


def _vr_filter(state, pf, ctx: PassContext):
    ids = pf["vol_dev_ids"]  # (S,) engine base features
    active = ids >= 0
    safe = jnp.maximum(ids, 0)
    uses = state.dev_counts[safe]  # (S, N)
    rw_uses = state.dev_rw_counts[safe]
    ro = ~pf["vol_dev_rw"]
    # Read-only want: conflicts only with a writer; writer want: any use.
    conflict = jnp.where(ro[:, None], rw_uses > 0, uses > 0) & active[:, None]
    return pf["vr_rwop_ok"] & ~conflict.any(0)


def _vr_active(pod: t.Pod, fctx: FeaturizeContext) -> bool:
    return any(v.device_id or v.pvc for v in pod.spec.volumes)


# --------------------------------------------------------------------------
# NodeVolumeLimits
# --------------------------------------------------------------------------


def _nvl_filter(state, pf, ctx: PassContext):
    """Attach-limit check by DISTINCT volume (csi.go:219): the pod's volumes
    already attached to the node (csivol_counts > 0) do not count again."""
    ids = pf["vol_csi_ids"]  # (S,) engine base features, -1 pad
    act = ids >= 0
    present = state.csivol_counts[jnp.maximum(ids, 0)] > 0  # (S, N)
    newv = act[:, None] & ~present  # (S, N) — genuinely new attachments
    dr = state.csi_used.shape[0]
    drv_oh = (pf["vol_csi_drv"][:, None] == jnp.arange(dr)[None, :]) & act[:, None]
    new_cnt = (drv_oh[:, :, None] & newv[:, None, :]).sum(0)  # (DR, N)
    ok = state.csi_used + new_cnt <= state.csi_limit
    return (ok | (new_cnt == 0)).all(0)


def _nvl_active(pod: t.Pod, fctx: FeaturizeContext) -> bool:
    return any(v.pvc for v in pod.spec.volumes) and len(fctx.interns.drivers) > 0


for _k, _fill in [
    ("vb_op", -1), ("vb_key", -1), ("vb_vals", -1), ("vb_int", 0),
    ("vb_term_valid", 0), ("vb_group", -1), ("vb_ngroups", 0), ("vb_feasible", 1),
    ("vz_op", -1), ("vz_key", -1), ("vz_vals", -1), ("vz_int", 0),
    ("vz_term_valid", 0), ("vz_group", -1), ("vz_ngroups", 0), ("vz_feasible", 1),
    ("vr_rwop_ok", 1),
]:
    feature_fill(_k, _fill)

register(
    OpDef(
        name="VolumeBinding",
        featurize=_vb_featurize,
        filter=_vb_filter,
        hard_filter=_vb_hard,
        is_active=_vb_active,
    )
)
register(
    OpDef(
        name="VolumeZone",
        featurize=_vz_featurize,
        filter=_vz_filter,
        # Zone label mismatches are UnschedulableAndUnresolvable
        # (volume_zone.go ErrReasonConflict).
        hard_filter=invert_filter(_vz_filter),
        is_active=_vz_active,
    )
)
register(
    OpDef(
        name="VolumeRestrictions",
        featurize=_vr_featurize,
        filter=_vr_filter,
        is_active=_vr_active,
    )
)
register(
    OpDef(
        name="NodeVolumeLimits",
        filter=_nvl_filter,
        is_active=_nvl_active,
    )
)
