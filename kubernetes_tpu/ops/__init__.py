from . import common  # noqa: F401

# Importing an op module registers its OpDefs.
from . import (  # noqa: F401
    dynamicresources,
    imagelocality,
    interpodaffinity,
    learned,
    nodeaffinity,
    nodeports,
    noderesources,
    podtopologyspread,
    tainttoleration,
    throughput,
    trivial,
    volumes,
)
