from . import common  # noqa: F401

# Importing an op module registers its OpDefs.
from . import (  # noqa: F401
    dynamicresources,
    imagelocality,
    interpodaffinity,
    nodeaffinity,
    nodeports,
    noderesources,
    podtopologyspread,
    tainttoleration,
    trivial,
    volumes,
)
