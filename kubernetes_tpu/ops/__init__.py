from . import common  # noqa: F401

# Importing an op module registers its OpDefs.
from . import noderesources, trivial  # noqa: F401
