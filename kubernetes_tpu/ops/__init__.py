from . import common  # noqa: F401

# Importing an op module registers its OpDefs.
from . import nodeports, noderesources, tainttoleration, trivial  # noqa: F401
