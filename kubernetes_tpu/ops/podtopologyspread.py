"""PodTopologySpread, vectorized.

Reference (plugins/podtopologyspread/):
  * Filter (filtering.go:283): for each DoNotSchedule constraint, the
    candidate node must carry the topology key, and
    ``matchNum + selfMatch − minMatchNum ≤ maxSkew`` where matchNum counts
    selector-matching pods in the candidate's topology domain and minMatchNum
    is the global minimum over existing domains (0 if fewer than minDomains
    domains exist; MaxInt32 when no eligible domain exists —
    newCriticalPaths, filtering.go:113).
  * Score (scoring.go): per ScheduleAnyway constraint, a node is credited
    ``cnt × log(topoSize+2) + (maxSkew−1)`` (scoreForCount :318) where cnt is
    the domain's matching-pod count (per-node count for the hostname key,
    :254); nodes missing a topology key are "ignored" → score 0; the final
    normalization maps to ``100 × (max + min − s) / max`` (:276).
  * Domain counting eligibility (filtering.go:262 processNode): nodes must
    carry all constraint topology keys, and per-constraint node inclusion
    policies apply (nodeAffinityPolicy Honor → pod's nodeSelector/required
    affinity; nodeTaintsPolicy Honor → pod tolerates the node's
    hard taints; defaults Honor/Ignore).

TPU design: pods with identical (namespace, labels) share an interned *group*;
the cluster state keeps per-(group, node) pod counts.  A constraint's selector
is compiled host-side to a (G,) group bitmask, so per-node matching-pod counts
are one f32 matmul ``(C,G) × (G,N)`` on the MXU.  Domains are interned
topology-value ids; per-domain sums/minima are segment reductions into a
(DV,)-bucketed table, gathered back per node.  Node-inclusion policies reuse
the NodeAffinity and TaintToleration ops' device filters on the same pod
features.

The DoNotSchedule constraint masks (``tps_h_groups`` — the ``tps_h``
prefix is the HARD subset) are load-bearing twice: the chunked pass's
conflict deferral (engine/pass_.py ``_conflict_pairs``) AND the
conflict-aware chunk packer's class derivation (engine/packing.py
``conflict_classes``) both consume them — renaming the key must update
both, or packed batches silently lose their sequential-equivalence
guarantee.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..api import types as t
from ..framework.config import MAX_NODE_SCORE
from ..snapshot import _bucket
from .common import FeaturizeContext, OpDef, PassContext, feature_fill, register
from .helpers import domain_tables
from . import nodeaffinity, tainttoleration

from ..intern import InternTable

HOSTNAME_KEY = InternTable.HOSTNAME_KEY
MAX_INT32 = np.int64(2**31 - 1)


def groups_matching(it, g_cap: int, ns_ids: set[int] | None, selector) -> np.ndarray:
    """(G,) bitmask of pod label-groups matched by ``selector`` within the
    given namespace-id set (None = any namespace) — the host-side analog of
    countPodsMatchSelector (podtopologyspread/common.go).  Scalar reference
    implementation; the hot paths use the vectorized
    GroupIndex.match_selector (intern.py), which must stay equivalent."""
    mask = np.zeros(g_cap, np.bool_)
    for gid in range(len(it.groups)):
        ns_id, fs = it.groups.value(gid)  # type: ignore[misc]
        if ns_ids is not None and ns_id not in ns_ids:
            continue
        if t.label_selector_matches(selector, dict(fs)):
            mask[gid] = True
    return mask


def _constraint_feats(
    constraints, pod: t.Pod, fctx: FeaturizeContext, prefix: str
) -> dict:
    it = fctx.interns
    builder = fctx.builder
    cdim = _bucket(max(len(constraints), 1), 1)
    ns_id = it.namespaces.id(pod.namespace)
    slots = np.zeros(cdim, np.int32)
    skew = np.ones(cdim, np.int32)
    mindom = np.ones(cdim, np.int32)
    selfm = np.zeros(cdim, np.bool_)
    hostname = np.zeros(cdim, np.bool_)
    honor_aff = np.zeros(cdim, np.bool_)
    honor_taint = np.zeros(cdim, np.bool_)
    valid = np.zeros(cdim, np.bool_)
    masks = np.zeros((cdim, builder.schema.G), np.bool_)
    # Gates (plfeature.Features analog): inclusion policies fall back to
    # the legacy fixed policy (honor affinity, ignore taints) when
    # NodeInclusionPolicyInPodTopologySpread is off; matchLabelKeys is
    # ignored when MatchLabelKeysInPodTopologySpread is off.
    incl = fctx.gates.enabled("NodeInclusionPolicyInPodTopologySpread")
    mlk = fctx.gates.enabled("MatchLabelKeysInPodTopologySpread")
    for i, c in enumerate(constraints):
        slot = builder.ensure_topo_key(c.topology_key)
        valid[i] = True
        slots[i] = slot
        skew[i] = c.max_skew
        mindom[i] = c.min_domains or 1
        sel = (
            t.spread_effective_selector(c, pod.metadata.labels)
            if mlk
            else c.label_selector
        )
        selfm[i] = t.label_selector_matches(sel, pod.metadata.labels)
        hostname[i] = c.topology_key == HOSTNAME_KEY
        honor_aff[i] = (c.node_affinity_policy == t.POLICY_HONOR) if incl else True
        honor_taint[i] = (c.node_taints_policy == t.POLICY_HONOR) if incl else False
        m = builder.group_index.match_selector(sel, {ns_id})
        masks[i, : m.shape[0]] = m
    return {
        f"{prefix}_valid": valid,
        f"{prefix}_slot": slots,
        f"{prefix}_skew": skew,
        f"{prefix}_mindom": mindom,
        f"{prefix}_self": selfm,
        f"{prefix}_hostname": hostname,
        f"{prefix}_aff": honor_aff,
        f"{prefix}_taint": honor_taint,
        f"{prefix}_groups": masks,
    }


def _effective_constraints(pod: t.Pod, fctx: FeaturizeContext):
    """Pod constraints, or the profile's defaultConstraints for pods without
    any (PodTopologySpreadArgs List defaulting, types_pluginargs.go:72).
    The reference derives each default's selector from the pod's owning
    services/replicasets (plugins/helper.DefaultSelector); without a
    controller model the analog is the pod's own full label set, and
    label-less pods skip defaulting (like selector-less defaults do)."""
    cons = pod.spec.topology_spread_constraints
    if cons:
        return cons
    prof = fctx.profile
    if prof is None or not prof.pts_default_constraints or not pod.metadata.labels:
        return cons
    import dataclasses

    sel = t.LabelSelector(
        match_labels=tuple(sorted(pod.metadata.labels.items()))
    )
    return tuple(
        dataclasses.replace(c, label_selector=sel)
        for c in prof.pts_default_constraints
    )


def featurize(pod: t.Pod, fctx: FeaturizeContext) -> dict:
    cons = _effective_constraints(pod, fctx)
    hard = [c for c in cons if c.when_unsatisfiable == t.DO_NOT_SCHEDULE]
    soft = [c for c in cons if c.when_unsatisfiable == t.SCHEDULE_ANYWAY]
    feats = _constraint_feats(hard, pod, fctx, "tps_h")
    feats.update(_constraint_feats(soft, pod, fctx, "tps_s"))
    # Node-inclusion policies are evaluated with the NodeAffinity and
    # TaintToleration device filters — their features must exist whenever
    # spread is active, even when those ops are absent from the profile or
    # batch-inactive (skipped by their is_active predicates).  When they ARE
    # batch-active the engine's op loop produces the identical keys already.
    if fctx.active is None or "NodeAffinity" not in fctx.active:
        feats.update(nodeaffinity.featurize(pod, fctx))
    if fctx.active is None or "TaintToleration" not in fctx.active:
        feats.update(tainttoleration.featurize(pod, fctx))
    return feats


def _per_constraint(state, pf, ctx: PassContext, prefix: str):
    """Shared geometry: values, key presence, counting eligibility, counts.

    Returns (valid (C,), vals (C,N), key_present (C,N), all_keys (N,),
    elig (C,N), cnt (C,N) f32)."""
    valid = pf[f"{prefix}_valid"]  # (C,)
    slots = pf[f"{prefix}_slot"]  # (C,)
    vals = jnp.take(state.topo_vals, slots, axis=1).T  # (C, N)
    key_present = vals >= 0
    all_keys = (key_present | ~valid[:, None]).all(0)  # (N,)
    na_ok = nodeaffinity.filter_fn(state, pf, ctx)  # (N,)
    taint_ok = tainttoleration.filter_fn(state, pf, ctx)  # (N,)
    elig = (
        state.valid[None, :]
        & all_keys[None, :]
        & jnp.where(pf[f"{prefix}_aff"][:, None], na_ok[None, :], True)
        & jnp.where(pf[f"{prefix}_taint"][:, None], taint_ok[None, :], True)
    )
    # Matching-pod counts per node: (C,G) × (G,N) matmul.  Counts are small
    # integers — f32 is exact far beyond any real pod count.
    cnt_raw = jnp.einsum(
        "cg,gn->cn",
        pf[f"{prefix}_groups"].astype(jnp.float32),
        state.group_counts.astype(jnp.float32),
    )
    cnt = jnp.where(elig, cnt_raw, 0.0)
    return valid, vals, key_present, all_keys, elig, cnt, cnt_raw


def _segment_tables(state, slots, elig, cnt, dv, onehot=None):
    """Per-domain totals and presence: (C, DV) tables (MXU matmuls).

    The counting-eligibility mask is per-pod (node-inclusion policies), so
    these stay per-step einsums — but over the engine's hoisted one-hot
    (ctx.dom.onehot), never rebuilding the (N, TK, DV) tensor in the scan."""
    _v, _k, _m, tbl = domain_tables(state, slots, cnt, dv, onehot)
    _v, _k, _m, pres = domain_tables(state, slots, elig.astype(jnp.float32), dv, onehot)
    return tbl, pres > 0.5


def _segment_presence(state, slots, mask, dv, onehot=None):
    """(C, DV) bool: domains containing a True-masked node."""
    _v, _k, _m, pres = domain_tables(state, slots, mask.astype(jnp.float32), dv, onehot)
    return pres > 0.5


def _onehot(ctx: PassContext):
    return ctx.dom.onehot if ctx.dom is not None else None


def filter_fn(state, pf, ctx: PassContext):
    valid, vals, key_present, _all_keys, elig, cnt, _raw = _per_constraint(
        state, pf, ctx, "tps_h"
    )
    host = pf["tps_h_hostname"]  # (C,)
    # Generic path: per-domain tables over the (hostname-free) DV vocabulary.
    tbl, present = _segment_tables(
        state, pf["tps_h_slot"], elig, cnt, ctx.schema.DV, _onehot(ctx)
    )
    tbl = tbl.astype(jnp.int64)
    min_g = jnp.min(jnp.where(present, tbl, MAX_INT32), axis=1)  # (C,)
    dom_g = present.sum(axis=1)
    # Table read-back as a one-hot MXU contraction, not a node-axis gather
    # (gathers are the TPU slow path; invalid vals have all-zero one-hot
    # rows and are masked by key_present downstream).  Contract over the
    # shared (N, TK·DV) one-hot via the slot one-hot — a per-pod take of
    # the table would materialize (N, C, DV) per batch lane.
    oh = _onehot(ctx)
    n_, tk_, dv_ = oh.shape
    slot_oh = (
        pf["tps_h_slot"][:, None] == jnp.arange(tk_)[None, :]
    ).astype(jnp.float32)
    tbl_kd = jnp.einsum(
        "cd,ck->ckd", tbl.astype(jnp.float32), slot_oh
    ).reshape(-1, tk_ * dv_)
    match_g = (tbl_kd @ oh.reshape(n_, tk_ * dv_).T).astype(jnp.int64)
    # Hostname fast path: every domain is a single node (its vocabulary is
    # excluded from DV), so counts/minima are per-node reductions.
    cnt_i = cnt.astype(jnp.int64)
    min_h = jnp.min(jnp.where(elig, cnt_i, MAX_INT32), axis=1)
    dom_h = elig.sum(axis=1)
    # Global minimum over existing domains; MaxInt32 when none exist
    # (newCriticalPaths) — then every skew check passes, like the reference.
    min_tbl = jnp.where(host, min_h, min_g)
    domains = jnp.where(host, dom_h, dom_g)
    match_n = jnp.where(host[:, None], cnt_i, match_g)  # (C, N)
    min_match = jnp.where(domains < pf["tps_h_mindom"], 0, min_tbl)
    skew = match_n + pf["tps_h_self"][:, None].astype(jnp.int64) - min_match[:, None]
    ok = key_present & (skew <= pf["tps_h_skew"][:, None])
    return (ok | ~valid[:, None]).all(0)


def score_fn(state, pf, ctx: PassContext, feasible):
    valid, vals, key_present, all_keys, elig, cnt, cnt_raw = _per_constraint(
        state, pf, ctx, "tps_s"
    )
    any_constraint = valid.any()
    # Pod-defined constraints require all topology keys on scored nodes
    # (requireAllTopologies, scoring.go:150); nodes missing one are "ignored"
    # and end at score 0 via the final `scored` mask.
    scored = feasible & all_keys

    tbl, _present = _segment_tables(
        state, pf["tps_s_slot"], elig, cnt, ctx.schema.DV, _onehot(ctx)
    )
    # Domains/topoSize count distinct pairs among *scored candidate* nodes
    # (initPreScoreState iterates filteredNodes); hostname topoSize is the
    # number of scored nodes.
    present_cand = _segment_presence(
        state,
        pf["tps_s_slot"],
        jnp.broadcast_to(scored[None, :], vals.shape),
        ctx.schema.DV,
        _onehot(ctx),
    )
    # One-hot contraction instead of a node-axis gather (see filter_fn).
    oh_s = _onehot(ctx)
    n_, tk_, dv_ = oh_s.shape
    slot_oh_s = (
        pf["tps_s_slot"][:, None] == jnp.arange(tk_)[None, :]
    ).astype(jnp.float32)
    tbl_kd_s = jnp.einsum(
        "cd,ck->ckd", tbl.astype(jnp.float32), slot_oh_s
    ).reshape(-1, tk_ * dv_)
    pair_cnt = (tbl_kd_s @ oh_s.reshape(n_, tk_ * dv_).T).astype(tbl.dtype)  # (C, N)
    # Hostname counts the node's own pods directly, with no counting-
    # eligibility mask (scoring.go:254 uses nodeInfo.Pods).
    cnt_for_node = jnp.where(pf["tps_s_hostname"][:, None], cnt_raw, pair_cnt)
    # Hostname topoSize = len(filteredNodes) − len(IgnoredNodes)
    # (scoring.go:104) = the scored set (feasible ∧ all keys present).
    topo_size = jnp.where(
        pf["tps_s_hostname"],
        scored.sum(),
        present_cand.sum(axis=1),
    )  # (C,)
    w = jnp.log(topo_size.astype(jnp.float64) + 2.0)
    term = key_present * (
        cnt_for_node.astype(jnp.float64) * w[:, None]
        + (pf["tps_s_skew"][:, None].astype(jnp.float64) - 1.0)
    )
    raw = jnp.where(valid[:, None], term, 0.0).sum(0)  # (N,)
    # math.Round semantics (half away from zero); terms are non-negative.
    raw = jnp.floor(raw + 0.5).astype(jnp.int64)

    big = jnp.int64(2**62)
    mn = jnp.min(jnp.where(scored, raw, big))
    mn = jnp.where(scored.any(), mn, 0)
    mx = jnp.max(jnp.where(scored, raw, 0))
    norm = jnp.where(
        mx == 0,
        MAX_NODE_SCORE,
        MAX_NODE_SCORE * (mx + mn - raw) // jnp.maximum(mx, 1),
    )
    norm = jnp.where(scored, norm, 0)
    # No soft constraints → plugin is skipped (PreScore returns Skip):
    # contribute 0 everywhere.
    return jnp.where(any_constraint, norm, 0)


for _k, _fill in [
    ("tps_h_valid", 0), ("tps_h_slot", 0), ("tps_h_skew", 1), ("tps_h_mindom", 1),
    ("tps_h_self", 0), ("tps_h_hostname", 0), ("tps_h_aff", 0), ("tps_h_taint", 0),
    ("tps_h_groups", 0),
    ("tps_s_valid", 0), ("tps_s_slot", 0), ("tps_s_skew", 1), ("tps_s_mindom", 1),
    ("tps_s_self", 0), ("tps_s_hostname", 0), ("tps_s_aff", 0), ("tps_s_taint", 0),
    ("tps_s_groups", 0),
]:
    feature_fill(_k, _fill)

def hard_filter_fn(state, pf, ctx: PassContext):
    """Missing topology keys are UnschedulableAndUnresolvable
    (filtering.go:337 ErrReasonNodeLabelNotMatch); skew violations are not."""
    valid = pf["tps_h_valid"]
    slots = pf["tps_h_slot"]
    vals = jnp.take(state.topo_vals, slots, axis=1).T
    return ((vals < 0) & valid[:, None]).any(0)


def is_active(pod: t.Pod, fctx: FeaturizeContext) -> bool:
    # No constraints: both PreFilter and PreScore return Skip
    # (filtering.go:152, scoring.go:140).  Profile defaultConstraints make
    # the op active for any labelled pod of the profile (cheap check only —
    # the derived constraints are built in featurize, not here).
    if pod.spec.topology_spread_constraints:
        return True
    prof = fctx.profile
    return bool(
        prof is not None
        and prof.pts_default_constraints
        and pod.metadata.labels
    )


register(
    OpDef(
        name="PodTopologySpread",
        featurize=featurize,
        filter=filter_fn,
        score=score_fn,
        hard_filter=hard_filter_fn,
        is_active=is_active,
    )
)
