"""NodeName and NodeUnschedulable — the two one-comparison filters.

Reference: plugins/nodename/node_name.go (pod.Spec.NodeName == node.Name) and
plugins/nodeunschedulable/node_unschedulable.go (node.Spec.Unschedulable,
unless the pod tolerates the node.kubernetes.io/unschedulable:NoSchedule
taint)."""

from __future__ import annotations

import numpy as np

from ..api import types as t
from .common import FeaturizeContext, OpDef, PassContext, invert_filter, register

UNSCHEDULABLE_TAINT = t.Taint(
    key="node.kubernetes.io/unschedulable", effect=t.EFFECT_NO_SCHEDULE
)


def nodename_featurize(pod: t.Pod, fctx: FeaturizeContext) -> dict:
    name = pod.spec.node_name
    nid = fctx.interns.node_names.get(name) if name else -1
    # A named node that does not exist matches no row: use -2 (never equals a
    # row's name_id, and != -1 which means "no constraint").
    if name and nid < 0:
        nid = -2
    return {"nodename_id": np.int32(nid)}


def nodename_filter(state, pf, ctx: PassContext):
    want = pf["nodename_id"]
    return (want == -1) | (state.name_id == want)


def unschedulable_featurize(pod: t.Pod, fctx: FeaturizeContext) -> dict:
    tolerated = any(tol.tolerates(UNSCHEDULABLE_TAINT) for tol in pod.spec.tolerations)
    return {"tolerates_unschedulable": np.bool_(tolerated)}


def unschedulable_filter(state, pf, ctx: PassContext):
    return ~state.unschedulable | pf["tolerates_unschedulable"]


register(
    OpDef(
        name="NodeName",
        featurize=nodename_featurize,
        filter=nodename_filter,
        hard_filter=invert_filter(nodename_filter),
    )
)
register(
    OpDef(
        name="NodeUnschedulable",
        featurize=unschedulable_featurize,
        filter=unschedulable_filter,
        hard_filter=invert_filter(unschedulable_filter),
    )
)
