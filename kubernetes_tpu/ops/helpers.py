"""Shared jax helpers for vectorized score ops."""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.config import MAX_NODE_SCORE


def default_normalize_score(raw: jnp.ndarray, feasible: jnp.ndarray, reverse: bool) -> jnp.ndarray:
    """Vectorized DefaultNormalizeScore (plugins/helper/normalize_score.go):
    rescale raw scores by the max over *feasible* nodes to [0, MaxNodeScore];
    with ``reverse`` higher raw scores map to lower results.  maxCount == 0
    short-circuits (all MaxNodeScore when reversed, all 0 otherwise)."""
    raw = raw.astype(jnp.int64)
    max_count = jnp.max(jnp.where(feasible, raw, 0))
    safe_max = jnp.maximum(max_count, 1)
    scaled = raw * MAX_NODE_SCORE // safe_max
    if reverse:
        scaled = MAX_NODE_SCORE - scaled
        return jnp.where(max_count == 0, MAX_NODE_SCORE, scaled)
    return jnp.where(max_count == 0, 0, scaled)


def make_topo_onehot(topo_vals: jnp.ndarray, dv: int) -> jnp.ndarray:
    """(N, TK, DV) f32 one-hot of the per-node topology values.  Scan-invariant
    (node topology never changes while a batch commits pods), so the engine
    computes it ONCE per device pass and closes the scan body over it — the
    hoist that turns the per-step domain reductions from O(N·TK·DV) rebuilds
    into cheap table gathers.  Hostname-key values exceed DV by design
    (excluded from the vocabulary); ops take a per-node fast path for them,
    and any hostname ids that happen to fall inside [0, DV) produce garbage
    table rows that every reader masks out via its ``host`` flags."""
    return (
        (topo_vals[:, :, None] == jnp.arange(dv)[None, None, :])
        & (topo_vals >= 0)[:, :, None]
    ).astype(jnp.float32)


def domain_tables(state, slots, counts, dv, onehot=None):
    """Per-term domain sums as MXU matmuls (no scatters).

    ``slots`` (T,) topology-key slot per term; ``counts`` (T, N) f32
    contributions.  Returns (vals (T,N), key_present (T,N), masked (T,N),
    tbl (T, DV)) where ``tbl[t, d] = Σ_n masked[t, n]·[vals[t, n] == d]``.
    The one-hot of topo_vals is shared across terms, so the reduction is one
    ``(T,N)×(N,TK·DV)`` einsum — scatter-free, which is what the TPU wants.
    Pass the engine's hoisted ``onehot`` (ctx.dom.onehot) so the scan does not
    rebuild it every step."""
    vals_all = state.topo_vals  # (N, TK)
    vals = jnp.take(vals_all, slots, axis=1).T  # (T, N)
    key_present = vals >= 0
    masked = jnp.where(key_present, counts, 0.0)
    if onehot is None:
        onehot = make_topo_onehot(vals_all, dv)
    tbl_all = jnp.einsum("tn,nkd->tkd", masked, onehot.astype(counts.dtype))
    tbl = jnp.take_along_axis(
        tbl_all, slots[:, None, None].astype(jnp.int32), axis=1
    )[:, 0, :]  # (T, DV)
    return vals, key_present, masked, tbl


def gather_mask(table: jnp.ndarray, ids: jnp.ndarray) -> jnp.ndarray:
    """table[ids] with -1-padded ids contributing False/0.

    ``table`` is a per-pod vocabulary mask (V,); ``ids`` node slot ids (N, S).
    """
    safe = jnp.maximum(ids, 0)
    return jnp.where(ids >= 0, table[safe], jnp.zeros((), table.dtype))
