"""Shared jax helpers for vectorized score ops."""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.config import MAX_NODE_SCORE


def default_normalize_score(raw: jnp.ndarray, feasible: jnp.ndarray, reverse: bool) -> jnp.ndarray:
    """Vectorized DefaultNormalizeScore (plugins/helper/normalize_score.go):
    rescale raw scores by the max over *feasible* nodes to [0, MaxNodeScore];
    with ``reverse`` higher raw scores map to lower results.  maxCount == 0
    short-circuits (all MaxNodeScore when reversed, all 0 otherwise)."""
    raw = raw.astype(jnp.int64)
    max_count = jnp.max(jnp.where(feasible, raw, 0))
    safe_max = jnp.maximum(max_count, 1)
    scaled = raw * MAX_NODE_SCORE // safe_max
    if reverse:
        scaled = MAX_NODE_SCORE - scaled
        return jnp.where(max_count == 0, MAX_NODE_SCORE, scaled)
    return jnp.where(max_count == 0, 0, scaled)


def gather_mask(table: jnp.ndarray, ids: jnp.ndarray) -> jnp.ndarray:
    """table[ids] with -1-padded ids contributing False/0.

    ``table`` is a per-pod vocabulary mask (V,); ``ids`` node slot ids (N, S).
    """
    safe = jnp.maximum(ids, 0)
    return jnp.where(ids >= 0, table[safe], jnp.zeros((), table.dtype))
