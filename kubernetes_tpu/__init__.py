"""kubernetes_tpu — a TPU-native scheduling framework.

Re-implements the capabilities of Kubernetes' kube-scheduler (reference:
tkashem/kubernetes, `pkg/scheduler/`) as a batched pod×node constraint engine
evaluated on-device with JAX/XLA.  The reference's goroutine-parallel Filter and
Score hot loops (`pkg/scheduler/schedule_one.go:591,755`) become vectorized ops
over a device-resident cluster-state tensor; the serialized one-pod-at-a-time
outer loop (`pkg/scheduler/scheduler.go:470`) becomes a `lax.scan` over a pod
batch with sequential-equivalent greedy commits, so an entire batch of pending
pods is scheduled in one device dispatch.

Layering (mirrors SURVEY.md §7):
  api/        — the object model (Pod, Node, affinity, quantities) + test builders
  intern      — string interning: labels/taints/topology values → dense ids
  cache       — host-side authoritative cluster state w/ assume/forget + generations
  snapshot    — device tensor schema + incremental (generation-diff) uploader
  ops/        — vectorized scheduling plugins (filters + scorers)
  engine/     — the jitted batch pass: filter → score → select → commit scan
  queue       — activeQ/backoffQ/unschedulable three-stage scheduling queue
  scheduler   — the driving loop (ScheduleOne-equivalent, batched)
  parallel/   — multi-chip sharding of the node axis (jax.sharding.Mesh)
  perf/       — scheduler_perf-style benchmark harness
"""

import jax

# Score and resource arithmetic is int64 for bit-identical parity with the
# reference's Go int64 math (e.g. leastRequestedScore in
# pkg/scheduler/framework/plugins/noderesources/least_allocated.go:97:
# ((capacity-requested)*MaxNodeScore)/capacity must truncate identically).
# Kubernetes memory quantities are int64 bytes and exceed int32 range.
jax.config.update("jax_enable_x64", True)
# All matmuls in this framework are integer-count/score math cast to f32
# for the MXU (domain tables, selector masks, weighted sums).  The TPU
# default (bfloat16 passes) truncates integers above 256 — a domain holding
# 300 pods would read back as 298/302 and flip exact skew/affinity
# comparisons — so force full-f32 accumulation: counts < 2^24 stay exact.
jax.config.update("jax_default_matmul_precision", "highest")

# Persist XLA compilations across processes: the batch pass compiles once per
# (profile, schema, batch-size) and those shapes are stable run-to-run.
try:  # pragma: no cover - best effort on experimental backends
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_kubernetes_tpu")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:  # noqa: BLE001
    pass

__version__ = "0.1.0"
