"""Speculative batching frontend for the sidecar's integrated path.

The reference scheduler's outer loop is one pod at a time
(pkg/scheduler/scheduler.go:470 wait.UntilWithContext(sched.ScheduleOne, 0);
schedule_one.go:65), so the Go plugin necessarily asks the sidecar one pod
per PreFilter call.  Answering each call with a device batch of ONE forfeits
the entire batching win — the per-call cost degenerates to
wire RTT + a full device pass.

This frontend wins the batch back without any change to the host's
serialized loop: the plugin's informer already sees every PENDING
(unassigned) pod before the scheduler pops it, and streams them here as
``PendingPod`` hints.  On the first `Schedule(pod)` miss the frontend
schedules the requested pod TOGETHER with up to batch_size-1 hinted pods
in one device pass, commits the assignments to the sidecar mirror (the
assume protocol — cache.go:361), and caches the co-scheduled outcomes.

Two delivery paths for the cached outcomes:
  - the wire hit path: the host's next `Schedule` calls are answered from
    the cache at pure wire-RTT cost;
  - the PUSH path: subscribers (SubscribeRequest connections) receive the
    batch's decisions as Push frames the moment they commit, so the host
    plugin can answer its own PreFilter from a local map with NO wire
    round trip at all — the `.status.nominatedNodeName` precedent
    (schedule_one.go:491–502: a cached placement consulted before
    computing).  Preemption nominations are never pushed — they need the
    host's PostFilter victim deletes, so they always travel the wire.

Consistency contract:
  - Cached decisions are ASSUMED state.  Mutations of the sidecar's
    cluster view invalidate intersecting decisions, SCOPED by per-decision
    dependency sets (the O(changed) principle of the reference's
    generation-diff snapshot, backend/cache/cache.go:186):
      * a decision depends on its chosen node's row, and — only if the pod
        carries the relevant terms — on topology-domain state (pod
        affinity/anti-affinity/spread), volume objects, DRA objects, and
        its gang;
      * unschedulable verdicts additionally depend on anything that could
        free or add capacity (node adds, capacity updates, pod deletes,
        foreign binds — the queueing-hint events that would requeue the
        pod upstream, scheduling_queue.go:406);
      * node label/taint/unschedulable-flag changes remap topology domains
        and feasibility globally → full rollback (the documented
        all-or-nothing fallback for global mutations);
      * gang members invalidate together (the gang committed
        transactionally; a partial rollback would strand a partial gang).
    Rolling back decision A while keeping later decision B (made atop A)
    is the reference's own assume/forget semantics: ForgetPod
    (cache.go:404) never revisits other pods scheduled meanwhile.
  - Epoch ordering: every invalidation bumps `epoch` and emits an
    invalidation Push frame BEFORE any decision recomputed after it, on
    the same ordered stream — so a subscriber applying frames in order
    can never hold a decision from a rolled-back epoch.
  - The host's eventual bound-pod informer upsert for a decision we
    handed over (wire-delivered OR push-consumed) is a confirmation, not
    a mutation: it matches the cached/delivered node, retires the entry,
    and the remaining cache survives.
  - Order: the hint pool admits pods in the sidecar queue's QueueSort
    order (priority, then arrival) — the same comparator the host's
    activeQ pops by — so under synchronized views the speculative commit
    order matches the host's pop order.
  - A speculative PREEMPTION verdict (nominated node + victims) parks its
    pod out of the queue until delivered: the victims exist until the
    HOST deletes them via the API (prepareCandidate, preemption.go:342),
    so re-batching the pod before delivery would just re-fail it and
    overwrite the nomination the host never saw.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field

from ..api import types as t
from ..scheduler import ScheduleOutcome, TPUScheduler
from . import sidecar_pb2 as pb

# Object kinds whose mutations touch only volume-dependent decisions.
_VOLUME_KINDS = frozenset(
    {"PersistentVolume", "PersistentVolumeClaim", "StorageClass", "CSINode"}
)
# Kinds whose mutations touch only DRA-dependent decisions.
_DRA_KINDS = frozenset({"ResourceClaim", "ResourceSlice"})


@dataclass
class SpecStats:
    hits: int = 0
    misses: int = 0
    invalidations: int = 0  # invalidation events (full or scoped)
    full_invalidations: int = 0
    rolled_back: int = 0  # decisions unwound by invalidations
    speculated: int = 0  # co-scheduled pods cached ahead of their request
    pushed: int = 0  # decisions streamed to subscribers
    # _run_batch exhausted its drain bound with the requested pod still
    # queued — the host was told "no feasible node" about a pod that was
    # merely behind stragglers (VERDICT r4 weak-4: an availability lie
    # worth counting).
    drain_exhausted: int = 0

    def as_dict(self) -> dict:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "invalidations": self.invalidations,
            "full_invalidations": self.full_invalidations,
            "rolled_back": self.rolled_back,
            "speculated": self.speculated,
            "pushed": self.pushed,
            "drain_exhausted": self.drain_exhausted,
        }


@dataclass
class DepSet:
    """What a cached decision's validity depends on (beyond the snapshot
    it was computed from).  `node` is None for unschedulable verdicts."""

    node: str | None
    domains: bool  # pod affinity/anti-affinity/topology spread terms
    volumes: bool
    dra: bool
    gang: str | None
    nomination: bool = False  # conservative: invalidated by any event


def _deps_of(pod: t.Pod, out: ScheduleOutcome) -> DepSet:
    aff = pod.spec.affinity
    return DepSet(
        node=out.node_name,
        domains=bool(pod.spec.topology_spread_constraints)
        or (
            aff is not None
            and (aff.pod_affinity is not None or aff.pod_anti_affinity is not None)
        ),
        volumes=bool(pod.spec.volumes),
        dra=bool(pod.spec.resource_claims),
        gang=pod.spec.pod_group or None,
        nomination=bool(out.nominated_node and not out.node_name),
    )


class SpeculativeFrontend:
    """Wraps a TPUScheduler with a decision cache fed by pending-pod hints.

    The server routes every informer message through `note_*` BEFORE
    applying it, and `schedule` requests through `schedule_requested`."""

    def __init__(self, sched: TPUScheduler, lookahead: int | None = None):
        self.sched = sched
        # How many hinted pods join a miss's batch (device batch = 1 + this).
        self.lookahead = lookahead or (sched.batch_size - 1)
        # Coalesced PendingPods frames, kept as UNPARSED JSON arrays: the
        # ingestion ack returns immediately and the parse/build cost runs
        # in _on_dispatched — i.e. under an in-flight device pass.
        # Parsing is INCREMENTAL (a cursor into the blob being decoded):
        # a miss only pays for the pods its batch can admit, never a full
        # multi-MiB array decode on the critical path — at 10k hinted
        # pods the whole-array json.loads was the single biggest
        # non-device host cost in the push-consumer path (~1.3s, fully
        # exposed on the FIRST miss, before any device pass it could
        # hide under was in flight).
        self.raw_blobs: list[bytes] = []
        self._blob_cursor: tuple[str, int] | None = None
        # Hint uids whose pool entry is still a raw dict, in arrival
        # order — the build queue _on_dispatched drains.
        self._unbuilt: deque[str] = deque()
        self.hints: dict[str, t.Pod] = {}
        self.cached: dict[str, ScheduleOutcome] = {}
        self.deps: dict[str, DepSet] = {}
        # uid → node of decisions handed to the host over the WIRE, awaiting
        # its bind's informer echo.  Push-consumed decisions stay in
        # `cached` until the echo confirms them (the sidecar cannot see a
        # local map lookup happen).
        self.delivered: dict[str, str] = {}
        self.stats = SpecStats()
        # Monotonic speculation epoch; bumped by every invalidation.
        # Resumes from the journaled value when the scheduler was recovered
        # (journal.recover stashes it): subscribers hold epoch-stamped
        # decisions, so a restarted frontend must continue the sequence,
        # not restart it — and registering on the scheduler lets snapshots
        # checkpoint the live value (journal.scheduler_state).
        self.epoch = getattr(sched, "_recovered_spec_epoch", 0)
        sched._spec_frontend = self
        # Node-lifecycle taint writes originate INSIDE the scheduler (a
        # Lease renewal trips the transition), so they never pass through
        # note_add — the scheduler calls back here instead.  Taints flip
        # feasibility globally: same full rollback as a wire-fed taint
        # change through the Node branch below.
        sched.taints_changed_hook = lambda _name: self.invalidate()
        # Reverse domain dependencies: an EXISTING pod's required
        # anti-affinity constrains FUTURE pods (the symmetry the reference
        # computes as existingAntiAffinityCounts,
        # interpodaffinity/filtering.go:155) — so once any such pod has
        # been seen, a terms-free cached decision can still be staled by a
        # domain event (e.g. a NamespaceLabels change flipping an existing
        # pod's namespaceSelector match).  The intern table is grow-only,
        # so the flag is monotone; affinity-free workloads keep precise
        # scoping.
        self._terms_seen = 0
        self._reverse = False
        # Push sinks: callables taking a pb.Envelope (the server wraps the
        # subscriber socket write).  A sink raising OSError is dropped.
        self._sinks: list = []
        # Prefetch (featurize k+1 overlapping device k) stays ON: a
        # prefetched batch's pods produce outcomes on the NEXT
        # schedule_batch call, and _run_batch keeps draining until the
        # requested pod's outcome lands — a pod held in a prefetched
        # batch is reached by the drain loop, never stranded.  Staleness
        # is version-guarded at dispatch (_dispatch_batch drops work whose
        # feature_version moved), and deletions dissolve the prefetch
        # (scheduler.delete_pod).
        # The post-dispatch hook runs hint parse/build/admission between
        # the async device dispatch and the blocking fetch — that host
        # work hides under the in-flight pass (the same overlap trick as
        # the featurize prefetch, applied to deserialization).
        sched.post_dispatch_hook = self._on_dispatched
        # Speculation exposition (scheduler_speculation_* — the soak's
        # miss-rate knee reads these off a live scrape instead of the
        # dump frame).  Collector-backed: the hot path keeps bumping the
        # plain SpecStats ints; scrape time syncs the cells.  Registered
        # once per scheduler and resolved through _spec_frontend, so a
        # re-created frontend keeps exporting without re-registering.
        reg = sched.metrics.registry
        if not getattr(sched, "_spec_metrics_registered", False):
            sched._spec_metrics_registered = True
            events_total = reg.counter(
                "scheduler_speculation_events_total",
                "Speculative-frontend decision-cache events by kind "
                "(hits, misses, invalidations, rolled_back, speculated, "
                "pushed, drain_exhausted, full_invalidations).",
            )
            hit_ratio = reg.gauge(
                "scheduler_speculation_hit_ratio",
                "Decision-cache hit ratio (hits / (hits + misses)) since "
                "the frontend started.",
            )

            def collect(_reg) -> None:
                front = getattr(sched, "_spec_frontend", None)
                if front is None:
                    return
                for k, v in front.stats.as_dict().items():
                    events_total.set(float(v), event=k)
                served = front.stats.hits + front.stats.misses
                hit_ratio.set(
                    front.stats.hits / served if served else 0.0
                )

            reg.add_collector(collect)

    # -- push stream --------------------------------------------------------

    def add_sink(self, sink) -> None:
        self._sinks.append(sink)

    def _emit(self, env: pb.Envelope) -> None:
        dead = []
        for sink in self._sinks:
            try:
                sink(env)
            except OSError:
                dead.append(sink)
        for sink in dead:
            self._sinks.remove(sink)

    def _push_invalidation(self, uids) -> None:
        """uids=None → all.  Emitted BEFORE recomputation can push new
        decisions, inside the same dispatch — stream order IS the
        consistency contract."""
        if not self._sinks:
            return
        env = pb.Envelope()
        env.push.epoch = self.epoch
        if uids is None:
            env.push.invalidate_all = True
        else:
            env.push.invalidate_uids.extend(sorted(uids))
        self._emit(env)

    def _push_decisions(self, outs: list[ScheduleOutcome]) -> None:
        if not self._sinks:
            return
        env = pb.Envelope()
        env.push.epoch = self.epoch
        n = 0
        for o in outs:
            if o.nominated_node and not o.node_name:
                continue  # nominations always travel the wire (PostFilter)
            d = env.push.decisions.add()
            d.pod_uid = o.pod.uid
            d.node_name = o.node_name or ""
            d.score = o.score
            d.feasible_nodes = o.feasible_nodes
            if o.diagnosis is not None and not o.node_name:
                d.unschedulable_plugins.extend(
                    sorted(o.diagnosis.unschedulable_plugins)
                )
            n += 1
        if n:
            self.stats.pushed += n
            self._emit(env)

    # -- hint feed ----------------------------------------------------------
    # Hints are stored lazily: a raw-JSON dict from the wire, or a built
    # t.Pod (internal rollback path).  The dataclass reconstruction — the
    # expensive half of deserialization — happens only if the hint is
    # actually admitted into a batch.

    @staticmethod
    def _uid_of(data: dict) -> str:
        """Uid from a raw pod-JSON dict, matching t.Pod.uid's fallback
        exactly (api/types.py:355 — including the ObjectMeta namespace
        default): a divergent key would commit the outcome under one uid
        and pop it with another."""
        meta = data.get("metadata", {})
        ns = meta.get("namespace") or "default"
        return meta.get("uid") or f"{ns}/{meta.get('name')}"

    def add_hint(self, pod: t.Pod) -> None:
        self._add_hint(pod.uid, pod)

    def add_hint_raw(self, raw: bytes) -> None:
        import json

        data = json.loads(raw)
        self._add_hint(self._uid_of(data), data)

    def add_hint_data(self, data: dict) -> None:
        self._add_hint(self._uid_of(data), data)

    def add_hint_blob(self, raw: bytes) -> None:
        """A coalesced PendingPods frame, deferred whole: parsed by
        _parse_blobs under a device pass (or on first demand)."""
        self.raw_blobs.append(raw)

    def _parse_blobs(self, need: int | None = None) -> None:
        """Parse deferred blobs into the hint pool — up to ``need`` NEW
        pool entries (None = everything).  A pool entry that already
        exists WINS over a blob entry — the pool entry arrived later (a
        direct informer add/update), the blob was queued first.

        Incremental by design: ``raw_decode`` consumes one pod object
        per step and the cursor persists across calls, so the cost of a
        large coalesced frame amortizes across batches (and hides under
        in-flight device passes via _on_dispatched) instead of landing
        whole on the first miss.  Partial parsing means the priority
        sort in _admit_hints only sees the decoded prefix — hints are
        best-effort speculation, so a deep-in-the-blob priority
        inversion costs at most one deferred speculation, never a wrong
        answer.  The decode time is observed as the ``hint_decode``
        phase (a sub-slice, like journal_append — it overlaps device
        time and stays out of the tiling sum)."""
        if need is not None and need <= 0:
            return
        if not self.raw_blobs and self._blob_cursor is None:
            return
        import json

        t0 = time.perf_counter()
        decoder = json.JSONDecoder()
        added = 0
        try:
            while self.raw_blobs or self._blob_cursor is not None:
                if self._blob_cursor is None:
                    text = self.raw_blobs.pop(0).decode("utf-8")
                    pos = 0
                    while pos < len(text) and text[pos] in " \t\n\r":
                        pos += 1
                    if pos >= len(text):
                        continue
                    if text[pos] != "[":
                        raise ValueError(
                            "PendingPods frame is not a JSON array"
                        )
                    self._blob_cursor = (text, pos + 1)
                text, pos = self._blob_cursor
                while True:
                    while pos < len(text) and text[pos] in " \t\n\r,":
                        pos += 1
                    if pos >= len(text) or text[pos] == "]":
                        self._blob_cursor = None
                        break
                    data, pos = decoder.raw_decode(text, pos)
                    uid = self._uid_of(data)
                    if uid not in self.hints and self._add_hint(uid, data):
                        self._unbuilt.append(uid)
                        added += 1
                        if need is not None and added >= need:
                            self._blob_cursor = (text, pos)
                            return
        except ValueError:
            # A malformed blob cannot be resumed (framing inside the
            # array is lost); drop its remainder and surface the error
            # where the old whole-array parse would have.
            self._blob_cursor = None
            raise
        finally:
            self._observe_decode(time.perf_counter() - t0)

    def _observe_decode(self, secs: float) -> None:
        """Attribute hint deserialization to the phase split
        (scheduler_phase_duration_seconds{phase="hint_decode"}) — the
        evidence surface for the push-consumer host-cost work."""
        hist = getattr(self.sched, "_phase_hist", None)
        if hist is not None:
            hist.observe(secs, phase="hint_decode")

    def _build_hints(self, budget: int) -> None:
        """Convert up to ``budget`` raw-dict pool entries into built
        t.Pod objects (the expensive half of deserialization), oldest
        first."""
        unbuilt = self._unbuilt
        hints = self.hints
        t0 = time.perf_counter()
        while budget > 0 and unbuilt:
            uid = unbuilt.popleft()
            obj = hints.get(uid)
            if isinstance(obj, dict):
                hints[uid] = self._hint_pod(obj)
                budget -= 1
        self._observe_decode(time.perf_counter() - t0)

    def _on_dispatched(self) -> None:
        """scheduler.post_dispatch_hook: a device pass is in flight; do
        the deserialization work now, under it — and feed the queue so
        the scheduler's featurize-prefetch has a next batch to pop."""
        self._parse_blobs(self.sched.batch_size * 2)
        self._build_hints(self.sched.batch_size * 2)
        self._admit_hints(self.sched.batch_size)

    def _add_hint(self, uid: str, obj) -> bool:
        if uid in self.cached or uid in self.delivered:
            return False
        if uid in self.sched.cache.pods:
            return False  # already bound/assumed in the mirror
        if uid in self.sched._inflight_uids:
            # The pod is IN the batch currently dispatching (it arrived
            # both as a direct Schedule request and in a
            # still-unparsed blob, and the incremental parse reached it
            # mid-flight).  Re-pooling it would re-admit it to the
            # active queue under the commit's feet — the commit's
            # queue.done() would strand a stale active entry.  Its
            # outcome is already on the way; drop the duplicate hint.
            return False
        self.hints[uid] = obj
        return True

    @staticmethod
    def _hint_priority(obj) -> int:
        if isinstance(obj, dict):
            return obj.get("spec", {}).get("priority") or 0
        return obj.spec.priority

    @staticmethod
    def _hint_pod(obj) -> t.Pod:
        if isinstance(obj, dict):
            from ..api import serialize

            return serialize.pod_from_data(obj)
        return obj

    # -- mutation classification -------------------------------------------

    def _reverse_domain_deps(self) -> bool:
        """True once any required anti-affinity term has been interned —
        from then on every cached decision is domain-dependent (see
        __init__).  Scans only the vocab's new tail (grow-only)."""
        if self._reverse:
            return True
        vocab = self.sched.builder.interns.terms._to_val
        n = len(vocab)
        if n > self._terms_seen:
            for key in vocab[self._terms_seen :]:
                if key[0] == 1:  # category 1 = required anti-affinity
                    self._reverse = True
                    break
            self._terms_seen = n
        return self._reverse

    @staticmethod
    def _carries_required_antiaffinity(pod: t.Pod) -> bool:
        aff = pod.spec.affinity
        return (
            aff is not None
            and aff.pod_anti_affinity is not None
            and bool(aff.pod_anti_affinity.required)
        )

    def _scope(self, *, node: str | None = None, domains: bool = False,
               volumes: bool = False, dra: bool = False,
               unschedulable: bool = False, gangs: bool = False,
               uids: set | None = None) -> None:
        """Invalidate the cached decisions intersecting the event's scope.
        Nominations are always included (conservative — they are rare and
        carry victim sets no dependency class captures)."""
        # With reverse domain deps in play, a domain event can stale ANY
        # decision, not just those whose pod carries terms.
        reverse = domains and self._reverse_domain_deps()

        def hit(d: DepSet) -> bool:
            return (
                d.nomination
                or (node is not None and d.node == node)
                or (domains and (d.domains or reverse))
                or (volumes and d.volumes)
                or (dra and d.dra)
                or (unschedulable and d.node is None and not d.nomination)
                or (gangs and d.gang is not None)
            )

        sel = {u for u, d in self.deps.items() if hit(d)}
        if uids:
            sel |= uids & self.cached.keys()
        if sel:
            self.invalidate(sel)

    def _note_confirmed_labels(self, uid: str, obj: t.Pod) -> None:
        """A bind echo matched our decision, but its labels may have
        changed since decision time — the same domain shift the
        known-binding re-delivery branch escalates on."""
        rec = self.sched.cache.pods.get(uid)
        if rec is None or rec.pod.metadata.labels == obj.metadata.labels:
            return
        if self._carries_required_antiaffinity(obj):
            self.invalidate()
        else:
            self._scope(domains=True, unschedulable=True)

    def note_add(self, kind: str, obj) -> None:
        """Called before the server applies an AddObject.  Decides which
        cached decisions survive the message."""
        if kind == "Pod":
            uid = obj.uid
            if obj.spec.node_name:
                if self.delivered.get(uid) == obj.spec.node_name:
                    # The host bound our wire-delivered pick; update_pod's
                    # diff is a no-op on the mirror.  Confirmation — but
                    # the echo may also carry labels changed since the
                    # decision (a controller raced the bind), shifting the
                    # domain counts other cached decisions read.
                    self.delivered.pop(uid, None)
                    self._note_confirmed_labels(uid, obj)
                    return
                out = self.cached.get(uid)
                if out is not None and out.node_name == obj.spec.node_name:
                    # The host bound a PUSH-consumed decision: same
                    # confirmation, arriving without a wire serve.  Retire
                    # the entry; update_pod's diff is a no-op.
                    self.cached.pop(uid, None)
                    self.deps.pop(uid, None)
                    self._note_confirmed_labels(uid, obj)
                    return
                rec = self.sched.cache.pods.get(uid)
                if rec is not None and rec.node_name == obj.spec.node_name:
                    # Known binding — but an UPDATE can still change the
                    # pod's labels, which shifts the domain counts other
                    # cached decisions read.
                    if rec.pod.metadata.labels != obj.metadata.labels:
                        if self._carries_required_antiaffinity(obj):
                            self.invalidate()
                        else:
                            self._scope(domains=True, unschedulable=True)
                    return
                # A bind we didn't decide (foreign profile, or a stale
                # push raced an invalidation): it consumes its node's
                # resources and shifts topology domains.  A foreign pod
                # CARRYING required anti-affinity imposes a brand-new
                # reverse constraint no cached DepSet anticipated — full
                # rollback (its terms are only interned after this note).
                if self._carries_required_antiaffinity(obj):
                    self.invalidate()
                    return
                self._scope(
                    node=obj.spec.node_name, domains=True, unschedulable=True,
                    uids={uid},
                )
            else:
                out = self.cached.get(uid)
                if out is not None:
                    # The pod already has a committed (undelivered)
                    # decision.  A spec/label change makes it stale —
                    # invalidate so the recompute sees the new object; an
                    # identical re-delivery (watch relist) changes nothing.
                    # Compare modulo the binding the commit stamped on our
                    # copy (spec.node_name) — the re-delivered object is
                    # unassigned by definition of this branch.
                    import dataclasses

                    old = out.pod
                    if old.metadata.labels != obj.metadata.labels or (
                        dataclasses.replace(old.spec, node_name=None)
                        != dataclasses.replace(obj.spec, node_name=None)
                    ):
                        # Its labels/terms were committed into the mirror;
                        # domain-reading and unschedulable verdicts may
                        # have counted them.  New required anti-affinity is
                        # a reverse constraint nothing anticipated.
                        if self._carries_required_antiaffinity(obj):
                            self.invalidate()
                        else:
                            self._scope(
                                domains=True, unschedulable=True, uids={uid}
                            )
                        self.add_hint(obj)
                    return
                if uid in self.delivered:
                    return  # host is binding our pick; ignore re-delivery
                # An unassigned pod entering the queue mutates nothing
                # committed; treat as a hint too.
                self.add_hint(obj)
            return
        if kind == "Node":
            rec = self.sched.cache.nodes.get(obj.name)
            if rec is None:
                # New capacity: resource-only placements stay valid
                # (upstream pods scheduled against a pre-add snapshot keep
                # their bindings too); unschedulable verdicts must
                # recompute (the node-add queueing hint,
                # scheduling_queue.go:1029), and so must domain-dependent
                # decisions — the new node is a new (empty) topology
                # domain, which can push a cached DoNotSchedule spread
                # placement past maxSkew (global min drops to 0).
                self._scope(domains=True, unschedulable=True)
                return
            old = rec.node
            if (
                old.spec.taints != obj.spec.taints
                or old.metadata.labels != obj.metadata.labels
                or old.spec.unschedulable != obj.spec.unschedulable
            ):
                # Labels remap topology domains and zone programs;
                # taints/cordon flip feasibility globally.  Full rollback.
                self.invalidate()
                return
            if (
                old.status.allocatable == obj.status.allocatable
                and old.status.images == obj.status.images
            ):
                # Heartbeat: update_node's diff emits no event for this
                # either — decisions survive.
                return
            # Capacity-only change: decisions ON this node re-check;
            # grown capacity can wake unschedulable verdicts.
            self._scope(node=obj.name, unschedulable=True)
            return
        if kind == "NamespaceLabels":
            # Namespace-selector affinity matching reads these.
            self._scope(domains=True, unschedulable=True)
            return
        if kind in _VOLUME_KINDS:
            self._scope(volumes=True, unschedulable=True)
            return
        if kind in _DRA_KINDS:
            self._scope(dra=True, unschedulable=True)
            return
        if kind == "PodGroup":
            # Quorum thresholds changed: gang decisions + gated members.
            self._scope(gangs=True, unschedulable=True)
            return
        if kind == "PodDisruptionBudget":
            # Only preemption verdicts read PDB budgets; bind decisions
            # don't.  Nominations are always in scope.
            self._scope()
            return
        if kind == "Lease":
            # A heartbeat renewal mutates no scheduling state by itself;
            # the taint transitions it may trip invalidate through the
            # scheduler's taints_changed_hook (registered in __init__).
            return
        self.invalidate()

    def note_remove(self, kind: str, uid: str) -> None:
        if kind == "Pod":
            if self.raw_blobs or self._blob_cursor is not None:
                # The deleted pod may sit in an unparsed blob; parsing
                # later would resurrect it.  Deletes are rare next to
                # hints — pay the full parse on this path.
                self._parse_blobs()
            if not (
                uid in self.cached
                or uid in self.delivered
                or uid in self.sched.cache.pods
            ):
                # The pod touches nothing committed (a hint, or a pod
                # parked in the queue): dropping it cannot stale any
                # cached decision.
                self.hints.pop(uid, None)
                return
            rec = self.sched.cache.pods.get(uid)
            node = rec.node_name if rec is not None else None
            # Deleting a pod frees capacity (unschedulable verdicts may
            # now fit) and shifts topology domains; decisions on OTHER
            # nodes keep their feasibility (freed resources cannot break
            # a placement).  Scope first (it returns cached pods to the
            # hint pool), THEN drop the deleted pod's own traces — so a
            # pod deleted with an undelivered decision doesn't resurrect
            # as a hint.
            self._scope(node=node, domains=True, unschedulable=True,
                        uids={uid})
            self.hints.pop(uid, None)
            self.delivered.pop(uid, None)
            return
        if kind == "Node":
            # Placements on the node vanish with it; its pods' labels
            # leave the topology domains.
            self._scope(node=uid, domains=True)
            return
        self.invalidate()

    # -- invalidation -------------------------------------------------------

    def invalidate(self, uids: set | None = None) -> None:
        """Roll back speculative decisions — all of them, or the scoped
        subset `uids` (closed over gang membership) — and return the pods
        to the hint pool (assume/forget: cache.go:404 ForgetPod)."""
        if not self.cached:
            return
        if uids is None:
            sel = set(self.cached.keys())
            self.stats.full_invalidations += 1
        else:
            sel = uids & self.cached.keys()
            if not sel:
                return
            # Gang closure: members committed together roll back together.
            gangs = {
                self.deps[u].gang
                for u in sel
                if u in self.deps and self.deps[u].gang
            }
            if gangs:
                sel |= {
                    u
                    for u, d in self.deps.items()
                    if d.gang in gangs and u in self.cached
                }
        self.stats.invalidations += 1
        self.epoch += 1
        # Write-ahead: the epoch bump is durable before the invalidation is
        # applied (pushed/rolled back), so recovery resumes the monotonic
        # sequence the PR 3 roadmap gap left cold-starting.  Muted during
        # recovery like every other append.
        j = self.sched.journal
        if j is not None:
            j.append("spec_epoch", {"epoch": self.epoch})
        # Mirror onto the scheduler too: a frontend re-created IN PROCESS
        # (not just across a crash) must also resume from here, or it
        # would re-emit epochs subscribers already hold.
        self.sched._recovered_spec_epoch = self.epoch
        self._push_invalidation(None if uids is None else sel)
        # Iterate in the cache's COMMIT order, not set order: rolled-back
        # pods re-enter the hint pool in this order, and _admit_hints'
        # stable priority sort preserves it for ties — set iteration is
        # hash-randomized and made the recomputed batch order (and the
        # golden push fixture) differ across PYTHONHASHSEED.
        for uid in [u for u in self.cached if u in sel]:
            out = self.cached.pop(uid)
            self.deps.pop(uid, None)
            if out.node_name:
                # Assumed+finalized in the mirror: remove cleanly (resource
                # delta, gang credit, DRA reservations all unwind).  The
                # commit path stamped spec.node_name on the pod object —
                # scrub it, or re-admission would take the bound-pod path
                # and re-bind to the old node with no re-filtering.
                self.sched.delete_pod(uid, notify=False)
                out.pod.spec.node_name = None
                self.stats.rolled_back += 1
            elif out.nominated_node:
                # Undelivered nomination: release the claim on the freed
                # node; the pod re-enters the hint pool for a fresh verdict
                # (with the now-meaningless nomination scrubbed).
                self.sched.nominator.pop(uid, None)
                self.sched.queue.delete(uid)
                out.pod.status.nominated_node_name = ""
            else:
                # Unschedulable verdict: pod sits in the sidecar's
                # unschedulable pool; re-adding via the hint path pops it
                # back to active for the recompute.
                pass
            self.hints[uid] = out.pod

    # -- the request path ---------------------------------------------------

    def _prefetched_uids(self) -> frozenset:
        """Uids held in the scheduler's prefetched (featurized) or
        predispatched (ISSUE 15 pipeline) batch: popped from the queue
        (so _in_active can't dedup them) but not yet scheduled —
        re-adding one would run it twice and double-commit."""
        uids = set()
        pre = self.sched._prefetched
        if pre is not None:
            uids.update(qp.pod.uid for qp in pre[0])
        pd = self.sched._predispatched
        if pd is not None:
            uids.update(qp.pod.uid for qp in pd.infos)
        return frozenset(uids)

    def _admit_hints(self, budget: int) -> None:
        if budget <= 0:
            return
        if len(self.hints) < budget:
            # Top up from the deferred blobs — only as many pods as this
            # admission can use (the incremental-parse contract).
            self._parse_blobs(budget - len(self.hints))
        if not self.hints:
            return
        # Both in-flight sets: the prefetched NEXT batch and the batch
        # currently dispatching (post_dispatch_hook runs inside it) —
        # re-admitting a member of either would double-commit it.
        in_flight = self._prefetched_uids() | self.sched._inflight_uids
        # Admit in QueueSort order (priority desc, arrival order) — the
        # host activeQ's comparator, so speculation follows its pop order.
        order = sorted(
            self.hints.items(), key=lambda kv: -self._hint_priority(kv[1])
        )[:budget]
        for uid, obj in order:
            self.hints.pop(uid, None)
            if (
                uid in self.sched.cache.pods
                or uid in self.cached
                or uid in self.delivered
                or uid in in_flight
            ):
                # Stale hint: the pod was meanwhile scheduled from the
                # queue or is mid-flight in the prefetched batch (it rode
                # in via a plain informer add too).  Re-admitting would
                # double-commit it.
                continue
            self.sched.add_pod(self._hint_pod(obj))

    def _run_batch(self, requested: t.Pod) -> None:
        self.hints.pop(requested.uid, None)
        if requested.uid not in self._prefetched_uids():
            self.sched.add_pod(requested)
        self._admit_hints(self.lookahead)
        # The requested pod may sort below admitted hints or behind
        # event-woken stragglers; keep draining batches until its outcome
        # lands (it is in the active queue, so successive pops reach it).
        for _ in range(64):
            outs = self.sched.schedule_batch()
            fresh = []
            for o in outs:
                self.cached[o.pod.uid] = o
                self.deps[o.pod.uid] = _deps_of(o.pod, o)
                if o.pod.uid != requested.uid:
                    self.stats.speculated += 1
                    fresh.append(o)  # the requested pod rides the response
                if o.nominated_node and not o.node_name:
                    # Park the nominee until its verdict is delivered (see
                    # module docstring) — the queue re-add in
                    # _record_preemption would re-batch it uselessly.
                    self.sched.queue.delete(o.pod.uid)
            self._push_decisions(fresh)
            if requested.uid in self.cached:
                return
            if (
                not outs
                and not len(self.sched.queue)
                and not self.sched.has_inflight_work
            ):
                return  # parked (gated / gang quorum / foreign scheduler)
        # Bound exhausted with the pod still queued: the synthesized
        # "no feasible node" below is an availability lie (the pod may
        # simply be behind stragglers) — count it so operators see it.
        self.stats.drain_exhausted += 1

    def flush_hints_to_queue(self) -> None:
        """Drain-request prelude: roll back the cache, then move every
        pending hint into the scheduler's queue so the drain sees the full
        pod set (the frontend owns hint storage — hints may be raw dicts
        or still-unparsed blobs)."""
        self._parse_blobs()
        self.invalidate()
        self._admit_hints(len(self.hints))

    def schedule_raw(self, raws: list[bytes]) -> list[ScheduleOutcome]:
        """Request path from wire JSON: on a cache hit only the uid is
        needed — skip the full dataclass reconstruction (the per-call fixed
        cost the hit path exists to avoid)."""
        import json

        from ..api import serialize

        results = []
        for raw in raws:
            data = json.loads(raw)
            results.append(
                self._serve_one(
                    self._uid_of(data),
                    lambda d=data: serialize.pod_from_data(d),
                )
            )
        return results

    def _serve_one(self, uid: str, parse) -> ScheduleOutcome:
        out = self.cached.pop(uid, None)
        if out is not None:
            self.deps.pop(uid, None)
            self.stats.hits += 1
        else:
            self.stats.misses += 1
            pod = parse()
            self._run_batch(pod)
            out = self.cached.pop(uid, None)
            self.deps.pop(uid, None)
            if out is None:
                # The pod produced no outcome this batch (parked: gated,
                # gang quorum pending, another scheduler's pod).  The
                # host sees "no feasible node" and requeues; its next
                # attempt re-asks.
                out = ScheduleOutcome(pod, None, 0, 0)
        if out.node_name:
            self.delivered[uid] = out.node_name
        # A delivered nomination stays parked sidecar-side: the host
        # deletes the victims and re-asks, and that miss recomputes via
        # the nominated fast path (the nominator claim is still held).
        return out
