"""Speculative batching frontend for the sidecar's integrated path.

The reference scheduler's outer loop is one pod at a time
(pkg/scheduler/scheduler.go:470 wait.UntilWithContext(sched.ScheduleOne, 0);
schedule_one.go:65), so the Go plugin necessarily asks the sidecar one pod
per PreFilter call.  Answering each call with a device batch of ONE forfeits
the entire batching win — the per-call cost degenerates to
wire RTT + a full device pass.

This frontend wins the batch back without any change to the host's
serialized loop: the plugin's informer already sees every PENDING
(unassigned) pod before the scheduler pops it, and streams them here as
``PendingPod`` hints (the PreEnqueue/EventsToRegister-driven pre-stream
VERDICT r3 missing-1 prescribes).  On the first `Schedule(pod)` miss the
frontend schedules the requested pod TOGETHER with up to batch_size-1
hinted pods in one device pass, commits the assignments to the sidecar
mirror (the assume protocol — cache.go:361), and caches the co-scheduled
outcomes.  The host's next ~255 `Schedule` calls are answered from that
cache at pure wire-RTT cost; the device amortizes one pass over the whole
window.

Consistency contract:
  - Cached decisions are ASSUMED state.  Any mutation of the sidecar's
    cluster view (node add/update/remove, pod delete, volume/DRA/PDB/
    namespace objects) invalidates the cache: undelivered assignments are
    rolled back through the ForgetPod analog (delete_pod) and their pods
    returned to the hint pool, so the next request recomputes against the
    fresh state.  This is exactly the scope the reference gives a cycle's
    snapshot — decisions made against a stale snapshot are re-made, not
    patched.
  - The host's eventual bound-pod informer upsert for a DELIVERED decision
    is a confirmation, not a mutation: serialize.py routes it through
    update_pod, whose diff sees a status-only change (the sidecar already
    holds the pod bound on that node), and the cache survives.
  - Order: the hint pool admits pods in the sidecar queue's QueueSort
    order (priority, then arrival) — the same comparator the host's
    activeQ pops by — so under synchronized views the speculative commit
    order matches the host's pop order.  When they diverge (an event
    raced), the miss path recomputes with the host's pod first; cached
    decisions are always mutually consistent because every one was
    committed transactionally to the single sidecar state.
  - A speculative PREEMPTION verdict (nominated node + victims) parks its
    pod out of the queue until delivered: the victims exist until the
    HOST deletes them via the API (prepareCandidate, preemption.go:342),
    so re-batching the pod before delivery would just re-fail it and
    overwrite the nomination the host never saw.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..api import types as t
from ..scheduler import ScheduleOutcome, TPUScheduler


@dataclass
class SpecStats:
    hits: int = 0
    misses: int = 0
    invalidations: int = 0
    rolled_back: int = 0
    speculated: int = 0  # co-scheduled pods cached ahead of their request

    def as_dict(self) -> dict:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "invalidations": self.invalidations,
            "rolled_back": self.rolled_back,
            "speculated": self.speculated,
        }


class SpeculativeFrontend:
    """Wraps a TPUScheduler with a decision cache fed by pending-pod hints.

    The server routes every informer message through `note_*` BEFORE
    applying it, and `schedule` requests through `schedule_requested`."""

    def __init__(self, sched: TPUScheduler, lookahead: int | None = None):
        self.sched = sched
        # How many hinted pods join a miss's batch (device batch = 1 + this).
        self.lookahead = lookahead or (sched.batch_size - 1)
        self.hints: dict[str, t.Pod] = {}
        self.cached: dict[str, ScheduleOutcome] = {}
        # uid → node of decisions handed to the host, awaiting its bind's
        # informer echo (the confirmation path).
        self.delivered: dict[str, str] = {}
        self.stats = SpecStats()
        # Batches run synchronously inside a request here; a prefetched
        # batch would strand pods popped for it (they'd produce outcomes
        # only on the NEXT request's batch, racing the host's ask order).
        sched._prefetch_enabled = False

    # -- hint feed ----------------------------------------------------------
    # Hints are stored lazily: a raw-JSON dict from the wire, or a built
    # t.Pod (internal rollback path).  The dataclass reconstruction — the
    # expensive half of deserialization — happens only if the hint is
    # actually admitted into a batch.

    @staticmethod
    def _uid_of(data: dict) -> str:
        """Uid from a raw pod-JSON dict, matching t.Pod.uid's fallback
        exactly (api/types.py:355 — including the ObjectMeta namespace
        default): a divergent key would commit the outcome under one uid
        and pop it with another."""
        meta = data.get("metadata", {})
        ns = meta.get("namespace") or "default"
        return meta.get("uid") or f"{ns}/{meta.get('name')}"

    def add_hint(self, pod: t.Pod) -> None:
        self._add_hint(pod.uid, pod)

    def add_hint_raw(self, raw: bytes) -> None:
        import json

        data = json.loads(raw)
        self._add_hint(self._uid_of(data), data)

    def _add_hint(self, uid: str, obj) -> None:
        if uid in self.cached or uid in self.delivered:
            return
        if uid in self.sched.cache.pods:
            return  # already bound/assumed in the mirror
        self.hints[uid] = obj

    @staticmethod
    def _hint_priority(obj) -> int:
        if isinstance(obj, dict):
            return obj.get("spec", {}).get("priority") or 0
        return obj.spec.priority

    @staticmethod
    def _hint_pod(obj) -> t.Pod:
        if isinstance(obj, dict):
            from ..api import serialize

            return serialize._build(t.Pod, obj)
        return obj

    # -- mutation classification -------------------------------------------

    def note_add(self, kind: str, obj) -> None:
        """Called before the server applies an AddObject.  Decides whether
        the cached decisions survive the message."""
        if kind == "Pod":
            uid = obj.uid
            if obj.spec.node_name:
                if self.delivered.get(uid) == obj.spec.node_name:
                    # The host bound our pick; update_pod's diff is a no-op
                    # on the mirror.  Confirmation, not mutation.
                    self.delivered.pop(uid, None)
                    return
                if uid in self.sched.cache.pods and (
                    self.sched.cache.pods[uid].node_name == obj.spec.node_name
                ):
                    return  # idempotent re-delivery of a known binding
                self.invalidate()  # a bind we didn't decide
            else:
                out = self.cached.get(uid)
                if out is not None:
                    # The pod already has a committed (undelivered)
                    # decision.  A spec/label change makes it stale —
                    # invalidate so the recompute sees the new object; an
                    # identical re-delivery (watch relist) changes nothing.
                    # Compare modulo the binding the commit stamped on our
                    # copy (spec.node_name) — the re-delivered object is
                    # unassigned by definition of this branch.
                    import dataclasses

                    old = out.pod
                    if old.metadata.labels != obj.metadata.labels or (
                        dataclasses.replace(old.spec, node_name=None)
                        != dataclasses.replace(obj.spec, node_name=None)
                    ):
                        self.invalidate()
                        self.add_hint(obj)
                    return
                if uid in self.delivered:
                    return  # host is binding our pick; ignore re-delivery
                # An unassigned pod entering the queue mutates nothing
                # committed; treat as a hint too.
                self.add_hint(obj)
            return
        if kind == "Node":
            rec = self.sched.cache.nodes.get(obj.name)
            if rec is not None:
                old = rec.node
                if (
                    old.spec.taints == obj.spec.taints
                    and old.metadata.labels == obj.metadata.labels
                    and old.spec.unschedulable == obj.spec.unschedulable
                    and old.status.allocatable == obj.status.allocatable
                    and old.status.images == obj.status.images
                ):
                    # Heartbeat: update_node's diff emits no event for this
                    # either — decisions survive.
                    return
        self.invalidate()

    def note_remove(self, kind: str, uid: str) -> None:
        if kind == "Pod" and not (
            uid in self.cached
            or uid in self.delivered
            or uid in self.sched.cache.pods
        ):
            # The pod touches nothing committed (a hint, or a pod parked in
            # the queue): dropping it cannot stale any cached decision.
            self.hints.pop(uid, None)
            return
        # Unwind first (invalidate returns cached pods to the hint pool),
        # THEN forget the deleted pod everywhere — so a pod deleted with an
        # undelivered decision doesn't resurrect as a hint.
        self.invalidate()
        if kind == "Pod":
            self.hints.pop(uid, None)
            self.delivered.pop(uid, None)

    # -- invalidation -------------------------------------------------------

    def invalidate(self) -> None:
        """Roll back every undelivered speculative decision and return the
        pods to the hint pool (assume/forget: cache.go:404 ForgetPod)."""
        if not self.cached:
            return
        self.stats.invalidations += 1
        for uid, out in self.cached.items():
            if out.node_name:
                # Assumed+finalized in the mirror: remove cleanly (resource
                # delta, gang credit, DRA reservations all unwind).  The
                # commit path stamped spec.node_name on the pod object —
                # scrub it, or re-admission would take the bound-pod path
                # and re-bind to the old node with no re-filtering.
                self.sched.delete_pod(uid, notify=False)
                out.pod.spec.node_name = None
                self.stats.rolled_back += 1
            elif out.nominated_node:
                # Undelivered nomination: release the claim on the freed
                # node; the pod re-enters the hint pool for a fresh verdict
                # (with the now-meaningless nomination scrubbed).
                self.sched.nominator.pop(uid, None)
                self.sched.queue.delete(uid)
                out.pod.status.nominated_node_name = ""
            else:
                # Unschedulable verdict: pod sits in the sidecar's
                # unschedulable pool; re-adding via the hint path pops it
                # back to active for the recompute.
                pass
            self.hints[uid] = out.pod
        self.cached.clear()

    # -- the request path ---------------------------------------------------

    def _admit_hints(self, budget: int) -> None:
        if budget <= 0 or not self.hints:
            return
        # Admit in QueueSort order (priority desc, arrival order) — the
        # host activeQ's comparator, so speculation follows its pop order.
        order = sorted(
            self.hints.items(), key=lambda kv: -self._hint_priority(kv[1])
        )[:budget]
        for uid, obj in order:
            self.hints.pop(uid, None)
            if (
                uid in self.sched.cache.pods
                or uid in self.cached
                or uid in self.delivered
            ):
                # Stale hint: the pod was meanwhile scheduled from the
                # queue (it rode in via a plain informer add too).
                # Re-admitting would double-commit it.
                continue
            self.sched.add_pod(self._hint_pod(obj))

    def _run_batch(self, requested: t.Pod) -> None:
        self.hints.pop(requested.uid, None)
        self.sched.add_pod(requested)
        self._admit_hints(self.lookahead)
        # The requested pod may sort below admitted hints or behind
        # event-woken stragglers; keep draining batches until its outcome
        # lands (it is in the active queue, so successive pops reach it).
        for _ in range(64):
            outs = self.sched.schedule_batch()
            for o in outs:
                self.cached[o.pod.uid] = o
                if o.pod.uid != requested.uid:
                    self.stats.speculated += 1
                if o.nominated_node and not o.node_name:
                    # Park the nominee until its verdict is delivered (see
                    # module docstring) — the queue re-add in
                    # _record_preemption would re-batch it uselessly.
                    self.sched.queue.delete(o.pod.uid)
            if requested.uid in self.cached:
                return
            if not outs and not len(self.sched.queue):
                return  # parked (gated / gang quorum / foreign scheduler)

    def flush_hints_to_queue(self) -> None:
        """Drain-request prelude: roll back the cache, then move every
        pending hint into the scheduler's queue so the drain sees the full
        pod set (the frontend owns hint storage — hints may be raw dicts)."""
        self.invalidate()
        self._admit_hints(len(self.hints))

    def schedule_raw(self, raws: list[bytes]) -> list[ScheduleOutcome]:
        """Request path from wire JSON: on a cache hit only the uid is
        needed — skip the full dataclass reconstruction (the per-call fixed
        cost the hit path exists to avoid)."""
        import json

        from ..api import serialize

        results = []
        for raw in raws:
            data = json.loads(raw)
            results.append(
                self._serve_one(
                    self._uid_of(data),
                    lambda d=data: serialize._build(t.Pod, d),
                )
            )
        return results

    def _serve_one(self, uid: str, parse) -> ScheduleOutcome:
        out = self.cached.pop(uid, None)
        if out is not None:
            self.stats.hits += 1
        else:
            self.stats.misses += 1
            pod = parse()
            self._run_batch(pod)
            out = self.cached.pop(uid, None)
            if out is None:
                # The pod produced no outcome this batch (parked: gated,
                # gang quorum pending, another scheduler's pod).  The
                # host sees "no feasible node" and requeues; its next
                # attempt re-asks.
                out = ScheduleOutcome(pod, None, 0, 0)
        if out.node_name:
            self.delivered[uid] = out.node_name
        # A delivered nomination stays parked sidecar-side: the host
        # deletes the victims and re-asks, and that miss recomputes via
        # the nominated fast path (the nominator claim is still held).
        return out

