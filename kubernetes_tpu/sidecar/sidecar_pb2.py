# -*- coding: utf-8 -*-
# Generated protocol buffer code.  DO NOT EDIT BY HAND —
# regenerate with scripts/gen_sidecar_pb2.py (protoc-free: the serialized
# FileDescriptorProto is evolved programmatically; proto/sidecar.proto is
# the human-readable source of truth).
"""Generated protocol buffer code."""
from google.protobuf.internal import builder as _builder
from google.protobuf import descriptor as _descriptor
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database
# @@protoc_insertion_point(imports)

_sym_db = _symbol_database.Default()


DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile(b'\n\rsidecar.proto\x12\x19kubernetes_tpu.sidecar.v1"\xc2\x05\n\x08Envelope\x12\x0b\n\x03seq\x18\x01 \x01(\x04\x123\n\x03add\x18\x02 \x01(\x0b2$.kubernetes_tpu.sidecar.v1.AddObjectH\x00\x129\n\x06remove\x18\x03 \x01(\x0b2\'.kubernetes_tpu.sidecar.v1.RemoveObjectH\x00\x12C\n\x08schedule\x18\x04 \x01(\x0b2/.kubernetes_tpu.sidecar.v1.ScheduleBatchRequestH\x00\x127\n\x08response\x18\x05 \x01(\x0b2#.kubernetes_tpu.sidecar.v1.ResponseH\x00\x126\n\x04dump\x18\x06 \x01(\x0b2&.kubernetes_tpu.sidecar.v1.DumpRequestH\x00\x12@\n\tsubscribe\x18\x07 \x01(\x0b2+.kubernetes_tpu.sidecar.v1.SubscribeRequestH\x00\x12/\n\x04push\x18\x08 \x01(\x0b2\x1f.kubernetes_tpu.sidecar.v1.PushH\x00\x12:\n\x06health\x18\t \x01(\x0b2(.kubernetes_tpu.sidecar.v1.HealthRequestH\x00\x12E\n\x07metrics\x18\n \x01(\x0b2).kubernetes_tpu.sidecar.v1.MetricsRequestH\x00R\x07metrics\x12B\n\x06events\x18\x0b \x01(\x0b2(.kubernetes_tpu.sidecar.v1.EventsRequestH\x00R\x06events\x12B\n\x06flight\x18\x0c \x01(\x0b2(.kubernetes_tpu.sidecar.v1.FlightRequestH\x00R\x06flightB\x05\n\x03msg".\n\tAddObject\x12\x0c\n\x04kind\x18\x01 \x01(\t\x12\x13\n\x0bobject_json\x18\x02 \x01(\x0c")\n\x0cRemoveObject\x12\x0c\n\x04kind\x18\x01 \x01(\t\x12\x0b\n\x03uid\x18\x02 \x01(\t"x\n\x14ScheduleBatchRequest\x12\x10\n\x08pod_json\x18\x01 \x03(\x0c\x12\r\n\x05drain\x18\x02 \x01(\x08\x12\x19\n\x08trace_id\x18\x03 \x01(\tR\x07traceId\x12$\n\x0eparent_span_id\x18\x04 \x01(\tR\x0cparentSpanId"\xc9\x01\n\tPodResult\x12\x0f\n\x07pod_uid\x18\x01 \x01(\t\x12\x11\n\tnode_name\x18\x02 \x01(\t\x12\r\n\x05score\x18\x03 \x01(\x03\x12\x16\n\x0efeasible_nodes\x18\x04 \x01(\x05\x12\x1d\n\x15unschedulable_plugins\x18\x05 \x03(\t\x12\x16\n\x0enominated_node\x18\x06 \x01(\t\x12\x0f\n\x07victims\x18\x07 \x01(\x05\x12\x13\n\x0bvictim_uids\x18\x08 \x03(\t\x12\x14\n\x0cvictim_names\x18\t \x03(\t"\r\n\x0bDumpRequest"\x12\n\x10SubscribeRequest"~\n\x04Push\x12\r\n\x05epoch\x18\x01 \x01(\x04\x12\x16\n\x0einvalidate_all\x18\x02 \x01(\x08\x12\x17\n\x0finvalidate_uids\x18\x03 \x03(\t\x126\n\tdecisions\x18\x04 \x03(\x0b2#.kubernetes_tpu.sidecar.v1.Decision"t\n\x08Decision\x12\x0f\n\x07pod_uid\x18\x01 \x01(\t\x12\x11\n\tnode_name\x18\x02 \x01(\t\x12\r\n\x05score\x18\x03 \x01(\x03\x12\x16\n\x0efeasible_nodes\x18\x04 \x01(\x05\x12\x1d\n\x15unschedulable_plugins\x18\x05 \x03(\t"\x0f\n\rHealthRequest"\xf6\x01\n\x08Response\x12\r\n\x05error\x18\x01 \x01(\t\x125\n\x07results\x18\x02 \x03(\x0b2$.kubernetes_tpu.sidecar.v1.PodResult\x12\x11\n\tdump_json\x18\x03 \x01(\x0c\x12\x13\n\x0bhealth_json\x18\x04 \x01(\x0c\x12!\n\x0cmetrics_text\x18\x05 \x01(\x0cR\x0bmetricsText\x12\x1f\n\x0bevents_json\x18\x06 \x01(\x0cR\neventsJson\x12\x17\n\x07span_id\x18\x07 \x01(\tR\x06spanId\x12\x1f\n\x0bflight_json\x18\x08 \x01(\x0cR\nflightJson"\x10\n\x0eMetricsRequest"\x0f\n\rEventsRequest"%\n\rFlightRequest\x12\x14\n\x05limit\x18\x01 \x01(\rR\x05limitb\x06proto3')

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, 'sidecar_pb2', globals())
# @@protoc_insertion_point(module_scope)
