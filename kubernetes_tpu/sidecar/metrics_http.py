"""Plain-HTTP observability endpoint: /metrics, /healthz, /events.

The reference scheduler serves /metrics and /healthz from its secure
serving port (cmd/kube-scheduler/app/server.go:181–210 newHealthEndpoints
+ the component-base metrics handler); the sidecar's analog is this tiny
threaded HTTP listener, started by ``cmd_serve --http-port`` (or
``SidecarServer(http_port=...)``) next to the framed-socket protocol so
an unmodified Prometheus can scrape the engine without speaking frames.

The text payload is byte-identical to the sidecar `metrics` frame — both
render the same ``MetricsRegistry`` — which is what the tier-1 smoke test
asserts."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

# The Prometheus text exposition content type (format version 0.0.4).
CONTENT_TYPE_TEXT = "text/plain; version=0.0.4; charset=utf-8"


def health_state(scheduler, extra: dict | None = None) -> dict:
    """The /healthz (and sidecar health-frame) payload: liveness plus the
    cheap state counts an operator probes first."""
    state = {
        "healthy": True,
        "ready": True,
        "nodes": len(scheduler.cache.nodes),
        "pods": len(scheduler.cache.pods),
        "pending": len(scheduler.queue),
    }
    journal = getattr(scheduler, "journal", None)
    if journal is not None:
        # Durability probes: the epoch the writer holds and how far the
        # log has grown past its last checkpoint.
        state["journal"] = {
            "epoch": journal.epoch,
            "seq": journal.seq,
            "snapshot_seq": journal.snapshot_seq,
        }
    if extra:
        state.update(extra)
    return state


class ObservabilityHTTPServer:
    """Threaded HTTP listener over one scheduler's registry/events.

    Port 0 binds an ephemeral port (tests); read ``self.port`` after
    construction.  ``lock`` serializes /metrics against the scheduler:
    render_text() iterates (and its collectors mutate) dicts the
    scheduling thread concurrently grows, so an unlocked scrape can hit
    "dictionary changed size during iteration".  SidecarServer passes its
    dispatch lock — a scrape then reads a quiescent scheduler, exactly
    like the framed `metrics` kind; standalone embedders get a private
    lock, which at least serializes concurrent scrapes."""

    def __init__(
        self,
        scheduler,
        port: int = 0,
        host: str = "127.0.0.1",
        health_extra: dict | None = None,
        lock: "threading.Lock | None" = None,
    ):
        self.scheduler = scheduler
        self.health_extra = health_extra if health_extra is not None else {}
        self.lock = lock if lock is not None else threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    with outer.lock:
                        body = outer.scheduler.metrics.registry.render_text()
                    self._send(200, CONTENT_TYPE_TEXT, body.encode())
                elif path == "/healthz":
                    # Answering at all IS the liveness signal (the healthz
                    # contract), so NO dispatch lock here: a probe must not
                    # hang behind a long batch — /metrics is the deeper,
                    # serialized probe.  health_state only does len() calls
                    # (GIL-atomic snapshots).
                    state = health_state(outer.scheduler, outer.health_extra)
                    self._send(
                        200, "application/json", json.dumps(state).encode()
                    )
                elif path == "/events":
                    # EventBroadcaster.list() takes the broadcaster's own
                    # lock; no scheduler state is touched.
                    self._send(
                        200, "application/json",
                        json.dumps(outer.scheduler.events.list()).encode(),
                    )
                else:
                    self._send(404, "text/plain", b"not found\n")

            def _send(self, code: int, ctype: str, body: bytes) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args) -> None:  # scrapes are not news
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread: threading.Thread | None = None

    def serve_background(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
