"""Plain-HTTP observability endpoint: /metrics, /healthz, /events,
/debug/flight, /debug/trace, /debug/explain.

The reference scheduler serves /metrics and /healthz from its secure
serving port (cmd/kube-scheduler/app/server.go:181–210 newHealthEndpoints
+ the component-base metrics handler); the sidecar's analog is this tiny
threaded HTTP listener, started by ``cmd_serve --http-port`` (or
``SidecarServer(http_port=...)``) next to the framed-socket protocol so
an unmodified Prometheus can scrape the engine without speaking frames.

The text payload is byte-identical to the sidecar `metrics` frame — both
render the same ``MetricsRegistry`` — which is what the tier-1 smoke test
asserts.

Two backings, one handler:

- ``scheduler=`` (the sidecar deployment): serve the engine's registry,
  events, flight ring and health directly.
- ``client=`` (a host deployment's ``ResyncingClient``): serve THROUGH
  the resilient client — while the breaker is open, /metrics and /events
  keep answering from the host's own registry/fallback (the
  degraded-but-serving contract PR 2 established for the in-process
  path), and /healthz carries the breaker/degraded block so a liveness
  probe can tell degraded from dead.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

# The Prometheus text exposition content type (format version 0.0.4).
CONTENT_TYPE_TEXT = "text/plain; version=0.0.4; charset=utf-8"


def health_state(scheduler, extra: dict | None = None) -> dict:
    """The /healthz (and sidecar health-frame) payload: liveness plus the
    cheap state counts an operator probes first.  ``journal_armed`` is
    explicit either way — a probe must distinguish "durable and current"
    from "never journaling" without guessing from a missing key."""
    state = {
        "healthy": True,
        "ready": True,
        "nodes": len(scheduler.cache.nodes),
        "pods": len(scheduler.cache.pods),
        "pending": len(scheduler.queue),
    }
    journal = getattr(scheduler, "journal", None)
    state["journal_armed"] = journal is not None
    if journal is not None:
        # Durability probes: the epoch the writer holds and how far the
        # log has grown past its last checkpoint.
        state["journal"] = {
            "epoch": journal.epoch,
            "seq": journal.seq,
            "snapshot_seq": journal.snapshot_seq,
        }
    if extra:
        state.update(extra)
    return state


def _parse_limit(path: str) -> int:
    """?limit=N from a request path (0 = whole ring / default)."""
    if "?" not in path:
        return 0
    for part in path.split("?", 1)[1].split("&"):
        if part.startswith("limit="):
            try:
                return max(0, int(part[len("limit="):]))
            except ValueError:
                return 0
    return 0


def _parse_q(path: str, key: str) -> str:
    """?key=value from a request path ("" when absent), %-decoded so a
    "namespace/pod" uid survives the query string."""
    from urllib.parse import unquote

    if "?" not in path:
        return ""
    for part in path.split("?", 1)[1].split("&"):
        if part.startswith(key + "="):
            return unquote(part[len(key) + 1:])
    return ""


class ObservabilityHTTPServer:
    """Threaded HTTP listener over one scheduler's registry/events — or,
    with ``client=``, over a host's ResyncingClient (see module
    docstring).

    Port 0 binds an ephemeral port (tests); read ``self.port`` after
    construction.  ``lock`` serializes /metrics against the scheduler:
    render_text() iterates (and its collectors mutate) dicts the
    scheduling thread concurrently grows, so an unlocked scrape can hit
    "dictionary changed size during iteration".  SidecarServer passes its
    dispatch lock — a scrape then reads a quiescent scheduler, exactly
    like the framed `metrics` kind; standalone embedders get a private
    lock, which at least serializes concurrent scrapes."""

    def __init__(
        self,
        scheduler=None,
        port: int = 0,
        host: str = "127.0.0.1",
        health_extra: dict | None = None,
        lock: "threading.Lock | None" = None,
        client=None,
    ):
        if (scheduler is None) == (client is None):
            raise ValueError("pass exactly one of scheduler= or client=")
        self.scheduler = scheduler
        self.client = client
        self.health_extra = health_extra if health_extra is not None else {}
        self.lock = lock if lock is not None else threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = outer._metrics()
                    self._send(200, CONTENT_TYPE_TEXT, body.encode())
                elif path == "/healthz":
                    # Answering at all IS the liveness signal (the healthz
                    # contract), so NO dispatch lock on the scheduler
                    # path: a probe must not hang behind a long batch —
                    # /metrics is the deeper, serialized probe.
                    # health_state only does len() calls (GIL-atomic
                    # snapshots); the client path is deadline-bounded.
                    state = outer._health()
                    self._send(
                        200, "application/json", json.dumps(state).encode()
                    )
                elif path == "/events":
                    # EventBroadcaster.list() takes the broadcaster's own
                    # lock; no scheduler state is touched.
                    self._send(
                        200, "application/json",
                        json.dumps(outer._events()).encode(),
                    )
                elif path == "/debug/flight":
                    # Flight-recorder readout — same JSON the `flight`
                    # frame and the auto-dumps produce.
                    doc = outer._flight(_parse_limit(self.path))
                    self._send(
                        200, "application/json", json.dumps(doc).encode()
                    )
                elif path == "/debug/explain":
                    # Decision provenance: one pod's structured decision
                    # record (framework/provenance.py) — same JSON the
                    # `explain` frame and CLI subcommand produce.
                    uid = _parse_q(self.path, "uid")
                    if not uid:
                        self._send(
                            400, "text/plain", b"missing ?uid=\n"
                        )
                        return
                    seq = _parse_q(self.path, "seq")
                    try:
                        seq_n = int(seq) if seq else 0
                    except ValueError:
                        self._send(400, "text/plain", b"bad ?seq=\n")
                        return
                    doc = outer._explain(uid, seq_n)
                    self._send(
                        200, "application/json",
                        json.dumps(doc, sort_keys=True).encode(),
                    )
                elif path == "/debug/trace":
                    # Perfetto/Chrome trace-event rendering of the same
                    # ring (framework/trace_export.py) — open the body
                    # in ui.perfetto.dev / chrome://tracing.  Logical
                    # timebase: deterministic, wall fields stripped —
                    # byte-identical to the `trace` CLI subcommand.
                    body = outer._trace(_parse_limit(self.path))
                    self._send(200, "application/json", body.encode())
                else:
                    self._send(404, "text/plain", b"not found\n")

            def _send(self, code: int, ctype: str, body: bytes) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args) -> None:  # scrapes are not news
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread: threading.Thread | None = None

    # -- backends ----------------------------------------------------------

    def _metrics(self) -> str:
        if self.client is not None:
            # The ResyncingClient serves the wire text when healthy and
            # the host registry (+ fallback engine, if built) when the
            # breaker is open — /metrics answers either way.
            return self.client.metrics()
        with self.lock:
            return self.scheduler.metrics.registry.render_text()

    def _health(self) -> dict:
        if self.client is not None:
            state = self.client.health()
            if self.health_extra:
                state.update(self.health_extra)
            return state
        return health_state(self.scheduler, self.health_extra)

    def _events(self) -> list:
        if self.client is not None:
            return self.client.events()
        return self.scheduler.events.list()

    def _flight(self, limit: int) -> dict:
        if self.client is not None:
            return self.client.flight(limit)
        return self.scheduler.flight.snapshot(limit or None)

    def _explain(self, uid: str, seq: int = 0) -> dict:
        if self.client is not None:
            return self.client.explain(uid, seq)
        with self.lock:
            return self.scheduler.explain_pod(uid, seq=seq or None)

    def _trace(self, limit: int) -> str:
        from ..framework import trace_export

        return trace_export.render(self._flight(limit), timebase="logical")

    def serve_background(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
