"""Host-side resilience: survive a sidecar that crashes, restarts, OR hangs.

The reference scheduler is stateless across restarts — etcd is the truth
and a restarted scheduler rebuilds cache+queue from informer LIST+WATCH
(app/server.go:249–271 informers Start + WaitForCacheSync).  In the
two-tier split, the HOST holds that informer truth and the sidecar's
device state is a pure cache of it — so when the sidecar dies, the host
reconnects and replays its object store, and the fresh sidecar rebuilds
exactly like the reference rebuilds from the apiserver.

``ResyncingClient`` is that host piece: a SidecarClient wrapper that
mirrors every object it ships (the informer-store analog), puts a
deadline on every call, detects a dead OR hung connection, reconnects
with jittered bounded retries, replays the full store in dependency
order, and re-issues the failed call.  Bound pods are replayed WITH
their node (the host learned the binding from the schedule response — in
the reference the binding lives in etcd), so a restarted sidecar's
resource accounting matches the pre-crash cluster.

Beyond the resync: a CIRCUIT BREAKER.  After ``breaker_threshold``
consecutive failures the client stops hammering the sidecar and enters
DEGRADED mode — filter/score evaluate host-side on a local engine built
from the same mirrored store (the in-process ops path the wire normally
bypasses; being the same deterministic engine, degraded bindings are
bit-identical to healthy ones) — while a background thread re-probes the
sidecar and the next dispatch after a successful probe replays the store
and resumes wire dispatch.  Observable via ``scheduler_sidecar_state``
and ``scheduler_degraded_dispatches_total`` on ``client.registry``; the
same semantics are mirrored by the Go plugin (go/tpubatchscore/client.go
SetDeadline + breaker, plugin.go Skip→default path)."""

from __future__ import annotations

import random
import threading
import time

from ..api import serialize
from ..framework.flight import FlightRecorder
from ..framework.metrics import MetricsRegistry
from . import sidecar_pb2 as pb
from .server import DeadlineExceeded, SidecarClient, fill_result

# Replay order: everything a pod references must exist before the pod.
_REPLAY_ORDER = (
    "Node", "StorageClass", "PersistentVolume", "PersistentVolumeClaim",
    "CSINode", "PodGroup", "PodDisruptionBudget", "ResourceSlice",
    "ResourceClaim", "Pod",
)


def _key(kind: str, obj) -> str:
    # remove("Node", uid) takes the node NAME; pods key by uid.
    return obj.uid if kind == "Pod" else obj.name


class BreakerOpen(ConnectionError):
    """The circuit breaker tripped: the sidecar keeps failing and calls
    now degrade to host-side evaluation instead of hammering it."""


class ResyncingClient:
    def __init__(
        self,
        path: str,
        max_reconnect_s: float = 10.0,
        retry_interval_s: float = 0.05,
        deadline_s: float = 5.0,
        max_call_retries: int = 3,
        breaker_threshold: int = 3,
        probe_interval_s: float = 0.5,
        fallback_factory=None,
        socket_wrapper=None,
        registry=None,
        seed: int = 0,
        journal=None,
        journal_snapshot_every: int = 256,
    ):
        self.path = path
        self.max_reconnect_s = max_reconnect_s
        self.retry_interval_s = retry_interval_s
        # Per-call deadline (the SetDeadline the Go client mirrors): a
        # HUNG sidecar — process alive, dispatch wedged — fails calls in
        # bounded time instead of blocking the host forever.
        self.deadline_s = deadline_s
        # Reconnect+reissue attempts per call before the failure escapes.
        self.max_call_retries = max_call_retries
        # Consecutive failures (across calls) that open the breaker.
        self.breaker_threshold = breaker_threshold
        self.probe_interval_s = probe_interval_s
        # Degraded-mode engine factory; None → a default TPUScheduler.
        # Wire deployments pass the factory that matches the sidecar's
        # configuration so degraded decisions are bit-identical.
        self.fallback_factory = fallback_factory
        # Optional socket decorator applied on every (re)connect — the
        # fault-injection seam (faults.FaultPlan.wrap), so injected
        # faults survive reconnects like a genuinely sick sidecar would.
        self.socket_wrapper = socket_wrapper
        self.resyncs = 0  # observable: how many times the store was replayed
        self.degraded = False
        self._rng = random.Random(seed)  # jitter source, seedable
        self._consecutive_failures = 0
        self._store: dict[str, dict[str, object]] = {k: {} for k in _REPLAY_ORDER}
        self._ns_labels: dict[str, dict] = {}
        self.registry = registry or MetricsRegistry()
        self._state_gauge = self.registry.gauge(
            "scheduler_sidecar_state",
            "Sidecar dispatch state (1 on the active cell).",
        )
        self._degraded_counter = self.registry.counter(
            "scheduler_degraded_dispatches_total",
            "Schedule dispatches evaluated host-side (breaker open).",
        )
        self._timeout_counter = self.registry.counter(
            "scheduler_sidecar_call_timeouts_total",
            "Sidecar calls that hit the per-call deadline.",
        )
        self._breaker_counter = self.registry.counter(
            "scheduler_sidecar_breaker_trips_total",
            "Times consecutive failures opened the circuit breaker.",
        )
        # Wire round-trip attribution (the host half of the flight
        # recorder's phase story: what the sidecar's own phases can't see
        # is the tunnel + retry + resync cost of reaching it).
        self._rt_hist = self.registry.histogram(
            "scheduler_sidecar_round_trip_duration_seconds",
            "Wire round-trip duration of sidecar calls (retries and "
            "resyncs included), by call kind.",
        )
        # Host-side flight recorder: per-schedule wire timings plus the
        # breaker/degraded/resync transition markers; breaker trips
        # auto-dump (the incident the ring exists for).
        self.flight_recorder = FlightRecorder(component="host")
        self._fallback = None
        # Deletes applied while DEGRADED never reached the sidecar; a
        # hung-but-alive sidecar still holds those objects, so the
        # recovery replay (upserts only) must reconcile removals first.
        self._tombstones: list[tuple[str, str]] = []
        self._probe_stop = threading.Event()
        self._probe_thread: threading.Thread | None = None
        self._probe_conn: SidecarClient | None = None
        self._lock = threading.Lock()  # guards the probe handover
        # Serializes the whole client surface: the framed protocol is one
        # request/response stream per socket, so a metrics scrape thread
        # (ObservabilityHTTPServer(client=...)) interleaving with the
        # scheduling thread would desync seq numbers — or worse, frames.
        self._call_lock = threading.Lock()
        # Durable replay store (journal.Journal): when given, every
        # object upsert/remove and every learned BINDING is journaled
        # before the in-memory mirror mutates, and the mirror itself is
        # REBUILT from snapshot+journal at construction — a host kill no
        # longer forgets what it told the sidecar, and the post-crash
        # replay ships the same bound world a live host would have.
        self.journal = journal
        self.journal_snapshot_every = journal_snapshot_every
        if journal is not None:
            self._load_durable()
        self._client = self._connect()
        self._set_state("healthy")
        if journal is not None and (
            self._ns_labels or any(self._store.values())
        ):
            # Cold-start recovery: the fresh connection gets the durable
            # world before any caller traffic (the reference's
            # WaitForCacheSync-then-schedule ordering).
            self._replay()
            self.resyncs += 1
            # The dump is the artifact a killed-host chaos cell asserts:
            # a restarted host leaves evidence of what it recovered.
            self.flight_recorder.record_marker(
                "recovery",
                store={k: len(v) for k, v in self._store.items() if v},
            )
            self.flight_recorder.dump("recovery")

    # -- wiring ------------------------------------------------------------

    def _connect(self) -> SidecarClient:
        client = SidecarClient(self.path, deadline_s=self.deadline_s)
        if self.socket_wrapper is not None:
            client.sock = self.socket_wrapper(client.sock)
        return client

    def _set_state(self, state: str) -> None:
        for s in ("healthy", "degraded"):
            self._state_gauge.set(1.0 if s == state else 0.0, state=s)

    # -- informer-store bookkeeping ---------------------------------------

    def _record(self, kind: str, obj) -> None:
        self._store.setdefault(kind, {})[_key(kind, obj)] = obj

    # -- durable replay store (journal.py) ---------------------------------

    def _obj_from_data(self, kind: str, data: dict):
        if kind == "Pod":
            return serialize.pod_from_data(data)
        return serialize.build(serialize.KINDS[kind][0], data)

    def _load_durable(self) -> None:
        """Rebuild the replay store from snapshot + fenced journal replay
        (instead of only from the live mirror a dead process took with
        it)."""
        snap, records, _stats = self.journal.replay()
        if snap is not None:
            st = snap["state"]
            self._ns_labels = dict(st.get("ns_labels", {}))
            for kind, objs in st.get("store", {}).items():
                self._store[kind] = {}
                for data in objs:
                    obj = self._obj_from_data(kind, data)
                    self._store[kind][_key(kind, obj)] = obj
        for rec in records:
            rtype, d = rec["t"], rec["d"]
            if rtype == "add":
                obj = self._obj_from_data(d["kind"], d["obj"])
                self._store.setdefault(d["kind"], {})[
                    _key(d["kind"], obj)
                ] = obj
            elif rtype == "remove":
                self._apply_remove_local(d["kind"], d["uid"])
            elif rtype == "bind":
                p = self._store["Pod"].get(d["uid"])
                if p is not None:
                    p.spec.node_name = d["node"]
            elif rtype == "ns":
                self._ns_labels[d["namespace"]] = dict(d["labels"])

    def _journal_mutation(self, rtype: str, data: dict) -> None:
        if self.journal is not None:
            self.journal.append(rtype, data)

    def _journal_group(self):
        """One group-commit fsync barrier for a batch of mutations
        (journal.group(), ISSUE 15) — a no-op context when the replay
        store is unjournaled."""
        import contextlib

        if self.journal is None:
            return contextlib.nullcontext()
        return self.journal.group()

    def _maybe_checkpoint(self) -> None:
        """Checkpoint cadence — call AFTER the mutation has been applied
        to the in-memory store: the snapshot's seq covers every appended
        record and truncates the log, so snapshotting a store that does
        not yet hold the last record would durably lose it (the exact
        double-bind window the journal exists to close)."""
        j = self.journal
        if (
            j is not None
            and self.journal_snapshot_every
            and j.seq - j.snapshot_seq >= self.journal_snapshot_every
        ):
            j.snapshot(
                {
                    "ns_labels": dict(self._ns_labels),
                    "store": {
                        kind: [serialize.to_dict(o) for o in objs.values()]
                        for kind, objs in self._store.items()
                        if objs
                    },
                }
            )

    def _apply_remove_local(self, kind: str, uid: str) -> None:
        self._store.get(kind, {}).pop(uid, None)
        if kind == "Node":
            # Pods on a removed node vanish from scheduling state (the
            # engine's remove_node contract); the store must mirror that
            # or a later replay re-adds pods bound to a node that no
            # longer exists — a server-side error that wedges the replay.
            self._store["Pod"] = {
                u: p
                for u, p in self._store["Pod"].items()
                if p.spec.node_name != uid
            }

    # -- reconnect + replay ------------------------------------------------

    def _reconnect(self) -> None:
        deadline = time.monotonic() + self.max_reconnect_s
        while True:
            try:
                self._client = self._connect()
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise ConnectionError(
                        f"sidecar at {self.path} did not come back within "
                        f"{self.max_reconnect_s}s"
                    )
                time.sleep(self.retry_interval_s)
        self._replay()
        self.resyncs += 1
        self.flight_recorder.record_marker("resync", resyncs=self.resyncs)

    def _replay(self) -> None:
        for ns, labels in self._ns_labels.items():
            self._client.set_namespace_labels(ns, labels)
        for kind in _REPLAY_ORDER:
            for obj in self._store.get(kind, {}).values():
                self._client.add(kind, obj)

    def _note_failure(self, exc: Exception) -> None:
        """Count one failed attempt; trips the breaker at the threshold."""
        if isinstance(exc, DeadlineExceeded):
            self._timeout_counter.inc()
        self._consecutive_failures += 1
        if self._consecutive_failures >= self.breaker_threshold:
            self._enter_degraded()
            raise BreakerOpen(
                f"{self._consecutive_failures} consecutive sidecar failures"
                f" (last: {exc})"
            ) from exc

    def _with_resync(self, fn):
        """Run ``fn`` against the live client.  On a dead/hung connection,
        reconnect+replay and re-issue in a BOUNDED loop with jittered
        sleeps — a second crash during the replay or the re-issued call is
        retried, not fatal.  ``breaker_threshold`` consecutive failures
        raise BreakerOpen instead (the caller degrades host-side)."""
        attempts = 0
        while True:
            try:
                result = fn()
            except (ConnectionError, BrokenPipeError, OSError) as exc:
                failure = exc
            else:
                self._consecutive_failures = 0
                return result
            while True:
                self._note_failure(failure)  # may raise BreakerOpen
                attempts += 1
                if attempts > self.max_call_retries:
                    raise failure
                time.sleep(self.retry_interval_s * (0.5 + self._rng.random()))
                try:
                    self._reconnect()
                    break
                except (ConnectionError, BrokenPipeError, OSError) as exc:
                    failure = exc

    # -- degraded mode -----------------------------------------------------

    def _enter_degraded(self) -> None:
        if self.degraded:
            return
        self.degraded = True
        self._breaker_counter.inc()
        self._set_state("degraded")
        # The page-worthy transition: mark it and persist the evidence
        # (the ring holds the wire timings leading up to the trip).
        self.flight_recorder.record_marker(
            "breaker_trip", consecutive_failures=self._consecutive_failures
        )
        self.flight_recorder.record_marker("degraded_enter")
        self.flight_recorder.dump("breaker_trip")
        try:
            self._client.close()
        except OSError:
            pass
        self._start_probe()

    def _start_probe(self) -> None:
        self._probe_stop.clear()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, daemon=True
        )
        self._probe_thread.start()

    def _probe_loop(self) -> None:
        """Background re-probe while degraded: dial + health until the
        sidecar answers, then park the verified connection for the next
        dispatch — the replay must interleave with the store, which only
        the caller's thread may touch."""
        while not self._probe_stop.wait(self.probe_interval_s):
            try:
                conn = self._connect()
                conn.health()
            except Exception:
                continue
            with self._lock:
                if self._probe_stop.is_set():
                    # close() already drained the handover slot: a
                    # connection parked now would leak.
                    conn.close()
                    return
                self._probe_conn = conn
            return

    def _maybe_recover(self) -> None:
        """Complete a recovery the probe thread initiated: replay the
        store through its verified connection and resume wire dispatch."""
        if not self.degraded:
            return
        with self._lock:
            conn, self._probe_conn = self._probe_conn, None
        if conn is None:
            return
        self._client = conn
        try:
            if self._tombstones:
                # The sidecar survived the outage WITH state: deletes made
                # while degraded (node removals, preemption victims) must
                # land before the upsert replay, or recovery resurrects
                # phantom objects a later batch could bind onto.  Node
                # removes are guarded by the live dump (remove_node of an
                # unknown node is a server error); pod deletes are
                # idempotent for unknown uids.
                state = self._client.dump()
                for kind, uid in self._tombstones:
                    if kind == "Node" and uid not in state.get("nodes", {}):
                        continue
                    self._client.remove(kind, uid)
            self._replay()
        except (ConnectionError, BrokenPipeError, OSError):
            # Died again between probe and replay: stay degraded.
            self._start_probe()
            return
        self._tombstones.clear()
        self.resyncs += 1
        self.degraded = False
        self._consecutive_failures = 0
        self._set_state("healthy")
        self.flight_recorder.record_marker(
            "degraded_exit", resyncs=self.resyncs
        )
        self._fallback = None  # its bindings live in the store; rebuild fresh

    def _ensure_fallback(self):
        """The degraded-mode engine, built by replaying the mirrored store
        host-side — the same in-process ops/eval path the wire normally
        offloads, so a breaker-open host keeps making progress with
        bit-identical decisions."""
        if self._fallback is None:
            from ..scheduler import TPUScheduler

            fb = (self.fallback_factory or TPUScheduler)()
            for ns, labels in self._ns_labels.items():
                fb.builder.set_namespace_labels(ns, dict(labels))
            for kind in _REPLAY_ORDER:
                for obj in self._store.get(kind, {}).values():
                    getattr(fb, serialize.KINDS[kind][1])(obj)
            self._fallback = fb
        return self._fallback

    def _dispatch_degraded(self, pods, drain: bool) -> list[pb.PodResult]:
        self._degraded_counter.inc()
        fb = self._ensure_fallback()
        for p in pods:
            fb.update_pod(p)
        outcomes = fb.schedule_all_pending() if drain else fb.schedule_batch()
        results = [fill_result(pb.PodResult(), o) for o in outcomes]
        for r in results:
            for vu in r.victim_uids:
                # Victims evicted host-side: the hung sidecar still holds
                # them bound — reconcile on recovery.
                self._tombstones.append(("Pod", vu))
        return results

    # -- client surface ----------------------------------------------------

    def _call_or_degraded(self, wire_fn, degraded_fn, kind: str = "call"):
        """The whole client-surface protocol in ONE place: finish any
        recovery the probe initiated, serve host-side while degraded,
        otherwise try the wire — with resync retries — and degrade when
        the breaker opens mid-call.  ``wire_fn`` must re-read
        ``self._client`` (a lambda over the attribute) so a retry after a
        reconnect targets the NEW connection.  Successful wire calls are
        timed into the round-trip histogram under ``kind`` (retries and
        replays included — the cost of REACHING the sidecar is exactly
        what the sidecar's own phase timings cannot see).  The call lock
        makes the surface thread-safe: one request/response at a time on
        the shared framed socket (and one mutator at a time on the
        store/fallback) — without it an HTTP scrape thread
        (ObservabilityHTTPServer(client=...)) interleaving with the
        scheduling thread would desync the frame stream."""
        with self._call_lock:
            self._maybe_recover()
            if not self.degraded:
                t0 = time.perf_counter()
                try:
                    result = self._with_resync(wire_fn)
                except BreakerOpen:
                    pass
                else:
                    self._rt_hist.observe(
                        time.perf_counter() - t0, call=kind
                    )
                    return result
            return degraded_fn()

    def set_namespace_labels(self, namespace: str, labels: dict) -> None:
        self._journal_mutation(
            "ns", {"namespace": namespace, "labels": dict(labels)}
        )
        self._ns_labels[namespace] = dict(labels)
        self._maybe_checkpoint()
        self._call_or_degraded(
            lambda: self._client.set_namespace_labels(namespace, labels),
            lambda: self._ensure_fallback().builder.set_namespace_labels(
                namespace, dict(labels)
            ),
            kind="add",
        )

    def add(self, kind: str, obj) -> None:
        self._journal_mutation(
            "add", {"kind": kind, "obj": serialize.to_dict(obj)}
        )
        self._record(kind, obj)
        self._maybe_checkpoint()
        self._call_or_degraded(
            lambda: self._client.add(kind, obj),
            lambda: self._fallback_add(kind, obj),
            kind="add",
        )

    def _fallback_add(self, kind: str, obj) -> None:
        fb = self._ensure_fallback()
        getattr(fb, serialize.KINDS[kind][1])(obj)

    def add_pending_batch(self, pods) -> None:
        """Ship one coalesced PendingPods hint frame (the flusher shape
        the soak driver and the Go plugin's informer backlog use).
        Hints are NOT cluster mutations: they are neither journaled nor
        mirrored into the replay store (a pod the scheduler never asks
        about must not be replayed into a restarted sidecar as if it
        were state), and while degraded they are simply dropped — the
        pods arrive again through Schedule, which is always correct."""
        self._call_or_degraded(
            lambda: self._client.add_pending_batch(pods),
            lambda: None,
            kind="add",
        )

    def remove(self, kind: str, uid: str) -> None:
        self._journal_mutation("remove", {"kind": kind, "uid": uid})
        self._apply_remove_local(kind, uid)
        self._maybe_checkpoint()
        self._call_or_degraded(
            lambda: self._client.remove(kind, uid),
            lambda: self._fallback_remove(kind, uid),
            kind="remove",
        )

    def _fallback_remove(self, kind: str, uid: str) -> None:
        self._tombstones.append((kind, uid))
        fb = self._ensure_fallback()
        if kind == "Node":
            # Tolerant: when the breaker opened on this very remove, the
            # fallback was just built from the store that ALREADY dropped
            # the node — there is nothing left to remove.
            if uid in fb.cache.nodes:
                fb.remove_node(uid)
        elif kind == "Pod":
            fb.delete_pod(uid)  # lenient for unknown uids
        else:
            remover = serialize.REMOVERS.get(kind)
            if remover is not None:
                getattr(fb, remover)(uid)  # the removers tolerate unknowns

    # Observability reads during an outage must not FORCE the fallback
    # engine into existence (its build replays the whole mirrored store —
    # seconds at scale) and must keep serving the outage-describing host
    # series: read the fallback only when a dispatch already built it.

    def dump(self) -> dict:
        return self._call_or_degraded(
            lambda: self._client.dump(),
            lambda: (
                self._fallback.dump_state()
                if self._fallback is not None
                else {
                    "degraded": True,
                    "store": {k: len(v) for k, v in self._store.items() if v},
                }
            ),
            kind="dump",
        )

    def host_health(self) -> dict:
        """The host's OWN health block (no wire touched): breaker and
        degraded state, so a liveness probe can tell degraded-but-serving
        from healthy — and from dead."""
        return {
            "sidecar_state": "degraded" if self.degraded else "healthy",
            "degraded": self.degraded,
            "breaker": {
                "consecutive_failures": self._consecutive_failures,
                "threshold": self.breaker_threshold,
                "trips": int(self._breaker_counter.total()),
            },
            "resyncs": self.resyncs,
            "pending_tombstones": len(self._tombstones),
            "journal_armed": self.journal is not None,
        }

    def health(self) -> dict:
        """healthz through the host: the sidecar's health frame when the
        wire is up, a host-synthesized liveness payload when degraded —
        always carrying the ``host`` breaker/degraded block."""
        state = self._call_or_degraded(
            lambda: self._client.health(),
            # Degraded-but-serving IS healthy for a liveness probe; the
            # host block below says which kind of healthy.
            lambda: {"healthy": True, "ready": True, "source": "host"},
            kind="health",
        )
        state["host"] = self.host_health()
        return state

    def flight(self, limit: int = 0) -> dict:
        """Flight-recorder readout through the host: the sidecar's ring
        when reachable (plus the host's own ring under ``host`` — wire
        round-trip timings and breaker/resync markers), the host ring
        alone while degraded."""
        doc = self._call_or_degraded(
            lambda: self._client.flight(limit),
            lambda: {"component": "scheduler", "unreachable": True,
                     "records": []},
            kind="flight",
        )
        doc["host"] = self.flight_recorder.snapshot(limit or None)
        return doc

    def explain(self, uid: str, seq: int = 0) -> dict:
        """Decision-provenance readout through the host: the sidecar's
        record when reachable, the warm-standby fallback engine's while
        degraded (its ring only holds decisions IT made), else an
        unreachable marker — never an exception for a read path."""
        return self._call_or_degraded(
            lambda: self._client.explain(uid, seq),
            lambda: (
                self._fallback.explain_pod(uid, seq=seq or None)
                if self._fallback is not None
                else {"uid": uid, "error": "sidecar unreachable (degraded)"}
            ),
            kind="explain",
        )

    def fleet(self, op: str, payload: dict | None = None) -> dict:
        """One partitioned-fleet protocol op against a shard owner behind
        this client (fleet/owner.py).  Fleet ops have NO degraded
        fallback by design: a shard owner the breaker gave up on is
        exactly the condition the fleet answers with TAKEOVER
        (fleet/takeover.py) — scheduling around it host-side would fork
        the shard's journal."""

        def _unreachable() -> dict:
            raise ConnectionError(
                f"fleet op {op!r}: shard owner unreachable (degraded) — "
                "take the shard over instead of degrading"
            )

        return self._call_or_degraded(
            lambda: self._client.fleet(op, payload),
            _unreachable,
            kind="fleet",
        )

    def _degraded_metrics(self) -> str:
        text = self.registry.render_text()
        if self._fallback is not None:
            # Disjoint family names: the host registry carries the
            # scheduler_sidecar_* series, the engine its scheduling ones.
            text += self._fallback.metrics.registry.render_text()
        return text

    def metrics(self) -> str:
        return self._call_or_degraded(
            lambda: self._client.metrics(), self._degraded_metrics,
            kind="metrics",
        )

    def events(self) -> list[dict]:
        return self._call_or_degraded(
            lambda: self._client.events(),
            lambda: (
                self._fallback.events.list()
                if self._fallback is not None
                else []
            ),
            kind="events",
        )

    def schedule(
        self, pods=(), drain: bool = True, trace=None
    ) -> list[pb.PodResult]:
        # Pending pods enter the store UNBOUND first: if the sidecar dies
        # mid-call the replay re-submits them (at-least-once; the engine's
        # upsert path makes re-delivery idempotent).  Journaled for the
        # same reason — a restarted HOST must re-submit them too.  Group
        # commit (ISSUE 15): ONE fsync barrier for the whole batch's add
        # records instead of one per pod, with the store mutations (the
        # apply) deferred past the barrier — journal-before-apply at
        # group scope, same contract as the scheduler's commit drain.
        pods = list(pods)
        with self._journal_group():
            for p in pods:
                self._journal_mutation(
                    "add", {"kind": "Pod", "obj": serialize.to_dict(p)}
                )
        for p in pods:
            self._record("Pod", p)
        t_wire = time.perf_counter()
        results = self._call_or_degraded(
            lambda: self._client.schedule(pods, drain=drain, trace=trace),
            lambda: self._dispatch_degraded(pods, drain),
            kind="schedule",
        )
        # Host flight record: the wire (or degraded host-eval) cost of
        # this dispatch — the phase the sidecar's own recorder can't see.
        # Empty drain polls stay off the ring (same gate as the
        # scheduler side): a 0.3s settle loop would otherwise evict every
        # incident-relevant record within minutes.
        if pods or any(r.node_name for r in results):
            self.flight_recorder.record_batch(
                {
                    "call": "schedule",
                    "pods": len(pods),
                    "bound": sum(1 for r in results if r.node_name),
                    "degraded": self.degraded,
                    "phases": {
                        "wire": round(time.perf_counter() - t_wire, 6)
                    },
                }
            )
        # Record bindings: the reference host persists them via the
        # apiserver; here the store is that persistence, so a later replay
        # re-adds bound pods as cache adds with their node set.
        by_uid = {p.uid: p for p in pods}
        staged_binds: list[tuple] = []  # (pod, node) applied post-barrier
        staged_removes: list[str] = []
        with self._journal_group():
            for r in results:
                p = by_uid.get(r.pod_uid) or self._store["Pod"].get(r.pod_uid)
                if p is None:
                    continue
                if r.node_name:
                    # Write-ahead: the learned binding is durable before
                    # the mirror records it — a host kill between the
                    # response and the next replay can no longer forget a
                    # commit the sidecar already made (the double-bind
                    # window).  The whole batch's records share one group
                    # fsync; the mirror mutations below run only after
                    # the barrier returned.
                    self._journal_mutation(
                        "bind", {"uid": r.pod_uid, "node": r.node_name}
                    )
                    staged_binds.append((p, r.node_name))
                for vu in r.victim_uids:
                    # Preemption victims were deleted sidecar-side;
                    # mirror that.
                    self._journal_mutation(
                        "remove", {"kind": "Pod", "uid": vu}
                    )
                    staged_removes.append(vu)
        for p, node_name in staged_binds:
            p.spec.node_name = node_name
        for vu in staged_removes:
            self._store["Pod"].pop(vu, None)
        self._maybe_checkpoint()
        return results

    def close(self) -> None:
        self._probe_stop.set()
        with self._lock:
            conn, self._probe_conn = self._probe_conn, None
        if conn is not None:
            conn.close()
        self._client.close()


class DecisionCache:
    """The plugin-local decision map fed by the sidecar's push stream —
    the Python emulation of the Go plugin's subscriber goroutine
    (go/tpubatchscore/plugin.go Subscriber), used by tests and the
    integrated benchmark driver.

    Owns its own subscribed connection and applies Push frames strictly
    in stream order, which is the whole consistency contract
    (proto/sidecar.proto Push): an invalidation frame precedes any
    decision recomputed after it, so an in-order consumer can never hold
    a decision from a rolled-back epoch.  A dedicated reader thread keeps
    the socket drained at all times (a stalled subscriber is dropped by
    the sidecar's bounded-blocking push); ``drain()`` then applies the
    buffered frames in the consumer's thread.  After a miss response the
    triggering batch's pushes were written BEFORE the response (same
    dispatch lock), so ``drain(min_frames=1)`` only ever waits out the
    reader thread's scheduling latency, not the sidecar.

    Across a sidecar RESTART the map is a dead epoch: the reader thread
    sees EOF, ``drain`` surfaces ConnectionError instead of pretending
    liveness, and the consumer falls back to the wire for every pod (a
    miss is always correct — the wire path re-evaluates) until it builds
    a fresh DecisionCache against the new sidecar."""

    def __init__(self, path: str):
        import threading

        self.client = SidecarClient(path)
        self.client.subscribe()
        self.sock = self.client.sock
        self.buf = bytearray()
        self.map: dict[str, pb.Decision] = {}
        self.epoch = 0
        self.frames = 0
        self._cond = threading.Condition()
        self._closed = False
        # The reader thread ONLY moves bytes off the socket — the Go
        # plugin's subscriber goroutine.  It must always be draining:
        # push frames can exceed the socket buffers (a big batch's
        # decisions), and the sidecar's bounded-blocking push drops a
        # subscriber whose socket stays full.  Frame parsing and map
        # application stay in the consumer thread, in stream order.
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def _read_loop(self) -> None:
        while True:
            try:
                chunk = self.sock.recv(1 << 20)
            except OSError:
                chunk = b""
            with self._cond:
                if chunk:
                    self.buf += chunk
                else:
                    self._closed = True
                self._cond.notify_all()
            if not chunk:
                return

    def drain(self, min_frames: int = 0, timeout: float = 1.0) -> int:
        """Apply every complete buffered Push frame; with ``min_frames``,
        wait up to ``timeout`` for at least that many (after a miss
        response, the triggering batch's pushes were written before the
        response, but the reader thread may still be mid-recv)."""
        deadline = None
        n = 0
        while True:
            with self._cond:
                frames, self.buf = self._frames_from(self.buf)
                if not frames and n < min_frames and not self._closed:
                    import time as _t

                    if deadline is None:
                        deadline = _t.monotonic() + timeout
                    left = deadline - _t.monotonic()
                    if left > 0:
                        self._cond.wait(left)
                        continue
            for push in frames:
                self._apply(push)
            n += len(frames)
            if n >= min_frames or not frames:
                break
        self.frames += n
        if n < min_frames and self._closed:
            raise ConnectionError("push stream closed")
        return n

    @staticmethod
    def _frames_from(buf: bytearray) -> tuple[list, bytearray]:
        out = []
        off = 0
        while len(buf) - off >= 4:
            ln = int.from_bytes(buf[off : off + 4], "big")
            if len(buf) - off - 4 < ln:
                break
            env = pb.Envelope()
            env.ParseFromString(bytes(buf[off + 4 : off + 4 + ln]))
            out.append(env.push)
            off += 4 + ln
        return out, buf[off:] if off else buf

    def _apply(self, push: pb.Push) -> None:
        # Invalidations first — a frame never carries both a rollback and
        # decisions from before it (the sidecar emits them separately, in
        # epoch order).
        if push.invalidate_all:
            self.map.clear()
        for uid in push.invalidate_uids:
            self.map.pop(uid, None)
        self.epoch = push.epoch
        for d in push.decisions:
            self.map[d.pod_uid] = d

    def pop(self, uid: str) -> pb.Decision | None:
        """Consume the cached decision for ``uid`` (PreFilter answering
        from the local map — schedule_one.go:491–502's cached-placement
        precedent), or None → the caller falls back to the wire."""
        return self.map.pop(uid, None)

    def close(self) -> None:
        self.client.close()
