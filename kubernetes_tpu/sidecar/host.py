"""Host-side resync: survive a sidecar crash/restart.

The reference scheduler is stateless across restarts — etcd is the truth
and a restarted scheduler rebuilds cache+queue from informer LIST+WATCH
(app/server.go:249–271 informers Start + WaitForCacheSync).  In the
two-tier split, the HOST holds that informer truth and the sidecar's
device state is a pure cache of it — so when the sidecar dies, the host
reconnects and replays its object store, and the fresh sidecar rebuilds
exactly like the reference rebuilds from the apiserver.

``ResyncingClient`` is that host piece: a SidecarClient wrapper that
mirrors every object it ships (the informer-store analog), detects a dead
connection on any call, reconnects with backoff, replays the full store
in dependency order, and then re-issues the failed call.  Bound pods are
replayed WITH their node (the host learned the binding from the schedule
response — in the reference the binding lives in etcd), so a restarted
sidecar's resource accounting matches the pre-crash cluster."""

from __future__ import annotations

import time

from ..api import serialize
from . import sidecar_pb2 as pb
from .server import SidecarClient

# Replay order: everything a pod references must exist before the pod.
_REPLAY_ORDER = (
    "Node", "StorageClass", "PersistentVolume", "PersistentVolumeClaim",
    "CSINode", "PodGroup", "PodDisruptionBudget", "ResourceSlice",
    "ResourceClaim", "Pod",
)


def _key(kind: str, obj) -> str:
    # remove("Node", uid) takes the node NAME; pods key by uid.
    return obj.uid if kind == "Pod" else obj.name


class ResyncingClient:
    def __init__(
        self,
        path: str,
        max_reconnect_s: float = 10.0,
        retry_interval_s: float = 0.05,
    ):
        self.path = path
        self.max_reconnect_s = max_reconnect_s
        self.retry_interval_s = retry_interval_s
        self.resyncs = 0  # observable: how many times the store was replayed
        self._store: dict[str, dict[str, object]] = {k: {} for k in _REPLAY_ORDER}
        self._ns_labels: dict[str, dict] = {}
        self._client = SidecarClient(path)

    # -- informer-store bookkeeping ---------------------------------------

    def _record(self, kind: str, obj) -> None:
        self._store.setdefault(kind, {})[_key(kind, obj)] = obj

    # -- reconnect + replay ------------------------------------------------

    def _reconnect(self) -> None:
        deadline = time.monotonic() + self.max_reconnect_s
        while True:
            try:
                self._client = SidecarClient(self.path)
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise ConnectionError(
                        f"sidecar at {self.path} did not come back within "
                        f"{self.max_reconnect_s}s"
                    )
                time.sleep(self.retry_interval_s)
        self._replay()
        self.resyncs += 1

    def _replay(self) -> None:
        for ns, labels in self._ns_labels.items():
            self._client.set_namespace_labels(ns, labels)
        for kind in _REPLAY_ORDER:
            for obj in self._store.get(kind, {}).values():
                self._client.add(kind, obj)

    def _with_resync(self, fn):
        """Run ``fn`` against the live client; on a dead connection,
        reconnect+replay once and re-issue."""
        try:
            return fn()
        except (ConnectionError, BrokenPipeError, OSError):
            self._reconnect()
            return fn()

    # -- client surface ----------------------------------------------------

    def set_namespace_labels(self, namespace: str, labels: dict) -> None:
        self._ns_labels[namespace] = dict(labels)
        self._with_resync(
            lambda: self._client.set_namespace_labels(namespace, labels)
        )

    def add(self, kind: str, obj) -> None:
        self._record(kind, obj)
        self._with_resync(lambda: self._client.add(kind, obj))

    def remove(self, kind: str, uid: str) -> None:
        self._store.get(kind, {}).pop(uid, None)
        self._with_resync(lambda: self._client.remove(kind, uid))

    def dump(self) -> dict:
        # NB: lambda re-reads self._client so the retry after a reconnect
        # targets the NEW connection, not the dead one's bound method.
        return self._with_resync(lambda: self._client.dump())

    def metrics(self) -> str:
        return self._with_resync(lambda: self._client.metrics())

    def events(self) -> list[dict]:
        return self._with_resync(lambda: self._client.events())

    def schedule(
        self, pods=(), drain: bool = True, trace=None
    ) -> list[pb.PodResult]:
        # Pending pods enter the store UNBOUND first: if the sidecar dies
        # mid-call the replay re-submits them (at-least-once; the engine's
        # upsert path makes re-delivery idempotent).
        pods = list(pods)
        for p in pods:
            self._record("Pod", p)
        results = self._with_resync(
            lambda: self._client.schedule(pods, drain=drain, trace=trace)
        )
        # Record bindings: the reference host persists them via the
        # apiserver; here the store is that persistence, so a later replay
        # re-adds bound pods as cache adds with their node set.
        by_uid = {p.uid: p for p in pods}
        for r in results:
            p = by_uid.get(r.pod_uid)
            if p is None:
                rec = self._store["Pod"].get(r.pod_uid)
                p = rec if rec is not None else None
            if p is None:
                continue
            if r.node_name:
                p.spec.node_name = r.node_name
            for vu in r.victim_uids:
                # Preemption victims were deleted sidecar-side; mirror that.
                self._store["Pod"].pop(vu, None)
        return results

    def close(self) -> None:
        self._client.close()


class DecisionCache:
    """The plugin-local decision map fed by the sidecar's push stream —
    the Python emulation of the Go plugin's subscriber goroutine
    (go/tpubatchscore/plugin.go Subscriber), used by tests and the
    integrated benchmark driver.

    Owns its own subscribed connection and applies Push frames strictly
    in stream order, which is the whole consistency contract
    (proto/sidecar.proto Push): an invalidation frame precedes any
    decision recomputed after it, so an in-order consumer can never hold
    a decision from a rolled-back epoch.  A dedicated reader thread keeps
    the socket drained at all times (a stalled subscriber is dropped by
    the sidecar's bounded-blocking push); ``drain()`` then applies the
    buffered frames in the consumer's thread.  After a miss response the
    triggering batch's pushes were written BEFORE the response (same
    dispatch lock), so ``drain(min_frames=1)`` only ever waits out the
    reader thread's scheduling latency, not the sidecar."""

    def __init__(self, path: str):
        import threading

        self.client = SidecarClient(path)
        self.client.subscribe()
        self.sock = self.client.sock
        self.buf = bytearray()
        self.map: dict[str, pb.Decision] = {}
        self.epoch = 0
        self.frames = 0
        self._cond = threading.Condition()
        self._closed = False
        # The reader thread ONLY moves bytes off the socket — the Go
        # plugin's subscriber goroutine.  It must always be draining:
        # push frames can exceed the socket buffers (a big batch's
        # decisions), and the sidecar's bounded-blocking push drops a
        # subscriber whose socket stays full.  Frame parsing and map
        # application stay in the consumer thread, in stream order.
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def _read_loop(self) -> None:
        while True:
            try:
                chunk = self.sock.recv(1 << 20)
            except OSError:
                chunk = b""
            with self._cond:
                if chunk:
                    self.buf += chunk
                else:
                    self._closed = True
                self._cond.notify_all()
            if not chunk:
                return

    def drain(self, min_frames: int = 0, timeout: float = 1.0) -> int:
        """Apply every complete buffered Push frame; with ``min_frames``,
        wait up to ``timeout`` for at least that many (after a miss
        response, the triggering batch's pushes were written before the
        response, but the reader thread may still be mid-recv)."""
        deadline = None
        n = 0
        while True:
            with self._cond:
                frames, self.buf = self._frames_from(self.buf)
                if not frames and n < min_frames and not self._closed:
                    import time as _t

                    if deadline is None:
                        deadline = _t.monotonic() + timeout
                    left = deadline - _t.monotonic()
                    if left > 0:
                        self._cond.wait(left)
                        continue
            for push in frames:
                self._apply(push)
            n += len(frames)
            if n >= min_frames or not frames:
                break
        self.frames += n
        if n < min_frames and self._closed:
            raise ConnectionError("push stream closed")
        return n

    @staticmethod
    def _frames_from(buf: bytearray) -> tuple[list, bytearray]:
        out = []
        off = 0
        while len(buf) - off >= 4:
            ln = int.from_bytes(buf[off : off + 4], "big")
            if len(buf) - off - 4 < ln:
                break
            env = pb.Envelope()
            env.ParseFromString(bytes(buf[off + 4 : off + 4 + ln]))
            out.append(env.push)
            off += 4 + ln
        return out, buf[off:] if off else buf

    def _apply(self, push: pb.Push) -> None:
        # Invalidations first — a frame never carries both a rollback and
        # decisions from before it (the sidecar emits them separately, in
        # epoch order).
        if push.invalidate_all:
            self.map.clear()
        for uid in push.invalidate_uids:
            self.map.pop(uid, None)
        self.epoch = push.epoch
        for d in push.decisions:
            self.map[d.pod_uid] = d

    def pop(self, uid: str) -> pb.Decision | None:
        """Consume the cached decision for ``uid`` (PreFilter answering
        from the local map — schedule_one.go:491–502's cached-placement
        precedent), or None → the caller falls back to the wire."""
        return self.map.pop(uid, None)

    def close(self) -> None:
        self.client.close()
