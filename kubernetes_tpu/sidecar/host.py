"""Host-side resync: survive a sidecar crash/restart.

The reference scheduler is stateless across restarts — etcd is the truth
and a restarted scheduler rebuilds cache+queue from informer LIST+WATCH
(app/server.go:249–271 informers Start + WaitForCacheSync).  In the
two-tier split, the HOST holds that informer truth and the sidecar's
device state is a pure cache of it — so when the sidecar dies, the host
reconnects and replays its object store, and the fresh sidecar rebuilds
exactly like the reference rebuilds from the apiserver.

``ResyncingClient`` is that host piece: a SidecarClient wrapper that
mirrors every object it ships (the informer-store analog), detects a dead
connection on any call, reconnects with backoff, replays the full store
in dependency order, and then re-issues the failed call.  Bound pods are
replayed WITH their node (the host learned the binding from the schedule
response — in the reference the binding lives in etcd), so a restarted
sidecar's resource accounting matches the pre-crash cluster."""

from __future__ import annotations

import time

from ..api import serialize
from . import sidecar_pb2 as pb
from .server import SidecarClient

# Replay order: everything a pod references must exist before the pod.
_REPLAY_ORDER = (
    "Node", "StorageClass", "PersistentVolume", "PersistentVolumeClaim",
    "CSINode", "PodGroup", "PodDisruptionBudget", "ResourceSlice",
    "ResourceClaim", "Pod",
)


def _key(kind: str, obj) -> str:
    # remove("Node", uid) takes the node NAME; pods key by uid.
    return obj.uid if kind == "Pod" else obj.name


class ResyncingClient:
    def __init__(
        self,
        path: str,
        max_reconnect_s: float = 10.0,
        retry_interval_s: float = 0.05,
    ):
        self.path = path
        self.max_reconnect_s = max_reconnect_s
        self.retry_interval_s = retry_interval_s
        self.resyncs = 0  # observable: how many times the store was replayed
        self._store: dict[str, dict[str, object]] = {k: {} for k in _REPLAY_ORDER}
        self._ns_labels: dict[str, dict] = {}
        self._client = SidecarClient(path)

    # -- informer-store bookkeeping ---------------------------------------

    def _record(self, kind: str, obj) -> None:
        self._store.setdefault(kind, {})[_key(kind, obj)] = obj

    # -- reconnect + replay ------------------------------------------------

    def _reconnect(self) -> None:
        deadline = time.monotonic() + self.max_reconnect_s
        while True:
            try:
                self._client = SidecarClient(self.path)
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise ConnectionError(
                        f"sidecar at {self.path} did not come back within "
                        f"{self.max_reconnect_s}s"
                    )
                time.sleep(self.retry_interval_s)
        self._replay()
        self.resyncs += 1

    def _replay(self) -> None:
        for ns, labels in self._ns_labels.items():
            self._client.set_namespace_labels(ns, labels)
        for kind in _REPLAY_ORDER:
            for obj in self._store.get(kind, {}).values():
                self._client.add(kind, obj)

    def _with_resync(self, fn):
        """Run ``fn`` against the live client; on a dead connection,
        reconnect+replay once and re-issue."""
        try:
            return fn()
        except (ConnectionError, BrokenPipeError, OSError):
            self._reconnect()
            return fn()

    # -- client surface ----------------------------------------------------

    def set_namespace_labels(self, namespace: str, labels: dict) -> None:
        self._ns_labels[namespace] = dict(labels)
        self._with_resync(
            lambda: self._client.set_namespace_labels(namespace, labels)
        )

    def add(self, kind: str, obj) -> None:
        self._record(kind, obj)
        self._with_resync(lambda: self._client.add(kind, obj))

    def remove(self, kind: str, uid: str) -> None:
        self._store.get(kind, {}).pop(uid, None)
        self._with_resync(lambda: self._client.remove(kind, uid))

    def dump(self) -> dict:
        # NB: lambda re-reads self._client so the retry after a reconnect
        # targets the NEW connection, not the dead one's bound method.
        return self._with_resync(lambda: self._client.dump())

    def schedule(self, pods=(), drain: bool = True) -> list[pb.PodResult]:
        # Pending pods enter the store UNBOUND first: if the sidecar dies
        # mid-call the replay re-submits them (at-least-once; the engine's
        # upsert path makes re-delivery idempotent).
        pods = list(pods)
        for p in pods:
            self._record("Pod", p)
        results = self._with_resync(
            lambda: self._client.schedule(pods, drain=drain)
        )
        # Record bindings: the reference host persists them via the
        # apiserver; here the store is that persistence, so a later replay
        # re-adds bound pods as cache adds with their node set.
        by_uid = {p.uid: p for p in pods}
        for r in results:
            p = by_uid.get(r.pod_uid)
            if p is None:
                rec = self._store["Pod"].get(r.pod_uid)
                p = rec if rec is not None else None
            if p is None:
                continue
            if r.node_name:
                p.spec.node_name = r.node_name
            for vu in r.victim_uids:
                # Preemption victims were deleted sidecar-side; mirror that.
                self._store["Pod"].pop(vu, None)
        return results

    def close(self) -> None:
        self._client.close()
