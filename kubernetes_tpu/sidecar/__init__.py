"""Out-of-process sidecar: the framed-socket protocol a host scheduler
(the Go kube-scheduler's out-of-tree plugin set, or the bundled native C++
client) uses to drive the TPU engine.  See proto/sidecar.proto."""

from .server import (  # noqa: F401
    DeadlineExceeded,
    FrameError,
    SidecarClient,
    SidecarServer,
    read_frame,
    read_frame_resync,
    write_frame,
)
