"""Sidecar server: hosts a TPUScheduler behind the framed-socket protocol.

This is the process boundary SURVEY §7 phase 6 describes: the host
scheduler keeps its informers/queue/binding and streams snapshot deltas +
pod batches here; the device pass answers with bindings, scores and
diagnosis (proto/sidecar.proto).  Framing is 4-byte big-endian length +
Envelope payload over a unix-domain (or TCP) socket — message-compatible
with a gRPC transport, which needs only the stub layer on the Go side.

The server is intentionally single-threaded per connection: the scheduler
is a sequential state machine (the reference's scheduling loop is too);
concurrency belongs to the host side (async binding, informers)."""

from __future__ import annotations

import os
import socket
import socketserver
import struct
import threading

from ..api import serialize
from ..scheduler import ScheduleOutcome, TPUScheduler
from . import sidecar_pb2 as pb

_LEN = struct.Struct(">I")
MAX_FRAME = 64 << 20
# Bound on how much of an oversized frame the server will stream-discard
# to stay synchronized.  A length beyond this is almost certainly a
# garbage header (the stream is byte-desynced), so the connection drops
# instead of reading gigabytes of nothing.
MAX_DISCARD = 4 * MAX_FRAME


class FrameError(Exception):
    """A malformed frame.  ``recoverable`` means its bytes were fully
    consumed — the connection is still frame-synchronized and can carry
    an error response; otherwise the stream is hopelessly desynced and
    the connection must drop."""

    def __init__(self, msg: str, recoverable: bool):
        super().__init__(msg)
        self.recoverable = recoverable


def write_frame(sock: socket.socket, env: pb.Envelope) -> None:
    payload = env.SerializeToString()
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _read_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def read_frame(sock: socket.socket) -> pb.Envelope | None:
    header = _read_exact(sock, _LEN.size)
    if header is None:
        return None
    (n,) = _LEN.unpack(header)
    if n > MAX_FRAME:
        raise ValueError(f"frame too large: {n}")
    payload = _read_exact(sock, n)
    if payload is None:
        return None
    env = pb.Envelope()
    env.ParseFromString(payload)
    return env


def read_frame_resync(sock: socket.socket) -> pb.Envelope | None:
    """Server-side framed read that SURVIVES a malformed frame where
    possible: an oversized length is stream-discarded and a garbage
    payload consumed, both raising a recoverable FrameError so the caller
    can answer with an error response instead of severing the connection
    (one bad message must not drop its healthy sibling requests).  Only a
    length too absurd to discard is unrecoverable."""
    header = _read_exact(sock, _LEN.size)
    if header is None:
        return None
    (n,) = _LEN.unpack(header)
    if n > MAX_FRAME:
        if n > MAX_DISCARD:
            raise FrameError(
                f"frame length {n} beyond discard bound", recoverable=False
            )
        remaining = n
        while remaining:
            chunk = sock.recv(min(remaining, 1 << 20))
            if not chunk:
                return None  # EOF mid-discard
            remaining -= len(chunk)
        raise FrameError(f"frame too large: {n}", recoverable=True)
    payload = _read_exact(sock, n)
    if payload is None:
        return None
    env = pb.Envelope()
    try:
        env.ParseFromString(payload)
    except Exception as exc:  # framing intact, payload garbage
        raise FrameError(f"unparseable frame: {exc}", recoverable=True)
    return env


class SidecarServer:
    """Serves one TPUScheduler over a unix-domain socket."""

    def __init__(
        self,
        path: str,
        scheduler: TPUScheduler | None = None,
        speculate: bool = False,
        lookahead: int | None = None,
        keepalive_s: float | None = None,
        health_extra: dict | None = None,
        http_port: int | None = None,
        http_host: str = "127.0.0.1",
        journal=None,
        snapshot_every_batches: int = 64,
        fleet_owner=None,
        **kw,
    ):
        self.path = path
        # Extra health-frame fields (e.g. leader-election state from
        # cmd_serve) merged into every health response.  The handler
        # closure captures this DICT object — mutate its contents to
        # change later responses; rebinding the attribute has no effect.
        self.health_extra = health_extra = health_extra or {}
        self.scheduler = scheduler or TPUScheduler(**kw)
        # Durability (journal.py): recover BEFORE serving — the first
        # frame must see the pre-crash world, exactly like the reference
        # waits out WaitForCacheSync before its loop — then arm the
        # write-ahead hooks for this tenure.
        if journal is not None:
            from ..journal import recover

            self.recovery_stats = recover(self.scheduler, journal)
            self.scheduler.attach_journal(
                journal, snapshot_every_batches=snapshot_every_batches
            )
        else:
            self.recovery_stats = None
        # Partitioned-fleet owner (fleet/owner.py, `serve --shard-of`):
        # the `fleet` frame dispatches through it.  Hung off the scheduler
        # so _dispatch — which receives only the scheduler — can reach it.
        self.fleet_owner = fleet_owner
        self.scheduler._fleet_owner = fleet_owner
        if fleet_owner is not None and journal is not None:
            # The owner was constructed BEFORE the serve-journal recovery
            # above replayed the pre-crash world — its recovered-taints
            # overlay (journal-authored lifecycle taints must survive the
            # router's host-truth node re-feed) would otherwise stay
            # empty in every `serve --shard-of` restart.
            fleet_owner.refresh_recovered_taints()
        # Wire deployments hand nominations back to the host (it owns the
        # victims' API deletes); the in-process inline commit would act on
        # them sidecar-side and desync the two views.
        self.scheduler.inline_preempt_commit = False
        self._thread: threading.Thread | None = None
        # Speculative batching frontend (speculate.py): PendingPod hints +
        # a decision cache let the one-pod-per-call integrated path keep
        # the device batch.  Off by default — per-call semantics (and the
        # golden transcripts) are unchanged unless the operator opts in.
        self.frontend = None
        if speculate:
            from .speculate import SpeculativeFrontend

            self.frontend = SpeculativeFrontend(self.scheduler, lookahead)

        sched = self.scheduler
        front = self.frontend
        # The scheduler is a sequential state machine; connections are
        # threaded but dispatch is serialized (concurrency belongs to the
        # host side).
        lock = threading.Lock()
        self._lock = lock
        self._keepalive_stop = threading.Event()
        if keepalive_s and front is not None:
            # Push-stream keepalive: an empty Push frame at the current
            # epoch, so a subscriber behind a silent TCP partition can
            # bound its staleness with a read deadline (the Go
            # subscriber's 60s window; tests/fixtures leave this off to
            # stay deterministic).
            def _beat():
                while not self._keepalive_stop.wait(keepalive_s):
                    with lock:
                        env = pb.Envelope()
                        env.push.epoch = front.epoch
                        front._emit(env)

            threading.Thread(target=_beat, daemon=True).start()

        conns: set[socket.socket] = set()
        self._conns = conns

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                try:
                    self._serve_frames()
                finally:
                    conns.discard(self.request)

            def _serve_frames(self) -> None:
                subscribed = False
                malformed = sched.metrics.registry.counter(
                    "sidecar_malformed_frames_total",
                    "Client frames rejected as oversized or unparseable.",
                )
                while True:
                    try:
                        env = read_frame_resync(self.request)
                    except TimeoutError:
                        # Subscribed sockets carry a write timeout (push
                        # backpressure bound) which applies to this idle
                        # read too — just keep listening for EOF.
                        continue
                    except FrameError as fe:
                        malformed.inc()
                        if not fe.recoverable or subscribed:
                            # Desynced stream, or a write onto a one-way
                            # push stream: the connection is done.
                            return
                        # Frame consumed, stream synchronized: answer with
                        # an error response (seq 0 — the malformed payload
                        # never yielded one) and keep serving.
                        err = pb.Envelope()
                        err.response.error = f"bad frame: {fe}"
                        try:
                            write_frame(self.request, err)
                        except OSError:
                            return
                        continue
                    except (ValueError, OSError):
                        return
                    if env is None:
                        return
                    if subscribed:
                        # The push stream is one-way after the subscribe
                        # ack; a request frame here would race the pushes
                        # (two writers interleaving on one socket).  Drop
                        # the connection — the protocol violation is the
                        # client's.
                        return
                    out = pb.Envelope(seq=env.seq)
                    responded = False
                    try:
                        with lock:
                            responded = _dispatch(
                                sched, env, out, front, self.request,
                                health_extra,
                            )
                    except Exception as exc:  # surface, don't kill the server
                        out.response.error = f"{type(exc).__name__}: {exc}"
                    if responded:
                        subscribed = True
                        continue
                    try:
                        write_frame(self.request, out)
                    except OSError:  # peer (or close()) severed mid-dispatch
                        return

        class Server(socketserver.ThreadingUnixStreamServer):
            daemon_threads = True

            def process_request(self, request, client_address):
                # Register in the ACCEPT thread, before the handler thread
                # spawns: close() then cannot miss a just-accepted socket
                # (shutdown() stops this loop first, so registration
                # happens-before the close() snapshot).
                conns.add(request)
                super().process_request(request, client_address)

        if os.path.exists(path):
            os.unlink(path)
        self._server = Server(path, Handler)
        # Optional plain-HTTP observability listener (/metrics, /healthz,
        # /events) over the SAME scheduler — Prometheus scrapes it while
        # the Go host speaks frames; 0 binds an ephemeral port (tests).
        self.http = None
        if http_port is not None:
            from .metrics_http import ObservabilityHTTPServer

            # Scrapes share the dispatch lock: render-time collectors read
            # scheduler dicts the dispatch thread mutates.
            self.http = ObservabilityHTTPServer(
                self.scheduler, http_port, host=http_host,
                health_extra=health_extra, lock=lock,
            )
            self.http.serve_background()

    def serve_background(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def serve_forever(self) -> None:
        self._server.serve_forever()

    def close(self) -> None:
        self._keepalive_stop.set()
        if self.http is not None:
            self.http.close()
        self._server.shutdown()
        self._server.server_close()
        # Sever live connections too: handler threads otherwise keep
        # serving established sockets after shutdown(), so a "stopped"
        # server would silently answer from stale state (and a crash —
        # the case the host's resync exists for — kills them anyway).
        for conn in list(self._conns):
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        if os.path.exists(self.path):
            os.unlink(self.path)


def _dispatch(
    sched: TPUScheduler,
    env: pb.Envelope,
    out: pb.Envelope,
    front=None,
    conn=None,
    health_extra: dict | None = None,
) -> bool:
    """Handle one frame.  Returns True when the response was already
    written inside the dispatch lock (the subscribe handshake — its ack
    must be ordered against subsequent Push frames on the same socket,
    and every write to a subscriber happens under this lock)."""
    kind = env.WhichOneof("msg")
    if kind == "subscribe":
        # Turn this connection into a decision push stream (watch-stream
        # idiom).  Requires the speculative frontend — without it there
        # are no speculative decisions to stream.
        if front is None:
            raise ValueError("subscribe requires speculation enabled")
        if conn is None:
            raise ValueError("subscribe needs a connection")
        out.response.SetInParent()
        write_frame(conn, out)  # ack, ordered before any push frame
        # Bounded-blocking pushes: a subscriber that stops draining its
        # socket must not wedge the dispatch lock (and with it every
        # other connection).  The timeout turns backpressure into an
        # OSError and the frontend drops the sink — a stalled subscriber
        # has missed frames and must resubscribe anyway.
        conn.settimeout(5.0)

        def _sink(e, c=conn):
            try:
                write_frame(c, e)
            except OSError:
                # A failed/timed-out push leaves a partial frame on the
                # socket — unrecoverable for the stream.  shutdown() (not
                # close()) wakes the handler thread blocked in recv on
                # this fd without freeing the fd for reuse under it; the
                # handler's normal exit path owns the close (the same
                # pattern SidecarServer.close() uses).
                try:
                    c.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                raise

        front.add_sink(_sink)
        return True
    if kind == "health":
        # healthz/readyz analog (cmd/kube-scheduler/app/server.go:181–210):
        # a liveness surface the host can probe beyond a failed dial.
        # Same payload shape the plain-HTTP /healthz serves.
        import json as _json

        from .metrics_http import health_state

        state = health_state(sched, health_extra)
        state["speculation"] = front is not None
        state["epoch"] = front.epoch if front is not None else 0
        out.response.health_json = _json.dumps(state).encode()
        return False
    if kind == "metrics":
        # Prometheus text exposition over the wire — byte-identical to the
        # plain-HTTP /metrics scrape (one registry, one renderer).
        out.response.metrics_text = sched.metrics.registry.render_text().encode()
        return False
    if kind == "events":
        import json as _json

        out.response.events_json = _json.dumps(sched.events.list()).encode()
        return False
    if kind == "flight":
        # Flight-recorder readout: the per-batch phase-attribution ring +
        # transition markers as one JSON document (framework/flight.py) —
        # same payload the auto-dumps write and /debug/flight serves.
        import json as _json

        out.response.flight_json = _json.dumps(
            sched.flight.snapshot(env.flight.limit or None)
        ).encode()
        return False
    if kind == "explain":
        # Decision provenance (framework/provenance.py): one pod's
        # structured decision record — per-op attribution, the selectHost
        # tie-break trace, and the journal-reconstructed bit-identity
        # replay when available.  Read path only; sorted keys so two
        # same-seed servers emit byte-identical documents.
        import json as _json

        doc = sched.explain_pod(
            env.explain.uid, seq=env.explain.seq or None
        )
        out.response.explain_json = _json.dumps(doc, sort_keys=True).encode()
        return False
    if kind == "fleet":
        # Partitioned-fleet protocol (fleet/owner.py fleet_dispatch): one
        # frame = one op against this process's shard owner.  Requires
        # `serve --shard-of` — a plain sidecar has no shard identity.
        import json as _json

        owner = getattr(sched, "_fleet_owner", None)
        if owner is None:
            raise ValueError("fleet ops require serve --shard-of")
        from ..fleet.owner import fleet_dispatch

        result = fleet_dispatch(
            owner,
            env.fleet.op,
            _json.loads(env.fleet.payload_json or b"{}"),
        )
        out.response.fleet_json = _json.dumps(result).encode()
        return False
    if kind == "add":
        if env.add.kind == "PendingPod":
            # A pending-pod HINT (speculate.py): the host's informer saw an
            # unassigned pod the scheduler will likely ask about soon.  Not
            # a cluster mutation — without the speculative frontend it is
            # simply dropped (the pod arrives again via Schedule).
            if front is not None:
                front.add_hint_raw(env.add.object_json)
            out.response.SetInParent()
            return
        if env.add.kind == "PendingPods":
            # Batched hints: one frame carrying a JSON ARRAY of pods.  The
            # plugin's informer handlers fire per pod, but nothing forces
            # one frame per event — a flusher goroutine coalescing its
            # backlog sends one array and pays one ack (the same batching
            # client-go's Reflector does for its initial List).  The blob
            # is parsed lazily, under a later batch's device pass.
            if front is not None:
                front.add_hint_blob(env.add.object_json)
            out.response.SetInParent()
            return
        if env.add.kind == "NamespaceLabels":
            # {"namespace": ..., "labels": {...}} — the namespace informer
            # feeding affinity namespaceSelector matching.
            import json

            data = json.loads(env.add.object_json)
            if front is not None:
                front.note_add("NamespaceLabels", data)
            sched.builder.set_namespace_labels(data["namespace"], data["labels"])
            out.response.SetInParent()
            return
        obj = serialize.from_json(env.add.kind, env.add.object_json)
        if front is not None:
            front.note_add(env.add.kind, obj)
        getattr(sched, serialize.KINDS[env.add.kind][1])(obj)
        out.response.SetInParent()
    elif kind == "remove":
        if front is not None:
            front.note_remove(env.remove.kind, env.remove.uid)
        remover = serialize.REMOVERS.get(env.remove.kind)
        if remover is None:
            raise ValueError(f"cannot remove kind {env.remove.kind}")
        getattr(sched, remover)(env.remove.uid)
        out.response.SetInParent()
    elif kind == "dump":
        import json

        state = sched.dump_state()
        if front is not None:
            state["speculation"] = front.stats.as_dict()
        out.response.dump_json = json.dumps(state).encode()
    elif kind == "schedule":
        # Cross-boundary trace join: install the client's trace context so
        # the batch's root span (scheduler.py ScheduleBatch) carries the
        # HOST's trace id — a slow server-side cycle then logs an id the
        # operator can grep in both processes' logs.
        if env.schedule.trace_id:
            sched.trace_parent = (
                env.schedule.trace_id, env.schedule.parent_span_id or None
            )
        sched.last_batch_span = None
        try:
            if front is not None and not env.schedule.drain:
                outcomes = front.schedule_raw(list(env.schedule.pod_json))
            else:
                if front is not None:
                    # A drain request bypasses the cache; flush it first so
                    # drained decisions and cached ones cannot double-commit.
                    front.flush_hints_to_queue()
                req_uids = []
                for raw in env.schedule.pod_json:
                    p = serialize.pod_from_json(raw)
                    req_uids.append(p.uid)
                    sched.add_pod(p)
                outcomes = (
                    sched.schedule_all_pending()
                    if env.schedule.drain
                    else sched.schedule_batch()
                )
                outcomes = list(outcomes)
                # At-least-once completion: a re-issued call (the host
                # timed out and lost the first response) may ask about
                # pods an earlier execution already committed — add_pod
                # dropped them, so the drain yields no outcome.  Answer
                # from the cache; the committed placement IS the
                # decision.  Pods still in a wait room (Permit/PreBind)
                # stay unanswered — their bind is not final.
                answered = {o.pod.uid for o in outcomes}
                waiting = {
                    e[0].pod.uid
                    for lst in sched.permit_waiting.values()
                    for e in lst
                } | set(sched.prebind_waiting)
                for uid in req_uids:
                    if uid in answered or uid in waiting:
                        continue
                    pr = sched.cache.pods.get(uid)
                    if pr is not None and pr.node_name:
                        outcomes.append(
                            ScheduleOutcome(pr.pod, pr.node_name)
                        )
        finally:
            sched.trace_parent = None
        span = sched.last_batch_span
        if span is not None and env.schedule.trace_id:
            out.response.span_id = span.span_id
        for o in outcomes:
            fill_result(out.response.results.add(), o)
    else:
        raise ValueError(f"unhandled message {kind}")


def fill_result(r: pb.PodResult, o) -> pb.PodResult:
    """ScheduleOutcome → wire PodResult.  Shared with the host's degraded
    dispatch (sidecar/host.py), so the two serializations cannot drift."""
    r.pod_uid = o.pod.uid
    r.node_name = o.node_name or ""
    r.score = o.score
    r.feasible_nodes = o.feasible_nodes
    r.nominated_node = o.nominated_node or ""
    r.victims = o.victims
    r.victim_uids.extend(o.victim_uids)
    r.victim_names.extend(o.victim_names)
    if o.diagnosis is not None:
        r.unschedulable_plugins.extend(
            sorted(o.diagnosis.unschedulable_plugins)
        )
    return r


class DeadlineExceeded(ConnectionError):
    """A per-call deadline fired: the sidecar is reachable but not
    answering (hung, or drowning).  Distinct from a plain ConnectionError
    so the resilient host can count timeouts separately."""


class SidecarClient:
    """Minimal Python client (the same framing the native C++ client in
    native/sidecar_client.cc speaks)."""

    def __init__(self, path: str, deadline_s: float | None = None):
        """``deadline_s`` bounds every request/response round trip: a hung
        sidecar (process alive, dispatch wedged) turns into a TimeoutError
        the caller can retry/degrade on, instead of a recv that blocks
        forever.  None (the default) keeps unbounded blocking — fixtures
        and the golden transcripts rely on it; resilient hosts
        (sidecar/host.py ResyncingClient) always set one."""
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.sock.connect(path)
        self.deadline_s = deadline_s
        if deadline_s is not None:
            self.sock.settimeout(deadline_s)
        self._seq = 0

    def _call(self, env: pb.Envelope) -> pb.Envelope:
        self._seq += 1
        env.seq = self._seq
        write_frame(self.sock, env)
        try:
            resp = read_frame(self.sock)
        except TimeoutError as exc:
            # The response may still arrive later — the connection is
            # desynced and must be treated as dead, not retried in place.
            raise DeadlineExceeded(
                f"sidecar call deadline ({self.deadline_s}s) exceeded"
            ) from exc
        if resp is None:
            raise ConnectionError("sidecar closed the connection")
        if resp.seq != self._seq:
            raise RuntimeError(
                f"protocol desync: seq {resp.seq} != {self._seq}"
            )
        if resp.response.error:
            raise RuntimeError(resp.response.error)
        return resp

    def set_namespace_labels(self, namespace: str, labels: dict) -> None:
        import json

        env = pb.Envelope()
        env.add.kind = "NamespaceLabels"
        env.add.object_json = json.dumps(
            {"namespace": namespace, "labels": labels}
        ).encode()
        self._call(env)

    def add(self, kind: str, obj) -> None:
        env = pb.Envelope()
        env.add.kind = kind
        env.add.object_json = serialize.to_json(obj)
        self._call(env)

    def add_stream(self, kind: str, objs) -> None:
        """Pipelined adds: ship frames while draining responses as they
        arrive.  Models the Go informer handlers, which fire
        asynchronously and don't gate the next event on the previous ack
        (frames are still processed in order — the protocol is sequential
        per connection).  Writes and reads interleave via select —
        write-everything-then-read deadlocks once the in-flight frames
        exceed the socket buffers (the server blocks writing acks, stops
        reading, and both sides stall).  ALL responses are drained before
        any error is raised, so a failed add cannot desync the connection
        for later calls."""
        import select

        pending = bytearray()
        for obj in objs:
            env = pb.Envelope()
            env.add.kind = kind
            env.add.object_json = serialize.to_json(obj)
            self._seq += 1
            env.seq = self._seq
            payload = env.SerializeToString()
            pending += _LEN.pack(len(payload)) + payload
        want = self._seq - len(objs)
        last = self._seq
        errors = []
        view = memoryview(pending)
        sock = self.sock
        sock.setblocking(False)
        try:
            while want < last or view:
                rl, wl, _ = select.select(
                    [sock], [sock] if view else [], []
                )
                if wl:
                    try:
                        n = sock.send(view[: 1 << 20])
                    except BlockingIOError:
                        n = 0
                    view = view[n:]
                if rl:
                    sock.setblocking(True)
                    try:
                        resp = read_frame(sock)
                    finally:
                        sock.setblocking(False)
                    if resp is None:
                        raise ConnectionError("sidecar closed the connection")
                    want += 1
                    if resp.seq != want:
                        raise RuntimeError(
                            f"protocol desync: seq {resp.seq} != {want}"
                        )
                    if resp.response.error:
                        errors.append(resp.response.error)
        finally:
            # setblocking(True) wipes any configured timeout; restore the
            # per-call deadline for subsequent requests.
            sock.settimeout(self.deadline_s)
        if errors:
            raise RuntimeError(
                f"{len(errors)} of {len(objs)} adds failed; first: {errors[0]}"
            )

    def add_pending_batch(self, pods) -> None:
        """One PendingPods frame carrying a JSON array of pods (the
        coalesced-hint form — see the server's PendingPods branch)."""
        env = pb.Envelope()
        env.add.kind = "PendingPods"
        env.add.object_json = (
            b"[" + b",".join(serialize.to_json(p) for p in pods) + b"]"
        )
        self._call(env)

    def remove(self, kind: str, uid: str) -> None:
        env = pb.Envelope()
        env.remove.kind = kind
        env.remove.uid = uid
        self._call(env)

    def dump(self) -> dict:
        """Debugger state dump of the live scheduler (the SIGUSR2 analog)."""
        import json

        env = pb.Envelope()
        env.dump.SetInParent()
        return json.loads(self._call(env).response.dump_json)

    def health(self) -> dict:
        """healthz/readyz probe (app/server.go:181–210 analog)."""
        import json

        env = pb.Envelope()
        env.health.SetInParent()
        return json.loads(self._call(env).response.health_json)

    def metrics(self) -> str:
        """Scrape the registry in Prometheus text exposition format —
        byte-identical to the sidecar's plain-HTTP /metrics payload."""
        env = pb.Envelope()
        env.metrics.SetInParent()
        return self._call(env).response.metrics_text.decode()

    def events(self) -> list[dict]:
        """Read the event-recorder ring (Scheduled / FailedScheduling /
        Preempted / GangWaiting, aggregated)."""
        import json

        env = pb.Envelope()
        env.events.SetInParent()
        return json.loads(self._call(env).response.events_json)

    def flight(self, limit: int = 0) -> dict:
        """Read the flight recorder: per-batch phase attribution records
        + state-transition markers (``limit`` keeps the newest N)."""
        import json

        env = pb.Envelope()
        env.flight.SetInParent()
        if limit:
            env.flight.limit = limit
        return json.loads(self._call(env).response.flight_json)

    def explain(self, uid: str, seq: int = 0) -> dict:
        """One pod's decision-provenance record
        (framework/provenance.py): per-op attribution columns, the
        selectHost tie-break trace, and the recorded live decision.
        ``seq`` pins the journal reconstruction point (0 = let the
        recorded capsule choose)."""
        import json

        env = pb.Envelope()
        env.explain.uid = uid
        if seq:
            env.explain.seq = seq
        return json.loads(self._call(env).response.explain_json or b"{}")

    def fleet(self, op: str, payload: dict | None = None) -> dict:
        """One partitioned-fleet protocol op against a shard owner
        (``serve --shard-of``): propose/commit/reserve/…, JSON in and
        out (fleet/owner.py fleet_dispatch)."""
        import json

        env = pb.Envelope()
        env.fleet.op = op
        env.fleet.payload_json = json.dumps(payload or {}).encode()
        return json.loads(self._call(env).response.fleet_json or b"{}")

    def subscribe(self) -> None:
        """Turn THIS connection into a decision push stream.  After the
        ack, use read_push() exclusively — request methods would desync
        against the server-initiated frames."""
        env = pb.Envelope()
        env.subscribe.SetInParent()
        self._call(env)
        # Push streams idle legitimately (no decisions to push): the
        # request/response deadline does not apply to them.
        self.sock.settimeout(None)

    def read_push(self) -> pb.Push | None:
        """Blocking read of the next Push frame (None on EOF)."""
        env = read_frame(self.sock)
        if env is None:
            return None
        if env.WhichOneof("msg") != "push":
            raise RuntimeError("non-push frame on a subscribed connection")
        return env.push

    def schedule(
        self, pods=(), drain: bool = True, trace=None
    ) -> list[pb.PodResult]:
        """``trace`` (a framework.tracing.Trace) propagates the host span's
        (trace_id, span_id) through the envelope; the server's batch span
        joins that trace and its span_id comes back on the response, where
        it is recorded as a step on the host span (the joined tree)."""
        env = pb.Envelope()
        env.schedule.drain = drain
        if trace is not None:
            env.schedule.trace_id = trace.trace_id
            env.schedule.parent_span_id = trace.span_id
        for p in pods:
            env.schedule.pod_json.append(serialize.to_json(p))
        resp = self._call(env)
        if trace is not None and resp.response.span_id:
            trace.step(f"sidecar batch span={resp.response.span_id}")
        return list(resp.response.results)

    def close(self) -> None:
        self.sock.close()
