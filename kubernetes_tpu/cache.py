"""Host-side authoritative cluster state with the assume/forget protocol.

Mirrors the responsibilities of the reference's scheduler cache
(pkg/scheduler/backend/cache/cache.go): it is the source of truth the device
snapshot is built from, and it implements optimistic binding — `assume_pod`
records a pod on its chosen node immediately so the next scheduling batch sees
it, `finish_binding`/`forget_pod` resolve the optimism when the (async) bind
succeeds or fails (cache.go:361 AssumePod, :376 FinishBinding, :404 ForgetPod).

Unlike the reference there is no per-cycle snapshot copy: the device mirror in
SnapshotBuilder *is* the snapshot, updated incrementally row-by-row (the
analog of UpdateSnapshot's generation diff, cache.go:186)."""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from .api import types as t
from .snapshot import SnapshotBuilder

# Zone label keys, GA + legacy beta (utilnode.GetZoneKey).
_ZONE_LABELS = (
    "topology.kubernetes.io/zone",
    "failure-domain.beta.kubernetes.io/zone",
)


def _zone_of(node: t.Node) -> str:
    for key in _ZONE_LABELS:
        z = node.metadata.labels.get(key)
        if z:
            return z
    return ""


@dataclass
class NodeRecord:
    node: t.Node
    row: int
    pods: dict[str, t.Pod] = field(default_factory=dict)  # uid → pod
    generation: int = 0
    zone: str = ""
    # Pod-membership generation: bumped (from a cache-global monotonic
    # counter, so values never collide across row reuse) whenever this
    # node's pod set or any resident pod's object changes.  The preemption
    # evaluator keys its incremental victim-staging cache on it.
    pods_gen: int = 0


class NodeTree:
    """Zone → node-name lists with round-robin interleaved iteration — the
    reference's nodeTree (backend/cache/node_tree.go:119 list()): snapshot
    order spreads consecutive scan positions across zones so truncated
    search (percentageOfNodesToScore) samples every zone fairly."""

    def __init__(self) -> None:
        self.zones: dict[str, list[str]] = {}

    def add(self, zone: str, name: str) -> None:
        self.zones.setdefault(zone, []).append(name)

    def remove(self, zone: str, name: str) -> None:
        names = self.zones.get(zone)
        if names is not None:
            try:
                names.remove(name)
            except ValueError:
                pass
            if not names:
                self.zones.pop(zone, None)

    def list(self) -> list[str]:
        """Round-robin over zones: zone0[0], zone1[0], …, zone0[1], …"""
        out: list[str] = []
        idx = 0
        exhausted = 0
        zone_lists = list(self.zones.values())
        while zone_lists and exhausted < len(zone_lists):
            exhausted = 0
            for names in zone_lists:
                if idx < len(names):
                    out.append(names[idx])
                else:
                    exhausted += 1
            idx += 1
        return out


@dataclass
class PodRecord:
    pod: t.Pod
    node_name: str
    delta: dict  # the precomputed row-delta vectors applied to the node row
    assumed: bool = False
    bound: bool = False
    assumed_at: float = 0.0


class Cache:
    def __init__(self, builder: SnapshotBuilder):
        self.builder = builder
        self.nodes: dict[str, NodeRecord] = {}
        self.pods: dict[str, PodRecord] = {}
        self._free_rows: list[int] = []
        self._next_row = 0
        self._generation = 0
        self._row_to_name: dict[int, str] = {}
        self.node_tree = NodeTree()
        self._order_cache: tuple[int, np.ndarray] | None = None
        self._pods_gen = 0

    def _bump_pods_gen(self, rec: NodeRecord) -> None:
        self._pods_gen += 1
        rec.pods_gen = self._pods_gen

    # -- nodes ---------------------------------------------------------------

    def node_count(self) -> int:
        return len(self.nodes)

    def row_of(self, node_name: str) -> int:
        return self.nodes[node_name].row

    def node_name_at_row(self, row: int) -> str | None:
        return self._row_to_name.get(row)

    def add_node(self, node: t.Node) -> None:
        if node.name in self.nodes:
            self.update_node(node)
            return
        row = self._free_rows.pop() if self._free_rows else self._next_row
        if row == self._next_row:
            self._next_row += 1
        self._generation += 1
        zone = _zone_of(node)
        rec = NodeRecord(
            node=node, row=row, generation=self._generation, zone=zone
        )
        self._bump_pods_gen(rec)
        self.nodes[node.name] = rec
        self.builder.set_node_row(row, node)
        self._row_to_name[row] = node.name
        self.node_tree.add(zone, node.name)

    def update_node(self, node: t.Node) -> None:
        rec = self.nodes[node.name]
        rec.node = node
        self._generation += 1
        rec.generation = self._generation
        zone = _zone_of(node)
        if zone != rec.zone:
            self.node_tree.remove(rec.zone, node.name)
            self.node_tree.add(zone, node.name)
            rec.zone = zone
        # set_node_row rewrites only the node's static attributes; pod-derived
        # state (req/num_pods/counts) lives in separate arrays and is untouched.
        self.builder.set_node_row(rec.row, node)

    def remove_node(self, name: str) -> None:
        rec = self.nodes.pop(name)
        self.builder.clear_node_row(rec.row)
        self._free_rows.append(rec.row)
        self._row_to_name.pop(rec.row, None)
        self.node_tree.remove(rec.zone, name)
        self._generation += 1
        for uid in list(rec.pods):
            pr = self.pods.pop(uid, None)
            del pr  # pods on a removed node vanish from scheduling state

    def order_pos(self, n: int) -> np.ndarray:
        """(n,) i32: each row's position in the zone-interleaved node order
        (node_tree.go:119); unoccupied rows get a huge sentinel.  Cached per
        cache generation."""
        if self._order_cache is not None and self._order_cache[0] == self._generation:
            arr = self._order_cache[1]
            if arr.shape[0] == n:
                return arr
        arr = np.full(n, 2**30, np.int32)
        for i, name in enumerate(self.node_tree.list()):
            arr[self.nodes[name].row] = i
        self._order_cache = (self._generation, arr)
        return arr

    # -- pods ----------------------------------------------------------------

    def add_pod(self, pod: t.Pod, node_name: str | None = None, device_already: bool = False) -> None:
        """Record an assigned pod (from the informer path or a fresh bind)."""
        node_name = node_name or pod.spec.node_name
        rec = self.nodes[node_name]
        delta = self.builder.pod_delta_vectors(pod)
        pr = PodRecord(pod=pod, node_name=node_name, delta=delta, bound=True)
        self.pods[pod.uid] = pr
        rec.pods[pod.uid] = pod
        self._bump_pods_gen(rec)
        self.builder.apply_pod_delta(rec.row, delta, +1, device_already=device_already)

    def assume_pod(
        self,
        pod: t.Pod,
        node_name: str,
        device_already: bool = True,
        delta: dict | None = None,
    ) -> None:
        """Optimistically place a pod (cache.go:361). device_already=True when
        the engine's scan already committed the delta on device; `delta` skips
        re-featurizing when the batch featurizer already computed it."""
        rec = self.nodes[node_name]
        if delta is None:
            delta = self.builder.pod_delta_vectors(pod)
        pr = PodRecord(
            pod=pod, node_name=node_name, delta=delta, assumed=True, assumed_at=time.monotonic()
        )
        self.pods[pod.uid] = pr
        rec.pods[pod.uid] = pod
        self._bump_pods_gen(rec)
        self.builder.apply_pod_delta(rec.row, delta, +1, device_already=device_already)

    def finish_binding(self, uid: str) -> None:
        pr = self.pods[uid]
        pr.assumed, pr.bound = False, True

    def forget_pod(self, uid: str) -> None:
        """Undo an assume after a failed bind (cache.go:404)."""
        pr = self.pods.pop(uid)
        rec = self.nodes[pr.node_name]
        rec.pods.pop(uid, None)
        self._bump_pods_gen(rec)
        self.builder.apply_pod_delta(rec.row, pr.delta, -1, device_already=False)

    def remove_pod(self, uid: str) -> None:
        pr = self.pods.pop(uid, None)
        if pr is None:
            return
        rec = self.nodes.get(pr.node_name)
        if rec is not None:
            rec.pods.pop(uid, None)
            self._bump_pods_gen(rec)
            self.builder.apply_pod_delta(rec.row, pr.delta, -1, device_already=False)

    def update_pod(self, pod: t.Pod) -> None:
        """Re-apply a cached pod's row delta after an object update
        (cache.go updatePod: removePod + addPod).  The device mirror's
        group/term/port counts follow through apply_pod_delta, so a bound
        pod's label change rewrites the node's domain tensors."""
        pr = self.pods[pod.uid]
        rec = self.nodes[pr.node_name]
        self.builder.apply_pod_delta(rec.row, pr.delta, -1, device_already=False)
        delta = self.builder.pod_delta_vectors(pod)
        pr.pod = pod
        pr.delta = delta
        rec.pods[pod.uid] = pod
        self._bump_pods_gen(rec)
        self.builder.apply_pod_delta(rec.row, delta, +1, device_already=False)

    def cleanup_assumed(
        self, ttl_s: float = 30.0, skip: frozenset[str] | set[str] = frozenset()
    ):
        """Expire assumed-but-never-bound pods (cache.go:730 cleanupAssumedPods).
        ``skip`` excludes pods whose assume is deliberate and governed by
        another expiry (the WaitOnPermit room's gang timeout).  Returns the
        expired pod objects so a caller without an informer can requeue them."""
        now = time.monotonic()
        expired = [
            pr.pod
            for uid, pr in self.pods.items()
            if pr.assumed
            and not pr.bound
            and uid not in skip
            and now - pr.assumed_at > ttl_s
        ]
        for pod in expired:
            self.forget_pod(pod.uid)
        return expired
