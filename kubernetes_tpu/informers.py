"""List/watch machinery — the client-go slice between an external state
source and the scheduler's informer surface.

Reference: staging/src/k8s.io/client-go/tools/cache/reflector.go (Reflector:
ListAndWatch — one full LIST establishes the resourceVersion, then a WATCH
stream of typed events resumes from it; an expired/stale version forces a
relist) and shared_informer.go (periodic RESYNC re-delivers the store's
state as update events so level-based controllers re-reconcile).

TPU-host adaptation: the scheduler already exposes the informer HANDLER
surface (add/update/delete for pods, add/update/remove for nodes — the one
state-routing design the Go plugin mirrors, eventhandlers.go:341).  What
was missing is the pull side: a Reflector that keeps that surface fed from
any (lister, watcher) pair — an apiserver client, a test fixture, a replay
file — with the three client-go behaviors that matter for correctness:

  - LIST is a REPLACE: objects present in the scheduler but absent from
    the list are deleted (DeltaFIFO Replace semantics — missed-delete
    repair after a watch gap);
  - WATCH resumes from the last seen resourceVersion; a
    StaleResourceVersion from the watcher triggers relist-and-rewatch
    (reflector.go's "too old resource version" path);
  - RESYNC re-delivers every stored object as an update on a period.

Events are (type, object) with type in {"ADDED", "MODIFIED", "DELETED"} —
watch.Event's verbs.  The driver is PULL-based (step()/run_once()) rather
than goroutine-based: the host batch loop owns the cadence, exactly like
the queue's flush timers."""

from __future__ import annotations

import time
from typing import Callable, Iterable

from .api import types as t

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"

# The full object surface the plugins consume (ISSUE 9): kind →
# (uid function, scheduler upsert method, scheduler remove method).
# Pod/Node keep their dedicated delivery branches in _deliver (recovery
# overlays, diffing update routes); every other kind routes through this
# table — the generated add_*/remove_* informer handler pairs.
KIND_HANDLERS: dict[str, tuple[Callable[[object], str], str, str]] = {
    "PersistentVolume": (lambda o: o.name, "add_pv", "remove_pv"),
    "PersistentVolumeClaim": (lambda o: o.uid, "add_pvc", "remove_pvc"),
    "StorageClass": (
        lambda o: o.name, "add_storage_class", "remove_storage_class"
    ),
    "CSINode": (lambda o: o.name, "add_csinode", "remove_csinode"),
    "PodDisruptionBudget": (lambda o: o.name, "add_pdb", "remove_pdb"),
    "ResourceClaim": (
        lambda o: o.uid, "add_resource_claim", "remove_resource_claim"
    ),
    "ResourceSlice": (
        lambda o: f"{o.node_name}/{o.device_class}",
        "add_resource_slice",
        "remove_resource_slice",
    ),
    # coordination.k8s.io Lease (node heartbeats): ADD/MODIFY is a renewal
    # (monotone — a stale replayed stamp cannot rewind the clock), DELETE/
    # absence-from-relist drops the node from heartbeat tracking.  This is
    # the relist surface ROADMAP's takeover rung names: a recovering
    # owner LISTS Leases to restore pre-crash heartbeat state instead of
    # re-deriving it from a re-fed renewal schedule.
    "Lease": (lambda o: o.node_name, "renew_node_lease", "remove_node_lease"),
}

REFLECTED_KINDS = ("Node", "Pod") + tuple(KIND_HANDLERS)


class StaleResourceVersion(Exception):
    """Raised by a watcher whose resume point has been compacted away —
    the Reflector relists (reflector.go: apierrors.IsResourceExpired)."""


def _uid_of(kind: str, obj) -> str:
    if kind == "Node":
        return obj.name if isinstance(obj, t.Node) else str(obj)
    if kind == "Pod":
        return obj.uid  # pods carry namespace/name uids
    if isinstance(obj, str):
        return obj
    return KIND_HANDLERS[kind][0](obj)


class Reflector:
    """Keep a scheduler fed from a (lister, watcher) source for one KIND
    — "Pod", "Node", or any entry of :data:`KIND_HANDLERS` (the full
    object surface the plugins consume).

    ``lister() -> (resource_version, [objects])`` — the full state.
    ``watcher(resource_version) -> iterable of (rv, type, object)`` —
    events AFTER the given version; may return an empty iterable when
    nothing new; raises StaleResourceVersion when the resume point is
    gone.  DELETED events carry the full last-seen object (watch.Event
    does), but only its uid/name is consulted."""

    def __init__(
        self,
        scheduler,
        kind: str,
        lister: Callable[[], tuple[int, list]],
        watcher: Callable[[int], Iterable[tuple[int, str, object]]],
        resync_s: float = 0.0,
        clock=time.monotonic,
    ) -> None:
        assert kind in REFLECTED_KINDS, kind
        self.sched = scheduler
        self.kind = kind
        self.lister = lister
        self.watcher = watcher
        self.resync_s = resync_s
        self._clock = clock
        self.resource_version: int | None = None
        self._next_resync = clock() + resync_s if resync_s else None
        # uid → last delivered object: the Reflector's store view, used by
        # LIST-replace diffing and resync (cache.Store behind DeltaFIFO).
        self.store: dict[str, object] = {}
        self.relists = 0
        # Crash-recovery overlay (reconcile_after_recovery): while set, a
        # listed pod arriving UNBOUND whose uid maps to a recovered
        # binding is delivered WITH that node — the journal is the bind
        # authority when the relist hasn't (or never) observed the bind.
        # A listed pod bound elsewhere is delivered as-is and wins
        # (update_pod relocates — relist truth over a stale local view).
        self.recovered_bindings: dict[str, str] = {}
        # Same contract for NOMINATIONS (scheduler-authored pod status —
        # the reference PATCHes .status.nominatedNodeName to the
        # apiserver, so a relist would carry it; our recovered journal
        # state is that authority here): a listed pod still unbound keeps
        # its recovered nomination, or the preemptor would lose its
        # claim on the freed node across the restart.
        self.recovered_nominations: dict[str, str] = {}
        # And for node-lifecycle TAINTS (scheduler-authored node spec —
        # upstream's node-lifecycle controller PATCHes them to the
        # apiserver, so a relist carries them; here the journal's taint
        # records are that authority): while set, a listed node is
        # delivered with its recovered lifecycle taints merged in, or the
        # LIST-replace would silently heal a dead node and cancel every
        # pending eviction the replay just re-armed.
        self.recovered_taints: dict[str, tuple] = {}

    # -- delivery into the scheduler's handler surface ----------------------

    def _deliver(self, ev: str, obj) -> None:
        s = self.sched
        if self.kind in KIND_HANDLERS:
            uid_fn, add_m, remove_m = KIND_HANDLERS[self.kind]
            if ev == DELETED:
                uid = obj if isinstance(obj, str) else uid_fn(obj)
                getattr(s, remove_m)(uid)
            else:
                # The add_* handlers are upserts (informer re-delivery
                # is routine) — MODIFIED routes through the same method.
                getattr(s, add_m)(obj)
            return
        if self.kind == "Node":
            if ev == DELETED:
                name = obj if isinstance(obj, str) else _uid_of("Node", obj)
                if name in s.cache.nodes:
                    s.remove_node(name)
                return
            if self.recovered_taints:
                recovered = self.recovered_taints.get(obj.name)
                if recovered:
                    from .controllers import LIFECYCLE_TAINT_KEYS

                    listed = tuple(
                        taint
                        for taint in obj.spec.taints
                        if taint.key not in LIFECYCLE_TAINT_KEYS
                    )
                    import copy

                    obj = copy.deepcopy(obj)
                    obj.spec.taints = listed + tuple(recovered)
            if ev == ADDED:
                s.add_node(obj)
            else:
                s.update_node(obj)
        else:
            if ev == DELETED:
                uid = obj if isinstance(obj, str) else _uid_of("Pod", obj)
                s.delete_pod(uid)
                return
            if not obj.spec.node_name and (
                self.recovered_bindings or self.recovered_nominations
            ):
                node = self.recovered_bindings.get(obj.uid)
                nom = self.recovered_nominations.get(obj.uid)
                if node is not None or nom is not None:
                    # Re-apply the journal's binding/nomination onto the
                    # listed object (copy: the lister's object is host
                    # truth and must not be mutated in place).
                    import copy

                    obj = copy.deepcopy(obj)
                    if node is not None:
                        obj.spec.node_name = node
                    elif nom is not None:
                        obj.status.nominated_node_name = nom
            if ev == ADDED:
                s.add_pod(obj)
            else:
                s.update_pod(obj)

    # -- ListAndWatch ---------------------------------------------------------

    def _scheduler_uids(self) -> set[str]:
        """The scheduler's current view of this kind — the diff basis for
        LIST-as-replace.  Diffing against the SCHEDULER (not just this
        Reflector's store) makes the replace guarantee hold even for
        objects an embedder seeded directly before attaching the
        Reflector (client-go's Replace diffs against the shared informer
        cache, which is the same store the handlers fed)."""
        s = self.sched
        if self.kind == "Node":
            return set(s.cache.nodes)
        if self.kind == "Pod":
            # Bound/assumed pods live in the cache; pending in the queue.
            return set(s.cache.pods) | set(s.queue._info)
        vols = s.builder.volumes
        if self.kind == "PersistentVolume":
            return set(vols.pvs)
        if self.kind == "PersistentVolumeClaim":
            return set(vols.pvcs)
        if self.kind == "StorageClass":
            return set(vols.classes)
        if self.kind == "CSINode":
            return set(vols.csinodes)
        if self.kind == "PodDisruptionBudget":
            return set(s.pdbs)
        if self.kind == "ResourceClaim":
            return set(s.builder.dra.claims)
        if self.kind == "ResourceSlice":
            return {
                f"{n}/{c}" for (n, c) in s.builder.dra.slices
            }
        if self.kind == "Lease":
            return set(s.node_lifecycle.heartbeats)
        raise AssertionError(self.kind)

    def run_once(self) -> int:
        """LIST: replace the scheduler's view of this kind.  New objects
        are adds, survivors are updates (their object may have changed
        across the gap), vanished objects are deletes — DeltaFIFO's
        Replace, which repairs deletes a broken watch never delivered.
        Returns the number of events delivered."""
        rv, objs = self.lister()
        fresh = {_uid_of(self.kind, o): o for o in objs}
        n = 0
        known = self._scheduler_uids() | set(self.store)
        for uid in known:
            if uid not in fresh:
                stale = self.store.pop(uid, None)
                self._deliver(DELETED, stale if stale is not None else uid)
                n += 1
        for uid, obj in fresh.items():
            self._deliver(MODIFIED if uid in known else ADDED, obj)
            self.store[uid] = obj
            n += 1
        self.resource_version = rv
        # A (re)list restarts the resync period (client-go recreates the
        # resync timer per ListAndWatch) — the replace just re-delivered
        # everything, so an immediately-due resync would be a double.
        if self.resync_s:
            self._next_resync = self._clock() + self.resync_s
        return n

    def step(self) -> int:
        """Drain available watch events (and the resync timer); returns
        how many events were delivered.  Call from the host loop between
        batches — the pull-based stand-in for the watch goroutine."""
        if self.resource_version is None:
            return self.run_once()
        n = 0
        try:
            for rv, ev, obj in self.watcher(self.resource_version):
                if ev == DELETED:
                    self.store.pop(_uid_of(self.kind, obj), None)
                else:
                    self.store[_uid_of(self.kind, obj)] = obj
                self._deliver(ev, obj)
                self.resource_version = rv
                n += 1
        except StaleResourceVersion:
            # The resume point was compacted: relist (reflector.go's
            # resource-expired path).  The LIST replace repairs whatever
            # the gap swallowed, including deletes.
            self.relists += 1
            return n + self.run_once()
        if self._next_resync is not None and self._clock() >= self._next_resync:
            self._next_resync = self._clock() + self.resync_s
            n += self.resync()
        return n

    def resync(self) -> int:
        """Re-deliver the store as updates (shared_informer.go resync):
        level-based consumers re-reconcile state they may have dropped."""
        for obj in list(self.store.values()):
            self._deliver(MODIFIED, obj)
        return len(self.store)


def reconcile_after_recovery(
    scheduler,
    node_reflector,
    pod_reflector,
    object_reflectors=(),
    lease_reflector=None,
) -> dict:
    """Cold-start recovery ordering (journal.py docstring step 3): after
    journal.recover() rebuilt the scheduler from snapshot + fenced
    replay, reconcile against a fresh LIST.

    1. Nodes relist first (bindings need rows to land on) — LIST-as-
       replace, so nodes gone from host truth vanish with their pods.
    2. The OBJECT catalogs relist (``object_reflectors``: any
       KIND_HANDLERS kinds — PV/PVC/StorageClass/CSINode/PDB/
       ResourceClaim/ResourceSlice) before pods, because pod
       featurization and the plugins read them.
    3. Journal bind records whose node was unknown at replay time
       (scheduler._recovered_bindings) re-apply now that the LIST may
       have delivered the node; bindings whose node never relists are
       GC'd — the node is truly gone, so an ARMED pod-GC requeues the
       pods (journaled ``evict``) to reschedule on surviving nodes,
       and a disarmed one drops them (the pre-GC behavior).
    4. Pods relist under the recovered-bindings overlay: a listed pod
       the journal holds bound but the relist shows unbound keeps the
       journal's binding (re-applied), a listed pod bound elsewhere wins
       as host truth (update_pod relocates), and pods absent from the
       relist are deleted (DeltaFIFO Replace).
    5. ``lease_reflector`` (when given) relists Lease objects LAST — the
       takeover rung ROADMAP names: heartbeat state restores from host
       truth's CURRENT renewals instead of re-deriving from a re-fed
       schedule.  Last because an armed controller's relist-driven tick
       may taint/evict, which must judge the fully reconciled pod set.
    """
    from .controllers import LIFECYCLE_TAINT_KEYS

    node_reflector.recovered_taints = {
        name: tuple(
            taint
            for taint in rec.node.spec.taints
            if taint.key in LIFECYCLE_TAINT_KEYS
        )
        for name, rec in scheduler.cache.nodes.items()
        if any(
            taint.key in LIFECYCLE_TAINT_KEYS
            for taint in rec.node.spec.taints
        )
    }
    try:
        stats = {"nodes": node_reflector.run_once()}
    finally:
        node_reflector.recovered_taints = {}
    for refl in object_reflectors:
        stats[f"objects:{refl.kind}"] = refl.run_once()
    pending = getattr(scheduler, "_recovered_bindings", None) or {}
    applied = dropped = requeued = 0
    if pending:
        from .api import serialize

        pod_gc = getattr(scheduler, "pod_gc", None)
        for uid, d in list(pending.items()):
            pod = serialize.pod_from_data(d["pod"])
            if d["node"] in scheduler.cache.nodes:
                pod.spec.node_name = d["node"]
                scheduler.add_pod(pod)
                applied += 1
            elif pod_gc is not None and pod_gc.armed:
                pod_gc.collect_orphan(uid, pod)
                requeued += 1
            else:
                dropped += 1
            pending.pop(uid, None)
    stats["late_bindings_applied"] = applied
    stats["late_bindings_dropped"] = dropped
    stats["late_bindings_requeued"] = requeued
    pod_reflector.recovered_bindings = {
        uid: pr.node_name
        for uid, pr in scheduler.cache.pods.items()
        if pr.node_name
    }
    pod_reflector.recovered_nominations = {
        uid: node for uid, (node, _d, _p) in scheduler.nominator.items()
    }
    try:
        stats["pods"] = pod_reflector.run_once()
    finally:
        pod_reflector.recovered_bindings = {}
        pod_reflector.recovered_nominations = {}
    if lease_reflector is not None:
        stats["leases"] = lease_reflector.run_once()
    return stats


class ReflectorSet:
    """One Reflector per kind over a shared-or-per-kind source surface —
    the SharedInformerFactory analog.  ``sources`` maps kind →
    (lister, watcher); step order is deterministic: Node first (rows
    before bindings), then the object catalogs, Pod last (featurization
    reads the catalogs)."""

    # Node first (rows before bindings), catalogs next, Pod LAST —
    # featurization and the volume/DRA plugins read the catalogs, so a
    # cold-start pod list must never be judged against empty ones.
    _ORDER = {
        k: i
        for i, k in enumerate(("Node",) + tuple(KIND_HANDLERS) + ("Pod",))
    }

    def __init__(self, scheduler, sources: dict, resync_s: float = 0.0):
        self.reflectors: dict[str, Reflector] = {}
        for kind in sorted(
            sources, key=lambda k: (self._ORDER.get(k, 99), k)
        ):
            lister, watcher = sources[kind]
            self.reflectors[kind] = Reflector(
                scheduler, kind, lister, watcher, resync_s=resync_s
            )

    def step(self) -> int:
        return sum(r.step() for r in self.reflectors.values())

    def run_once(self) -> int:
        return sum(r.run_once() for r in self.reflectors.values())

    def __getitem__(self, kind: str) -> Reflector:
        return self.reflectors[kind]


class FakeSource:
    """An in-memory (lister, watcher) pair for tests and embedders — the
    client-go fake clientset's watch surface.  Mutations bump the
    resource version; watchers replay the event log from their resume
    point; ``compact()`` drops history so stale watchers must relist."""

    def __init__(self) -> None:
        self.rv = 0
        self.objects: dict[str, object] = {}
        self.log: list[tuple[int, str, object]] = []
        self._floor = 0  # oldest rv still replayable

    def _record(self, ev: str, kind_uid: str, obj) -> None:
        self.rv += 1
        if ev == DELETED:
            self.objects.pop(kind_uid, None)
        else:
            self.objects[kind_uid] = obj
        self.log.append((self.rv, ev, obj))

    def add(self, kind_uid: str, obj) -> None:
        self._record(ADDED, kind_uid, obj)

    def update(self, kind_uid: str, obj) -> None:
        self._record(MODIFIED, kind_uid, obj)

    def delete(self, kind_uid: str) -> None:
        obj = self.objects.get(kind_uid)
        if obj is not None:
            self._record(DELETED, kind_uid, obj)

    def compact(self) -> None:
        """Forget the event log (etcd compaction): watchers resuming from
        before ``rv`` get StaleResourceVersion."""
        self.log.clear()
        self._floor = self.rv

    def lister(self):
        return self.rv, list(self.objects.values())

    def watcher(self, since: int):
        if since < self._floor:
            raise StaleResourceVersion(since)
        return [(rv, ev, obj) for rv, ev, obj in self.log if rv > since]
