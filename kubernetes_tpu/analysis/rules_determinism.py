"""Determinism: the scoring kernels must be bit-identical, run to run.

The parity story (wire == degraded == crash-recovery bindings, the A/B
oracle, the golden transcripts, PYTHONHASHSEED-proof push fixtures) all
assume the batch engine is a pure function of its inputs — and the soak
story (loadgen/) assumes the TRAFFIC is too: a generator whose arrivals
read wall clocks or ambient entropy cannot replay, so same-seed soaks
could never assert bit-identical bindings.  Code in ``ops/``,
``engine/``, ``loadgen/``, ``fleet/`` (the router's hash routing and
host-side selectHost mirror must replay bit-identically too) and the
speculative frontend therefore must not:

- read wall clocks (``time.time``/``time_ns``, ``datetime.now``/
  ``utcnow``) — ``time.perf_counter``/``monotonic`` stay allowed: they
  feed latency metrics, never decisions;
- draw entropy (``random.*``, ``os.urandom``, ``uuid.uuid4``) — seeded
  ``numpy.random.Generator`` streams are the loadgen idiom and pass;
- iterate a bare set where the element order can reach an output —
  syntactically visible set expressions (literals, comprehensions,
  ``set()``/``frozenset()`` calls, unions of those) used directly as a
  ``for``/comprehension iterable or materialized via ``list()``/
  ``tuple()``.  ``sorted(...)`` over a set is the fix and is exempt.
  (Named variables of set type are invisible to a syntactic pass; the
  speculative frontend's documented commit-order iteration is exactly
  the idiom this rule pushes toward.)
- key on ``id()`` — CPython address order varies per process;
- route or bucket by builtin ``hash()`` — string hashing is salted per
  process (PYTHONHASHSEED), so a router hashing a pod uid or a shard
  map hashing a node name with it would assign DIFFERENT owners in
  different processes: the fleet's Lease frames, home-shard routing and
  ownership records all key on ``zlib.crc32`` (shardmap.py
  ``stable_shard_hash``) for exactly this reason.

Findings: ``det-wallclock``, ``det-random``, ``det-set-iteration``,
``det-id-key``, ``det-builtin-hash``.
"""

from __future__ import annotations

import ast
import os

from .core import Finding, Rule, dotted_name, make_key

WALLCLOCK = {
    "time.time",
    "time.time_ns",
    "datetime.now",
    "datetime.utcnow",
    "datetime.today",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
}
ENTROPY_MODULES = {"random"}
ENTROPY_CALLS = {"os.urandom", "uuid.uuid4", "uuid.uuid1", "secrets.token_bytes"}


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        if node.func.id in ("set", "frozenset"):
            return True
    if isinstance(node, ast.BinOp) and isinstance(
        node.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)
    ):
        return _is_set_expr(node.left) or _is_set_expr(node.right)
    return False


class DeterminismRule(Rule):
    name = "det"

    def files(self, root) -> list[str]:
        rels = [
            "kubernetes_tpu/sidecar/speculate.py",
            # PR 16's derived-artifact surfaces promise byte-identical
            # output across same-seed runs: the measured-matrix deriver
            # must window on the logical clock (never wall time) and
            # iterate its cells in sorted order, and the trace exporter's
            # logical timebase must never read a clock at all.
            "kubernetes_tpu/framework/measured.py",
            "kubernetes_tpu/framework/trace_export.py",
            # ISSUE 17: the weighted-fair admission policy IS replayed
            # decision state — a wall-clock read, salted hash or
            # unordered iteration in its ledger arithmetic diverges the
            # recovered admission order from the interrupted run's.
            "kubernetes_tpu/framework/fairness.py",
            # ISSUE 20: decision provenance replays the device's own
            # tie-break arithmetic (hash_u32, select_host_trace) and
            # diffs records field by field — a wall clock, entropy
            # source or unordered iteration here would make an explain
            # disagree with the decision it explains.
            "kubernetes_tpu/framework/provenance.py",
        ]
        # The recursive walk below picks up fleet/standby.py and
        # loadgen/checkpoint.py (ISSUE 18) — the warm-standby pool's
        # slot selection and the checkpoint writer's state digest are
        # replayed decision state, so wall clocks / entropy / salted
        # hashing there would diverge a resumed run from its
        # uninterrupted twin.
        for sub in ("ops", "engine", "loadgen", "fleet"):
            top = os.path.join(root, "kubernetes_tpu", sub)
            # Recursive: a future subpackage under ops/ or engine/ must not
            # silently escape the determinism contract.
            for dirpath, dirnames, filenames in os.walk(top):
                dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        rels.append(
                            os.path.relpath(
                                os.path.join(dirpath, name), root
                            ).replace(os.sep, "/")
                        )
        return rels

    def run(self, ctxs, root) -> list[Finding]:
        out: list[Finding] = []
        for path, ctx in ctxs.items():
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.Call):
                    out.extend(self._check_call(path, node))
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    out.extend(self._check_iter(path, node.iter))
                elif isinstance(
                    node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
                ):
                    for gen in node.generators:
                        out.extend(self._check_iter(path, gen.iter))
        return out

    def _check_call(self, path: str, call: ast.Call) -> list[Finding]:
        out: list[Finding] = []
        name = dotted_name(call.func)
        if name in WALLCLOCK:
            out.append(
                Finding(
                    rule="det-wallclock",
                    path=path,
                    line=call.lineno,
                    message=(
                        f"{name}() in a determinism-critical module — "
                        "wall-clock reads vary run to run; use "
                        "time.perf_counter for latency metrics and keep "
                        "clocks out of decisions"
                    ),
                    key=make_key("det-wallclock", path, f"{name}:{call.lineno}"),
                )
            )
        if name is not None:
            head = name.split(".")[0]
            if head in ENTROPY_MODULES or name in ENTROPY_CALLS:
                out.append(
                    Finding(
                        rule="det-random",
                        path=path,
                        line=call.lineno,
                        message=(
                            f"{name}() draws entropy in a determinism-"
                            "critical module — decisions must be a pure "
                            "function of cluster state"
                        ),
                        key=make_key("det-random", path, f"{name}:{call.lineno}"),
                    )
                )
        if isinstance(call.func, ast.Name):
            if call.func.id == "hash" and len(call.args) == 1:
                out.append(
                    Finding(
                        rule="det-builtin-hash",
                        path=path,
                        line=call.lineno,
                        message=(
                            "builtin hash() in a determinism-critical "
                            "module — string hashing is salted per "
                            "process (PYTHONHASHSEED); route/bucket with "
                            "zlib.crc32 (fleet/shardmap.py "
                            "stable_shard_hash) instead"
                        ),
                        key=make_key(
                            "det-builtin-hash", path, f"hash:{call.lineno}"
                        ),
                    )
                )
            if call.func.id == "id" and len(call.args) == 1:
                out.append(
                    Finding(
                        rule="det-id-key",
                        path=path,
                        line=call.lineno,
                        message=(
                            "builtin id() in a determinism-critical module "
                            "— CPython addresses vary per process; key on "
                            "a stable identity (uid/name) instead"
                        ),
                        key=make_key("det-id-key", path, f"id:{call.lineno}"),
                    )
                )
            if call.func.id in ("list", "tuple") and call.args:
                if _is_set_expr(call.args[0]):
                    out.append(self._set_finding(path, call.lineno, "materialized"))
        return out

    def _check_iter(self, path: str, it: ast.AST) -> list[Finding]:
        if _is_set_expr(it):
            return [self._set_finding(path, it.lineno, "iterated")]
        return []

    def _set_finding(self, path: str, line: int, verb: str) -> Finding:
        return Finding(
            rule="det-set-iteration",
            path=path,
            line=line,
            message=(
                f"bare set {verb} in order-sensitive position — set "
                "iteration order is hash-randomized (PYTHONHASHSEED); "
                "wrap in sorted(...) or iterate an ordered container"
            ),
            key=make_key("det-set-iteration", path, f"set:{line}"),
        )


#: rule documentation consumed by check_lint --explain / --rule-catalog
DOCS = {
    "det-wallclock": {
        "family": "det",
        "summary": "Wall-clock read (time.time/now) inside a scoring or decision path.",
        "scope": "Scoring kernels and decision paths under ops/, engine/, loadgen/, fleet/.",
        "rationale": "Replay equivalence (paper §2) requires decisions to be a pure function of the journaled inputs; a wall-clock read makes re-execution diverge from the recorded run.",
        "fix": "Thread the tick/timestamp in from the journaled envelope instead of reading the clock.",
    },
    "det-random": {
        "family": "det",
        "summary": "Unseeded RNG use in a decision path.",
        "scope": "Same decision-path scope as det-wallclock.",
        "rationale": "Unseeded randomness breaks bit-identical replay; every stochastic choice must flow from the journaled seed.",
        "fix": "Derive randomness from the journaled seed (jax.random with an explicit key, or the seeded stdlib Random instance).",
    },
    "det-set-iteration": {
        "family": "det",
        "summary": "Bare set iterated/materialized in an order-sensitive position.",
        "scope": "Decision paths; iteration feeding scores, packing or serialization.",
        "rationale": "Set order is hash-randomized per process (PYTHONHASHSEED) — the same inputs can produce different orderings, hence different bindings.",
        "fix": "Wrap in sorted(...) or keep an ordered container.",
    },
    "det-builtin-hash": {
        "family": "det",
        "summary": "Builtin hash() used where the value feeds a decision.",
        "scope": "Decision paths.",
        "rationale": "str/bytes hashing is salted per process; hashes must be stable across restarts to replay.",
        "fix": "Use the repo's stable hash helper (_hash_u32 / hashlib) instead.",
    },
    "det-id-key": {
        "family": "det",
        "summary": "id() used as a key or ordering basis.",
        "scope": "Decision paths.",
        "rationale": "Object addresses differ across runs; any ordering or keying by id() is unreproducible.",
        "fix": "Key by a stable identifier (uid, name) instead.",
    },
}
