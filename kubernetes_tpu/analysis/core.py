"""tpulint core: the AST-walking invariant engine.

The system's headline guarantee — bit-identical binding decisions across
wire/degraded/crash-recovery paths — rests on conventions that nothing
used to machine-check between PRs: journal-before-apply ordering in the
commit paths (journal.py), pure-deterministic scoring kernels (ops/,
engine/), one coherent metrics namespace (framework/metrics.py), and a
wire protocol whose every frame kind has a live handler and client
method.  Each convention is a :class:`Rule` here; ``run_lint`` walks the
rule's scoped files once, hands shared parse trees to every rule, and
applies the suppression + baseline filters.

Vocabulary:

- **Finding** — one violation: rule id, repo-relative path, line,
  message, and a line-independent ``key`` used for baseline matching
  (line numbers churn; keys survive refactors that keep the symbol).
- **Suppression** — ``# tpulint: disable=<rule>[,<rule>...]`` on the
  finding's line (or alone on the line above it) silences it; a rule
  FAMILY name (``wal``, ``det``, ``metrics``, ``wire``) silences the
  whole family; ``all`` silences everything on that line.  A
  ``# tpulint: disable-file=<rule>`` comment within the first five
  lines silences a file.
- **Baseline** — a committed JSON file of grandfathered finding keys.
  Every entry MUST carry a non-empty written ``justification``; the
  runner refuses a baseline that merely lists keys (grandfathering
  without a reason is how invariants rot).

The engine is dependency-free stdlib (``ast`` + ``re`` + ``json``) so
``scripts/check_lint.py`` can load it without importing the package
root (which pulls JAX).
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    message: str
    key: str  # stable baseline key: "<rule>::<path>::<token>"

    def as_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "key": self.key,
        }

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.message}"


def make_key(rule: str, path: str, token: str) -> str:
    return f"{rule}::{path}::{token}"


@dataclass
class FileCtx:
    """One parsed source file shared by every rule that scopes it."""

    path: str  # repo-relative
    source: str
    tree: ast.Module
    lines: list[str] = field(default_factory=list)

    def __post_init__(self) -> None:
        if not self.lines:
            self.lines = self.source.splitlines()


class Rule:
    """One rule family.  ``name`` is the family prefix (``wal``, ``det``,
    ``metrics``, ``wire``); individual findings carry ids like
    ``wal-apply-before-journal``."""

    name = "rule"

    def files(self, root) -> list[str]:
        """Repo-relative paths this rule wants parsed (existing only)."""
        raise NotImplementedError

    def run(self, ctxs: dict[str, FileCtx], root) -> list[Finding]:
        raise NotImplementedError


# -- suppressions -----------------------------------------------------------

_SUPPRESS_RE = re.compile(r"#\s*tpulint:\s*disable=([\w\-,]+)")
_SUPPRESS_FILE_RE = re.compile(r"#\s*tpulint:\s*disable-file=([\w\-,]+)")


def _rules_match(names: str, rule: str) -> bool:
    family = rule.split("-", 1)[0]
    for name in names.split(","):
        name = name.strip()
        if name in ("all", rule, family):
            return True
    return False


def is_suppressed(finding: Finding, ctx: FileCtx | None) -> bool:
    if ctx is None:
        return False
    # File-level pragma in the header.
    for line in ctx.lines[:5]:
        m = _SUPPRESS_FILE_RE.search(line)
        if m and _rules_match(m.group(1), finding.rule):
            return True
    # Same line, or a standalone comment on the line above.
    for lineno in (finding.line, finding.line - 1):
        if 1 <= lineno <= len(ctx.lines):
            text = ctx.lines[lineno - 1]
            if lineno != finding.line and not text.lstrip().startswith("#"):
                continue
            m = _SUPPRESS_RE.search(text)
            if m and _rules_match(m.group(1), finding.rule):
                return True
    return False


# -- baseline ---------------------------------------------------------------


class BaselineError(ValueError):
    """The baseline file is malformed or carries an unjustified entry."""


def load_baseline(path) -> dict[str, dict]:
    """key → entry.  Raises BaselineError for entries without a written
    justification — the baseline records *why* a finding is tolerated,
    not just that it is."""
    import os

    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        try:
            doc = json.load(f)
        except ValueError as exc:
            raise BaselineError(f"unparseable baseline {path}: {exc}")
    entries = doc.get("findings", []) if isinstance(doc, dict) else doc
    out: dict[str, dict] = {}
    for entry in entries:
        key = entry.get("key")
        just = (entry.get("justification") or "").strip()
        if not key:
            raise BaselineError(f"baseline entry missing 'key': {entry}")
        if not just:
            raise BaselineError(
                f"baseline entry for {key!r} has no justification — "
                "grandfathered findings must say why"
            )
        out[key] = entry
    return out


# -- the runner -------------------------------------------------------------


@dataclass
class LintResult:
    findings: list[Finding]  # unsuppressed, un-baselined — the failures
    suppressed: int
    baselined: int
    stale_baseline: list[str]  # baseline keys no rule produced

    @property
    def clean(self) -> bool:
        return not self.findings

    def as_dict(self) -> dict:
        return {
            "findings": [f.as_dict() for f in self.findings],
            "suppressed": self.suppressed,
            "baselined": self.baselined,
            "stale_baseline": self.stale_baseline,
            "clean": self.clean,
        }


def default_rules() -> list[Rule]:
    from .rules_determinism import DeterminismRule
    from .rules_metrics import MetricsRule
    from .rules_wal import WalRule
    from .rules_wire import WireRule

    return [WalRule(), DeterminismRule(), MetricsRule(), WireRule()]


def run_lint(root, rules=None, baseline=None) -> LintResult:
    """Run ``rules`` (default: all four families) over the tree at
    ``root``.  ``baseline`` is a key → entry dict (see load_baseline)."""
    import os

    rules = default_rules() if rules is None else rules
    baseline = baseline or {}
    ctxs: dict[str, FileCtx] = {}
    findings: list[Finding] = []
    for rule in rules:
        scoped: dict[str, FileCtx] = {}
        for rel in rule.files(root):
            if rel not in ctxs:
                full = os.path.join(root, rel)
                if not os.path.exists(full):
                    continue
                with open(full, "r", encoding="utf-8") as f:
                    src = f.read()
                if rel.endswith(".py"):
                    try:
                        tree = ast.parse(src, filename=rel)
                    except SyntaxError as exc:
                        findings.append(
                            Finding(
                                rule="parse-error",
                                path=rel,
                                line=exc.lineno or 1,
                                message=f"unparseable: {exc.msg}",
                                key=make_key("parse-error", rel, "syntax"),
                            )
                        )
                        continue
                else:
                    tree = ast.Module(body=[], type_ignores=[])
                ctxs[rel] = FileCtx(path=rel, source=src, tree=tree)
            if rel in ctxs:
                scoped[rel] = ctxs[rel]
        findings.extend(rule.run(scoped, root))

    kept: list[Finding] = []
    suppressed = 0
    baselined = 0
    seen_keys: set[str] = set()
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        seen_keys.add(f.key)
        if is_suppressed(f, ctxs.get(f.path)):
            suppressed += 1
            continue
        if f.key in baseline:
            baselined += 1
            continue
        kept.append(f)
    stale = sorted(k for k in baseline if k not in seen_keys)
    return LintResult(
        findings=kept,
        suppressed=suppressed,
        baselined=baselined,
        stale_baseline=stale,
    )


# -- shared AST helpers -----------------------------------------------------


def dotted_name(node: ast.AST) -> str | None:
    """'self.journal.append' for nested Attribute chains, None when the
    chain bottoms out in anything but a Name."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def str_const(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def walk_functions(tree: ast.Module):
    """Yield (qualname, FunctionDef) for every function/method, including
    nested ones (qualname joins with '.')."""

    def visit(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                yield q, child
                yield from visit(child, q + ".")
            elif isinstance(child, ast.ClassDef):
                yield from visit(child, f"{prefix}{child.name}.")
            else:
                yield from visit(child, prefix)

    yield from visit(tree, "")
