"""tpulint core: the AST-walking invariant engine.

The system's headline guarantee — bit-identical binding decisions across
wire/degraded/crash-recovery paths — rests on conventions that nothing
used to machine-check between PRs: journal-before-apply ordering in the
commit paths (journal.py), pure-deterministic scoring kernels (ops/,
engine/), one coherent metrics namespace (framework/metrics.py), and a
wire protocol whose every frame kind has a live handler and client
method.  Each convention is a :class:`Rule` here; ``run_lint`` walks the
rule's scoped files once, hands shared parse trees to every rule, and
applies the suppression + baseline filters.

Vocabulary:

- **Finding** — one violation: rule id, repo-relative path, line,
  message, and a line-independent ``key`` used for baseline matching
  (line numbers churn; keys survive refactors that keep the symbol).
- **Suppression** — ``# tpulint: disable=<rule>[,<rule>...]`` on the
  finding's line (or alone on the line above it) silences it; a rule
  FAMILY name (``wal``, ``det``, ``metrics``, ``wire``) silences the
  whole family; ``all`` silences everything on that line.  A
  ``# tpulint: disable-file=<rule>`` comment within the first five
  lines silences a file.
- **Baseline** — a committed JSON file of grandfathered finding keys.
  Every entry MUST carry a non-empty written ``justification``; the
  runner refuses a baseline that merely lists keys (grandfathering
  without a reason is how invariants rot).

The engine is dependency-free stdlib (``ast`` + ``re`` + ``json``) so
``scripts/check_lint.py`` can load it without importing the package
root (which pulls JAX).
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import pickle
import re
from dataclasses import dataclass, field


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    message: str
    key: str  # stable baseline key: "<rule>::<path>::<token>"
    # Extra (path, line) sites whose suppressions also silence this
    # finding.  The flow-aware WAL rules report an interprocedural chain
    # at its outermost frontier, but a pragma at any hop of the chain —
    # e.g. the terminal apply site a recovery path deliberately leaves
    # unjournaled — still covers it: the suppression documents the site,
    # wherever the chain is reported from.
    also: tuple = ()

    def as_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "key": self.key,
        }

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.message}"


def make_key(rule: str, path: str, token: str) -> str:
    return f"{rule}::{path}::{token}"


@dataclass
class FileCtx:
    """One parsed source file shared by every rule that scopes it."""

    path: str  # repo-relative
    source: str
    tree: ast.Module
    lines: list[str] = field(default_factory=list)

    def __post_init__(self) -> None:
        if not self.lines:
            self.lines = self.source.splitlines()


class Rule:
    """One rule family.  ``name`` is the family prefix (``wal``, ``det``,
    ``metrics``, ``wire``); individual findings carry ids like
    ``wal-apply-before-journal``."""

    name = "rule"

    def files(self, root) -> list[str]:
        """Repo-relative paths this rule wants parsed (existing only)."""
        raise NotImplementedError

    def run(self, ctxs: dict[str, FileCtx], root) -> list[Finding]:
        raise NotImplementedError


# -- suppressions -----------------------------------------------------------

_SUPPRESS_RE = re.compile(r"#\s*tpulint:\s*disable=([\w\-,]+)")
_SUPPRESS_FILE_RE = re.compile(r"#\s*tpulint:\s*disable-file=([\w\-,]+)")


def _rules_match(names: str, rule: str) -> bool:
    family = rule.split("-", 1)[0]
    for name in names.split(","):
        name = name.strip()
        if name in ("all", rule, family):
            return True
    return False


@dataclass(frozen=True)
class Pragma:
    """One suppression comment, addressable so the runner can prove it
    still matches something.  A pragma no unsuppressed finding needs is
    dead weight that hides future regressions — ``run_lint`` reports it
    in ``LintResult.unused_suppressions`` and the runner exits 2."""

    path: str
    line: int  # lineno of the comment (file-level pragmas too)
    names: str
    file_level: bool

    def render(self) -> str:
        kind = "disable-file" if self.file_level else "disable"
        return f"{self.path}:{self.line}: tpulint: {kind}={self.names}"


def collect_pragmas(ctx: FileCtx) -> list[Pragma]:
    out: list[Pragma] = []
    for i, text in enumerate(ctx.lines[:5], start=1):
        m = _SUPPRESS_FILE_RE.search(text)
        if m:
            out.append(Pragma(ctx.path, i, m.group(1), True))
    for i, text in enumerate(ctx.lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if m:
            out.append(Pragma(ctx.path, i, m.group(1), False))
    return out


def _match_pragma(
    finding: Finding,
    ctxs: dict[str, FileCtx],
    pragmas: dict[str, list[Pragma]],
) -> Pragma | None:
    """The pragma (if any) that silences ``finding``, checking the
    finding's own site first and then every chain hop in ``also``."""
    sites = [(finding.path, finding.line)] + [tuple(s) for s in finding.also]
    for path, line in sites:
        ctx = ctxs.get(path)
        plist = pragmas.get(path)
        if ctx is None or not plist:
            continue
        for p in plist:
            if p.file_level and _rules_match(p.names, finding.rule):
                return p
        for lineno in (line, line - 1):
            if not 1 <= lineno <= len(ctx.lines):
                continue
            text = ctx.lines[lineno - 1]
            # a pragma on the line above must be a standalone comment
            if lineno != line and not text.lstrip().startswith("#"):
                continue
            for p in plist:
                if (
                    not p.file_level
                    and p.line == lineno
                    and _rules_match(p.names, finding.rule)
                ):
                    return p
    return None


def is_suppressed(finding: Finding, ctx: FileCtx | None) -> bool:
    """Single-file compatibility wrapper over :func:`_match_pragma`
    (chain hops in other files are not visible here)."""
    if ctx is None:
        return False
    return (
        _match_pragma(finding, {ctx.path: ctx}, {ctx.path: collect_pragmas(ctx)})
        is not None
    )


# -- baseline ---------------------------------------------------------------


class BaselineError(ValueError):
    """The baseline file is malformed or carries an unjustified entry."""


def load_baseline(path) -> dict[str, dict]:
    """key → entry.  Raises BaselineError for entries without a written
    justification — the baseline records *why* a finding is tolerated,
    not just that it is."""
    import os

    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        try:
            doc = json.load(f)
        except ValueError as exc:
            raise BaselineError(f"unparseable baseline {path}: {exc}")
    entries = doc.get("findings", []) if isinstance(doc, dict) else doc
    out: dict[str, dict] = {}
    for entry in entries:
        key = entry.get("key")
        just = (entry.get("justification") or "").strip()
        if not key:
            raise BaselineError(f"baseline entry missing 'key': {entry}")
        if not just:
            raise BaselineError(
                f"baseline entry for {key!r} has no justification — "
                "grandfathered findings must say why"
            )
        out[key] = entry
    return out


# -- the runner -------------------------------------------------------------


@dataclass
class LintResult:
    findings: list[Finding]  # unsuppressed, un-baselined — the failures
    suppressed: int
    baselined: int
    stale_baseline: list[str]  # baseline keys no rule produced
    # pragmas that silenced nothing this run (rendered "path:line: ...").
    # Like stale baseline keys, these are exit-2 material in a full run:
    # the suppression surface may only shrink.
    unused_suppressions: list[str] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.findings

    def as_dict(self) -> dict:
        return {
            "findings": [f.as_dict() for f in self.findings],
            "suppressed": self.suppressed,
            "baselined": self.baselined,
            "stale_baseline": self.stale_baseline,
            "unused_suppressions": self.unused_suppressions,
            "clean": self.clean,
        }


def default_rules() -> list[Rule]:
    from .rules_determinism import DeterminismRule
    from .rules_jax import JaxRule
    from .rules_metrics import MetricsRule
    from .rules_wal import WalRule
    from .rules_wire import WireRule

    return [WalRule(), DeterminismRule(), MetricsRule(), WireRule(), JaxRule()]


def rule_docs() -> dict[str, dict]:
    """``rule id → doc dict`` collected from every rules module's DOCS
    (the check_lint --explain / --rule-catalog surface).  Collected
    lazily so importing core stays cheap, and asserted complete: a rule
    module that grows a finding without documenting it fails loudly in
    the catalog tests rather than silently shipping an unexplainable
    finding."""
    from . import rules_determinism, rules_jax, rules_metrics, rules_wal, rules_wire

    docs: dict[str, dict] = {}
    for mod in (rules_wal, rules_determinism, rules_metrics, rules_wire, rules_jax):
        for rule_id, doc in mod.DOCS.items():
            if rule_id in docs:
                raise ValueError(f"duplicate rule doc: {rule_id}")
            docs[rule_id] = doc
    return docs


class ParseCache:
    """Parse trees keyed by content hash, pickled under ``cache_dir``.

    Best-effort on both ends: a missing/corrupt entry re-parses, a
    failed store is ignored.  Keyed purely by source bytes, so a stale
    entry is impossible — edits change the key."""

    def __init__(self, cache_dir: str):
        self.dir = cache_dir
        self.hits = 0
        self.misses = 0

    def _slot(self, source: str) -> str:
        digest = hashlib.sha256(source.encode("utf-8")).hexdigest()
        return os.path.join(self.dir, f"{digest}.ast.pkl")

    def load(self, source: str) -> ast.Module | None:
        try:
            with open(self._slot(source), "rb") as f:
                tree = pickle.load(f)
        except Exception:
            self.misses += 1
            return None
        if not isinstance(tree, ast.Module):
            self.misses += 1
            return None
        self.hits += 1
        return tree

    def store(self, source: str, tree: ast.Module) -> None:
        try:
            os.makedirs(self.dir, exist_ok=True)
            slot = self._slot(source)
            tmp = slot + ".tmp"
            with open(tmp, "wb") as f:
                pickle.dump(tree, f, protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, slot)
        except Exception:
            pass


def run_lint(root, rules=None, baseline=None, cache=None) -> LintResult:
    """Run ``rules`` (default: all four families) over the tree at
    ``root``.  ``baseline`` is a key → entry dict (see load_baseline);
    ``cache`` an optional :class:`ParseCache`."""
    rules = default_rules() if rules is None else rules
    baseline = baseline or {}
    ctxs: dict[str, FileCtx] = {}
    findings: list[Finding] = []
    for rule in rules:
        scoped: dict[str, FileCtx] = {}
        for rel in rule.files(root):
            if rel not in ctxs:
                full = os.path.join(root, rel)
                if not os.path.exists(full):
                    continue
                with open(full, "r", encoding="utf-8") as f:
                    src = f.read()
                if rel.endswith(".py"):
                    tree = cache.load(src) if cache is not None else None
                    if tree is None:
                        try:
                            tree = ast.parse(src, filename=rel)
                        except SyntaxError as exc:
                            findings.append(
                                Finding(
                                    rule="parse-error",
                                    path=rel,
                                    line=exc.lineno or 1,
                                    message=f"unparseable: {exc.msg}",
                                    key=make_key("parse-error", rel, "syntax"),
                                )
                            )
                            continue
                        if cache is not None:
                            cache.store(src, tree)
                else:
                    tree = ast.Module(body=[], type_ignores=[])
                ctxs[rel] = FileCtx(path=rel, source=src, tree=tree)
            if rel in ctxs:
                scoped[rel] = ctxs[rel]
        findings.extend(rule.run(scoped, root))

    pragmas = {path: collect_pragmas(ctx) for path, ctx in ctxs.items()}
    used: set[tuple[str, int]] = set()
    kept: list[Finding] = []
    suppressed = 0
    baselined = 0
    seen_keys: set[str] = set()
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        seen_keys.add(f.key)
        pragma = _match_pragma(f, ctxs, pragmas)
        if pragma is not None:
            used.add((pragma.path, pragma.line))
            suppressed += 1
            continue
        if f.key in baseline:
            baselined += 1
            continue
        kept.append(f)
    stale = sorted(k for k in baseline if k not in seen_keys)
    unused = sorted(
        p.render()
        for plist in pragmas.values()
        for p in plist
        if (p.path, p.line) not in used
    )
    return LintResult(
        findings=kept,
        suppressed=suppressed,
        baselined=baselined,
        stale_baseline=stale,
        unused_suppressions=unused,
    )


# -- shared AST helpers -----------------------------------------------------


def dotted_name(node: ast.AST) -> str | None:
    """'self.journal.append' for nested Attribute chains, None when the
    chain bottoms out in anything but a Name."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def str_const(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def walk_functions(tree: ast.Module):
    """Yield (qualname, FunctionDef) for every function/method, including
    nested ones (qualname joins with '.')."""

    def visit(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                yield q, child
                yield from visit(child, q + ".")
            elif isinstance(child, ast.ClassDef):
                yield from visit(child, f"{prefix}{child.name}.")
            else:
                yield from visit(child, prefix)

    yield from visit(tree, "")
